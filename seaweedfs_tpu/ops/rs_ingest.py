"""Streaming ingest encode (r20): RS parity for stripe rows as they
complete on the WRITE path.

The bulk executor (storage/ec/bulk.py) encodes a finished `.dat` after
the fact; the ingest plane (seaweedfs_tpu/ingest/) encodes each stripe
row — [k, block] bytes of the still-growing `.dat` — the moment the row
fills.  This module is the device entry for that plane:

  * one jitted GF(2) bitsliced matmul per row, the SAME kernels the read
    path dispatches (rs_tpu.apply_matrix_device), so encode and
    reconstruct can never drift numerically;
  * the r11 AOT warm / shed-cold discipline, shared registry and
    counters with rs_resident: the live write path never inline-compiles
    — a cold row shape raises ColdShape, the row encodes on the host
    codec, and the background executor compiles the shape for the next
    row (`warm()` pre-compiles the volume block sizes at startup);
  * donation flows the OPPOSITE way from reads: the read path donates a
    tiny [N] request vector and keeps survivor shards resident; ingest
    donates the big [k, block] staged data block itself (its bytes are
    already on their way to the shard files — the device copy is
    dead after the multiply).  On a zero-copy PJRT client (CPU) the
    staged arena row is therefore NEVER handed to the donating call —
    `_donatable()` makes the defensive copy, and the viewguard harness
    patches it to enforce the discipline at test time;
  * IngestArena: the bounded pool of staged row buffers whose
    exhaustion IS the write path's backpressure (a writer that cannot
    stage blocks until the codec drains — bounded memory, bounded
    lag, no unbounded queue between the front door and the device).
"""
from __future__ import annotations

import queue
import threading

import time

import numpy as np

from ..obs import devledger
from ..stats import metrics as stats_metrics
from . import rs

DATA_SHARDS = rs.DATA_SHARDS
PARITY_SHARDS = rs.PARITY_SHARDS


class ArenaExhausted(RuntimeError):
    """No staging row freed within the backpressure budget."""


def _donatable(rows: np.ndarray, on_tpu: bool) -> np.ndarray:
    """The array actually handed to the donating device call.  On TPU the
    transfer copies, so donating the staged view is the designed fast
    path; on a zero-copy CPU client donation would hand the live arena
    row's memory to XLA — exactly the aliasing the arena pool exists to
    prevent — so the call gets a fresh copy.  Viewguard patches this
    boundary (tests/viewguard.py) to fail a gating regression at the
    dispatch, not as scribbled shard bytes."""
    if on_tpu:
        return rows
    return np.array(rows)


class IngestArena:
    """Bounded pool of [k, block] staged row buffers for ONE pipeline.

    stage() blocks (up to the backpressure budget) until a row buffer is
    free — that wait propagates through IngestPipeline.feed() to the
    HTTP writer as honest backpressure.  seal() marks a filled row
    immutable-until-reclaim (viewguard export point); reclaim() returns
    the buffer to the pool once its shard rows are on disk (viewguard
    verifies the bytes never drifted in between)."""

    def __init__(self, k: int, block: int, slots: int = 2):
        if slots < 1:
            raise ValueError(f"arena needs >= 1 slot, got {slots}")
        self.k = k
        self.block = block
        self.slots = slots
        self.waits = 0  # stage() calls that had to block
        self._free: queue.Queue = queue.Queue()
        for _ in range(slots):
            self._free.put(np.empty((k, block), dtype=np.uint8))

    def stage(self, timeout_s: float | None = None) -> np.ndarray:
        try:
            return self._free.get_nowait()
        except queue.Empty:
            pass
        self.waits += 1
        stats_metrics.VOLUME_SERVER_INGEST_BACKPRESSURE.inc()
        try:
            return self._free.get(timeout=timeout_s)
        except queue.Empty:
            raise ArenaExhausted(
                f"no ingest arena row freed in {timeout_s}s "
                f"({self.slots} slots of [{self.k}, {self.block}])"
            ) from None

    def seal(self, buf: np.ndarray) -> np.ndarray:
        """The row is full: its bytes are final until reclaim()."""
        return buf

    def reclaim(self, buf: np.ndarray) -> None:
        self._free.put(buf)

    @property
    def free_slots(self) -> int:
        return self._free.qsize()


class StreamEncoder:
    """RS(k, p) parity for one staged row, device-first with AOT
    shed-cold, host codec fallback.  Thread-safe: the per-volume
    pipeline workers share one encoder (one prepared matrix, one AOT
    registry entry per block size)."""

    def __init__(
        self,
        backend: str = "auto",
        shed_cold: bool = True,
        interpret: bool | None = None,
    ):
        self.backend = rs.resolve_backend(backend)
        self.device = self.backend in ("xla", "pallas")
        self.shed_cold = bool(shed_cold)
        self.k = DATA_SHARDS
        self.p = PARITY_SHARDS
        # host fallback/oracle: native kernel when built, numpy otherwise
        self._host = rs.RSCodec(backend="cpu")
        self.host_rows = 0  # rows encoded on the host (shed or CPU backend)
        self.device_rows = 0
        self._mu = threading.Lock()
        if self.device:
            from . import rs_tpu

            self._tpu = rs_tpu
            self.interpret = (
                (not rs_tpu.on_tpu()) if interpret is None else bool(interpret)
            )
            self._a_prep = rs_tpu.prepare_matrix(self._host.matrix[self.k :])
            self._a_shape = tuple(self._a_prep.shape)

    # ------------------------------------------------------------- AOT grid

    def _key(self, block: int) -> tuple:
        """Streaming-encode twin of rs_resident._call_key: one entry in
        the SAME registry/miss-counter/shed namespace (the leading
        "ingest_encode" family tag keeps it disjoint from every
        reconstruct key)."""
        return (
            "ingest_encode", self.backend, self._a_shape, self.k,
            int(block), bool(self.interpret),
        )

    def _compile_key(self, key: tuple) -> None:
        """Lower + compile one row shape (runs on the shared AOT
        executor, so ingest compiles queue behind/ahead of serving warms
        in one global submission order)."""
        import jax

        from . import rs_resident

        _, kernel, a_shape, k, block, interpret = key
        a_aval = jax.ShapeDtypeStruct(a_shape, np.int8)
        x_aval = jax.ShapeDtypeStruct((k, block), np.uint8)
        with rs_resident._quiet_donation():
            exe = _encode_entry().lower(
                a_aval, x_aval, kernel=kernel, interpret=interpret, k_true=k
            ).compile()
        rs_resident._register_compiled(key, exe)

    def warm(self, blocks, wait: bool = False) -> list:
        """Pre-compile the streaming-encode executable for each row
        width a volume can stage (the small/large block sizes), exactly
        like rs_resident.warm parks the serving ladder: first write
        traffic hits a parked executable or sheds cleanly — never an
        inline compile on the live path."""
        if not self.device:
            return []
        from . import rs_resident

        jobs = []
        with rs_resident._shapes_lock:
            for block in blocks:
                key = self._key(block)
                if (
                    key in rs_resident._aot_executables
                    or key in rs_resident._aot_pending
                    or key in rs_resident._dispatched_shapes
                    or key in rs_resident._aot_failed
                ):
                    continue
                rs_resident._aot_pending.add(key)
                jobs.append(key)
        ex = rs_resident._aot_executor()
        futs = [ex.submit(self._compile_logged, key) for key in jobs]
        if wait:
            import concurrent.futures

            concurrent.futures.wait(futs)
        return futs

    def _compile_logged(self, key: tuple) -> None:
        from . import rs_resident

        # explicit warmup attribution: the shared compile executor's
        # thread has no tagging context (see rs_resident._compile_shape_logged)
        t0 = time.perf_counter()
        try:
            with devledger.workload("warmup"):
                self._compile_key(key)
            devledger.record(
                workload="warmup",
                busy_s=time.perf_counter() - t0, dispatches=1,
            )
        except Exception:  # noqa: BLE001 — a failed ingest AOT compile
            # must not kill the shared executor; the shape keeps
            # encoding on the host codec, which serves it fine
            import logging

            logging.getLogger(__name__).exception(
                "ingest AOT compile failed for %s", key
            )
            with rs_resident._shapes_lock:
                rs_resident._aot_pending.discard(key)
                rs_resident._aot_failed.add(key)

    def shape_is_warm(self, block: int) -> bool:
        if not self.device:
            return True  # host codec: nothing to compile
        from . import rs_resident

        return rs_resident._shape_is_warm(self._key(block))

    # ------------------------------------------------------------- encoding

    def encode(self, rows: np.ndarray) -> np.ndarray:
        """rows [k, B] u8 -> parity [p, B] u8.  Device path: AOT
        executable when parked, shed-cold otherwise (the CALLER encodes
        the shed row via encode_host — raising keeps the shed explicit
        in the pipeline's stats)."""
        from . import rs_resident

        rows = np.ascontiguousarray(rows, dtype=np.uint8)
        if not self.device:
            return self.encode_host(rows)
        key = self._key(rows.shape[1])
        if self.shed_cold and not rs_resident._shape_is_warm(key):
            self.warm((rows.shape[1],))  # arm the background compile
            raise rs_resident.ColdShape(
                f"ingest encode shape [{self.k}, {rows.shape[1]}] is cold"
            )
        rs_resident._note_shape(key)
        x = _donatable(rows, self._tpu.on_tpu())
        exe = rs_resident._aot_executables.get(key)
        # pipeline workers call encode() directly from their own threads,
        # so the ingest class is pinned here rather than inherited; the
        # busy window covers dispatch through the D2H np.asarray fetch —
        # the row's whole device occupancy
        t0 = time.perf_counter()
        with devledger.workload("ingest"), rs_resident._quiet_donation():
            if exe is not None:
                out = exe(self._a_prep, x)
            else:
                out = _encode_entry()(
                    self._a_prep, x, kernel=self.backend,
                    interpret=self.interpret, k_true=self.k,
                )
            parity = np.asarray(out)[: self.p]
        devledger.record(
            workload="ingest",
            busy_s=time.perf_counter() - t0, dispatches=1,
            nbytes=int(x.nbytes) + int(parity.nbytes),
        )
        with self._mu:
            self.device_rows += 1
        return parity

    def encode_host(self, rows: np.ndarray) -> np.ndarray:
        with self._mu:
            self.host_rows += 1
        return self._host.encode(rows)


def _encode_rows_impl(a_bm, x, kernel="xla", interpret=False, k_true=None):
    from . import rs_tpu

    return rs_tpu.apply_matrix_device(
        a_bm, x, kernel=kernel, interpret=interpret, k_true=k_true
    )


_ENCODE_JIT = None
_ENCODE_JIT_LOCK = threading.Lock()


def _encode_entry():
    """The jitted streaming-encode entry, built on first use: donate the
    staged data block (the big H2D buffer — dead after the multiply,
    unlike the read path where the survivors stay resident and only the
    request vec donates).  Both the live dispatch and the AOT
    lower().compile() go through this ONE jit wrapper so a warmed
    executable and an inline trace can never diverge."""
    global _ENCODE_JIT
    if _ENCODE_JIT is None:
        with _ENCODE_JIT_LOCK:
            if _ENCODE_JIT is None:
                import jax

                _ENCODE_JIT = jax.jit(
                    _encode_rows_impl,
                    static_argnames=("kernel", "interpret", "k_true"),
                    donate_argnums=(1,),
                )
    return _ENCODE_JIT
