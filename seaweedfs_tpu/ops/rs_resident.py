"""Device-resident EC shard cache + batched degraded-read reconstruction.

Round-2 measurement showed why a naive device degraded read loses: every
per-needle reconstruct shipped 10x the payload (the survivor intervals)
host->device before the kernel could run, so the call was transfer-bound
(3965 ms p99 vs 0.75 ms for the C++ CPU kernel on this rig's tunneled
device).  The fix is to keep hot shards *resident in HBM*: then a degraded
read sends only (offset, row) scalars up and the reconstructed interval
bytes down, and any number of concurrent needle reconstructions batch into
ONE device call that gathers survivor slices from the resident buffers.

This is the TPU answer to the reference's per-needle goroutine fan-in
(/root/reference/weed/storage/store_ec.go:339-393): instead of fetching
interval bytes from >=10 peers per needle, the rebuilder/reader node pins
the survivor shards once (mount time or first read) and serves every
degraded needle from device memory.

Shapes and compile hygiene:
  * shard buffers are padded to SHARD_QUANTUM so volumes of similar size
    share jit caches, plus MAX_TILE slack so slices never clamp;
  * request sizes quantize to SIZE_BUCKETS, request counts to
    COUNT_BUCKETS, offsets align down to LANE (128) with the residual
    sliced off on host — a handful of compiles total, warmable up front.
"""
from __future__ import annotations

import contextlib
import functools
import threading
import time
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import gf256, rs_tpu
from ..obs import trace as obs_trace
from ..stats import metrics as stats_metrics

DATA_SHARDS = 10
TOTAL_SHARDS = 14

LANE = 128  # TPU lane tile: device slices start lane-aligned
# The fused kernel's DMA source is a (1024)-tiled 1-D HBM memref: Mosaic
# must PROVE slice starts divisible by 1024, so fused offsets align down
# to this and the <=1023-byte residual joins the host-trimmed delta.
FUSED_ALIGN = 1024
SIZE_BUCKETS = (2048, 8192, 32768, 131072, 524288, 2 * 1024 * 1024)
# a 256-wide bucket amortizes the per-call dispatch RTT over whole read
# bursts on tunneled rigs (padding past the true count costs only device
# compute: the in-jit [:n] trim keeps padded rows off the wire).  The
# ladder jumps 64 -> 256 on purpose: every bucket is a compiled shape
# warm() must pay 20-40s for, and a 65-request batch padded to 256 wastes
# only microseconds of MXU time
COUNT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 256)
MAX_TILE = SIZE_BUCKETS[-1]
# split oversized intervals into chunks that fit the largest bucket even
# after the <=FUSED_ALIGN-1 alignment residual
CHUNK = MAX_TILE - FUSED_ALIGN
SHARD_QUANTUM = 64 * 1024 * 1024


class CacheMiss(LookupError):
    """Not enough resident shards to serve the request."""


_COMPILE_CACHE_SET = False


def enable_persistent_compile_cache(path: str) -> bool:
    """Point XLA's persistent compilation cache at `path` so the
    reconstruct kernel's per-(size, count)-shape compiles (tens of
    seconds each on remote-compile rigs) survive process restarts.

    The setting is PROCESS-GLOBAL, so call this once from the process
    entry point (the volume CLI does, next to -ec.deviceCacheMB); later
    calls no-op.  Returns True when the cache was enabled."""
    global _COMPILE_CACHE_SET
    if _COMPILE_CACHE_SET:
        return False
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # noqa: BLE001 — older jax without the knobs
        import logging

        logging.getLogger(__name__).warning(
            "persistent compile cache unavailable (%s): every restart "
            "will recompile the reconstruct kernel shapes", e,
        )
        return False
    _COMPILE_CACHE_SET = True
    return True


def compile_cache_for_volume_dirs(ec_device_cache_mb: int, dirs) -> bool:
    """CLI bootstrap shared by `volume` and `server`: when the device
    shard cache is enabled, persist kernel compiles next to the data."""
    import os

    if ec_device_cache_mb <= 0 or not dirs:
        return False
    return enable_persistent_compile_cache(
        os.path.join(dirs[0], "jax_compile_cache")
    )


def _bucket(values: tuple[int, ...], need: int) -> int:
    for v in values:
        if need <= v:
            return v
    raise ValueError(f"{need} exceeds largest bucket {values[-1]}")


# bound per-call output (count * size bucket) so a wide batch of large
# intervals can't balloon device/host buffers; small-needle batches (the
# dominant serving shape) still ride the widest counts
_MAX_CALL_OUT = 32 * 1024 * 1024
# bound AGGREGATE un-fetched output across pipelined calls: each pending
# call parks its [n, fetch] result in HBM until the fetch loop reaches it
_MAX_PENDING_OUT = 128 * 1024 * 1024


def _max_count(size_bucket: int) -> int:
    return max(1, min(COUNT_BUCKETS[-1], _MAX_CALL_OUT // size_bucket))


# resident shard layouts.  "flat": the round-5/6 layout — one 1-D padded
# buffer per shard, reconstructed with the plain [8m,8k] bit matrix.
# "blockdiag": the same resident bytes SERVED through the block-diagonal
# g-group system (rs_tpu round-3: A_blk [128, 320] fills the MXU's M
# dimension, ~157 vs ~121 GB/s flat).  The host stages the layout for
# free: a request's tile (or scrub's shard span) splits into g
# CONTIGUOUS segments — segment-stacked [g*k, B/g] input rows are just
# g slices per survivor, so the gather reads them straight out of the
# flat resident buffers and no device restack (58 GB/s byte transposes,
# the round-3 dealbreaker) ever happens.
LAYOUTS = ("flat", "blockdiag")


class DevicePipeline:
    """Double-buffered staging gate for the device leg of batched
    reconstruct calls: `slots=2` lets batch N+1 pack (outside the slot)
    and ship+execute (inside it) while batch N drains its D2H — only
    N's fetch blocks N's completion.  `slots=1` is the serial baseline
    (bench.py's overlap-off axis).  The overlap-fraction gauge is
    device-busy seconds / wall seconds over the current batch window (a
    window opens when the pipeline leaves idle; the ratio refreshes at
    EVERY batch completion — a drain-only update would go stale under
    exactly the sustained load it exists to measure), so 1.0 means the
    device section ran the whole window and >1 means the staging slots
    genuinely overlapped."""

    def __init__(self, slots: int = 2):
        self._cond = threading.Condition()
        self._slots = max(1, slots)
        self._active = 0
        self._busy_s = 0.0
        self._window_t0 = 0.0
        self.last_overlap = 0.0

    @property
    def slots(self) -> int:
        return self._slots

    def set_slots(self, n: int) -> None:
        with self._cond:
            self._slots = max(1, int(n))
            self._cond.notify_all()

    @contextlib.contextmanager
    def slot(self):
        """Hold one staging slot for a device section; yields the time
        spent waiting for the slot (annotated on the device span so a
        saturated pipeline is attributable)."""
        t_req = time.perf_counter()
        with self._cond:
            while self._active >= self._slots:
                self._cond.wait()
            self._active += 1
            if self._active == 1:
                self._window_t0 = time.perf_counter()
                self._busy_s = 0.0
        t0 = time.perf_counter()
        try:
            yield t0 - t_req
        finally:
            dur = time.perf_counter() - t0
            with self._cond:
                self._active -= 1
                self._busy_s += dur
                wall = time.perf_counter() - self._window_t0
                if wall > 0:
                    self.last_overlap = self._busy_s / wall
                    stats_metrics.VOLUME_SERVER_EC_OVERLAP_FRACTION.set(
                        self.last_overlap
                    )
                self._cond.notify()


class DeviceShardCache:
    """LRU cache of EC shard bytes pinned in device memory.

    Keyed by (vid, shard_id).  `budget_bytes` bounds device-padded bytes;
    inserting past the budget evicts least-recently-used shards (whole
    shards — a partially resident volume simply fails over to the host
    path via CacheMiss).
    """

    def __init__(
        self,
        budget_bytes: int = 8 << 30,
        shard_quantum: int = SHARD_QUANTUM,
        layout: str = "flat",
        groups: int = rs_tpu.BLOCKDIAG_GROUPS,
    ):
        if layout not in LAYOUTS:
            raise ValueError(f"unknown resident layout {layout!r}")
        if groups < 1 or SIZE_BUCKETS[0] % (groups * LANE):
            # every size bucket is a multiple of the smallest, so this
            # one check guarantees lane-aligned tile/groups segments on
            # the XLA path (the fused path re-derives its own
            # groups*FUSED_ALIGN-aligned ladder)
            raise ValueError(
                f"groups={groups} must split the {SIZE_BUCKETS[0]}-byte "
                "size bucket into lane-aligned segments"
            )
        self.budget = budget_bytes
        self.quantum = shard_quantum
        # which reconstruct/scrub kernel family serves this cache's bytes
        # (-ec.serving.layout); mutable at runtime — the bytes are
        # layout-agnostic (blockdiag segments are contiguous slices of
        # the same flat buffers), only the compiled shapes differ
        self.layout = layout
        self.groups = groups
        # the double-buffered device staging gate shared by every
        # reconstruct call against this cache (-ec.serving.overlap)
        self.pipeline = DevicePipeline()
        # the (size, count) bucket shapes the store's pin thread
        # pre-compiles after pinning a volume (warm()); deployments with
        # a known workload shape can narrow these to cut mount-time
        # compile cost (each shape is 20-40s on remote-compile rigs).
        # 256 covers the widest burst bucket so a >64-read coalesce
        # never hits a compile cliff on the serving path
        self.warm_sizes: tuple[int, ...] = (4096, 65536, 1 << 20)
        self.warm_counts: tuple[int, ...] = (1, 8, 64, 256)
        self._lock = threading.Lock()
        self._arrays: OrderedDict[tuple[int, int], object] = OrderedDict()
        self._true_sizes: dict[tuple[int, int], int] = {}
        # vid -> the disk-location directory whose shard files were
        # pinned.  The cache is keyed by (vid, shard) only, so a vid
        # mounted in several locations is ambiguous without this: scrub
        # and read verdicts must be attributed to the location whose
        # bytes are actually resident (ADVICE r5).
        self._pin_source: dict[int, str] = {}
        # vid -> resident shard count, maintained on put/evict so the
        # serving path's per-read routing predicate is O(1) instead of
        # a scan-and-sort of the whole key set under the lock
        self._vid_counts: dict[int, int] = {}
        self.bytes_used = 0
        # cumulative telemetry counters, reported up the heartbeat
        # (pb VolumeServerTelemetry): budget-pressure evictions are the
        # "HBM is too small for the working set" signal, pin claims the
        # "how many volumes ever went resident here" one
        self.evictions = 0
        self.pin_claims = 0

    def _padded_len(self, n: int) -> int:
        need = n + MAX_TILE
        return -(-need // self.quantum) * self.quantum

    def put(self, vid: int, shard_id: int, data) -> None:
        host = np.frombuffer(bytes(data), dtype=np.uint8) if isinstance(
            data, (bytes, bytearray, memoryview)
        ) else np.asarray(data, dtype=np.uint8)
        # stage via np.empty + tail-only zeroing: np.zeros memsets the
        # WHOLE padded buffer and then overwrites all but the tail — a
        # redundant full-size host pass per shard when pinning a large
        # volume.  A reused per-cache staging buffer would cut the
        # allocation too, but the CPU PJRT client zero-copies aligned
        # numpy arrays into jax Arrays, so reuse would alias (and
        # corrupt) previously pinned shards; a fresh buffer per put is
        # the safe form of the optimization (alloc is cheap, memset of
        # gigabytes is not).  The padded buffer doubles as the blockdiag
        # segment-stacked layout: its g segments are contiguous slices,
        # staged by the host for free.
        padded = np.empty(self._padded_len(host.size), dtype=np.uint8)
        padded[: host.size] = host
        padded[host.size :] = 0
        arr = jax.device_put(padded)
        key = (vid, shard_id)
        with self._lock:
            if key in self._arrays:
                self.bytes_used -= self._arrays.pop(key).size
                self._vid_counts[vid] -= 1
            while self._arrays and self.bytes_used + padded.size > self.budget:
                old_key, old = self._arrays.popitem(last=False)
                self._true_sizes.pop(old_key, None)
                self.bytes_used -= old.size
                self.evictions += 1
                self._vid_counts[old_key[0]] -= 1
                if not self._vid_counts[old_key[0]]:
                    del self._vid_counts[old_key[0]]
                # deliberately KEEP the evicted vid's pin-source claim:
                # budget pressure can evict a volume's own oldest shards
                # while its pin thread is still uploading, and dropping
                # the claim here would leave the remaining pins
                # unclaimed (never routed resident) or let a second
                # location interleave its shard set.  A stale claim is
                # conservative: scrub/serving just see too few resident
                # shards and stay on the file path; explicit evict()/
                # clear() (unmount, destroy) release the claim.
            self._arrays[key] = arr
            self._true_sizes[key] = host.size
            self._vid_counts[vid] = self._vid_counts.get(vid, 0) + 1
            self.bytes_used += padded.size

    def resident_count(self, vid: int) -> int:
        """O(1) resident shard count for `vid` (the serving dispatcher's
        per-read routing predicate — shard_ids() would scan the whole
        key set under the lock on every read)."""
        with self._lock:
            return self._vid_counts.get(vid, 0)

    def _forget_if_gone(self, vid: int) -> None:
        """Drop per-vid bookkeeping once no shard of `vid` remains
        (caller holds the lock; _vid_counts already knows, no key scan)."""
        if not self._vid_counts.get(vid):
            self._vid_counts.pop(vid, None)
            self._pin_source.pop(vid, None)

    def claim_pin_source(self, vid: int, source: str) -> str:
        """Atomically claim which disk location's shard files back this
        vid's resident bytes; returns the winning source (first claimant
        keeps it — two locations' pin threads racing must not interleave
        their shard sets under one key space)."""
        with self._lock:
            if vid not in self._pin_source:
                self.pin_claims += 1
            return self._pin_source.setdefault(vid, source)

    def release_pin_source(self, vid: int, source: str) -> None:
        """Release `source`'s claim if nothing of `vid` is resident: a
        pin attempt that failed before uploading anything (unreadable
        shard file, aborted thread) must not block another location's
        healthy copy until process restart.  A partially pinned claim is
        kept — those bytes are still the vid's resident identity."""
        with self._lock:
            if (
                self._pin_source.get(vid) == source
                and not self._vid_counts.get(vid)
            ):
                del self._pin_source[vid]

    def pin_source(self, vid: int) -> str | None:
        with self._lock:
            return self._pin_source.get(vid)

    def get(self, vid: int, shard_id: int):
        with self._lock:
            key = (vid, shard_id)
            arr = self._arrays.get(key)
            if arr is not None:
                self._arrays.move_to_end(key)
            return arr

    def shard_size(self, vid: int, shard_id: int) -> int | None:
        return self._true_sizes.get((vid, shard_id))

    def stats(self) -> tuple[int, int]:
        """(resident shard count, padded device bytes held)."""
        with self._lock:
            return len(self._arrays), self.bytes_used

    def resident_by_vid(self) -> dict[int, list[int]]:
        """One locked snapshot of vid -> sorted resident shard ids (status
        pages render many volumes; per-vid shard_ids() calls would scan
        the key set once per volume under the serving path's lock)."""
        out: dict[int, list[int]] = {}
        with self._lock:
            for v, s in self._arrays:
                out.setdefault(v, []).append(s)
        for ids in out.values():
            ids.sort()
        return out

    def shard_ids(self, vid: int) -> list[int]:
        with self._lock:
            return sorted(s for (v, s) in self._arrays if v == vid)

    def evict(self, vid: int, shard_id: int | None = None) -> None:
        with self._lock:
            keys = [
                k
                for k in self._arrays
                if k[0] == vid and (shard_id is None or k[1] == shard_id)
            ]
            for k in keys:
                self.bytes_used -= self._arrays.pop(k).size
                self._true_sizes.pop(k, None)
                self._vid_counts[vid] -= 1
            if shard_id is None or keys:
                # a whole-vid evict (unmount/destroy) always releases
                # the claim — even when budget pressure already removed
                # the shards, the claim must not outlive the volume.  A
                # PARTIAL evict that matched nothing must not drop a
                # mid-pin claim (the pin thread claimed before its first
                # put) and open the two-location interleave window.
                self._forget_if_gone(vid)

    def clear(self) -> None:
        with self._lock:
            self._arrays.clear()
            self._true_sizes.clear()
            self._pin_source.clear()
            self._vid_counts.clear()
            self.bytes_used = 0


@functools.lru_cache(maxsize=64)
def _prepared_matrix(matrix_bytes: bytes, m: int, k: int):
    return rs_tpu.prepare_matrix(
        np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(m, k)
    )


# block-diagonal prepared matrices share rs_tpu's cache (the bulk
# encoder prepares the same parity system — one cached device copy)
_prepared_blockdiag_matrix = rs_tpu._prepared_blockdiag


# --- fused gather+reconstruct kernel ----------------------------------------
#
# The round-3 serving path ran FOUR chained XLA ops per call (vmap
# dynamic_slice gather -> stack/reshape -> pallas matmul -> take_along_axis
# -> vmap slice): every stage round-trips HBM and the chain costs several
# dispatches of fixed overhead per 4KB needle.  The fused kernel does the
# whole thing in ONE pallas program: per grid step it DMAs each survivor's
# slice HBM->VMEM at a scalar-prefetched offset, unpacks to GF(2) bit
# planes, runs the MXU dot, packs, and row-selects the wanted shard — no
# gathered intermediate ever touches HBM.  The sub-lane `delta` trim
# happens on host after D2H (<=127 bytes per needle of extra wire).
#
# Mosaic layout constraints (probed on v5e, experiments/r4_fused_probe.py +
# the memref_slice divisibility errors that followed):
#   * output/VMEM blocks need their second-minor dim divisible by 8 (or
#     equal to the array dim) — so each grid step serves a GROUP of 8
#     requests, output block (8, tile);
#   * DMA slice starts must be PROVABLY divisible by the memref tiling
#     (1024 for 1-D u8) — offsets travel in FUSED_ALIGN units and multiply
#     in-kernel, and every destination offset is a static multiple of tile;
#   * single-row slices of 2-D VMEM scratch are rejected (sublane tile 8),
#     and 1-D->2-D reshapes relayout — so the gather lands in a FLAT 1-D
#     HBM buffer laid out so a free XLA reshape yields [chunks, G, k, W],
#     which a second, regular-BlockSpec kernel consumes (block (1,1,k,W):
#     leading dims are unconstrained, trailing dims equal the array's);
#   * jax.lax.dynamic_slice has no Mosaic lowering — the per-request row
#     select is an iota-mask reduction.
# Both pallas calls live in ONE jit: a single host dispatch, and the only
# intermediate (the gathered slices) never rides the host link.

FUSED_GROUP = 8  # requests per grid step (output sublane tile)
FUSED_TILE = 4096  # per-request lane chunk; x8 group = 32768-lane compute
                   # width (bits 4MB + counts 4MB int32 in VMEM)


def _make_gather_body(k: int, g_n: int, tile: int, n_groups: int):
    w = g_n * tile

    def body(offs_ref, *rest):
        surv = rest[:k]
        o_ref = rest[k]
        sems = rest[k + 1]
        g = pl.program_id(0)
        j = pl.program_id(1)
        copies = []
        for r in range(g_n):
            # the explicit multiply is what lets Mosaic PROVE alignment
            src = offs_ref[g * g_n + r] * FUSED_ALIGN + j * tile
            for i in range(k):
                dst = ((j * n_groups + g) * k + i) * w + r * tile
                copies.append(
                    pltpu.make_async_copy(
                        surv[i].at[pl.ds(src, tile)],
                        o_ref.at[pl.ds(dst, tile)],
                        sems.at[i, r],
                    )
                )
        for c in copies:
            c.start()
        for c in copies:
            c.wait()

    return body


def _make_select_body(k: int, k_pad: int, m_pad: int, g_n: int, tile: int):
    w = g_n * tile

    def body(rows_ref, a_ref, x_ref, o_ref):
        g = pl.program_id(0)
        xv = x_ref[0, 0]  # (k, w); leading unit dims index away for free
        if k < k_pad:
            xv = jnp.concatenate(
                [xv, jnp.zeros((k_pad - k, w), jnp.uint8)], axis=0
            )
        bits = rs_tpu._unpack_bits_bitmajor(xv)
        counts = jnp.dot(a_ref[:], bits, preferred_element_type=jnp.int32)
        packed = rs_tpu._pack_bits_bitmajor(counts, m_pad)  # (m_pad, w)
        ridx = jax.lax.broadcasted_iota(jnp.int32, (m_pad, tile), 0)
        outs = []
        for r in range(g_n):
            row = rows_ref[g * g_n + r]
            blk = packed[:, r * tile : (r + 1) * tile]
            sel = jnp.where(ridx == row, blk, jnp.uint8(0)).astype(jnp.int32)
            outs.append(jnp.sum(sel, axis=0, keepdims=True).astype(jnp.uint8))
        o_ref[:] = jnp.concatenate(outs, axis=0)

    return body


@functools.partial(
    jax.jit, static_argnames=("tile", "fetch", "k_true", "interpret")
)
def _fused_reconstruct(
    a_bm, survivors, meta, *, tile, fetch, k_true, interpret
):
    """survivors: tuple of [L] u8 resident shards (HBM) in matrix column
    order; meta [2, N] int32 — row 0 the offsets in FUSED_ALIGN units
    (byte offset / FUSED_ALIGN), row 1 the wanted matrix rows (packed so
    the call ships ONE scalar vector).  -> [N, fetch] u8 of raw
    reconstructed bytes starting at each aligned offset (caller trims the
    delta head).  N pads to the 8-request group internally.  Returns the
    [N, fetch] result FLATTENED (1-D, true-N rows only): 2-D transfers
    pay a per-row tunnel cost; callers reshape host-side."""
    k = len(survivors)
    if k_true is not None and k != k_true:
        raise ValueError(f"{k} survivors but matrix was built for {k_true}")
    m_pad8, k_pad8 = a_bm.shape
    m_pad, k_pad = m_pad8 // 8, k_pad8 // 8
    n = meta.shape[1]
    pad = (-n) % FUSED_GROUP
    if pad:
        meta = jnp.pad(meta, ((0, 0), (0, pad)))
    offsets, row_idx = meta[0], meta[1]
    n_pad = n + pad
    tile = min(tile, fetch)
    chunks = max(1, fetch // tile)
    n_groups = n_pad // FUSED_GROUP
    w = FUSED_GROUP * tile

    gathered = pl.pallas_call(
        _make_gather_body(k, FUSED_GROUP, tile, n_groups),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_groups, chunks),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * k,
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA((k, FUSED_GROUP))],
        ),
        out_shape=jax.ShapeDtypeStruct((chunks * n_groups * k * w,), jnp.uint8),
        cost_estimate=pl.CostEstimate(
            flops=0,
            bytes_accessed=2 * chunks * n_groups * k * w,
            transcendentals=0,
        ),
        interpret=interpret,
    )(offsets, *survivors)
    x4 = gathered.reshape(chunks, n_groups, k, w)  # contiguous: free

    out = pl.pallas_call(
        _make_select_body(k, k_pad, m_pad, FUSED_GROUP, tile),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_groups, chunks),
            in_specs=[
                pl.BlockSpec(
                    a_bm.shape, lambda *_: (0, 0), memory_space=pltpu.VMEM
                ),
                pl.BlockSpec(
                    (1, 1, k, w),
                    lambda gi, ji, *_: (ji, gi, 0, 0),
                    memory_space=pltpu.VMEM,
                ),
            ],
            out_specs=pl.BlockSpec(
                (FUSED_GROUP, tile),
                lambda gi, ji, *_: (gi, ji),
                memory_space=pltpu.VMEM,
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, fetch), jnp.uint8),
        cost_estimate=pl.CostEstimate(
            flops=2 * m_pad8 * k_pad8 * n_pad * fetch,
            bytes_accessed=(k + 1) * n_pad * fetch,
            transcendentals=0,
        ),
        interpret=interpret,
    )(row_idx, a_bm, x4)
    return (out[:n] if pad else out).reshape(-1)


# --- block-diagonal variants -------------------------------------------------
#
# Same fused two-kernel structure, but the reconstruction system is the
# block-diagonal [g*w, g*k] expansion (rs_tpu.blockdiag_system): each
# request's tile splits into g contiguous segments, group jg's input
# rows are the survivors' slices of segment jg, and group jg's output
# row is the wanted shard's bytes of that segment — concatenating the
# groups along lanes reassembles the contiguous tile.  The fatter
# contraction (8*pad16(g*k) = 384 vs 128 bits for k=10, g=4) is what
# lifts the MXU roof from ~121 to ~157 GB/s (rs_tpu.py round 3/4).
# Mosaic constraints inherited from the flat kernel: every DMA slice
# start must stay provably FUSED_ALIGN-divisible, so per-chunk segments
# are tile/groups wide and the blockdiag fetch ladder rounds up to a
# multiple of groups*FUSED_ALIGN (a coarser ladder — the caller pays at
# most one extra 4KB step of D2H per request, against a ~30% MXU win).


def _make_gather_body_blockdiag(k, groups, g_n, tile, n_groups):
    seg = tile // groups
    w = g_n * seg
    gk = groups * k

    def body(offs_ref, *rest):
        surv = rest[:k]
        o_ref = rest[k]
        sems = rest[k + 1]
        g = pl.program_id(0)
        j = pl.program_id(1)
        copies = []
        for r in range(g_n):
            base = offs_ref[g * g_n + r] * FUSED_ALIGN + j * tile
            for jg in range(groups):
                # seg is a multiple of FUSED_ALIGN (caller-enforced), so
                # base + jg*seg keeps the alignment proof intact
                src = base + jg * seg
                for i in range(k):
                    dst = (
                        ((j * n_groups + g) * gk + jg * k + i) * w + r * seg
                    )
                    copies.append(
                        pltpu.make_async_copy(
                            surv[i].at[pl.ds(src, seg)],
                            o_ref.at[pl.ds(dst, seg)],
                            sems.at[i, jg * g_n + r],
                        )
                    )
        for c in copies:
            c.start()
        for c in copies:
            c.wait()

    return body


def _make_select_body_blockdiag(k, groups, w_true, k_pad, m_pad, g_n, tile):
    seg = tile // groups
    w = g_n * seg
    gk = groups * k

    def body(rows_ref, a_ref, x_ref, o_ref):
        g = pl.program_id(0)
        xv = x_ref[0, 0]  # (g*k, w)
        if gk < k_pad:
            xv = jnp.concatenate(
                [xv, jnp.zeros((k_pad - gk, w), jnp.uint8)], axis=0
            )
        bits = rs_tpu._unpack_bits_bitmajor(xv)
        counts = jnp.dot(a_ref[:], bits, preferred_element_type=jnp.int32)
        packed = rs_tpu._pack_bits_bitmajor(counts, m_pad)  # (m_pad, w)
        ridx = jax.lax.broadcasted_iota(jnp.int32, (m_pad, seg), 0)
        outs = []
        for r in range(g_n):
            row = rows_ref[g * g_n + r]
            blk = packed[:, r * seg : (r + 1) * seg]  # (m_pad, seg)
            segs = []
            for jg in range(groups):
                # group jg's wanted row sits at jg*w_true + row in the
                # block-diagonal system; its seg lanes are the request's
                # bytes [jg*seg, (jg+1)*seg) of this chunk's tile
                sel = jnp.where(
                    ridx == jg * w_true + row, blk, jnp.uint8(0)
                ).astype(jnp.int32)
                segs.append(
                    jnp.sum(sel, axis=0, keepdims=True).astype(jnp.uint8)
                )
            outs.append(jnp.concatenate(segs, axis=1))  # (1, tile)
        o_ref[:] = jnp.concatenate(outs, axis=0)

    return body


@functools.partial(
    jax.jit,
    static_argnames=("tile", "fetch", "k_true", "w_true", "groups", "interpret"),
)
def _fused_reconstruct_blockdiag(
    a_blk, survivors, meta, *, tile, fetch, k_true, w_true, groups, interpret
):
    """Block-diagonal twin of _fused_reconstruct: same meta packing and
    flat 1-D output contract; `w_true` is the reconstruction system's
    pre-expansion row count (len(wanted)) so the per-group row select
    can address jg*w_true + row.  Caller guarantees tile % (groups *
    FUSED_ALIGN) == 0 and fetch % tile == 0."""
    k = len(survivors)
    if k_true is not None and k != k_true:
        raise ValueError(f"{k} survivors but matrix was built for {k_true}")
    m_pad8, k_pad8 = a_blk.shape
    m_pad, k_pad = m_pad8 // 8, k_pad8 // 8
    n = meta.shape[1]
    pad = (-n) % FUSED_GROUP
    if pad:
        meta = jnp.pad(meta, ((0, 0), (0, pad)))
    offsets, row_idx = meta[0], meta[1]
    n_pad = n + pad
    chunks = fetch // tile
    n_groups = n_pad // FUSED_GROUP
    seg = tile // groups
    w = FUSED_GROUP * seg
    gk = groups * k

    gathered = pl.pallas_call(
        _make_gather_body_blockdiag(k, groups, FUSED_GROUP, tile, n_groups),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_groups, chunks),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * k,
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((k, groups * FUSED_GROUP))
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(
            (chunks * n_groups * gk * w,), jnp.uint8
        ),
        cost_estimate=pl.CostEstimate(
            flops=0,
            bytes_accessed=2 * chunks * n_groups * gk * w,
            transcendentals=0,
        ),
        interpret=interpret,
    )(offsets, *survivors)
    x4 = gathered.reshape(chunks, n_groups, gk, w)  # contiguous: free

    out = pl.pallas_call(
        _make_select_body_blockdiag(
            k, groups, w_true, k_pad, m_pad, FUSED_GROUP, tile
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_groups, chunks),
            in_specs=[
                pl.BlockSpec(
                    a_blk.shape, lambda *_: (0, 0), memory_space=pltpu.VMEM
                ),
                pl.BlockSpec(
                    (1, 1, gk, w),
                    lambda gi, ji, *_: (ji, gi, 0, 0),
                    memory_space=pltpu.VMEM,
                ),
            ],
            out_specs=pl.BlockSpec(
                (FUSED_GROUP, tile),
                lambda gi, ji, *_: (gi, ji),
                memory_space=pltpu.VMEM,
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, fetch), jnp.uint8),
        cost_estimate=pl.CostEstimate(
            flops=2 * m_pad8 * k_pad8 * n_pad * (fetch // groups),
            bytes_accessed=(k + 1) * n_pad * fetch,
            transcendentals=0,
        ),
        interpret=interpret,
    )(row_idx, a_blk, x4)
    return (out[:n] if pad else out).reshape(-1)


@functools.partial(
    jax.jit,
    static_argnames=("tile", "fetch", "kernel", "interpret", "k_true"),
)
def _gather_reconstruct(
    a_bm,
    survivors,
    offsets,
    row_idx,
    deltas,
    *,
    tile,
    fetch,
    kernel,
    interpret,
    k_true,
):
    """survivors: tuple of [L] u8 resident shards in matrix column order;
    offsets [N] int32 lane-aligned; row_idx [N] int32 selects each
    request's wanted matrix row; deltas [N] the sub-lane alignment
    residual.  -> [N, fetch] u8.

    `tile` is the compute width (size bucket); `fetch` <= tile is the D2H
    width (power-of-two cover of the largest actual request): the result
    is delta-shifted and narrowed ON DEVICE so the transfer back — the
    scarce resource on a tunneled device — carries only useful bytes.
    Returns the [N, fetch] result FLATTENED (1-D): 2-D transfers pay a
    per-row tunnel cost; callers reshape host-side."""
    cols = [
        jax.vmap(
            lambda off, arr=arr: jax.lax.dynamic_slice(arr, (off,), (tile,))
        )(offsets)
        for arr in survivors
    ]  # k x [N, tile]
    x = jnp.stack(cols, axis=0)  # [k, N, tile]
    k, n, _ = x.shape
    out = rs_tpu.apply_matrix_device(
        a_bm,
        x.reshape(k, n * tile),
        kernel=kernel,
        interpret=interpret,
        k_true=k_true,
    )  # [m_pad, n*tile]
    out3 = out.reshape(out.shape[0], n, tile).transpose(1, 0, 2)
    sel = jnp.take_along_axis(out3, row_idx[:, None, None], axis=1)[:, 0, :]
    if fetch < tile:
        sel = jax.vmap(
            lambda row, d: jax.lax.dynamic_slice(row, (d,), (fetch,))
        )(sel, deltas)
    return sel.reshape(-1)


@functools.partial(
    jax.jit,
    static_argnames=(
        "tile", "fetch", "groups", "w_true", "kernel", "interpret", "k_true",
    ),
)
def _gather_reconstruct_blockdiag(
    a_blk,
    survivors,
    offsets,
    row_idx,
    deltas,
    *,
    tile,
    fetch,
    groups,
    w_true,
    kernel,
    interpret,
    k_true,
):
    """Block-diagonal twin of _gather_reconstruct (the XLA fallback and
    bench path): each request's tile splits into `groups` contiguous
    segments gathered into segment-stacked [g*k, N*seg] rows, one
    apply of the block-diagonal matrix reconstructs every segment, and
    the per-group wanted rows (jg*w_true + row) concatenate back into
    the contiguous [N, tile] before the same on-device delta/narrow."""
    seg = tile // groups
    cols = []
    for jg in range(groups):
        for arr in survivors:
            cols.append(
                jax.vmap(
                    lambda off, arr=arr, jg=jg: jax.lax.dynamic_slice(
                        arr, (off + jg * seg,), (seg,)
                    )
                )(offsets)
            )
    x = jnp.stack(cols, axis=0)  # [g*k, N, seg]
    gk, n, _ = x.shape
    out = rs_tpu.apply_matrix_device(
        a_blk,
        x.reshape(gk, n * seg),
        kernel=kernel,
        interpret=interpret,
        k_true=None if k_true is None else groups * k_true,
    )  # [m_pad >= groups*w_true, n*seg]
    out3 = out.reshape(out.shape[0], n, seg).transpose(1, 0, 2)
    segs = []
    for jg in range(groups):
        rows = row_idx + jg * w_true
        segs.append(
            jnp.take_along_axis(out3, rows[:, None, None], axis=1)[:, 0, :]
        )
    sel = jnp.concatenate(segs, axis=-1)  # [N, tile], contiguous bytes
    if fetch < tile:
        sel = jax.vmap(
            lambda row, d: jax.lax.dynamic_slice(row, (d,), (fetch,))
        )(sel, deltas)
    return sel.reshape(-1)


def _plan(requests: list[tuple[int, int, int]]):
    """Split/align requests into device sub-requests.

    Each request (wanted_shard, offset, size) becomes >=1 sub-requests
    (req_index, aligned_off, delta, take, bucket) with delta+take <= bucket.
    """
    subs = []
    for idx, (_, off, size) in enumerate(requests):
        pos = off
        remaining = size
        while remaining > 0:
            take = min(remaining, CHUNK)
            aligned = pos - (pos % LANE)
            delta = pos - aligned
            subs.append(
                (idx, aligned, delta, take, _bucket(SIZE_BUCKETS, delta + take))
            )
            pos += take
            remaining -= take
    return subs


def _resolve_codec(cache, vid, requests, data_shards, total_shards, layout):
    """Shared preamble: reconstruction matrix (flat or block-diagonal,
    per the active layout) + resident survivor tuple + the system's
    pre-expansion row count."""
    wanted = sorted({r[0] for r in requests})
    resident = cache.shard_ids(vid)
    present = [s for s in resident if s not in wanted]
    if len(present) < data_shards:
        raise CacheMiss(
            f"vid {vid}: {len(present)} resident survivors, need {data_shards}"
        )
    rmat, use = gf256.reconstruction_matrix(
        data_shards, total_shards, present, wanted
    )
    if layout == "blockdiag":
        a_prep = _prepared_blockdiag_matrix(
            rmat.tobytes(), *rmat.shape, cache.groups
        )
    else:
        a_prep = _prepared_matrix(rmat.tobytes(), *rmat.shape)
    survivors = tuple(cache.get(vid, s) for s in use)
    if any(s is None for s in survivors):  # evicted between listing and get
        raise CacheMiss(f"vid {vid}: survivor shard evicted mid-request")
    row_of = {sid: i for i, sid in enumerate(wanted)}
    return a_prep, survivors, row_of, use, rmat.shape[0]


def _group_vectors(part, requests, row_of, pad):
    """HOST-side offset/row/delta vectors (np): the H2D transfer happens
    at dispatch time under the pipeline's h2d_copy stage, not here."""
    offsets = np.array([s[1] for _, s in part] + [0] * pad, dtype=np.int32)
    rows = np.array(
        [row_of[requests[s[0]][0]] for _, s in part] + [0] * pad,
        dtype=np.int32,
    )
    deltas = np.array([s[2] for _, s in part] + [0] * pad, dtype=np.int32)
    return offsets, rows, deltas


def _fetch_cover(span: int) -> int:
    """Smallest of {2^n, 3*2^(n-1)} covering span (min 2048).  A pure
    power-of-two ladder wastes ~2x D2H whenever the alignment delta pushes
    a power-of-two-sized request just past the boundary (the common case:
    any unaligned 1MB needle); the 1.5x steps cap the waste at ~50% while
    adding at most one compiled shape per size class."""
    p = max(1 << (span - 1).bit_length(), 2048)
    three_halves = 3 * (p >> 2)
    return three_halves if three_halves >= max(span, 2048) else p


def _fused_tile_for(fetch: int) -> int:
    """Largest per-chunk tile <= FUSED_TILE dividing fetch (fetch is
    2^n or 3*2^(n-1), so halving always lands on a divisor >= 1024)."""
    t = FUSED_TILE
    while fetch % t:
        t //= 2
    return t


def _fused_vectors(part, requests, row_of, pad):
    """Re-align each sub-request down to FUSED_ALIGN: offsets become unit
    counts, the residual joins the host-trimmed delta.  -> (meta, deltas,
    fetch): meta is the packed [2, N] int32 (offset units / wanted rows,
    one H2D transfer) and fetch covers the largest delta+take (CHUNK
    keeps it <= MAX_TILE)."""
    offs_units, deltas = [], []
    for _, s in part:
        extra = s[1] % FUSED_ALIGN
        offs_units.append((s[1] - extra) // FUSED_ALIGN)
        deltas.append(s[2] + extra)
    span = max(d + s[3] for d, (_, s) in zip(deltas, part))
    fetch = _fetch_cover(span)
    # ONE packed [2, N] host->device transfer (row 0: offset units, row 1:
    # wanted matrix rows): tiny scalar vectors each pay a full dispatch
    # RTT on tunneled rigs, so two transfers would double that tax.
    # Stays a HOST array here — the ship happens under h2d_copy.
    meta = np.array(
        [
            offs_units + [0] * pad,
            [row_of[requests[s[0]][0]] for _, s in part] + [0] * pad,
        ],
        dtype=np.int32,
    )
    return meta, deltas, fetch


def _use_fused(kernel: str, interpret: bool) -> bool:
    """The fused DMA kernel is the serving path on real TPUs; interpret
    mode also supports it (tests), but the XLA fallback kernel cannot."""
    return kernel == "pallas"


# shapes this process has already dispatched: first use of a shape is a
# jit compile (tens of seconds on remote-compile rigs) — the trace
# annotation + compile counter are what let a tail spike be attributed
# to "hit an unwarmed shape" instead of guessed at
_dispatched_shapes: set = set()
_shapes_lock = threading.Lock()


# (size_bucket, count_bucket) -> dispatch count, recorded per device
# call: warm() compiles the observed buckets FIRST, so a re-pin (budget
# churn, volume move) reaches serving-readiness for the live workload's
# shapes before burning 20-40s/compile on ladder corners nobody hits
_observed_buckets: dict[tuple[int, int], int] = {}


def _note_observed(size_bucket: int, count_bucket: int) -> None:
    with _shapes_lock:
        key = (size_bucket, count_bucket)
        _observed_buckets[key] = _observed_buckets.get(key, 0) + 1


def observed_buckets() -> list[tuple[int, int]]:
    """(size_bucket, count_bucket) pairs this process has dispatched,
    most-frequent first — warm()'s compile-priority order."""
    with _shapes_lock:
        items = sorted(_observed_buckets.items(), key=lambda kv: -kv[1])
    return [k for k, _ in items]


def _blockdiag_fetch_tile(fetch: int, groups: int) -> tuple[int, int]:
    """(fetch, tile) for the fused blockdiag kernel: per-chunk segments
    must stay FUSED_ALIGN-provable, so fetch rounds UP to a multiple of
    groups*FUSED_ALIGN and tile is the fixed groups*FUSED_ALIGN-aligned
    chunk (= FUSED_TILE for g=4).  Coarser D2H ladder than flat — at
    most one extra step per request, traded for the blockdiag MXU win."""
    q = groups * FUSED_ALIGN
    fetch = -(-fetch // q) * q
    tile = FUSED_TILE if FUSED_TILE % q == 0 and fetch % FUSED_TILE == 0 else q
    return fetch, tile


def _note_shape(key: tuple) -> bool:
    """Record one device call's shape; True when it was a compile miss
    (first use).  Locked: concurrent drain lanes dispatching the same
    first-ever shape must count ONE miss, or the hit/miss ratio skews
    exactly under the load it exists to diagnose."""
    with _shapes_lock:
        if key in _dispatched_shapes:
            miss = False
        else:
            _dispatched_shapes.add(key)
            miss = True
    stats_metrics.VOLUME_SERVER_EC_DEVICE_COMPILE.labels(
        result="miss" if miss else "hit"
    ).inc()
    return miss


def _pack_calls(
    cache, vid, requests, kernel, interpret, layout, data_shards,
    total_shards, record_observed=True,
):
    """PACK stage: resolve the codec, split/align the requests, group
    them into device calls, and build every call's HOST-side vectors.
    Returns (calls, subs, survivors, a_prep, use, w_true) — nothing has
    touched the device yet, so a double-buffered caller can pack batch
    N+1 while batch N still owns a staging slot.  `record_observed=False`
    keeps synthetic probes (warm's ladder walk) out of the
    observed-shape ranking, which must reflect live traffic only."""
    a_prep, survivors, row_of, use, w_true = _resolve_codec(
        cache, vid, requests, data_shards, total_shards, layout
    )
    fused = _use_fused(kernel, interpret)
    groups = cache.groups if layout == "blockdiag" else 1
    subs = _plan(requests)
    calls = []  # (fused?, part, host vectors, fetch, tile/bucket, deltas)
    for bucket in SIZE_BUCKETS:
        group = [(i, s) for i, s in enumerate(subs) if s[4] == bucket]
        if not group:
            continue
        n_bucket = _bucket(COUNT_BUCKETS, min(len(group), _max_count(bucket)))
        for start in range(0, len(group), n_bucket):
            part = group[start : start + n_bucket]
            pad = n_bucket - len(part)
            if record_observed:
                _note_observed(bucket, n_bucket)
            if fused:
                # fetch covers the realigned delta+take (the host trims
                # the delta head after D2H; no in-kernel shift needed)
                meta, deltas, fetch = _fused_vectors(
                    part, requests, row_of, pad
                )
                if layout == "blockdiag":
                    fetch, tile = _blockdiag_fetch_tile(fetch, groups)
                else:
                    tile = _fused_tile_for(fetch)
                calls.append(
                    ("fused", part, (meta,), fetch, tile, n_bucket, deltas)
                )
            else:
                vectors = _group_vectors(part, requests, row_of, pad)
                # D2H width: power-of-two cover of the largest actual
                # request in this call, never wider than the compute tile
                max_take = max(s[3] for _, s in part)
                fetch = min(bucket, 1 << (max_take - 1).bit_length())
                calls.append(
                    ("xla", part, vectors, fetch, bucket, n_bucket, None)
                )
    return calls, subs, survivors, a_prep, use, w_true


def _dispatch_call(
    kind, dev_vectors, a_prep, survivors, n_use, w_true, groups, tile,
    fetch, kernel, interpret,
):
    """Route one packed call's ON-DEVICE vectors to its kernel — the
    single home of the fused/xla x flat/blockdiag dispatch, shared by
    reconstruct_intervals' drain loop and make_batched_call's bench
    thunk so the benchmark can never measure a different compiled shape
    than the serving path dispatches."""
    if kind == "fused":
        (meta,) = dev_vectors
        if groups > 1:
            return _fused_reconstruct_blockdiag(
                a_prep, survivors, meta, tile=tile, fetch=fetch,
                k_true=n_use, w_true=w_true, groups=groups,
                interpret=interpret,
            )
        return _fused_reconstruct(
            a_prep, survivors, meta, tile=tile, fetch=fetch,
            k_true=n_use, interpret=interpret,
        )
    offsets, rows, deltas = dev_vectors
    if groups > 1:
        return _gather_reconstruct_blockdiag(
            a_prep, survivors, offsets, rows, deltas, tile=tile,
            fetch=fetch, groups=groups, w_true=w_true, kernel=kernel,
            interpret=interpret, k_true=n_use,
        )
    return _gather_reconstruct(
        a_prep, survivors, offsets, rows, deltas, tile=tile, fetch=fetch,
        kernel=kernel, interpret=interpret, k_true=n_use,
    )


def reconstruct_intervals(
    cache: DeviceShardCache,
    vid: int,
    requests: list[tuple[int, int, int]],
    kernel: str | None = None,
    interpret: bool | None = None,
    data_shards: int = DATA_SHARDS,
    total_shards: int = TOTAL_SHARDS,
    layout: str | None = None,
    record_observed: bool = True,
) -> list[bytes]:
    """Reconstruct interval bytes for a batch of degraded reads in as few
    device calls as possible (one per size bucket actually present).

    requests: [(wanted_shard_id, shard_offset, size)].  All gather inputs
    are resident shards; per-call H2D is just the offset/row vectors and
    D2H is exactly the reconstructed bytes.  Raises CacheMiss when fewer
    than `data_shards` non-wanted shards of `vid` are resident.

    `layout` (None = the cache's active layout) picks the kernel family:
    "blockdiag" serves through the block-diagonal g-group system (the
    ~157 GB/s round-3 kernel), "flat" the plain one.  The call is staged
    pack -> H2D -> execute -> D2H: packing runs before a staging slot is
    taken (cache.pipeline, 2 slots = double buffering), so a concurrent
    batch packs and ships while the previous one executes and only each
    batch's own D2H blocks it.  Every stage is a trace span feeding
    SeaweedFS_request_stage_seconds."""
    if not requests:
        return []
    if kernel is None:
        kernel = "pallas" if rs_tpu.on_tpu() else "xla"
    if interpret is None:
        interpret = not rs_tpu.on_tpu()
    if layout is None:
        layout = cache.layout
    if layout not in LAYOUTS:
        raise ValueError(f"unknown resident layout {layout!r}")
    groups = cache.groups if layout == "blockdiag" else 1
    fused = _use_fused(kernel, interpret)
    with obs_trace.span(
        "batch_pack", requests=len(requests), layout=layout
    ):
        calls, subs, survivors, a_prep, use, w_true = _pack_calls(
            cache, vid, requests, kernel, interpret, layout,
            data_shards, total_shards, record_observed,
        )
    # the device-execute stage of the request trace: every dispatched
    # call's H2D/D2H bytes and compile-cache outcome annotate the span
    # (and the SeaweedFS_volumeServer_ec_device_* counters), so a slow
    # read can say "compile cliff" or "tunnel-bound fetch" by itself
    dev_span = obs_trace.span(
        "device_execute", requests=len(requests), layout=layout,
        kernel=(("fused_" if fused else "") + ("blockdiag" if groups > 1
                                               else kernel)),
    )
    dev_calls = dev_misses = dev_h2d = dev_d2h = 0
    surv_len = int(survivors[0].size)
    sub_out: list[bytes | None] = [None] * len(subs)

    # PIPELINE: dispatch device calls ahead of fetching results (jax
    # dispatch is async — each call's H2D and compute start immediately).
    # On tunneled rigs this overlaps the per-call dispatch RTT and D2H of
    # call N with the compute of call N+1 instead of paying them serially
    # per size bucket.  Aggregate un-fetched output is bounded: every
    # pending call holds its [n, fetch] result in HBM, so a huge batch
    # must drain the oldest call before dispatching more.
    pending: list[tuple[list, object, int, list[int] | None]] = []
    pending_bytes = 0

    def _finish(entry) -> int:
        part, arr, fetch, deltas = entry
        nbytes = int(arr.size)  # padded rows ride the fetch too
        # completion boundary BEFORE the d2h span: jax dispatch is
        # async, so without it the fetch would absorb the kernel's
        # remaining execute time and an MXU/compile regression would
        # read as "tunnel-bound fetch" in the stage histogram — the
        # blocking wait lands in device_execute, where it belongs
        arr.block_until_ready()
        with obs_trace.span("d2h_copy", bytes=nbytes):
            out = np.asarray(arr).reshape(-1, fetch)
        stats_metrics.VOLUME_SERVER_EC_D2H_BYTES.inc(nbytes)
        if deltas is not None:  # fused: host trims the alignment delta
            for j, (sub_idx, (_, _, _, take, _)) in enumerate(part):
                d = deltas[j]
                sub_out[sub_idx] = out[j, d : d + take].tobytes()
        else:  # XLA fallback: delta was shifted on device iff narrowed
            bucket = part[0][1][4]
            for j, (sub_idx, (_, _, delta, take, _)) in enumerate(part):
                lo = 0 if fetch < bucket else delta
                sub_out[sub_idx] = out[j, lo : lo + take].tobytes()
        return len(part) * fetch

    with cache.pipeline.slot() as slot_wait_s, dev_span:
        for kind, part, vectors, fetch, tile, n_bucket, deltas in calls:
            # H2D: ship this call's packed host vectors.  Tiny, but on a
            # tunneled rig each transfer pays a dispatch RTT — making it
            # a named stage is what lets the stage histogram show
            # whether h2d or execute owns a regression.
            h2d_bytes = sum(int(v.nbytes) for v in vectors)
            with obs_trace.span("h2d_copy", bytes=h2d_bytes):
                dev_vectors = tuple(jnp.asarray(v) for v in vectors)
                for v in dev_vectors:
                    # the put is async too: wait it out INSIDE the span
                    # so the stage measures the transfer, not the
                    # enqueue (tiny vectors — the kernel needs them
                    # landed before it runs anyway)
                    v.block_until_ready()
            stats_metrics.VOLUME_SERVER_EC_H2D_BYTES.inc(h2d_bytes)
            dev_h2d += h2d_bytes
            # the prepared matrix's row dim tracks the wanted-shard
            # count EXACTLY as retracing does: blockdiag kernels take
            # w_true static (and a_blk rows = 8*pad4(g*w_true) moves
            # with it), while the flat kernels only retrace when
            # pad4(w_true) changes a_bm's shape — keying on the shape
            # neither misses a real compile nor counts phantom ones
            dev_misses += _note_shape(
                ("fused" if kind == "fused" else kernel, layout, tile,
                 fetch, n_bucket, len(use), int(a_prep.shape[0]),
                 surv_len)
            )
            arr = _dispatch_call(
                kind, dev_vectors, a_prep, survivors, len(use), w_true,
                groups, tile, fetch, kernel, interpret,
            )
            pending.append((part, arr, fetch, deltas))
            pending_bytes += len(part) * fetch
            dev_calls += 1
            # the padded rows ride the wire too: count what the
            # fetch actually moves, not just the useful subset
            dev_d2h += n_bucket * fetch
            while pending_bytes > _MAX_PENDING_OUT and len(pending) > 1:
                pending_bytes -= _finish(pending.pop(0))
        for entry in pending:
            _finish(entry)
        dev_span.annotate(
            device_calls=dev_calls, compile_misses=dev_misses,
            h2d_bytes=dev_h2d, d2h_bytes=dev_d2h,
            slot_wait_us=int(slot_wait_s * 1e6),
        )
        stats_metrics.VOLUME_SERVER_EC_DEVICE_H2D_BYTES.inc(dev_h2d)
        stats_metrics.VOLUME_SERVER_EC_DEVICE_D2H_BYTES.inc(dev_d2h)
    outputs: list[list[bytes]] = [[] for _ in requests]
    for (idx, *_), piece in zip(subs, sub_out):
        outputs[idx].append(piece)  # subs are in offset order per request
    return [b"".join(parts) for parts in outputs]


def make_batched_call(
    cache: DeviceShardCache,
    vid: int,
    requests: list[tuple[int, int, int]],
    kernel: str | None = None,
    interpret: bool | None = None,
    layout: str | None = None,
):
    """Zero-arg thunk running the ONE device call a homogeneous batch of
    requests (same size bucket, count <= COUNT_BUCKETS[-1]) maps to,
    returning the un-copied device array — bench.py profiler-times the
    serving call with this, without host copies in the measured region.
    `layout` follows the cache's active layout by default."""
    if kernel is None:
        kernel = "pallas" if rs_tpu.on_tpu() else "xla"
    if interpret is None:
        interpret = not rs_tpu.on_tpu()
    if layout is None:
        layout = cache.layout
    groups = cache.groups if layout == "blockdiag" else 1
    a_prep, survivors, row_of, use, w_true = _resolve_codec(
        cache, vid, requests, DATA_SHARDS, TOTAL_SHARDS, layout
    )
    subs = _plan(requests)
    buckets = {s[4] for s in subs}
    if len(buckets) != 1 or len(subs) > COUNT_BUCKETS[-1]:
        raise ValueError("bench batch must be one homogeneous bucket group")
    bucket = buckets.pop()
    part = list(enumerate(subs))
    # NOTE: deliberately NOT _pack_calls — the bench thunk keeps the
    # whole homogeneous batch in ONE device call (its contract), while
    # _pack_calls would split wide large-size batches at _max_count.
    pad = _bucket(COUNT_BUCKETS, len(part)) - len(part)
    if _use_fused(kernel, interpret):
        kind = "fused"
        meta_np, _deltas, fetch = _fused_vectors(
            part, requests, row_of, pad
        )
        if groups > 1:
            fetch, tile = _blockdiag_fetch_tile(fetch, groups)
        else:
            tile = _fused_tile_for(fetch)
        dev_vectors = (jnp.asarray(meta_np),)
    else:
        kind = "xla"
        dev_vectors = tuple(
            jnp.asarray(v)
            for v in _group_vectors(part, requests, row_of, pad)
        )
        max_take = max(s[3] for _, s in part)
        fetch = min(bucket, 1 << (max_take - 1).bit_length())
        tile = bucket
    return lambda: _dispatch_call(
        kind, dev_vectors, a_prep, survivors, len(use), w_true, groups,
        tile, fetch, kernel, interpret,
    )


# per-segment mismatch sums stay < 2^28 < int31, so a wholesale-corrupt
# multi-GB shard cannot wrap the (x64-disabled) int32 accumulator; the
# host adds the [p, n_seg] partials with Python ints
_SCRUB_SEG = 1 << 28


@functools.partial(
    jax.jit, static_argnames=("n_lanes", "kernel", "interpret")
)
def _scrub_call(a_bm, data, parity, *, n_lanes, kernel, interpret):
    """data: tuple of 10 resident [L_pad] u8 shards; parity: tuple of 4.
    Recompute parity over the first n_lanes bytes and count mismatching
    bytes per parity shard — the ONLY thing that leaves the device is the
    [p, n_seg] int32 mismatch partials, which is what makes scrubbing the
    one serving-family op a tunneled device wins end-to-end: ~1.4 bytes
    of compute per byte held, ~0 bytes moved."""
    x = jnp.stack([d[:n_lanes] for d in data])
    out = rs_tpu.apply_matrix_device(
        a_bm, x, kernel=kernel, interpret=interpret, k_true=len(data)
    )
    rows = []
    for j in range(len(parity)):
        diff = out[j] != parity[j][:n_lanes]
        rows.append(
            jnp.stack(
                [
                    jnp.sum(diff[s : s + _SCRUB_SEG].astype(jnp.int32))
                    for s in range(0, n_lanes, _SCRUB_SEG)
                ]
            )
        )
    return jnp.stack(rows)


@functools.partial(
    jax.jit, static_argnames=("n_lanes", "groups", "kernel", "interpret")
)
def _scrub_call_blockdiag(
    a_blk, data, parity, *, n_lanes, groups, kernel, interpret
):
    """Block-diagonal scrub: the verified span splits into `groups`
    contiguous segments per shard (the host-staged segment stacking —
    slices of the same resident buffers), one apply of the blockdiag
    parity system recomputes every segment's parity, and group jg's
    output rows compare against parity segment jg.  Same contract as
    _scrub_call: only the [p, n_seg] int32 mismatch partials leave the
    device."""
    k = len(data)
    p = len(parity)
    seg = n_lanes // groups
    x = jnp.concatenate(
        [
            data[i][jg * seg : (jg + 1) * seg][None, :]
            for jg in range(groups)
            for i in range(k)
        ],
        axis=0,
    )  # [g*k, seg], segment-stacked
    out = rs_tpu.apply_matrix_device(
        a_blk, x, kernel=kernel, interpret=interpret, k_true=groups * k
    )
    rows = []
    for j in range(p):
        diff = jnp.concatenate(
            [
                out[jg * p + j] != parity[j][jg * seg : (jg + 1) * seg]
                for jg in range(groups)
            ]
        )
        rows.append(
            jnp.stack(
                [
                    jnp.sum(diff[s : s + _SCRUB_SEG].astype(jnp.int32))
                    for s in range(0, n_lanes, _SCRUB_SEG)
                ]
            )
        )
    return jnp.stack(rows)


def scrub_volume(
    cache: DeviceShardCache,
    vid: int,
    kernel: str | None = None,
    interpret: bool | None = None,
    data_shards: int = DATA_SHARDS,
    total_shards: int = TOTAL_SHARDS,
    layout: str | None = None,
) -> tuple[list[int], int]:
    """Parity scrub of a fully resident volume: -> (per-parity-shard
    mismatch byte counts, bytes verified per shard).  Raises CacheMiss
    unless ALL shards are resident.  The verified span rounds the true
    shard size UP to the lane tile (blockdiag: to groups lane tiles, so
    every segment slice stays lane-aligned) — cache buffers are
    zero-padded and parity-of-zeros is zero, so the extra lanes verify
    trivially instead of costing a per-shard tail fetch (each tiny D2H
    pays a full tunnel round-trip).  `layout` (None = cache's active
    layout) picks the kernel: blockdiag runs the scrub matmul on the
    ~157 GB/s round-3 system."""
    if kernel is None:
        kernel = "pallas" if rs_tpu.on_tpu() else "xla"
    if interpret is None:
        interpret = not rs_tpu.on_tpu()
    if layout is None:
        layout = cache.layout
    resident = cache.shard_ids(vid)
    if len(resident) < total_shards:
        raise CacheMiss(
            f"vid {vid}: {len(resident)}/{total_shards} shards resident"
        )
    sizes = {cache.shard_size(vid, s) for s in range(total_shards)}
    if len(sizes) != 1:
        raise CacheMiss(f"vid {vid}: resident shard sizes differ: {sizes}")
    true_size = sizes.pop()
    parity_m = gf256.build_matrix(data_shards, total_shards)[data_shards:]
    data = tuple(cache.get(vid, s) for s in range(data_shards))
    parity = tuple(
        cache.get(vid, s) for s in range(data_shards, total_shards)
    )
    if any(s is None for s in data + parity):
        raise CacheMiss(f"vid {vid}: shard evicted mid-scrub")
    if layout == "blockdiag":
        quant = cache.groups * LANE
        n_lanes = -(-true_size // quant) * quant
        a_blk = _prepared_blockdiag_matrix(
            parity_m.tobytes(), *parity_m.shape, cache.groups
        )
        partials = np.asarray(
            _scrub_call_blockdiag(
                a_blk, data, parity,
                n_lanes=n_lanes, groups=cache.groups,
                kernel=kernel, interpret=interpret,
            )
        )
    else:
        n_lanes = -(-true_size // LANE) * LANE
        a_bm = _prepared_matrix(parity_m.tobytes(), *parity_m.shape)
        partials = np.asarray(
            _scrub_call(
                a_bm, data, parity,
                n_lanes=n_lanes, kernel=kernel, interpret=interpret,
            )
        )
    return [int(row.sum(dtype=np.int64)) for row in partials], n_lanes


def _warm_key(size: int, count: int) -> tuple[int, int]:
    """Map a warm-plan (size, count) to the (size_bucket, count_bucket)
    shape its ALIGNED-offset request compiles — the key space
    observed_buckets() records.  Ranking by the off=0 class (not
    size+delta) keeps boundary sizes like 2048 in their own bucket."""
    b = _bucket(SIZE_BUCKETS, min(size, MAX_TILE))
    return b, _bucket(COUNT_BUCKETS, min(count, _max_count(b)))


def warm(
    cache: DeviceShardCache,
    vid: int,
    sizes: tuple[int, ...] = (4096, 65536, 1 << 20),
    counts: tuple[int, ...] = (1, 8, 64),  # single read, a batcher
    # coalesce round, and a full burst — the serving path's count shapes
    total_shards: int = TOTAL_SHARDS,
    should_stop=None,  # callable -> bool: abort between compiles
    layout: str | None = None,
    observed: list[tuple[int, int]] | None = None,
    **kw,
) -> None:
    """Pre-compile the bucket combinations a serving path will hit, so the
    first real degraded read doesn't pay a 20-40s TPU compile.  The wanted
    shard is a NON-resident one when any exists (the realistic degraded
    case), so a volume with exactly DATA_SHARDS survivors still warms.

    Compiles the ACTIVE layout's ladder only (`layout`, None = the
    cache's — the other family's shapes would double the 20-40s/shape
    mount-time bill for a path the knob has switched off), and walks the
    grid OBSERVED-SHAPES-FIRST (`observed`, default this process's
    dispatch history): a re-pin under live traffic reaches
    serving-readiness for the workload's real (size, count) buckets
    before burning compiles on ladder corners nobody hits."""
    if layout is None:
        layout = cache.layout
    resident = cache.shard_ids(vid)
    non_resident = [s for s in range(total_shards) if s not in resident]
    if non_resident:
        missing = non_resident[0]
        if len(resident) < DATA_SHARDS:
            return
    else:
        missing = resident[-1]
        if len(resident) - 1 < DATA_SHARDS:
            return
    grid = [(size, count) for size in sizes for count in counts]
    if observed is None:
        observed = observed_buckets()
    if observed:
        rank = {b: i for i, b in enumerate(observed)}
        grid.sort(key=lambda sc: rank.get(_warm_key(*sc), len(rank)))
    for size, count in grid:
        # both alignment classes: an aligned offset keeps fetch at
        # cover(size); any other offset pushes the span past it onto
        # the next ladder step (usually the 3*2^(n-1) one, see
        # _fetch_cover) — each is its own compiled shape
        for off in (0, 1):
            if should_stop is not None and should_stop():
                return
            reqs = [(missing, off, size)] * count
            # record_observed=False: warm's own ladder walk must not
            # feed the observed-shape ranking it consults
            reconstruct_intervals(
                cache, vid, reqs, layout=layout,
                record_observed=False, **kw,
            )
