"""Device-resident EC shard cache + batched degraded-read reconstruction.

Round-2 measurement showed why a naive device degraded read loses: every
per-needle reconstruct shipped 10x the payload (the survivor intervals)
host->device before the kernel could run, so the call was transfer-bound
(3965 ms p99 vs 0.75 ms for the C++ CPU kernel on this rig's tunneled
device).  The fix is to keep hot shards *resident in HBM*: then a degraded
read sends only (offset, row) scalars up and the reconstructed interval
bytes down, and any number of concurrent needle reconstructions batch into
ONE device call that gathers survivor slices from the resident buffers.

This is the TPU answer to the reference's per-needle goroutine fan-in
(/root/reference/weed/storage/store_ec.go:339-393): instead of fetching
interval bytes from >=10 peers per needle, the rebuilder/reader node pins
the survivor shards once (mount time or first read) and serves every
degraded needle from device memory.

Shapes and compile hygiene:
  * shard buffers are padded to SHARD_QUANTUM so volumes of similar size
    share jit caches, plus MAX_TILE slack so slices never clamp;
  * request sizes quantize to SIZE_BUCKETS, request counts to
    COUNT_BUCKETS, offsets align down to LANE (128) with the residual
    sliced off on host — a handful of compiles total, warmable up front.
"""
from __future__ import annotations

import functools
import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from . import gf256, rs_tpu

DATA_SHARDS = 10
TOTAL_SHARDS = 14

LANE = 128  # TPU lane tile: device slices start lane-aligned
SIZE_BUCKETS = (2048, 8192, 32768, 131072, 524288, 2 * 1024 * 1024)
COUNT_BUCKETS = (1, 2, 4, 8, 16, 32, 64)
MAX_TILE = SIZE_BUCKETS[-1]
# split oversized intervals into chunks that fit the largest bucket even
# after the <=LANE-1 alignment residual
CHUNK = MAX_TILE - LANE
SHARD_QUANTUM = 64 * 1024 * 1024


class CacheMiss(LookupError):
    """Not enough resident shards to serve the request."""


def _bucket(values: tuple[int, ...], need: int) -> int:
    for v in values:
        if need <= v:
            return v
    raise ValueError(f"{need} exceeds largest bucket {values[-1]}")


class DeviceShardCache:
    """LRU cache of EC shard bytes pinned in device memory.

    Keyed by (vid, shard_id).  `budget_bytes` bounds device-padded bytes;
    inserting past the budget evicts least-recently-used shards (whole
    shards — a partially resident volume simply fails over to the host
    path via CacheMiss).
    """

    def __init__(
        self,
        budget_bytes: int = 8 << 30,
        shard_quantum: int = SHARD_QUANTUM,
    ):
        self.budget = budget_bytes
        self.quantum = shard_quantum
        self._lock = threading.Lock()
        self._arrays: OrderedDict[tuple[int, int], object] = OrderedDict()
        self._true_sizes: dict[tuple[int, int], int] = {}
        self.bytes_used = 0

    def _padded_len(self, n: int) -> int:
        need = n + MAX_TILE
        return -(-need // self.quantum) * self.quantum

    def put(self, vid: int, shard_id: int, data) -> None:
        host = np.frombuffer(bytes(data), dtype=np.uint8) if isinstance(
            data, (bytes, bytearray, memoryview)
        ) else np.asarray(data, dtype=np.uint8)
        padded = np.zeros(self._padded_len(host.size), dtype=np.uint8)
        padded[: host.size] = host
        arr = jax.device_put(padded)
        key = (vid, shard_id)
        with self._lock:
            if key in self._arrays:
                self.bytes_used -= self._arrays.pop(key).size
            while self._arrays and self.bytes_used + padded.size > self.budget:
                old_key, old = self._arrays.popitem(last=False)
                self._true_sizes.pop(old_key, None)
                self.bytes_used -= old.size
            self._arrays[key] = arr
            self._true_sizes[key] = host.size
            self.bytes_used += padded.size

    def get(self, vid: int, shard_id: int):
        with self._lock:
            key = (vid, shard_id)
            arr = self._arrays.get(key)
            if arr is not None:
                self._arrays.move_to_end(key)
            return arr

    def shard_size(self, vid: int, shard_id: int) -> int | None:
        return self._true_sizes.get((vid, shard_id))

    def shard_ids(self, vid: int) -> list[int]:
        with self._lock:
            return sorted(s for (v, s) in self._arrays if v == vid)

    def evict(self, vid: int, shard_id: int | None = None) -> None:
        with self._lock:
            keys = [
                k
                for k in self._arrays
                if k[0] == vid and (shard_id is None or k[1] == shard_id)
            ]
            for k in keys:
                self.bytes_used -= self._arrays.pop(k).size
                self._true_sizes.pop(k, None)

    def clear(self) -> None:
        with self._lock:
            self._arrays.clear()
            self._true_sizes.clear()
            self.bytes_used = 0


@functools.lru_cache(maxsize=64)
def _prepared_matrix(matrix_bytes: bytes, m: int, k: int):
    return rs_tpu.prepare_matrix(
        np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(m, k)
    )


@functools.partial(
    jax.jit,
    static_argnames=("tile", "fetch", "kernel", "interpret", "k_true"),
)
def _gather_reconstruct(
    a_bm,
    survivors,
    offsets,
    row_idx,
    deltas,
    *,
    tile,
    fetch,
    kernel,
    interpret,
    k_true,
):
    """survivors: tuple of [L] u8 resident shards in matrix column order;
    offsets [N] int32 lane-aligned; row_idx [N] int32 selects each
    request's wanted matrix row; deltas [N] the sub-lane alignment
    residual.  -> [N, fetch] u8.

    `tile` is the compute width (size bucket); `fetch` <= tile is the D2H
    width (power-of-two cover of the largest actual request): the result
    is delta-shifted and narrowed ON DEVICE so the transfer back — the
    scarce resource on a tunneled device — carries only useful bytes."""
    cols = [
        jax.vmap(
            lambda off, arr=arr: jax.lax.dynamic_slice(arr, (off,), (tile,))
        )(offsets)
        for arr in survivors
    ]  # k x [N, tile]
    x = jnp.stack(cols, axis=0)  # [k, N, tile]
    k, n, _ = x.shape
    out = rs_tpu.apply_matrix_device(
        a_bm,
        x.reshape(k, n * tile),
        kernel=kernel,
        interpret=interpret,
        k_true=k_true,
    )  # [m_pad, n*tile]
    out3 = out.reshape(out.shape[0], n, tile).transpose(1, 0, 2)
    sel = jnp.take_along_axis(out3, row_idx[:, None, None], axis=1)[:, 0, :]
    if fetch < tile:
        sel = jax.vmap(
            lambda row, d: jax.lax.dynamic_slice(row, (d,), (fetch,))
        )(sel, deltas)
    return sel


def _plan(requests: list[tuple[int, int, int]]):
    """Split/align requests into device sub-requests.

    Each request (wanted_shard, offset, size) becomes >=1 sub-requests
    (req_index, aligned_off, delta, take, bucket) with delta+take <= bucket.
    """
    subs = []
    for idx, (_, off, size) in enumerate(requests):
        pos = off
        remaining = size
        while remaining > 0:
            take = min(remaining, CHUNK)
            aligned = pos - (pos % LANE)
            delta = pos - aligned
            subs.append(
                (idx, aligned, delta, take, _bucket(SIZE_BUCKETS, delta + take))
            )
            pos += take
            remaining -= take
    return subs


def reconstruct_intervals(
    cache: DeviceShardCache,
    vid: int,
    requests: list[tuple[int, int, int]],
    kernel: str | None = None,
    interpret: bool | None = None,
    data_shards: int = DATA_SHARDS,
    total_shards: int = TOTAL_SHARDS,
) -> list[bytes]:
    """Reconstruct interval bytes for a batch of degraded reads in as few
    device calls as possible (one per size bucket actually present).

    requests: [(wanted_shard_id, shard_offset, size)].  All gather inputs
    are resident shards; per-call H2D is just the offset/row vectors and
    D2H is exactly the reconstructed bytes.  Raises CacheMiss when fewer
    than `data_shards` non-wanted shards of `vid` are resident.
    """
    if not requests:
        return []
    if kernel is None:
        kernel = "pallas" if rs_tpu.on_tpu() else "xla"
    if interpret is None:
        interpret = not rs_tpu.on_tpu()

    wanted = sorted({r[0] for r in requests})
    resident = cache.shard_ids(vid)
    present = [s for s in resident if s not in wanted]
    if len(present) < data_shards:
        raise CacheMiss(
            f"vid {vid}: {len(present)} resident survivors, need {data_shards}"
        )
    rmat, use = gf256.reconstruction_matrix(
        data_shards, total_shards, present, wanted
    )
    a_bm = _prepared_matrix(rmat.tobytes(), *rmat.shape)
    survivors = tuple(cache.get(vid, s) for s in use)
    if any(s is None for s in survivors):  # evicted between listing and get
        raise CacheMiss(f"vid {vid}: survivor shard evicted mid-request")
    row_of = {sid: i for i, sid in enumerate(wanted)}

    subs = _plan(requests)
    sub_out: list[bytes | None] = [None] * len(subs)
    for bucket in SIZE_BUCKETS:
        group = [(i, s) for i, s in enumerate(subs) if s[4] == bucket]
        if not group:
            continue
        n_bucket = _bucket(COUNT_BUCKETS, min(len(group), COUNT_BUCKETS[-1]))
        for start in range(0, len(group), n_bucket):
            part = group[start : start + n_bucket]
            pad = n_bucket - len(part)
            offsets = jnp.asarray(
                np.array([s[1] for _, s in part] + [0] * pad, dtype=np.int32)
            )
            rows = jnp.asarray(
                np.array(
                    [row_of[requests[s[0]][0]] for _, s in part] + [0] * pad,
                    dtype=np.int32,
                )
            )
            deltas = jnp.asarray(
                np.array([s[2] for _, s in part] + [0] * pad, dtype=np.int32)
            )
            # D2H width: power-of-two cover of the largest actual request
            # in this call, never wider than the compute tile
            max_take = max(s[3] for _, s in part)
            fetch = min(bucket, 1 << (max_take - 1).bit_length())
            out = np.asarray(
                _gather_reconstruct(
                    a_bm,
                    survivors,
                    offsets,
                    rows,
                    deltas,
                    tile=bucket,
                    fetch=fetch,
                    kernel=kernel,
                    interpret=interpret,
                    k_true=len(use),
                )
            )
            for j, (sub_idx, (_, _, delta, take, _)) in enumerate(part):
                lo = 0 if fetch < bucket else delta
                sub_out[sub_idx] = out[j, lo : lo + take].tobytes()
    outputs: list[list[bytes]] = [[] for _ in requests]
    for (idx, *_), piece in zip(subs, sub_out):
        outputs[idx].append(piece)  # subs are in offset order per request
    return [b"".join(parts) for parts in outputs]


def warm(
    cache: DeviceShardCache,
    vid: int,
    sizes: tuple[int, ...] = (4096, 65536, 1 << 20),
    counts: tuple[int, ...] = (1, 64),
    total_shards: int = TOTAL_SHARDS,
    **kw,
) -> None:
    """Pre-compile the bucket combinations a serving path will hit, so the
    first real degraded read doesn't pay a 20-40s TPU compile.  The wanted
    shard is a NON-resident one when any exists (the realistic degraded
    case), so a volume with exactly DATA_SHARDS survivors still warms."""
    resident = cache.shard_ids(vid)
    non_resident = [s for s in range(total_shards) if s not in resident]
    if non_resident:
        missing = non_resident[0]
        if len(resident) < DATA_SHARDS:
            return
    else:
        missing = resident[-1]
        if len(resident) - 1 < DATA_SHARDS:
            return
    for size in sizes:
        for count in counts:
            reqs = [(missing, 0, size)] * count
            reconstruct_intervals(cache, vid, reqs, **kw)
