"""Device-resident EC shard cache + batched degraded-read reconstruction.

Round-2 measurement showed why a naive device degraded read loses: every
per-needle reconstruct shipped 10x the payload (the survivor intervals)
host->device before the kernel could run, so the call was transfer-bound
(3965 ms p99 vs 0.75 ms for the C++ CPU kernel on this rig's tunneled
device).  The fix is to keep hot shards *resident in HBM*: then a degraded
read sends only (offset, row) scalars up and the reconstructed interval
bytes down, and any number of concurrent needle reconstructions batch into
ONE device call that gathers survivor slices from the resident buffers.

This is the TPU answer to the reference's per-needle goroutine fan-in
(/root/reference/weed/storage/store_ec.go:339-393): instead of fetching
interval bytes from >=10 peers per needle, the rebuilder/reader node pins
the survivor shards once (mount time or first read) and serves every
degraded needle from device memory.

Shapes and compile hygiene:
  * shard buffers are padded to SHARD_QUANTUM so volumes of similar size
    share jit caches, plus MAX_TILE slack so slices never clamp;
  * request sizes quantize to SIZE_BUCKETS, request counts to
    COUNT_BUCKETS, offsets align down to LANE (128) with the residual
    sliced off on host — a handful of compiles total, warmable up front.
"""
from __future__ import annotations

import concurrent.futures
import contextlib
import functools
import json
import os
import threading
import time
import warnings
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import NamedSharding, PartitionSpec as P

try:  # jax >= 0.8 promoted shard_map out of experimental
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from . import gf256, rs_tpu
from ..parallel import mesh as mesh_mod
from ..obs import devledger
from ..obs import incident as obs_incident
from ..obs import trace as obs_trace
from ..stats import metrics as stats_metrics

DATA_SHARDS = 10
TOTAL_SHARDS = 14

LANE = 128  # TPU lane tile: device slices start lane-aligned
# The fused kernel's DMA source is a (1024)-tiled 1-D HBM memref: Mosaic
# must PROVE slice starts divisible by 1024, so fused offsets align down
# to this and the <=1023-byte residual joins the host-trimmed delta.
FUSED_ALIGN = 1024
SIZE_BUCKETS = (2048, 8192, 32768, 131072, 524288, 2 * 1024 * 1024)
# a 256-wide bucket amortizes the per-call dispatch RTT over whole read
# bursts on tunneled rigs (padding past the true count costs only device
# compute: the in-jit [:n] trim keeps padded rows off the wire).  The
# ladder jumps 64 -> 256 on purpose: every bucket is a compiled shape
# warm() must pay 20-40s for, and a 65-request batch padded to 256 wastes
# only microseconds of MXU time
COUNT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 256)
MAX_TILE = SIZE_BUCKETS[-1]
# split oversized intervals into chunks that fit the largest bucket even
# after the <=FUSED_ALIGN-1 alignment residual
CHUNK = MAX_TILE - FUSED_ALIGN
SHARD_QUANTUM = 64 * 1024 * 1024

# The fused kernels' per-call staging is ONE packed [N] int32 vector:
# (offset in FUSED_ALIGN units) << META_ROW_BITS | wanted matrix row.
# Rows index the wanted-shard list (<= TOTAL_SHARDS = 14, 5 bits with
# margin), leaving 26 bits of offset units = 64GB of addressable shard —
# far past SHARD_QUANTUM padding.  Halving the r09 meta ([2, N] -> [N])
# halves serving H2D bytes per fused batch; the XLA fallback's three
# vectors collapse into one [3, N] array for the same reason (one
# device_put, one dispatch RTT, instead of three).
META_ROW_BITS = 5
_META_ROW_MASK = (1 << META_ROW_BITS) - 1
# the staging vectors are DONATED to their kernels (donate_argnums): a
# consumed batch's meta buffer frees as soon as the kernel reads it
# instead of surviving until the pipelined call's D2H.  XLA warns when a
# donated buffer cannot ALSO alias an output — always true here (int32
# staging in, uint8 bytes out), so the advisory is noise by construction.
# Applied per compile site via _quiet_donation too: pytest re-arms the
# global filter around every test, so the module-level form alone leaks
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)


@contextlib.contextmanager
def _quiet_donation():
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable"
        )
        yield


class CacheMiss(LookupError):
    """Not enough resident shards to serve the request."""


class ColdShape(CacheMiss):
    """A serving reconstruct would dispatch a device shape that is not
    compiled yet (the volume's AOT warm plan hasn't reached it): the
    caller must serve the read on the host path instead of stalling the
    dispatcher behind a 20-40s inline compile.  Raised BEFORE any device
    work, and only for caches with an AOT warm plan + shed_cold set —
    direct callers and never-warmed volumes keep inline compiles."""


_COMPILE_CACHE_SET = False
# observable cache state: a bad path used to log once and silently leave
# every restart recompiling — now the outcome is a gauge, a telemetry
# field, and a volume.device.status column (compile_cache_status())
_COMPILE_CACHE_STATE = {"enabled": False, "path": "", "error": ""}

# name of the observed-(size, count)-frequency sidecar persisted next to
# the compile cache, so warm()'s observed-buckets-first priority order
# survives process restarts instead of resetting to ladder order
OBSERVED_SHAPES_FILE = "observed_shapes.json"


def enable_persistent_compile_cache(path: str) -> bool:
    """Point XLA's persistent compilation cache at `path` so the
    reconstruct kernel's per-(size, count)-shape compiles (tens of
    seconds each on remote-compile rigs) survive process restarts, and
    load the observed-shape frequency state persisted next to it.

    The setting is PROCESS-GLOBAL, so call this once from the process
    entry point (the volume CLI does, next to -ec.deviceCacheMB); later
    calls no-op.  Returns True when the cache was enabled; the outcome
    either way is visible via compile_cache_status() and the
    SeaweedFS_volumeServer_ec_compile_cache_enabled gauge."""
    global _COMPILE_CACHE_SET
    if _COMPILE_CACHE_SET:
        return False
    try:
        # probe writability up front: jax.config.update accepts any
        # string and the failure would otherwise surface as a per-shape
        # cache-write warning long after the operator stopped looking
        os.makedirs(path, exist_ok=True)
        # pid-suffixed probe: two servers sharing a cache dir must not
        # race on one filename (the loser's os.remove would read as
        # "bad path" and silently disable ITS persistent cache)
        probe = os.path.join(path, f".write_probe.{os.getpid()}")
        with open(probe, "w"):
            pass
        os.remove(probe)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # noqa: BLE001 — bad path / older jax
        import logging

        logging.getLogger(__name__).warning(
            "persistent compile cache unavailable at %s (%s): every "
            "restart will recompile the reconstruct kernel shapes", path, e,
        )
        _COMPILE_CACHE_STATE.update(enabled=False, path=path, error=str(e))
        stats_metrics.VOLUME_SERVER_EC_COMPILE_CACHE_ENABLED.set(0)
        return False
    _COMPILE_CACHE_SET = True
    _COMPILE_CACHE_STATE.update(enabled=True, path=path, error="")
    stats_metrics.VOLUME_SERVER_EC_COMPILE_CACHE_ENABLED.set(1)
    load_observed_shapes(os.path.join(path, OBSERVED_SHAPES_FILE))
    return True


def compile_cache_status() -> dict:
    """{"enabled", "path", "error"} — the persistent-compile-cache
    outcome, shipped in heartbeat telemetry and volume.device.status."""
    return dict(_COMPILE_CACHE_STATE)


# --- observed-shape persistence ---------------------------------------------
# warm() walks the (size, count) grid observed-buckets-first; persisting
# the frequency map next to the compile cache means a RESTARTED process
# warms the live workload's shapes first too, not just a re-pin.

_OBSERVED_SAVE_INTERVAL_S = 5.0
_observed_path: str | None = None
_observed_dirty = False
_observed_last_save = 0.0


def load_observed_shapes(path: str) -> int:
    """Merge a persisted observed-shape frequency file into this
    process's ranking and adopt `path` for future saves.  Returns the
    number of (size, count) buckets loaded (0 when absent/corrupt —
    either way the path is adopted so the state starts persisting)."""
    global _observed_path
    _observed_path = path
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        # parse fully BEFORE touching shared state: a syntactically
        # valid JSON file with the wrong shape (bad row arity, non-list
        # buckets) is just as corrupt as unparseable JSON
        rows = [
            (int(size), int(count), int(hits))
            for size, count, hits in data["buckets"]
        ]
    except FileNotFoundError:
        return 0
    except Exception as e:  # noqa: BLE001 — corrupt file must not stop boot
        import logging

        logging.getLogger(__name__).warning(
            "ignoring corrupt observed-shapes file %s: %s", path, e
        )
        return 0
    with _shapes_lock:
        for size, count, hits in rows:
            key = (size, count)
            _observed_buckets[key] = _observed_buckets.get(key, 0) + hits
    return len(rows)


def persist_observed_shapes(path: str | None = None) -> bool:
    """Atomically write the observed-shape frequency map (tmp + rename)
    to `path` (default: the path adopted by load_observed_shapes).
    Returns True when written."""
    global _observed_dirty, _observed_last_save
    path = path or _observed_path
    if path is None:
        return False
    with _shapes_lock:
        buckets = [
            [s, c, n] for (s, c), n in sorted(_observed_buckets.items())
        ]
        _observed_dirty = False
    tmp = path + ".tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"buckets": buckets}, f)
        os.replace(tmp, path)
    except OSError:
        # the observations are still unsaved: re-arm the dirty flag so
        # the hook retries once the dir is writable again — but stamp
        # the attempt so a persistently broken dir costs one failed
        # open per save interval, not one per batch
        with _shapes_lock:
            _observed_dirty = True
        _observed_last_save = time.monotonic()
        return False
    _observed_last_save = time.monotonic()
    return True


def _maybe_persist_observed() -> None:
    """Throttled save hook on the dispatch path: cheap no-op unless a
    new observation landed and the last save is older than the
    interval (the file is tiny — a handful of bucket rows)."""
    if (
        _observed_path is not None
        and _observed_dirty
        and time.monotonic() - _observed_last_save > _OBSERVED_SAVE_INTERVAL_S
    ):
        persist_observed_shapes()


def compile_cache_for_volume_dirs(ec_device_cache_mb: int, dirs) -> bool:
    """CLI bootstrap shared by `volume` and `server`: when the device
    shard cache is enabled, persist kernel compiles next to the data."""
    import os

    if ec_device_cache_mb <= 0 or not dirs:
        return False
    return enable_persistent_compile_cache(
        os.path.join(dirs[0], "jax_compile_cache")
    )


def _bucket(values: tuple[int, ...], need: int) -> int:
    for v in values:
        if need <= v:
            return v
    raise ValueError(f"{need} exceeds largest bucket {values[-1]}")


# bound per-call output (count * size bucket) so a wide batch of large
# intervals can't balloon device/host buffers; small-needle batches (the
# dominant serving shape) still ride the widest counts
_MAX_CALL_OUT = 32 * 1024 * 1024
# bound AGGREGATE un-fetched output across pipelined calls: each pending
# call parks its [n, fetch] result in HBM until the fetch loop reaches it
_MAX_PENDING_OUT = 128 * 1024 * 1024


def _max_count(size_bucket: int) -> int:
    return max(1, min(COUNT_BUCKETS[-1], _MAX_CALL_OUT // size_bucket))


# resident shard layouts.  "flat": the round-5/6 layout — one 1-D padded
# buffer per shard, reconstructed with the plain [8m,8k] bit matrix.
# "blockdiag": the same resident bytes SERVED through the block-diagonal
# g-group system (rs_tpu round-3: A_blk [128, 320] fills the MXU's M
# dimension, ~157 vs ~121 GB/s flat).  The host stages the layout for
# free: a request's tile (or scrub's shard span) splits into g
# CONTIGUOUS segments — segment-stacked [g*k, B/g] input rows are just
# g slices per survivor, so the gather reads them straight out of the
# flat resident buffers and no device restack (58 GB/s byte transposes,
# the round-3 dealbreaker) ever happens.
LAYOUTS = ("flat", "blockdiag")


class StagingArena:
    """Per-slot preallocated host staging buffer for a batch's packed
    offset/row vectors: one [3, COUNT_BUCKETS[-1]] int32 block covers
    the widest device call of either kernel family (fused uses one
    packed row, the XLA fallback all three), so a slot's calls stage
    into reused memory instead of allocating fresh np arrays per batch.
    Two slots -> two arenas: a slot's arena is never touched by the
    other slot's in-flight batch.  Only safe where device_put COPIES
    (TPU/GPU): the CPU PJRT client zero-copies aligned numpy, so an
    arena there would alias (and corrupt) an asynchronously executing
    call's input — reconstruct_intervals gates arena use on on_tpu()."""

    # rows of the arena block, by kernel family
    ROWS_FUSED = 1   # packed (offset_units << META_ROW_BITS | row)
    ROWS_XLA = 3     # offsets / rows / deltas

    def __init__(self, width: int | None = None):
        self.width = width or COUNT_BUCKETS[-1]
        self._buf = np.empty((self.ROWS_XLA, self.width), dtype=np.int32)

    def stage_fused(self, packed: list[int], pad: int) -> np.ndarray:
        """-> [n] int32 view of the arena holding the packed meta."""
        n = len(packed) + pad
        view = self._buf[0, :n]
        view[: len(packed)] = packed
        view[len(packed):] = 0
        return view

    def stage_xla(
        self, offsets: list[int], rows: list[int], deltas: list[int],
        pad: int,
    ) -> np.ndarray:
        """-> [3, n] int32 view of the arena (offsets/rows/deltas)."""
        n = len(offsets) + pad
        view = self._buf[:, :n]
        for i, col in enumerate((offsets, rows, deltas)):
            view[i, : len(col)] = col
            view[i, len(col):] = 0
        return view


class PipelineSlot:
    """What DevicePipeline.slot() yields: the slot-acquisition wait (for
    the device span's saturation attribution) plus this slot's private
    staging arena."""

    __slots__ = ("wait_s", "arena")

    def __init__(self, wait_s: float, arena: StagingArena):
        self.wait_s = wait_s
        self.arena = arena


class DevicePipeline:
    """Double-buffered staging gate for the device leg of batched
    reconstruct calls: `slots=2` lets batch N+1 pack (outside the slot)
    and ship+execute (inside it) while batch N drains its D2H — only
    N's fetch blocks N's completion.  `slots=1` is the serial baseline
    (bench.py's overlap-off axis).  Each slot owns a preallocated
    StagingArena so a held slot's host vectors stage into reused pinned
    memory (no per-batch np alloc churn; the r11 donation work).  The
    overlap-fraction gauge is device-busy seconds / wall seconds over
    the current batch window (a window opens when the pipeline leaves
    idle; the ratio refreshes at EVERY batch completion — a drain-only
    update would go stale under exactly the sustained load it exists to
    measure), so 1.0 means the device section ran the whole window and
    >1 means the staging slots genuinely overlapped."""

    def __init__(self, slots: int = 2):
        self._cond = threading.Condition()
        self._slots = max(1, slots)
        self._active = 0
        self._busy_s = 0.0
        # cumulative (never-reset) busy clock — the conservation anchor
        # the devledger per-class sums reconcile against; _busy_s stays
        # windowed because the overlap gauge needs the window semantics
        self.total_busy_s = 0.0
        self._window_t0 = 0.0
        self.last_overlap = 0.0
        # arena pool: one per concurrently held slot, grown on demand so
        # set_slots() widening never reallocates under the lock-holder
        self._arenas: list[StagingArena] = []
        self._free_arenas: list[int] = []

    @property
    def slots(self) -> int:
        return self._slots

    def set_slots(self, n: int) -> None:
        with self._cond:
            self._slots = max(1, int(n))
            self._cond.notify_all()

    @contextlib.contextmanager
    def slot(self):
        """Hold one staging slot for a device section; yields a
        PipelineSlot carrying the time spent waiting for the slot
        (annotated on the device span so a saturated pipeline is
        attributable) and the slot's staging arena."""
        t_req = time.perf_counter()
        with self._cond:
            while self._active >= self._slots:
                self._cond.wait()
            self._active += 1
            if self._active == 1:
                self._window_t0 = time.perf_counter()
                self._busy_s = 0.0
            if self._free_arenas:
                arena_idx = self._free_arenas.pop()
            else:
                self._arenas.append(StagingArena())
                arena_idx = len(self._arenas) - 1
        t0 = time.perf_counter()
        try:
            yield PipelineSlot(t0 - t_req, self._arenas[arena_idx])
        finally:
            dur = time.perf_counter() - t0
            with self._cond:
                self._active -= 1
                self._free_arenas.append(arena_idx)
                self._busy_s += dur
                self.total_busy_s += dur
                wall = time.perf_counter() - self._window_t0
                if wall > 0:
                    self.last_overlap = self._busy_s / wall
                    stats_metrics.VOLUME_SERVER_EC_OVERLAP_FRACTION.set(
                        self.last_overlap
                    )
                self._cond.notify()
            # slot duration IS the device section's busy time, so the
            # ledger's per-class sum conserves against total_busy_s by
            # construction (workload/device ride the caller's context)
            devledger.record(busy_s=dur, queue_wait_s=t0 - t_req)


class DeviceShardCache:
    """LRU cache of EC shard bytes pinned in device memory.

    Keyed by (vid, shard_id).  `budget_bytes` bounds device-padded bytes;
    inserting past the budget evicts least-recently-used shards (whole
    shards — a partially resident volume simply fails over to the host
    path via CacheMiss).

    Mesh-sharded residency (r19, -ec.serving.mesh.*): with
    `mesh_devices` set (0 = every local device) the cache lays volumes
    out ACROSS the serving mesh instead of whole onto the default
    device.  A volume whose shard files reach `mesh_min_shard_bytes`
    is lane-sharded: each shard's padded buffer is staged with
    `jax.device_put(x, NamedSharding(mesh, P("shard")))`, so device d
    holds byte-chunk d of every shard and the volume's resident
    capacity is the WHOLE mesh's budget, not one chip's.  Smaller
    volumes pin whole onto the least-loaded device (spreading a tiny
    volume across 8 chips buys no capacity and pays mesh dispatch).
    Budgets are accounted PER DEVICE (`budget_bytes / n_devices`
    each): eviction pressure targets the device that is actually full,
    and the tiering ladder's fit arithmetic follows the same per-device
    vectors (serving/tiering.py).

    Pod scale (r20, -ec.mesh.*): with `global_mesh=True` the mesh spans
    EVERY process of a multi-controller job (parallel.mesh.
    global_serving_mesh) and the cache becomes one member of an SPMD
    group.  Three rules keep the group consistent without any cache-to-
    cache coordination channel:

      * the mesh/whole placement decision is a pure function of
        (shard_bytes, mesh_min_shard_bytes) — identical on every host —
        so one volume can never straddle layouts across hosts; only the
        least-loaded pick for a whole pin is host-local (a whole pin IS
        host-local: it lands on one of THIS process's devices);
      * mesh-placed arrays are staged with
        `jax.make_array_from_process_local_data`, each host providing
        exactly its devices' stripes (no survivor byte ever crosses the
        host boundary at pin time either);
      * eviction is PARTITIONED: mesh puts evict only mesh-placed
        victims (pressure from mesh bytes alone) and host-local puts
        never evict mesh-placed arrays — the mesh-array set stays a
        pure function of the SPMD put sequence, so no host can evict a
        lane of an array its peers still serve (a collective against a
        half-evicted array deadlocks the pod).
    """

    def __init__(
        self,
        budget_bytes: int = 8 << 30,
        shard_quantum: int = SHARD_QUANTUM,
        layout: str = "flat",
        groups: int = rs_tpu.BLOCKDIAG_GROUPS,
        mesh_devices: int | None = None,
        mesh_min_shard_bytes: int = 8 << 20,
        global_mesh: bool = False,
    ):
        if layout not in LAYOUTS:
            raise ValueError(f"unknown resident layout {layout!r}")
        if groups < 1 or SIZE_BUCKETS[0] % (groups * LANE):
            # every size bucket is a multiple of the smallest, so this
            # one check guarantees lane-aligned tile/groups segments on
            # the XLA path (the fused path re-derives its own
            # groups*FUSED_ALIGN-aligned ladder)
            raise ValueError(
                f"groups={groups} must split the {SIZE_BUCKETS[0]}-byte "
                "size bucket into lane-aligned segments"
            )
        self.budget = budget_bytes
        self.quantum = shard_quantum
        # the serving mesh (parallel/mesh.py — the one home shared with
        # the bulk plane): None = the pre-r19 single-device layout.
        # mesh_devices=None keeps it off; 0 = all local devices; n = the
        # first n.  A resolved 1-wide mesh degrades to None (shard_map
        # overhead with no capacity win).
        self.mesh = (
            (
                mesh_mod.global_serving_mesh(mesh_devices)
                if global_mesh
                else mesh_mod.serving_mesh(mesh_devices)
            )
            if mesh_devices is not None else None
        )
        self.n_devices = (
            int(self.mesh.devices.size) if self.mesh is not None else 1
        )
        # pod-scale bookkeeping: which hosts (process indices) the mesh
        # spans, and which global lane indices are THIS process's.  A
        # single-process global mesh degrades to n_hosts == 1 and
        # _local_dev_indices == range(n_devices) — every multiprocess
        # branch below collapses to the r19 behavior.
        self.n_hosts = max(1, len(mesh_mod.mesh_hosts(self.mesh)))
        self.multiprocess = self.n_hosts > 1
        if self.mesh is not None:
            me = mesh_mod.process_index()
            self._local_dev_indices = [
                i
                for i, d in enumerate(self.mesh.devices.reshape(-1))
                if mesh_mod.device_host(d) == me
            ]
        else:
            self._local_dev_indices = [0]
        self.mesh_min_shard_bytes = mesh_min_shard_bytes
        # interleaved stripe width of the lane-sharded layout: stripe c
        # of a padded buffer lives on device c % n (the host permutes
        # the buffer owner-major at put time so NamedSharding's
        # contiguous split lands each device exactly its stripes).
        # Interleaving is what keeps ownership EVEN at any volume size:
        # a contiguous chunk-per-device split would park all of a
        # small-ish volume's data (and every zipf-hot byte range) on
        # the first chunks' owners while the padding tail's owners sat
        # idle, and the per-device count padding of a skewed batch
        # multiplies compute.  Each stripe must fit the largest gather
        # window placeable in it (>= SIZE_BUCKETS[0]) and stay
        # FUSED_ALIGN-aligned.
        self.stripe = 0
        if self.mesh is not None:
            q = self.n_devices * max(FUSED_ALIGN, SIZE_BUCKETS[0])
            self.quantum = -(-self.quantum // q) * q
            self.stripe = self.quantum // self.n_devices
        # which reconstruct/scrub kernel family serves this cache's bytes
        # (-ec.serving.layout); mutable at runtime — the bytes are
        # layout-agnostic (blockdiag segments are contiguous slices of
        # the same flat buffers), only the compiled shapes differ
        self.layout = layout
        self.groups = groups
        # the double-buffered device staging gate shared by every
        # reconstruct call against this cache (-ec.serving.overlap)
        self.pipeline = DevicePipeline()
        # the (size, count) bucket shapes the store's pin thread
        # pre-compiles after pinning a volume (warm()); deployments with
        # a known workload shape can narrow these to cut mount-time
        # compile cost (each shape is 20-40s on remote-compile rigs).
        # 256 covers the widest burst bucket so a >64-read coalesce
        # never hits a compile cliff on the serving path
        self.warm_sizes: tuple[int, ...] = (4096, 65536, 1 << 20)
        self.warm_counts: tuple[int, ...] = (1, 8, 64, 256)
        # AOT shed policy (-ec.serving.aot.disable): when True AND a
        # volume has an AOT warm plan (aot_state != "none"), a serving
        # reconstruct that would hit a still-cold device shape raises
        # ColdShape (host fallback + background compile) instead of
        # paying a 20-40s inline compile.  Volumes never warmed (empty
        # warm plan — the CI convention warm_sizes=()) keep the legacy
        # inline-compile behavior so direct callers are unaffected.
        self.shed_cold = True
        self._lock = threading.Lock()
        # vid -> "none" | "warming" | "done": whether an AOT warm plan
        # was started/finished for this volume (warm() maintains it)
        self._aot_states: dict[int, str] = {}
        self._arrays: OrderedDict[tuple[int, int], object] = OrderedDict()
        self._true_sizes: dict[tuple[int, int], int] = {}
        # vid -> the disk-location directory whose shard files were
        # pinned.  The cache is keyed by (vid, shard) only, so a vid
        # mounted in several locations is ambiguous without this: scrub
        # and read verdicts must be attributed to the location whose
        # bytes are actually resident (ADVICE r5).
        self._pin_source: dict[int, str] = {}
        # vid -> resident shard count, maintained on put/evict so the
        # serving path's per-read routing predicate is O(1) instead of
        # a scan-and-sort of the whole key set under the lock
        self._vid_counts: dict[int, int] = {}
        # per-device padded bytes held (len 1 without a mesh): the
        # accounting the per-device budget/eviction/tiering all share.
        # bytes_used (the pre-r19 scalar every caller reads) is the sum.
        self._dev_bytes: list[int] = [0] * self.n_devices
        # per-device MESH-PLACED padded bytes only: the pressure signal
        # of the multiprocess eviction partition (mesh puts may only
        # evict mesh victims, so their budget check must not see
        # host-local whole-pins another host knows nothing about)
        self._mesh_dev_bytes: list[int] = [0] * self.n_devices
        # vid -> "mesh" | device index: where this volume's arrays
        # live, decided at first put (claimed like the pin source so a
        # partially pinned volume can never interleave placements)
        self._vid_place: dict[int, object] = {}
        # key -> (place, padded size): what evicting the key frees, per
        # device
        self._foot: dict[tuple[int, int], tuple[object, int]] = {}
        # cumulative telemetry counters, reported up the heartbeat
        # (pb VolumeServerTelemetry): budget-pressure evictions are the
        # "HBM is too small for the working set" signal, pin claims the
        # "how many volumes ever went resident here" one
        self.evictions = 0
        self.pin_claims = 0

    def _padded_len(self, n: int) -> int:
        need = n + MAX_TILE
        return -(-need // self.quantum) * self.quantum

    # ------------------------------------------------- per-device accounting

    @property
    def bytes_used(self) -> int:
        """Total padded device bytes held (sum over the mesh) — the
        pre-r19 scalar every status/telemetry caller reads."""
        return sum(self._dev_bytes)

    @property
    def device_budget(self) -> int:
        """Per-device byte budget: the total budget split evenly over
        the mesh (the whole budget on a single-device cache)."""
        return self.budget // self.n_devices

    def _shares(self, place, size: int) -> list[tuple[int, int]]:
        """(device index, padded bytes) pairs one array of `size` costs
        under placement `place` ("mesh" = an even split — NamedSharding
        over the byte axis gives every device exactly size/n)."""
        if place == "mesh":
            per = size // self.n_devices
            return [(d, per) for d in range(self.n_devices)]
        return [(int(place), size)]

    def _publish_dev_gauges(self) -> None:
        for d, used in enumerate(self._dev_bytes):
            stats_metrics.VOLUME_SERVER_EC_DEVICE_CACHE_BYTES.labels(
                device=str(d)
            ).set(used)

    def _claim_place_locked(self, vid: int, shard_bytes: int):
        """First put of a vid decides (and pins) its placement: mesh
        lane-sharding for volumes worth spreading, else whole onto the
        least-loaded device.  Later puts of the same vid follow the
        claim — one volume must never straddle placements (the
        reconstruct kernels assume a uniform survivor layout)."""
        place = self._vid_place.get(vid)
        if place is None:
            if self.mesh is None:
                place = 0
            elif shard_bytes >= self.mesh_min_shard_bytes:
                # deterministic across processes: a pure function of the
                # shard size, so every host of a pod mesh claims the
                # same layout for the same volume (host-aware placement
                # invariant — one volume never straddles layouts)
                place = "mesh"
            else:
                # a whole pin is HOST-LOCAL: only this process's lanes
                # are addressable landing spots (== range(n) when
                # single-process)
                place = min(
                    self._local_dev_indices,
                    key=lambda d: self._dev_bytes[d],
                )
            self._vid_place[vid] = place
        return place

    def placement(self, vid: int):
        """"mesh" | device index | None (nothing of `vid` was ever
        placed) — the layout the serving path must dispatch for."""
        with self._lock:
            return self._vid_place.get(vid)

    def vid_sharded(self, vid: int) -> bool:
        with self._lock:
            return self._vid_place.get(vid) == "mesh"

    def device_stats(self) -> list[dict]:
        """Per-device [{"used_bytes", "budget_bytes"}] — the telemetry
        breakdown behind volume.device.status and cluster.health."""
        budget = self.device_budget
        with self._lock:
            return [
                {"used_bytes": used, "budget_bytes": budget}
                for used in self._dev_bytes
            ]

    def pressure_devices(self) -> list[int]:
        """Devices currently over their per-device budget, fullest
        first — what the tiering ladder's pressure demotion targets."""
        budget = self.device_budget
        with self._lock:
            over = [
                (used - budget, d)
                for d, used in enumerate(self._dev_bytes)
                if used > budget
            ]
        return [d for _, d in sorted(over, reverse=True)]

    def vid_device_bytes(self, vid: int) -> dict[int, int]:
        """device -> padded bytes held by `vid` (what demoting it
        frees, per device)."""
        out: dict[int, int] = {}
        with self._lock:
            for key, (place, size) in self._foot.items():
                if key[0] != vid:
                    continue
                for d, share in self._shares(place, size):
                    out[d] = out.get(d, 0) + share
        return out

    def device_bytes_by_vid(self) -> dict[int, dict[int, int]]:
        """vid -> {device -> padded bytes} in ONE locked pass over the
        footprint map — the rebalance-cycle bulk form of
        vid_device_bytes (a per-vid call rescans the whole map under
        the serving-path lock once per volume per cycle)."""
        out: dict[int, dict[int, int]] = {}
        with self._lock:
            for (vid, _sid), (place, size) in self._foot.items():
                dev = out.setdefault(vid, {})
                for d, share in self._shares(place, size):
                    dev[d] = dev.get(d, 0) + share
        return out

    def plan_pin(
        self, n_shards: int, shard_bytes: int, vid: int | None = None
    ) -> dict[int, int]:
        """device -> padded bytes a full pin of (n_shards x shard_bytes)
        WOULD add, previewing the placement rule — the tiering ladder's
        per-device fit arithmetic.  Pass `vid` so an existing placement
        claim wins over the least-loaded preview: budget-pressure
        eviction deliberately RETAINS a vid's claim, so a re-pin lands
        back on the claimed device — the fit check must judge the
        device the pin will ACTUALLY land on, not where a fresh volume
        would go."""
        padded = self._padded_len(shard_bytes)
        with self._lock:
            place = self._vid_place.get(vid) if vid is not None else None
            if place is None:
                if self.mesh is None:
                    place = 0
                elif shard_bytes >= self.mesh_min_shard_bytes:
                    place = "mesh"
                else:
                    place = min(
                        self._local_dev_indices,
                        key=lambda i: self._dev_bytes[i],
                    )
        if place == "mesh":
            per = padded // self.n_devices
            return {d: n_shards * per for d in range(self.n_devices)}
        return {int(place): n_shards * padded}

    def _device_of(self, place):
        """The jax device (or sharding) one placement stages through."""
        if place == "mesh":
            return NamedSharding(self.mesh, P(mesh_mod.SHARD_AXIS))
        if self.mesh is not None:
            return self.mesh.devices.reshape(-1)[int(place)]
        return mesh_mod.default_device()

    def put(self, vid: int, shard_id: int, data) -> None:
        host = np.frombuffer(bytes(data), dtype=np.uint8) if isinstance(
            data, (bytes, bytearray, memoryview)
        ) else np.asarray(data, dtype=np.uint8)
        # stage via np.empty + tail-only zeroing: np.zeros memsets the
        # WHOLE padded buffer and then overwrites all but the tail — a
        # redundant full-size host pass per shard when pinning a large
        # volume.  A reused per-cache staging buffer would cut the
        # allocation too, but the CPU PJRT client zero-copies aligned
        # numpy arrays into jax Arrays, so reuse would alias (and
        # corrupt) previously pinned shards; a fresh buffer per put is
        # the safe form of the optimization (alloc is cheap, memset of
        # gigabytes is not).  The padded buffer doubles as the blockdiag
        # segment-stacked layout: its g segments are contiguous slices,
        # staged by the host for free.
        padded = np.empty(self._padded_len(host.size), dtype=np.uint8)
        padded[: host.size] = host
        padded[host.size :] = 0
        with self._lock:
            place = self._claim_place_locked(vid, host.size)
        if place == "mesh":
            # owner-major stripe permutation: NamedSharding splits the
            # 1-D buffer into n contiguous blocks, so reordering stripe
            # c to position (c % n major, c // n minor) lands device d
            # exactly its interleaved stripes {d, d+n, d+2n, ...}.  One
            # extra host copy per shard, paid at pin time.
            s_n = padded.size // self.stripe
            perm = (
                np.arange(s_n)
                .reshape(s_n // self.n_devices, self.n_devices)
                .T.ravel()
            )
            padded = padded.reshape(s_n, self.stripe)[perm].reshape(-1)
        # the H2D lands directly on the owning device(s): an explicit
        # sharding/device for every put (mesh puts split host-side and
        # ship each device its stripes; whole pins ship to the claimed
        # device) — also what graftlint GL115 enforces in this scope.
        # Multiprocess mesh puts can't device_put against a global
        # sharding (most of its devices aren't addressable here):
        # each process provides exactly ITS lanes' contiguous slice of
        # the owner-major buffer via make_array_from_process_local_data
        # — the pin path's no-survivor-byte-crosses-hosts rule.
        if place == "mesh" and self.multiprocess:
            chunk = padded.size // self.n_devices
            lo = self._local_dev_indices[0] * chunk
            hi = (self._local_dev_indices[-1] + 1) * chunk
            arr = jax.make_array_from_process_local_data(
                self._device_of(place), padded[lo:hi], (padded.size,)
            )
        else:
            arr = jax.device_put(padded, self._device_of(place))
        key = (vid, shard_id)
        shares = self._shares(place, padded.size)
        budget = self.device_budget
        with self._lock:
            if self._vid_place.get(vid) != place:
                # the claim this array was staged/permuted for vanished
                # (evict()/clear() between the claim read and here —
                # tiering demoting a vid whose pin thread is mid-upload
                # is a supported race): inserting would let one vid's
                # shards straddle placements, turning later reads into
                # jit device-mismatch errors instead of the documented
                # clean CacheMiss.  Drop the array; the pin loop's next
                # put re-claims fresh.
                return
            if key in self._arrays:
                self._drop_key_locked(key)
            # evict while any device the incoming array lands on would
            # exceed ITS budget: LRU order, restricted to keys that
            # actually hold bytes on an over-budget device — pressure
            # on a full device never flushes a whole-pin parked on a
            # device with headroom (mesh-sharded arrays touch every
            # device, so they stay evictable under any pressure).  ONE
            # forward pass suffices: dropping victims only shrinks the
            # over set, so a key skipped as off-pressure can never
            # match later — rescanning from the LRU head per victim
            # would cost O(victims x resident keys) under this lock.
            # Multiprocess eviction PARTITION: a pod cache's mesh-array
            # set must stay a pure function of the SPMD put sequence
            # (a lane evicted on one host deadlocks its peers' next
            # collective), so mesh puts judge pressure by mesh bytes
            # alone and evict only mesh victims, while host-local puts
            # may never touch a mesh victim (they break over budget
            # instead — tiering pressure demotion drains the rest).
            mesh_only = self.multiprocess and place == "mesh"
            skip_mesh = self.multiprocess and place != "mesh"
            pressure = self._mesh_dev_bytes if mesh_only else self._dev_bytes
            lru = iter(list(self._arrays))
            while self._arrays:
                over = {
                    d
                    for d, share in shares
                    if pressure[d] + share > budget
                }
                if not over:
                    break
                victim = next(
                    (
                        k
                        for k in lru
                        if (
                            not (mesh_only and self._foot[k][0] != "mesh")
                            and not (
                                skip_mesh and self._foot[k][0] == "mesh"
                            )
                            and any(
                                d in over
                                for d, _ in self._shares(*self._foot[k])
                            )
                        )
                    ),
                    None,
                )
                if victim is None:
                    break  # pressure is on devices nothing else holds
                self._drop_key_locked(victim)
                self.evictions += 1
                # deliberately KEEP the evicted vid's pin-source claim
                # (and placement): budget pressure can evict a volume's
                # own oldest shards while its pin thread is still
                # uploading, and dropping the claim here would leave
                # the remaining pins unclaimed (never routed resident)
                # or let a second location interleave its shard set.  A
                # stale claim is conservative: scrub/serving just see
                # too few resident shards and stay on the file path;
                # explicit evict()/clear() (unmount, destroy) release
                # the claim.
            self._arrays[key] = arr
            self._true_sizes[key] = host.size
            self._foot[key] = (place, padded.size)
            self._vid_counts[vid] = self._vid_counts.get(vid, 0) + 1
            for d, share in shares:
                self._dev_bytes[d] += share
                if place == "mesh":
                    self._mesh_dev_bytes[d] += share
            self._publish_dev_gauges()

    def _drop_key_locked(self, key: tuple[int, int]) -> None:
        """Remove one key's array + every piece of its accounting
        (caller holds the lock and owns claim/placement policy)."""
        self._arrays.pop(key)
        self._true_sizes.pop(key, None)
        place, size = self._foot.pop(key)
        for d, share in self._shares(place, size):
            self._dev_bytes[d] -= share
            if place == "mesh":
                self._mesh_dev_bytes[d] -= share
        self._vid_counts[key[0]] -= 1
        if not self._vid_counts[key[0]]:
            del self._vid_counts[key[0]]

    def resident_count(self, vid: int) -> int:
        """O(1) resident shard count for `vid` (the serving dispatcher's
        per-read routing predicate — shard_ids() would scan the whole
        key set under the lock on every read)."""
        with self._lock:
            return self._vid_counts.get(vid, 0)

    def aot_state(self, vid: int) -> str:
        """"none" | "warming" | "done" — whether warm() started/finished
        an AOT compile plan for this volume.  Anything but "none" arms
        the cold-shape shed (shed_cold): the plan's shapes are coming,
        so a read must not compile inline ahead of it."""
        with self._lock:
            return self._aot_states.get(vid, "none")

    def _set_aot_state(self, vid: int, state: str) -> None:
        with self._lock:
            if state == "none":
                # "none" == absent: pop instead of storing, so an
                # aborted plan leaves no entry behind for a never
                # re-pinned vid
                self._aot_states.pop(vid, None)
            elif state == "done" and vid not in self._aot_states:
                # the volume was evicted mid-warm (_forget_if_gone
                # dropped the entry): a straggling compile future's
                # done-callback must not resurrect it, or a later
                # re-pin starts shed-armed against a plan that never
                # covered its (possibly different) shapes
                return
            else:
                self._aot_states[vid] = state

    def _forget_if_gone(self, vid: int) -> None:
        """Drop per-vid bookkeeping once no shard of `vid` remains
        (caller holds the lock; _vid_counts already knows, no key scan)."""
        if not self._vid_counts.get(vid):
            self._vid_counts.pop(vid, None)
            self._pin_source.pop(vid, None)
            self._aot_states.pop(vid, None)  # a re-pin re-plans
            self._vid_place.pop(vid, None)  # a re-pin re-places

    def claim_pin_source(self, vid: int, source: str) -> str:
        """Atomically claim which disk location's shard files back this
        vid's resident bytes; returns the winning source (first claimant
        keeps it — two locations' pin threads racing must not interleave
        their shard sets under one key space)."""
        with self._lock:
            if vid not in self._pin_source:
                self.pin_claims += 1
            return self._pin_source.setdefault(vid, source)

    def release_pin_source(self, vid: int, source: str) -> None:
        """Release `source`'s claim if nothing of `vid` is resident: a
        pin attempt that failed before uploading anything (unreadable
        shard file, aborted thread) must not block another location's
        healthy copy until process restart.  A partially pinned claim is
        kept — those bytes are still the vid's resident identity."""
        with self._lock:
            if (
                self._pin_source.get(vid) == source
                and not self._vid_counts.get(vid)
            ):
                del self._pin_source[vid]

    def pin_source(self, vid: int) -> str | None:
        with self._lock:
            return self._pin_source.get(vid)

    def get(self, vid: int, shard_id: int):
        with self._lock:
            key = (vid, shard_id)
            arr = self._arrays.get(key)
            if arr is not None:
                self._arrays.move_to_end(key)
            return arr

    def shard_size(self, vid: int, shard_id: int) -> int | None:
        return self._true_sizes.get((vid, shard_id))

    def stats(self) -> tuple[int, int]:
        """(resident shard count, padded device bytes held)."""
        with self._lock:
            return len(self._arrays), self.bytes_used

    def resident_by_vid(self) -> dict[int, list[int]]:
        """One locked snapshot of vid -> sorted resident shard ids (status
        pages render many volumes; per-vid shard_ids() calls would scan
        the key set once per volume under the serving path's lock)."""
        out: dict[int, list[int]] = {}
        with self._lock:
            for v, s in self._arrays:
                out.setdefault(v, []).append(s)
        for ids in out.values():
            ids.sort()
        return out

    def shard_ids(self, vid: int) -> list[int]:
        with self._lock:
            return sorted(s for (v, s) in self._arrays if v == vid)

    def evict(self, vid: int, shard_id: int | None = None) -> None:
        with self._lock:
            keys = [
                k
                for k in self._arrays
                if k[0] == vid and (shard_id is None or k[1] == shard_id)
            ]
            for k in keys:
                self._drop_key_locked(k)
            if keys:
                self._publish_dev_gauges()
            if shard_id is None or keys:
                # a whole-vid evict (unmount/destroy) always releases
                # the claim — even when budget pressure already removed
                # the shards, the claim must not outlive the volume.  A
                # PARTIAL evict that matched nothing must not drop a
                # mid-pin claim (the pin thread claimed before its first
                # put) and open the two-location interleave window.
                self._forget_if_gone(vid)

    def clear(self) -> None:
        with self._lock:
            self._arrays.clear()
            self._true_sizes.clear()
            self._pin_source.clear()
            self._vid_counts.clear()
            self._aot_states.clear()
            self._vid_place.clear()
            self._foot.clear()
            self._dev_bytes = [0] * self.n_devices
            self._publish_dev_gauges()


@functools.lru_cache(maxsize=64)
def _prepared_matrix(matrix_bytes: bytes, m: int, k: int):
    return rs_tpu.prepare_matrix(
        np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(m, k)
    )


# block-diagonal prepared matrices share rs_tpu's cache (the bulk
# encoder prepares the same parity system — one cached device copy)
_prepared_blockdiag_matrix = rs_tpu._prepared_blockdiag


# --- fused gather+reconstruct kernel ----------------------------------------
#
# The round-3 serving path ran FOUR chained XLA ops per call (vmap
# dynamic_slice gather -> stack/reshape -> pallas matmul -> take_along_axis
# -> vmap slice): every stage round-trips HBM and the chain costs several
# dispatches of fixed overhead per 4KB needle.  The fused kernel does the
# whole thing in ONE pallas program: per grid step it DMAs each survivor's
# slice HBM->VMEM at a scalar-prefetched offset, unpacks to GF(2) bit
# planes, runs the MXU dot, packs, and row-selects the wanted shard — no
# gathered intermediate ever touches HBM.  The sub-lane `delta` trim
# happens on host after D2H (<=127 bytes per needle of extra wire).
#
# Mosaic layout constraints (probed on v5e, experiments/r4_fused_probe.py +
# the memref_slice divisibility errors that followed):
#   * output/VMEM blocks need their second-minor dim divisible by 8 (or
#     equal to the array dim) — so each grid step serves a GROUP of 8
#     requests, output block (8, tile);
#   * DMA slice starts must be PROVABLY divisible by the memref tiling
#     (1024 for 1-D u8) — offsets travel in FUSED_ALIGN units and multiply
#     in-kernel, and every destination offset is a static multiple of tile;
#   * single-row slices of 2-D VMEM scratch are rejected (sublane tile 8),
#     and 1-D->2-D reshapes relayout — so the gather lands in a FLAT 1-D
#     HBM buffer laid out so a free XLA reshape yields [chunks, G, k, W],
#     which a second, regular-BlockSpec kernel consumes (block (1,1,k,W):
#     leading dims are unconstrained, trailing dims equal the array's);
#   * jax.lax.dynamic_slice has no Mosaic lowering — the per-request row
#     select is an iota-mask reduction.
# Both pallas calls live in ONE jit: a single host dispatch, and the only
# intermediate (the gathered slices) never rides the host link.

FUSED_GROUP = 8  # requests per grid step (output sublane tile)
FUSED_TILE = 4096  # per-request lane chunk; x8 group = 32768-lane compute
                   # width (bits 4MB + counts 4MB int32 in VMEM)


def _make_gather_body(k: int, g_n: int, tile: int, n_groups: int):
    w = g_n * tile

    def body(offs_ref, *rest):
        surv = rest[:k]
        o_ref = rest[k]
        sems = rest[k + 1]
        g = pl.program_id(0)
        j = pl.program_id(1)
        copies = []
        for r in range(g_n):
            # unpack the offset units from the packed meta word; the
            # explicit multiply is what lets Mosaic PROVE alignment
            src = (
                (offs_ref[g * g_n + r] >> META_ROW_BITS) * FUSED_ALIGN
                + j * tile
            )
            for i in range(k):
                dst = ((j * n_groups + g) * k + i) * w + r * tile
                copies.append(
                    pltpu.make_async_copy(
                        surv[i].at[pl.ds(src, tile)],
                        o_ref.at[pl.ds(dst, tile)],
                        sems.at[i, r],
                    )
                )
        for c in copies:
            c.start()
        for c in copies:
            c.wait()

    return body


def _make_select_body(k: int, k_pad: int, m_pad: int, g_n: int, tile: int):
    w = g_n * tile

    def body(rows_ref, a_ref, x_ref, o_ref):
        g = pl.program_id(0)
        xv = x_ref[0, 0]  # (k, w); leading unit dims index away for free
        if k < k_pad:
            xv = jnp.concatenate(
                [xv, jnp.zeros((k_pad - k, w), jnp.uint8)], axis=0
            )
        bits = rs_tpu._unpack_bits_bitmajor(xv)
        counts = jnp.dot(a_ref[:], bits, preferred_element_type=jnp.int32)
        packed = rs_tpu._pack_bits_bitmajor(counts, m_pad)  # (m_pad, w)
        ridx = jax.lax.broadcasted_iota(jnp.int32, (m_pad, tile), 0)
        outs = []
        for r in range(g_n):
            row = rows_ref[g * g_n + r] & _META_ROW_MASK
            blk = packed[:, r * tile : (r + 1) * tile]
            sel = jnp.where(ridx == row, blk, jnp.uint8(0)).astype(jnp.int32)
            outs.append(jnp.sum(sel, axis=0, keepdims=True).astype(jnp.uint8))
        o_ref[:] = jnp.concatenate(outs, axis=0)

    return body


@functools.partial(
    jax.jit,
    static_argnames=("tile", "fetch", "k_true", "interpret"),
    donate_argnums=(2,),
)
def _fused_reconstruct(
    a_bm, survivors, meta, *, tile, fetch, k_true, interpret
):
    """survivors: tuple of [L] u8 resident shards (HBM) in matrix column
    order; meta [N] int32 — each word packs (offset in FUSED_ALIGN
    units) << META_ROW_BITS | wanted matrix row, so the call ships ONE
    scalar vector of half the r09 width.  The meta buffer is DONATED
    (staging dies with the call).  -> [N, fetch] u8 of raw reconstructed
    bytes starting at each aligned offset (caller trims the delta head).
    N pads to the 8-request group internally.  Returns the [N, fetch]
    result FLATTENED (1-D, true-N rows only): 2-D transfers pay a
    per-row tunnel cost; callers reshape host-side."""
    k = len(survivors)
    if k_true is not None and k != k_true:
        raise ValueError(f"{k} survivors but matrix was built for {k_true}")
    m_pad8, k_pad8 = a_bm.shape
    m_pad, k_pad = m_pad8 // 8, k_pad8 // 8
    n = meta.shape[0]
    pad = (-n) % FUSED_GROUP
    if pad:
        meta = jnp.pad(meta, (0, pad))
    # both pallas bodies consume the same packed word: the gather
    # shifts the offset units out, the select masks the row bits
    offsets = row_idx = meta
    n_pad = n + pad
    tile = min(tile, fetch)
    chunks = max(1, fetch // tile)
    n_groups = n_pad // FUSED_GROUP
    w = FUSED_GROUP * tile

    gathered = pl.pallas_call(
        _make_gather_body(k, FUSED_GROUP, tile, n_groups),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_groups, chunks),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * k,
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA((k, FUSED_GROUP))],
        ),
        out_shape=jax.ShapeDtypeStruct((chunks * n_groups * k * w,), jnp.uint8),
        cost_estimate=pl.CostEstimate(
            flops=0,
            bytes_accessed=2 * chunks * n_groups * k * w,
            transcendentals=0,
        ),
        interpret=interpret,
    )(offsets, *survivors)
    x4 = gathered.reshape(chunks, n_groups, k, w)  # contiguous: free

    out = pl.pallas_call(
        _make_select_body(k, k_pad, m_pad, FUSED_GROUP, tile),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_groups, chunks),
            in_specs=[
                pl.BlockSpec(
                    a_bm.shape, lambda *_: (0, 0), memory_space=pltpu.VMEM
                ),
                pl.BlockSpec(
                    (1, 1, k, w),
                    lambda gi, ji, *_: (ji, gi, 0, 0),
                    memory_space=pltpu.VMEM,
                ),
            ],
            out_specs=pl.BlockSpec(
                (FUSED_GROUP, tile),
                lambda gi, ji, *_: (gi, ji),
                memory_space=pltpu.VMEM,
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, fetch), jnp.uint8),
        cost_estimate=pl.CostEstimate(
            flops=2 * m_pad8 * k_pad8 * n_pad * fetch,
            bytes_accessed=(k + 1) * n_pad * fetch,
            transcendentals=0,
        ),
        interpret=interpret,
    )(row_idx, a_bm, x4)
    return (out[:n] if pad else out).reshape(-1)


# --- block-diagonal variants -------------------------------------------------
#
# Same fused two-kernel structure, but the reconstruction system is the
# block-diagonal [g*w, g*k] expansion (rs_tpu.blockdiag_system): each
# request's tile splits into g contiguous segments, group jg's input
# rows are the survivors' slices of segment jg, and group jg's output
# row is the wanted shard's bytes of that segment — concatenating the
# groups along lanes reassembles the contiguous tile.  The fatter
# contraction (8*pad16(g*k) = 384 vs 128 bits for k=10, g=4) is what
# lifts the MXU roof from ~121 to ~157 GB/s (rs_tpu.py round 3/4).
# Mosaic constraints inherited from the flat kernel: every DMA slice
# start must stay provably FUSED_ALIGN-divisible, so per-chunk segments
# are tile/groups wide and the blockdiag fetch ladder rounds up to a
# multiple of groups*FUSED_ALIGN (a coarser ladder — the caller pays at
# most one extra 4KB step of D2H per request, against a ~30% MXU win).


def _make_gather_body_blockdiag(k, groups, g_n, tile, n_groups):
    seg = tile // groups
    w = g_n * seg
    gk = groups * k

    def body(offs_ref, *rest):
        surv = rest[:k]
        o_ref = rest[k]
        sems = rest[k + 1]
        g = pl.program_id(0)
        j = pl.program_id(1)
        copies = []
        for r in range(g_n):
            base = (
                (offs_ref[g * g_n + r] >> META_ROW_BITS) * FUSED_ALIGN
                + j * tile
            )
            for jg in range(groups):
                # seg is a multiple of FUSED_ALIGN (caller-enforced), so
                # base + jg*seg keeps the alignment proof intact
                src = base + jg * seg
                for i in range(k):
                    dst = (
                        ((j * n_groups + g) * gk + jg * k + i) * w + r * seg
                    )
                    copies.append(
                        pltpu.make_async_copy(
                            surv[i].at[pl.ds(src, seg)],
                            o_ref.at[pl.ds(dst, seg)],
                            sems.at[i, jg * g_n + r],
                        )
                    )
        for c in copies:
            c.start()
        for c in copies:
            c.wait()

    return body


def _make_select_body_blockdiag(k, groups, w_true, k_pad, m_pad, g_n, tile):
    seg = tile // groups
    w = g_n * seg
    gk = groups * k

    def body(rows_ref, a_ref, x_ref, o_ref):
        g = pl.program_id(0)
        xv = x_ref[0, 0]  # (g*k, w)
        if gk < k_pad:
            xv = jnp.concatenate(
                [xv, jnp.zeros((k_pad - gk, w), jnp.uint8)], axis=0
            )
        bits = rs_tpu._unpack_bits_bitmajor(xv)
        counts = jnp.dot(a_ref[:], bits, preferred_element_type=jnp.int32)
        packed = rs_tpu._pack_bits_bitmajor(counts, m_pad)  # (m_pad, w)
        ridx = jax.lax.broadcasted_iota(jnp.int32, (m_pad, seg), 0)
        outs = []
        for r in range(g_n):
            row = rows_ref[g * g_n + r] & _META_ROW_MASK
            blk = packed[:, r * seg : (r + 1) * seg]  # (m_pad, seg)
            segs = []
            for jg in range(groups):
                # group jg's wanted row sits at jg*w_true + row in the
                # block-diagonal system; its seg lanes are the request's
                # bytes [jg*seg, (jg+1)*seg) of this chunk's tile
                sel = jnp.where(
                    ridx == jg * w_true + row, blk, jnp.uint8(0)
                ).astype(jnp.int32)
                segs.append(
                    jnp.sum(sel, axis=0, keepdims=True).astype(jnp.uint8)
                )
            outs.append(jnp.concatenate(segs, axis=1))  # (1, tile)
        o_ref[:] = jnp.concatenate(outs, axis=0)

    return body


@functools.partial(
    jax.jit,
    static_argnames=("tile", "fetch", "k_true", "w_true", "groups", "interpret"),
    donate_argnums=(2,),
)
def _fused_reconstruct_blockdiag(
    a_blk, survivors, meta, *, tile, fetch, k_true, w_true, groups, interpret
):
    """Block-diagonal twin of _fused_reconstruct: same packed-[N]-meta
    (donated) and flat 1-D output contract; `w_true` is the
    reconstruction system's pre-expansion row count (len(wanted)) so the
    per-group row select can address jg*w_true + row.  Caller guarantees
    tile % (groups * FUSED_ALIGN) == 0 and fetch % tile == 0."""
    k = len(survivors)
    if k_true is not None and k != k_true:
        raise ValueError(f"{k} survivors but matrix was built for {k_true}")
    m_pad8, k_pad8 = a_blk.shape
    m_pad, k_pad = m_pad8 // 8, k_pad8 // 8
    n = meta.shape[0]
    pad = (-n) % FUSED_GROUP
    if pad:
        meta = jnp.pad(meta, (0, pad))
    offsets = row_idx = meta
    n_pad = n + pad
    chunks = fetch // tile
    n_groups = n_pad // FUSED_GROUP
    seg = tile // groups
    w = FUSED_GROUP * seg
    gk = groups * k

    gathered = pl.pallas_call(
        _make_gather_body_blockdiag(k, groups, FUSED_GROUP, tile, n_groups),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_groups, chunks),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * k,
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((k, groups * FUSED_GROUP))
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(
            (chunks * n_groups * gk * w,), jnp.uint8
        ),
        cost_estimate=pl.CostEstimate(
            flops=0,
            bytes_accessed=2 * chunks * n_groups * gk * w,
            transcendentals=0,
        ),
        interpret=interpret,
    )(offsets, *survivors)
    x4 = gathered.reshape(chunks, n_groups, gk, w)  # contiguous: free

    out = pl.pallas_call(
        _make_select_body_blockdiag(
            k, groups, w_true, k_pad, m_pad, FUSED_GROUP, tile
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_groups, chunks),
            in_specs=[
                pl.BlockSpec(
                    a_blk.shape, lambda *_: (0, 0), memory_space=pltpu.VMEM
                ),
                pl.BlockSpec(
                    (1, 1, gk, w),
                    lambda gi, ji, *_: (ji, gi, 0, 0),
                    memory_space=pltpu.VMEM,
                ),
            ],
            out_specs=pl.BlockSpec(
                (FUSED_GROUP, tile),
                lambda gi, ji, *_: (gi, ji),
                memory_space=pltpu.VMEM,
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, fetch), jnp.uint8),
        cost_estimate=pl.CostEstimate(
            flops=2 * m_pad8 * k_pad8 * n_pad * (fetch // groups),
            bytes_accessed=(k + 1) * n_pad * fetch,
            transcendentals=0,
        ),
        interpret=interpret,
    )(row_idx, a_blk, x4)
    return (out[:n] if pad else out).reshape(-1)


@functools.partial(
    jax.jit,
    static_argnames=("tile", "fetch", "kernel", "interpret", "k_true"),
    donate_argnums=(2,),
)
def _gather_reconstruct(
    a_bm,
    survivors,
    vecs,
    *,
    tile,
    fetch,
    kernel,
    interpret,
    k_true,
):
    """survivors: tuple of [L] u8 resident shards in matrix column order;
    vecs [3, N] int32 (donated) — row 0 the lane-aligned offsets, row 1
    each request's wanted matrix row, row 2 the sub-lane alignment
    residual.  One array = ONE device_put and one dispatch RTT where the
    r09 path paid three.  -> [N, fetch] u8.

    `tile` is the compute width (size bucket); `fetch` <= tile is the D2H
    width (power-of-two cover of the largest actual request): the result
    is delta-shifted and narrowed ON DEVICE so the transfer back — the
    scarce resource on a tunneled device — carries only useful bytes.
    Returns the [N, fetch] result FLATTENED (1-D): 2-D transfers pay a
    per-row tunnel cost; callers reshape host-side."""
    offsets, row_idx, deltas = vecs[0], vecs[1], vecs[2]
    cols = [
        jax.vmap(
            lambda off, arr=arr: jax.lax.dynamic_slice(arr, (off,), (tile,))
        )(offsets)
        for arr in survivors
    ]  # k x [N, tile]
    x = jnp.stack(cols, axis=0)  # [k, N, tile]
    k, n, _ = x.shape
    out = rs_tpu.apply_matrix_device(
        a_bm,
        x.reshape(k, n * tile),
        kernel=kernel,
        interpret=interpret,
        k_true=k_true,
    )  # [m_pad, n*tile]
    out3 = out.reshape(out.shape[0], n, tile).transpose(1, 0, 2)
    sel = jnp.take_along_axis(out3, row_idx[:, None, None], axis=1)[:, 0, :]
    if fetch < tile:
        sel = jax.vmap(
            lambda row, d: jax.lax.dynamic_slice(row, (d,), (fetch,))
        )(sel, deltas)
    return sel.reshape(-1)


@functools.partial(
    jax.jit,
    static_argnames=(
        "tile", "fetch", "groups", "w_true", "kernel", "interpret", "k_true",
    ),
    donate_argnums=(2,),
)
def _gather_reconstruct_blockdiag(
    a_blk,
    survivors,
    vecs,
    *,
    tile,
    fetch,
    groups,
    w_true,
    kernel,
    interpret,
    k_true,
):
    """Block-diagonal twin of _gather_reconstruct (the XLA fallback and
    bench path), same single donated [3, N] vecs contract: each
    request's tile splits into `groups` contiguous segments gathered
    into segment-stacked [g*k, N*seg] rows, one apply of the
    block-diagonal matrix reconstructs every segment, and the per-group
    wanted rows (jg*w_true + row) concatenate back into the contiguous
    [N, tile] before the same on-device delta/narrow."""
    offsets, row_idx, deltas = vecs[0], vecs[1], vecs[2]
    seg = tile // groups
    cols = []
    for jg in range(groups):
        for arr in survivors:
            cols.append(
                jax.vmap(
                    lambda off, arr=arr, jg=jg: jax.lax.dynamic_slice(
                        arr, (off + jg * seg,), (seg,)
                    )
                )(offsets)
            )
    x = jnp.stack(cols, axis=0)  # [g*k, N, seg]
    gk, n, _ = x.shape
    out = rs_tpu.apply_matrix_device(
        a_blk,
        x.reshape(gk, n * seg),
        kernel=kernel,
        interpret=interpret,
        k_true=None if k_true is None else groups * k_true,
    )  # [m_pad >= groups*w_true, n*seg]
    out3 = out.reshape(out.shape[0], n, seg).transpose(1, 0, 2)
    segs = []
    for jg in range(groups):
        rows = row_idx + jg * w_true
        segs.append(
            jnp.take_along_axis(out3, rows[:, None, None], axis=1)[:, 0, :]
        )
    sel = jnp.concatenate(segs, axis=-1)  # [N, tile], contiguous bytes
    if fetch < tile:
        sel = jax.vmap(
            lambda row, d: jax.lax.dynamic_slice(row, (d,), (fetch,))
        )(sel, deltas)
    return sel.reshape(-1)


# --- mesh-sharded twins ------------------------------------------------------
#
# With the cache's mesh layout (r19), a volume's shard buffers are
# lane-sharded in INTERLEAVED STRIPES: stripe c (cache.stripe bytes) of
# every shard lives on device c % n — the host permutes each padded
# buffer owner-major at put time, so NamedSharding(mesh, P("shard"))'s
# contiguous split hands device d exactly its stripes.  Interleaving
# keeps ownership even at any volume size (a contiguous
# chunk-per-device split parks all of a small volume's data — and any
# zipf-hot byte range — on the first chunks' owners, and the uniform
# per-device count padding then multiplies compute).  The planner
# routes each sub-request to the device owning its gather window
# (splitting requests that straddle a stripe boundary, backward-
# aligning windows that would overhang one), so the whole batch
# reconstructs in ONE shard_map program across the mesh: each device
# gathers its own requests' survivor slices locally, runs the (flat or
# block-diagonal) GF(2) matmul over its ~1/n of the batch, and
# row-selects its wanted bytes — lane work genuinely parallelizes
# across devices instead of queueing on one chip, and no survivor byte
# ever crosses the interconnect (only the per-device request vectors
# go up and the reconstructed rows come down).  The staged vec is
# [n_dev, 2, N] int32 (per-device LOCAL offsets + wanted rows),
# sharded P("shard") so each device receives exactly its own requests
# — the sharding-aware H2D.  Host trims the alignment delta after D2H
# (the fused kernels' contract), so the kernel never shifts.


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "tile", "groups", "w_true", "kernel", "interpret", "k_true",
        "replicate_out",
    ),
    donate_argnums=(2,),
)
def _sharded_gather_reconstruct(
    a_prep, survivors, vecs, *, mesh, tile, groups, w_true, kernel,
    interpret, k_true, replicate_out=False,
):
    """survivors: tuple of [L_pad] u8 shards sharded P("shard") over
    `mesh`; vecs [n_dev, 2, N] int32 (donated), sharded P("shard") —
    row 0 each request's CHUNK-LOCAL aligned offset, row 1 its wanted
    matrix row.  `tile` is both the gather width and the D2H width
    (the planner sizes it to cover every request's delta+take, so the
    host-side delta trim needs no wider window).  groups > 1 applies
    the block-diagonal system exactly like _gather_reconstruct_blockdiag
    (g contiguous segments per window, per-group row select at
    jg*w_true + row).  -> [n_dev, N, tile] u8 sharded P("shard").

    `replicate_out` (the multi-controller mode): each lane all-gathers
    the RESULT rows over the shard axis so the output is fully
    replicated — same [n_dev, N, tile] global shape, but every process
    can np.asarray it locally.  Only the small result vecs cross the
    host boundary; survivor bytes never do (each lane still gathers
    exclusively from its own resident chunk)."""
    k = len(survivors)
    if k_true is not None and k != k_true:
        raise ValueError(f"{k} survivors but matrix was built for {k_true}")
    seg = tile // groups

    def kern(vecs_l, a_l, *surv_l):
        offsets, rows = vecs_l[0, 0], vecs_l[0, 1]
        n = offsets.shape[0]
        cols = []
        for jg in range(groups):
            for arr in surv_l:
                cols.append(
                    jax.vmap(
                        lambda o, arr=arr, jg=jg: jax.lax.dynamic_slice(
                            arr, (o + jg * seg,), (seg,)
                        )
                    )(offsets)
                )
        x = jnp.stack(cols, axis=0)  # [g*k, N, seg]
        gk = x.shape[0]
        out = rs_tpu.apply_matrix_device(
            a_l,
            x.reshape(gk, n * seg),
            kernel=kernel,
            interpret=interpret,
            k_true=None if k_true is None else groups * k_true,
        )  # [m_pad, N*seg]
        out3 = out.reshape(out.shape[0], n, seg).transpose(1, 0, 2)
        segs = []
        for jg in range(groups):
            want = rows + jg * w_true if groups > 1 else rows
            segs.append(
                jnp.take_along_axis(out3, want[:, None, None], axis=1)[
                    :, 0, :
                ]
            )
        sel = segs[0] if groups == 1 else jnp.concatenate(segs, axis=-1)
        if replicate_out:
            # [n_dev, N, tile] on EVERY lane: result rows (not survivor
            # bytes) cross the ICI/DCN once, so each host can read the
            # whole batch's answers without a second collective
            return jax.lax.all_gather(sel, mesh_mod.SHARD_AXIS)
        return sel[None]  # [1, N, tile]: this device's chunk of the out

    return _shard_map(
        kern,
        mesh=mesh,
        in_specs=(
            P(mesh_mod.SHARD_AXIS, None, None),
            P(None, None),
            *([P(mesh_mod.SHARD_AXIS)] * k),
        ),
        out_specs=(
            P(None, None, None)
            if replicate_out
            else P(mesh_mod.SHARD_AXIS, None, None)
        ),
        # the all_gather above really does replicate the output, but
        # shard_map's static replication checker cannot infer that
        # through the gather+select pipeline — disable the check only
        # for the replicated (multi-controller) variant
        **({"check_rep": False} if replicate_out else {}),
    )(vecs, a_prep, *survivors)


def _plan(requests: list[tuple[int, int, int]], l_loc: int = 0):
    """Split/align requests into device sub-requests.

    Each request (wanted_shard, offset, size) becomes >=1 sub-requests
    (req_index, aligned_off, delta, take, bucket) with delta+take <= bucket.

    `l_loc` > 0 is the mesh layout's stripe width: every sub-request's
    whole bucket window [aligned, aligned+bucket) must then sit inside
    ONE stripe (each stripe lives whole on its owner device), so
    requests additionally split at stripe boundaries and a window that
    would overhang its boundary is backward-aligned to END there
    instead (the delta grows up to bucket - take; the host trims it
    after D2H like any other delta).  Stripe starts are LANE-aligned by
    construction (the stripe is a multiple of FUSED_ALIGN), so
    backward-aligned offsets stay lane-aligned.
    """
    cap = SIZE_BUCKETS[-1]
    if l_loc:
        cap = max(v for v in SIZE_BUCKETS if v <= l_loc)
    subs = []
    for idx, (_, off, size) in enumerate(requests):
        pos = off
        remaining = size
        while remaining > 0:
            take = min(remaining, CHUNK)
            if l_loc:
                boundary = (pos // l_loc + 1) * l_loc
                take = min(take, boundary - pos)
                aligned = pos - (pos % LANE)
                delta = pos - aligned
                if delta + take > cap:
                    take = cap - delta
                bucket = _bucket(SIZE_BUCKETS, delta + take)
                if aligned + bucket > boundary:
                    # overhang: end the window AT the boundary (bucket
                    # <= cap <= l_loc keeps it inside the chunk); the
                    # residual pos - aligned joins the trimmed delta
                    aligned = boundary - bucket
                    delta = pos - aligned
            else:
                aligned = pos - (pos % LANE)
                delta = pos - aligned
                bucket = _bucket(SIZE_BUCKETS, delta + take)
            subs.append((idx, aligned, delta, take, bucket))
            pos += take
            remaining -= take
    return subs


@functools.lru_cache(maxsize=64)
def _prepared_matrix_placed(
    matrix_bytes, m, k, groups, mesh, place, multiprocess=False
):
    """Prepared (flat or blockdiag) matrix staged where the placement's
    kernels need it: replicated over the mesh for lane-sharded volumes,
    committed to the owning device for whole-pins — jit refuses to mix
    committed inputs across device sets, so the matrix must follow the
    survivors.  Cached per (system, placement) like _prepared_matrix.
    A multi-controller mesh can't device_put against the replicated
    sharding (non-addressable devices): every process holds the same
    matrix bytes, so each provides its full copy as the process-local
    data of the replicated global array."""
    if groups > 1:
        base = _prepared_blockdiag_matrix(matrix_bytes, m, k, groups)
    else:
        base = _prepared_matrix(matrix_bytes, m, k)
    if place == "mesh":
        sharding = NamedSharding(mesh, P(None, None))
        if multiprocess:
            return jax.make_array_from_process_local_data(
                # graftlint: allow(device-sync): `base` is host numpy —
                # asarray is a no-copy view, not a device sync
                sharding, np.asarray(base), base.shape
            )
        return jax.device_put(base, sharding)
    return jax.device_put(base, mesh.devices.reshape(-1)[int(place)])


def _resolve_codec(cache, vid, requests, data_shards, total_shards, layout):
    """Shared preamble: reconstruction matrix (flat or block-diagonal,
    per the active layout, staged on the vid's placement) + resident
    survivor tuple + the system's pre-expansion row count + the vid's
    placement ("mesh" | device index | 0 for the legacy default)."""
    wanted = sorted({r[0] for r in requests})
    resident = cache.shard_ids(vid)
    present = [s for s in resident if s not in wanted]
    if len(present) < data_shards:
        raise CacheMiss(
            f"vid {vid}: {len(present)} resident survivors, need {data_shards}"
        )
    rmat, use = gf256.reconstruction_matrix(
        data_shards, total_shards, present, wanted
    )
    place = cache.placement(vid)
    if place is None:
        place = 0
    groups = cache.groups if layout == "blockdiag" else 1
    if cache.mesh is not None:
        a_prep = _prepared_matrix_placed(
            rmat.tobytes(), *rmat.shape, groups, cache.mesh, place,
            cache.multiprocess,
        )
    elif layout == "blockdiag":
        a_prep = _prepared_blockdiag_matrix(
            rmat.tobytes(), *rmat.shape, cache.groups
        )
    else:
        a_prep = _prepared_matrix(rmat.tobytes(), *rmat.shape)
    survivors = tuple(cache.get(vid, s) for s in use)
    if any(s is None for s in survivors):  # evicted between listing and get
        raise CacheMiss(f"vid {vid}: survivor shard evicted mid-request")
    if place == "mesh" and len({int(s.size) for s in survivors}) != 1:
        # the sharded planner derives ONE per-device chunk length for
        # the whole batch; mixed padded lengths cannot serve sharded
        raise CacheMiss(f"vid {vid}: sharded survivors differ in size")
    row_of = {sid: i for i, sid in enumerate(wanted)}
    return a_prep, survivors, row_of, use, rmat.shape[0], place


def _group_vectors(part, requests, row_of):
    """HOST-side offset/row/delta COLUMNS (plain lists): numpy staging
    happens at dispatch time — into the held slot's arena on TPU, a
    fresh array elsewhere — so packing allocates nothing per batch and
    the H2D transfer lands under the pipeline's h2d_copy stage."""
    offsets = [s[1] for _, s in part]
    rows = [row_of[requests[s[0]][0]] for _, s in part]
    deltas = [s[2] for _, s in part]
    return offsets, rows, deltas


def _fetch_cover(span: int) -> int:
    """Smallest of {2^n, 3*2^(n-1)} covering span (min 2048).  A pure
    power-of-two ladder wastes ~2x D2H whenever the alignment delta pushes
    a power-of-two-sized request just past the boundary (the common case:
    any unaligned 1MB needle); the 1.5x steps cap the waste at ~50% while
    adding at most one compiled shape per size class."""
    p = max(1 << (span - 1).bit_length(), 2048)
    three_halves = 3 * (p >> 2)
    return three_halves if three_halves >= max(span, 2048) else p


def _sharded_fetch_rungs(fetch: int) -> list[int]:
    """Every fetch a live sharded sub-request in `fetch`'s size bucket
    can produce.  A sharded call's fetch is min(bucket, _fetch_cover(span))
    with span anywhere in (0, bucket]: _plan's stripe-boundary backward
    alignment grows delta up to bucket - take, so the reachable set is
    the whole {2^n, 3*2^(n-1)} cover ladder from 2048 up to the bucket —
    not just the aligned / off-by-one spans warm's probes enumerate.
    The smaller rungs double as cover for the boundary-SPLIT halves of a
    probe-sized read, whose takes land in buckets no probe size maps to."""
    bucket = _bucket(SIZE_BUCKETS, fetch)
    rungs, f = [], 2048
    while f <= bucket:
        rungs.append(f)
        if f + (f >> 1) <= bucket:
            rungs.append(f + (f >> 1))
        f <<= 1
    return rungs


def _fused_tile_for(fetch: int) -> int:
    """Largest per-chunk tile <= FUSED_TILE dividing fetch (fetch is
    2^n or 3*2^(n-1), so halving always lands on a divisor >= 1024)."""
    t = FUSED_TILE
    while fetch % t:
        t //= 2
    return t


def _fused_vectors(part, requests, row_of):
    """Re-align each sub-request down to FUSED_ALIGN: offsets become unit
    counts, the residual joins the host-trimmed delta.  -> (packed,
    deltas, fetch): `packed` is the [N] list of single int32 meta words
    ((units << META_ROW_BITS) | row — HALF the r09 [2, N] wire width,
    still one H2D transfer) and fetch covers the largest delta+take
    (CHUNK keeps it <= MAX_TILE).  Stays host-side lists here — numpy
    staging (arena or fresh) and the ship happen under h2d_copy."""
    packed, deltas = [], []
    for _, s in part:
        extra = s[1] % FUSED_ALIGN
        units = (s[1] - extra) // FUSED_ALIGN
        if units >= 1 << (31 - META_ROW_BITS):  # 64GB shard: unreachable
            raise ValueError(f"offset {s[1]} exceeds packed meta range")
        packed.append(
            (units << META_ROW_BITS) | row_of[requests[s[0]][0]]
        )
        deltas.append(s[2] + extra)
    span = max(d + s[3] for d, (_, s) in zip(deltas, part))
    fetch = _fetch_cover(span)
    return packed, deltas, fetch


def _use_fused(kernel: str, interpret: bool) -> bool:
    """The fused DMA kernel is the serving path on real TPUs; interpret
    mode also supports it (tests), but the XLA fallback kernel cannot."""
    return kernel == "pallas"


# shapes this process has already dispatched: first use of a shape is a
# jit compile (tens of seconds on remote-compile rigs) — the trace
# annotation + compile counter are what let a tail spike be attributed
# to "hit an unwarmed shape" instead of guessed at
_dispatched_shapes: set = set()
_shapes_lock = threading.Lock()


# (size_bucket, count_bucket) -> dispatch count, recorded per device
# call: warm() compiles the observed buckets FIRST, so a re-pin (budget
# churn, volume move) reaches serving-readiness for the live workload's
# shapes before burning 20-40s/compile on ladder corners nobody hits
_observed_buckets: dict[tuple[int, int], int] = {}


def _note_observed(size_bucket: int, count_bucket: int) -> None:
    global _observed_dirty
    with _shapes_lock:
        key = (size_bucket, count_bucket)
        _observed_buckets[key] = _observed_buckets.get(key, 0) + 1
        _observed_dirty = True


def observed_buckets() -> list[tuple[int, int]]:
    """(size_bucket, count_bucket) pairs this process has dispatched,
    most-frequent first — warm()'s compile-priority order."""
    with _shapes_lock:
        items = sorted(_observed_buckets.items(), key=lambda kv: -kv[1])
    return [k for k, _ in items]


# per-_call_key dispatch accounting for the live "what shape is hot
# right now" view (/debug/device/hot, volume.device.status -hot): the
# observed-bucket ranking above orders COMPILES, this answers the
# operator's runtime question — which compiled shape the device is
# actually spending its time in, and how long one dispatch of it takes.
# key -> [dispatch count, latency EWMA seconds, last dispatch unix]
_call_stats: dict[tuple, list] = {}
# EWMA weight: ~last 10 dispatches of the shape, same horizon as the
# QoS deadline estimator
_CALL_EWMA_ALPHA = 0.2


def _note_call_latency(key: tuple, seconds: float) -> None:
    """Record one device call's dispatch->fetch-complete wall seconds.
    Measured across the async pipeline (overlapped calls include their
    queue time behind siblings), so it is an OBSERVED service latency,
    not a pure kernel time — exactly what a tail investigation wants."""
    now = time.time()
    with _shapes_lock:
        rec = _call_stats.get(key)
        if rec is None:
            _call_stats[key] = [1, seconds, now]
            return
        rec[0] += 1
        rec[1] += _CALL_EWMA_ALPHA * (seconds - rec[1])
        rec[2] = now


def hot_shapes(limit: int = 10) -> list[dict]:
    """The hottest compiled call shapes, most-dispatched first:
    dispatch counts, per-dispatch latency EWMA, last-seen age — the
    `volume.device.status -hot` / /debug/device/hot payload."""
    with _shapes_lock:
        items = sorted(
            _call_stats.items(), key=lambda kv: -kv[1][0]
        )[: max(0, limit)]
    now = time.time()
    out = []
    for key, (count, ewma_s, last) in items:
        (
            family, groups, w_true, tile, fetch, n_bucket, k, a_shape,
            surv_len, interpret, place,
        ) = key
        out.append(
            {
                "kernel": family,
                "groups": groups,
                "w_true": w_true,
                "tile": tile,
                "fetch": fetch,
                "count_bucket": n_bucket,
                "k": k,
                "a_shape": list(a_shape),
                "survivor_len": surv_len,
                "interpret": bool(interpret),
                # 0 = default device; n = lane-sharded over n devices;
                # ["dev", d] = whole-pin on mesh device d; ["pod", n, h]
                # = lane-sharded over an n-device h-host global mesh;
                # ["podev", d] = whole-pin on global lane d of a pod
                "placement": list(place) if isinstance(place, tuple)
                else place,
                "dispatches": count,
                "ewma_ms": round(ewma_s * 1e3, 3),
                "last_dispatch_age_s": round(max(0.0, now - last), 3),
            }
        )
    return out


def _blockdiag_fetch_tile(fetch: int, groups: int) -> tuple[int, int]:
    """(fetch, tile) for the fused blockdiag kernel: per-chunk segments
    must stay FUSED_ALIGN-provable, so fetch rounds UP to a multiple of
    groups*FUSED_ALIGN and tile is the fixed groups*FUSED_ALIGN-aligned
    chunk (= FUSED_TILE for g=4).  Coarser D2H ladder than flat — at
    most one extra step per request, traded for the blockdiag MXU win."""
    q = groups * FUSED_ALIGN
    fetch = -(-fetch // q) * q
    tile = FUSED_TILE if FUSED_TILE % q == 0 and fetch % FUSED_TILE == 0 else q
    return fetch, tile


def _note_shape(key: tuple) -> bool:
    """Record one device call's shape; True when it was a compile miss
    (first use).  Locked: concurrent drain lanes dispatching the same
    first-ever shape must count ONE miss, or the hit/miss ratio skews
    exactly under the load it exists to diagnose."""
    with _shapes_lock:
        if key in _dispatched_shapes:
            miss = False
        else:
            _dispatched_shapes.add(key)
            miss = True
    stats_metrics.VOLUME_SERVER_EC_DEVICE_COMPILE.labels(
        result="miss" if miss else "hit"
    ).inc()
    return miss


# --- AOT serving grid --------------------------------------------------------
#
# warm() used to TRACE-AND-EXECUTE every ladder shape through
# reconstruct_intervals; now it lowers each device-call shape with
# jax.jit(...).lower(...).compile() on a background executor and parks
# the Compiled executable here.  _dispatch_call routes a matching call
# straight through the executable (the jit wrapper's own cache never
# sees it, so there is no second compile), and a serving read that would
# dispatch a shape neither AOT-compiled nor inline-compiled raises
# ColdShape instead of stalling 20-40s — the dispatcher serves it on the
# host path while the executor compiles the shape for the next read.

_aot_executables: dict[tuple, object] = {}  # call key -> jax Compiled
_aot_pending: set = set()  # keys queued/being compiled on the executor
# keys whose AOT compile RAISED: never re-queued (a deterministic
# compile failure would otherwise burn the single-worker executor
# 20-40s per matching read, forever) — the shape keeps shedding to the
# host path, which serves it fine
_aot_failed: set = set()
_AOT_EXECUTOR: concurrent.futures.Executor | None = None


def _call_key(
    kind, kernel, groups, w_true, tile, fetch, n_bucket, k, a_shape,
    surv_len, interpret, place=0,
) -> tuple:
    """Canonical identity of ONE device call's compiled shape — every
    static arg plus every aval dim of the reconstruct kernels.
    Shared by the miss counter, the AOT registry, and the shed check so
    the three can never disagree about what 'warm' means.  w_true only
    shapes the blockdiag kernels (the flat kernels' row select is purely
    data); normalizing it to 0 for flat keeps a warm plan's w_true=1
    probes valid for any wanted-set width with the same matrix shape.

    `place` is the r19 placement axis of the identity: 0 = the legacy
    default device, n >= 2 = lane-sharded over an n-device mesh (the
    sharded twin, compiled against NamedSharding avals), ("dev", d) = a
    whole-pin on mesh device d (an executable compiled for device 0
    cannot serve arrays committed to device d, so each owning device is
    its own compiled shape).  r20 grows the PROCESS dimension:
    ("pod", n_dev, n_hosts) = lane-sharded over a multi-controller
    global mesh (compiled with replicated output — a different program
    than the single-process n-wide twin), ("podev", d) = a whole-pin on
    GLOBAL lane d of a pod cache."""
    return (
        "fused" if kind == "fused" else kernel,
        groups,
        w_true if groups > 1 else 0,
        tile,
        fetch,
        n_bucket,
        k,
        tuple(a_shape),
        surv_len,
        bool(interpret),
        place,
    )


def _key_place(cache, place):
    """Map a cache placement to the _call_key placement element: the
    mesh width for lane-sharded vids, ("dev", d) for whole-pins on a
    mesh cache, 0 for the legacy single-device cache.  A multiprocess
    (pod) cache gets its own placement atoms — the SPMD executable with
    replicated output is a different program than the single-process
    sharded twin, and a pod whole-pin's owning device is a GLOBAL lane
    index resolved through the global mesh."""
    if place == "mesh":
        if cache.multiprocess:
            return ("pod", cache.n_devices, cache.n_hosts)
        return cache.n_devices
    if cache.mesh is not None:
        if cache.multiprocess:
            return ("podev", int(place))
        return ("dev", int(place))
    return 0


def _aot_executor() -> concurrent.futures.Executor:
    """Single-worker compile executor: AOT jobs run one at a time in
    submission order, so warm()'s observed-buckets-first priority IS the
    compile order even when several volumes pin at once."""
    global _AOT_EXECUTOR
    with _shapes_lock:
        if _AOT_EXECUTOR is None:
            _AOT_EXECUTOR = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ec-aot-compile"
            )
        return _AOT_EXECUTOR


def _compile_shape(key: tuple) -> None:
    """Build the Compiled executable for one call key (runs on the AOT
    executor).  Lowers against abstract avals only — no resident buffer
    is held while a 20-40s compile runs.  Placement rides in the avals:
    lane-sharded keys lower against NamedSharding'd ShapeDtypeStructs
    (the executable spans the mesh), whole-pin keys against the owning
    device, so a sharded volume's first read can hit a parked
    executable exactly like a single-device one."""
    (
        family, groups, w_true, tile, fetch, n_bucket, k, a_shape,
        surv_len, interpret, place,
    ) = key
    pod = isinstance(place, tuple) and place[0] == "pod"
    if (isinstance(place, int) and place >= 2) or pod:
        n_dev = place[1] if pod else place
        mesh = (
            mesh_mod.global_serving_mesh(n_dev)
            if pod
            else mesh_mod.serving_mesh(n_dev)
        )
        if mesh is None or int(mesh.devices.size) != n_dev:
            raise RuntimeError(
                f"serving mesh of {n_dev} devices unavailable"
            )
        if pod and len(mesh_mod.mesh_hosts(mesh)) != place[2]:
            raise RuntimeError(
                f"pod mesh spans {len(mesh_mod.mesh_hosts(mesh))} hosts, "
                f"key compiled for {place[2]}"
            )
        a_aval = jax.ShapeDtypeStruct(
            a_shape, jnp.int8, sharding=NamedSharding(mesh, P(None, None))
        )
        sv = NamedSharding(mesh, P(mesh_mod.SHARD_AXIS))
        survivors = tuple(
            jax.ShapeDtypeStruct((surv_len,), jnp.uint8, sharding=sv)
            for _ in range(k)
        )
        vec = jax.ShapeDtypeStruct(
            (n_dev, 2, n_bucket), jnp.int32,
            sharding=NamedSharding(mesh, P(mesh_mod.SHARD_AXIS, None, None)),
        )
        with _quiet_donation():
            exe = _sharded_gather_reconstruct.lower(
                a_aval, survivors, vec, mesh=mesh, tile=tile,
                groups=groups, w_true=w_true if groups > 1 else 1,
                kernel=family, interpret=interpret, k_true=k,
                replicate_out=pod,
            ).compile()
        _register_compiled(key, exe)
        return
    if isinstance(place, tuple):
        # whole-pin on mesh device place[1]: the avals commit there.
        # ("podev", d) resolves d through the GLOBAL mesh — a pod
        # cache's whole-pin lives on one global lane.
        mesh = (
            mesh_mod.global_serving_mesh(0)
            if place[0] == "podev"
            else mesh_mod.serving_mesh(0)
        )
        dev = mesh.devices.reshape(-1)[place[1]]
        from jax.sharding import SingleDeviceSharding

        sds = SingleDeviceSharding(dev)
        a_aval = jax.ShapeDtypeStruct(a_shape, jnp.int8, sharding=sds)
        survivors = tuple(
            jax.ShapeDtypeStruct((surv_len,), jnp.uint8, sharding=sds)
            for _ in range(k)
        )
        vec_sharding = sds
    else:
        a_aval = jax.ShapeDtypeStruct(a_shape, jnp.int8)
        survivors = tuple(
            jax.ShapeDtypeStruct((surv_len,), jnp.uint8) for _ in range(k)
        )
        vec_sharding = None

    def _vec_aval(shape):
        if vec_sharding is None:
            return jax.ShapeDtypeStruct(shape, jnp.int32)
        return jax.ShapeDtypeStruct(shape, jnp.int32, sharding=vec_sharding)

    with _quiet_donation():
        if family == "fused":
            vec = _vec_aval((n_bucket,))
            if groups > 1:
                lowered = _fused_reconstruct_blockdiag.lower(
                    a_aval, survivors, vec, tile=tile, fetch=fetch,
                    k_true=k, w_true=w_true, groups=groups,
                    interpret=interpret,
                )
            else:
                lowered = _fused_reconstruct.lower(
                    a_aval, survivors, vec, tile=tile, fetch=fetch,
                    k_true=k, interpret=interpret,
                )
        else:
            vec = _vec_aval((3, n_bucket))
            if groups > 1:
                lowered = _gather_reconstruct_blockdiag.lower(
                    a_aval, survivors, vec, tile=tile, fetch=fetch,
                    groups=groups, w_true=w_true, kernel=family,
                    interpret=interpret, k_true=k,
                )
            else:
                lowered = _gather_reconstruct.lower(
                    a_aval, survivors, vec, tile=tile, fetch=fetch,
                    kernel=family, interpret=interpret, k_true=k,
                )
        exe = lowered.compile()
    _register_compiled(key, exe)


def _register_compiled(key: tuple, exe) -> None:
    with _shapes_lock:
        _aot_executables[key] = exe
        # the shape is warm: a dispatch through the executable never
        # compiles, so the miss counter and shed check must see it
        _dispatched_shapes.add(key)
        _aot_pending.discard(key)
    stats_metrics.VOLUME_SERVER_EC_AOT_COMPILED.inc()


def _compile_shape_logged(key: tuple) -> None:
    # the compile executor's worker thread never inherits the caller's
    # tagging context, so warmup attribution is explicit here; a compile
    # occupies the (single) compile stream, not a serving slot, hence
    # its own class rather than folding into the requester's
    place = key[-1]
    dev_label = (
        "mesh" if isinstance(place, int) and place >= 2
        else str(place[1]) if isinstance(place, tuple)
        else "default"
    )
    t0 = time.perf_counter()
    try:
        with devledger.workload("warmup", device=dev_label):
            _compile_shape(key)
        devledger.record(
            workload="warmup", device=dev_label,
            busy_s=time.perf_counter() - t0, dispatches=1,
        )
    except Exception:  # noqa: BLE001 — a failed AOT compile must not
        # kill the executor; the shape stays cold and falls back to the
        # inline-compile path on a later non-shedding caller
        import logging

        logging.getLogger(__name__).exception(
            "AOT compile failed for shape %s", key
        )
        with _shapes_lock:
            _aot_pending.discard(key)
            _aot_failed.add(key)


def _schedule_aot_compiles(keys) -> list:
    """Queue cold call keys on the compile executor (dedup against the
    registry, the pending set, and inline-compiled shapes); returns the
    futures for callers that want to wait (warm)."""
    jobs = []
    with _shapes_lock:
        for key in keys:
            if (
                key in _aot_executables
                or key in _aot_pending
                or key in _dispatched_shapes
                or key in _aot_failed
            ):
                continue
            _aot_pending.add(key)
            jobs.append(key)
    if not jobs:
        return []
    ex = _aot_executor()
    return [ex.submit(_compile_shape_logged, key) for key in jobs]


def _shape_is_warm(key: tuple) -> bool:
    with _shapes_lock:
        return key in _dispatched_shapes or key in _aot_executables


def aot_stats() -> dict:
    """{"compiled", "pending", "failed"} — registry occupancy for
    status pages and tests."""
    with _shapes_lock:
        return {
            "compiled": len(_aot_executables),
            "pending": len(_aot_pending),
            "failed": len(_aot_failed),
        }


def _pack_calls_sharded(cache, requests, row_of, record_observed):
    """PACK stage for a lane-sharded volume: plan against the stripe
    width (requests split at stripe boundaries), partition each
    size-bucket group by OWNER DEVICE (stripe c lives on device c % n —
    the interleaving is what keeps ownership even at any volume size),
    and build per-device column lists of DEVICE-LOCAL offsets — device
    d's slots carry only d's requests, so the mesh does ~1/n of the
    batch's lane work per device.  Returns (calls, subs) with each call
    ("sharded", part, (dev_cols, width), 0, fetch, fetch, n_bucket,
    None): part entries are (sub_idx, sub, flat_row) where flat_row
    indexes the call's [n_dev * n_bucket, fetch] output (device-major),
    and fetch both gathers and ships — it covers every member's
    delta+take (backward-aligned deltas included), and the host trims
    the delta like the fused kernels' contract."""
    n_dev = cache.n_devices
    stripe = cache.stripe
    subs = _plan(requests, stripe)
    calls = []
    for bucket in SIZE_BUCKETS:
        group = [(i, s) for i, s in enumerate(subs) if s[4] == bucket]
        if not group:
            continue
        by_dev: list[list] = [[] for _ in range(n_dev)]
        for i, s in group:
            by_dev[(s[1] // stripe) % n_dev].append((i, s))
        widest = max(len(b) for b in by_dev)
        n_bucket = _bucket(COUNT_BUCKETS, min(widest, _max_count(bucket)))
        for start in range(0, widest, n_bucket):
            part = []
            dev_cols = []
            span = 0
            for d in range(n_dev):
                chunk = by_dev[d][start : start + n_bucket]
                # device-local offset of a global aligned offset o in
                # stripe c = o // stripe: the device holds its stripes
                # owner-major, so stripe c sits at local stripe index
                # c // n_dev
                offs = [
                    (s[1] // stripe // n_dev) * stripe + s[1] % stripe
                    for _, s in chunk
                ]
                rows = [row_of[requests[s[0]][0]] for _, s in chunk]
                dev_cols.append((offs, rows))
                for j, (i, s) in enumerate(chunk):
                    part.append((i, s, d * n_bucket + j))
                    span = max(span, s[2] + s[3])
            if record_observed:
                _note_observed(bucket, n_bucket)
            fetch = min(bucket, _fetch_cover(span))
            calls.append(
                ("sharded", part, (dev_cols, n_bucket), 0, fetch, fetch,
                 n_bucket, None)
            )
    return calls, subs


def _pack_calls(
    cache, vid, requests, kernel, interpret, layout, data_shards,
    total_shards, record_observed=True,
):
    """PACK stage: resolve the codec, split/align the requests, group
    them into device calls, and build every call's HOST-side columns
    (plain lists — numpy staging waits for the slot's arena).  Returns
    (calls, subs, survivors, a_prep, use, w_true) with each call a
    (kind, part, cols, pad, fetch, tile, n_bucket, deltas) tuple —
    nothing has touched the device yet, so a double-buffered caller can
    pack batch N+1 while batch N still owns a staging slot.
    `record_observed=False` keeps synthetic probes (warm's ladder walk)
    out of the observed-shape ranking, which must reflect live traffic
    only."""
    a_prep, survivors, row_of, use, w_true, place = _resolve_codec(
        cache, vid, requests, data_shards, total_shards, layout
    )
    groups = cache.groups if layout == "blockdiag" else 1
    if place == "mesh":
        # lane-sharded volume: one cross-device program per call — the
        # planner routes every sub-request to the device owning its
        # gather window, so the fused single-device DMA kernels do not
        # apply (the sharded twin IS the batched gather)
        calls, subs = _pack_calls_sharded(
            cache, requests, row_of, record_observed
        )
        return calls, subs, survivors, a_prep, use, w_true, place
    fused = _use_fused(kernel, interpret)
    subs = _plan(requests)
    calls = []
    for bucket in SIZE_BUCKETS:
        group = [(i, s) for i, s in enumerate(subs) if s[4] == bucket]
        if not group:
            continue
        n_bucket = _bucket(COUNT_BUCKETS, min(len(group), _max_count(bucket)))
        for start in range(0, len(group), n_bucket):
            part = group[start : start + n_bucket]
            pad = n_bucket - len(part)
            if record_observed:
                _note_observed(bucket, n_bucket)
            if fused:
                # fetch covers the realigned delta+take (the host trims
                # the delta head after D2H; no in-kernel shift needed)
                packed, deltas, fetch = _fused_vectors(
                    part, requests, row_of
                )
                if layout == "blockdiag":
                    fetch, tile = _blockdiag_fetch_tile(fetch, groups)
                else:
                    tile = _fused_tile_for(fetch)
                calls.append(
                    ("fused", part, packed, pad, fetch, tile, n_bucket,
                     deltas)
                )
            else:
                cols = _group_vectors(part, requests, row_of)
                # D2H width: power-of-two cover of the largest actual
                # request in this call, never wider than the compute tile
                max_take = max(s[3] for _, s in part)
                fetch = min(bucket, 1 << (max_take - 1).bit_length())
                calls.append(
                    ("xla", part, cols, pad, fetch, bucket, n_bucket,
                     None)
                )
    return calls, subs, survivors, a_prep, use, w_true, place


def _stage_call_vec(kind, cols, pad, arena=None) -> np.ndarray:
    """Materialize one call's host staging vector — [n] packed int32
    (fused) or [3, n] int32 (xla fallback) — into the held slot's arena
    when one is supplied (TPU: device_put copies, so the pinned arena
    block is reused batch after batch with zero host allocs) or a fresh
    array otherwise (CPU PJRT zero-copies aligned numpy into the jax
    Array, so a reused buffer would alias an asynchronously executing
    call's input)."""
    if kind == "sharded":
        # [n_dev, 2, width] per-device (local offset, wanted row)
        # slots: the NamedSharding put splits this host-side and ships
        # each device exactly its own requests — never through the
        # arena (one pinned block cannot back a device-sharded put)
        dev_cols, width = cols
        vec = np.zeros((len(dev_cols), 2, width), dtype=np.int32)
        for d, (offs, rows) in enumerate(dev_cols):
            vec[d, 0, : len(offs)] = offs
            vec[d, 1, : len(rows)] = rows
        return vec
    if kind == "fused":
        if arena is not None:
            return arena.stage_fused(cols, pad)
        return np.array(cols + [0] * pad, dtype=np.int32)
    offsets, rows, deltas = cols
    if arena is not None:
        return arena.stage_xla(offsets, rows, deltas, pad)
    return np.array(
        [col + [0] * pad for col in (offsets, rows, deltas)],
        dtype=np.int32,
    )


def _dispatch_call(
    kind, vec, a_prep, survivors, n_use, w_true, groups, tile,
    fetch, kernel, interpret, key=None, mesh=None, replicate_out=False,
):
    """Route one packed call's staged vector to its kernel — the single
    home of the fused/xla x flat/blockdiag dispatch, shared by
    reconstruct_intervals' drain loop and make_batched_call's bench
    thunk so the benchmark can never measure a different compiled shape
    than the serving path dispatches.  An AOT-compiled executable for
    the call's shape takes precedence: the jit wrappers' caches never
    see AOT-warmed shapes, so routing through the registry is what makes
    the background compile actually serve.  `key` is the call's
    _call_key when the caller already computed it (the serving drain
    loop shares one key list between the shed gate, the miss counter,
    and this lookup — recomputing here from the staged vec could drift
    from the gate's notion of "warm")."""
    if key is None:
        key = _call_key(
            kind, kernel, groups, w_true, tile, fetch, vec.shape[-1],
            n_use, a_prep.shape, int(survivors[0].size), interpret,
        )
    exe = _aot_executables.get(key)
    if exe is not None:
        return exe(a_prep, survivors, vec)
    with _quiet_donation():
        if kind == "sharded":
            return _sharded_gather_reconstruct(
                a_prep, survivors, vec, mesh=mesh, tile=tile,
                groups=groups, w_true=w_true if groups > 1 else 1,
                kernel=kernel, interpret=interpret, k_true=n_use,
                replicate_out=replicate_out,
            )
        if kind == "fused":
            if groups > 1:
                return _fused_reconstruct_blockdiag(
                    a_prep, survivors, vec, tile=tile, fetch=fetch,
                    k_true=n_use, w_true=w_true, groups=groups,
                    interpret=interpret,
                )
            return _fused_reconstruct(
                a_prep, survivors, vec, tile=tile, fetch=fetch,
                k_true=n_use, interpret=interpret,
            )
        if groups > 1:
            return _gather_reconstruct_blockdiag(
                a_prep, survivors, vec, tile=tile, fetch=fetch,
                groups=groups, w_true=w_true, kernel=kernel,
                interpret=interpret, k_true=n_use,
            )
        return _gather_reconstruct(
            a_prep, survivors, vec, tile=tile, fetch=fetch,
            kernel=kernel, interpret=interpret, k_true=n_use,
        )


def reconstruct_intervals(
    cache: DeviceShardCache,
    vid: int,
    requests: list[tuple[int, int, int]],
    kernel: str | None = None,
    interpret: bool | None = None,
    data_shards: int = DATA_SHARDS,
    total_shards: int = TOTAL_SHARDS,
    layout: str | None = None,
    record_observed: bool = True,
) -> list[bytes]:
    """Reconstruct interval bytes for a batch of degraded reads in as few
    device calls as possible (one per size bucket actually present).

    requests: [(wanted_shard_id, shard_offset, size)].  All gather inputs
    are resident shards; per-call H2D is just the offset/row vectors and
    D2H is exactly the reconstructed bytes.  Raises CacheMiss when fewer
    than `data_shards` non-wanted shards of `vid` are resident.

    `layout` (None = the cache's active layout) picks the kernel family:
    "blockdiag" serves through the block-diagonal g-group system (the
    ~157 GB/s round-3 kernel), "flat" the plain one.  The call is staged
    pack -> H2D -> execute -> D2H: packing runs before a staging slot is
    taken (cache.pipeline, 2 slots = double buffering), so a concurrent
    batch packs and ships while the previous one executes and only each
    batch's own D2H blocks it.  Every stage is a trace span feeding
    SeaweedFS_request_stage_seconds."""
    if not requests:
        return []
    if kernel is None:
        kernel = "pallas" if rs_tpu.on_tpu() else "xla"
    if interpret is None:
        interpret = not rs_tpu.on_tpu()
    if layout is None:
        layout = cache.layout
    if layout not in LAYOUTS:
        raise ValueError(f"unknown resident layout {layout!r}")
    groups = cache.groups if layout == "blockdiag" else 1
    fused = _use_fused(kernel, interpret)
    with obs_trace.span(
        "batch_pack", requests=len(requests), layout=layout
    ):
        calls, subs, survivors, a_prep, use, w_true, place = _pack_calls(
            cache, vid, requests, kernel, interpret, layout,
            data_shards, total_shards, record_observed,
        )
    surv_len = int(survivors[0].size)
    key_place = _key_place(cache, place)
    call_keys = [
        _call_key(
            kind, kernel, groups, w_true, tile, fetch, n_bucket,
            len(use), a_prep.shape, surv_len, interpret, key_place,
        )
        for kind, _part, _cols, _pad, fetch, tile, n_bucket, _d in calls
    ]
    # AOT shed gate: a volume with a warm plan must never pay an inline
    # compile on the serving path — a still-cold shape goes BACK to the
    # caller (host reconstruct) before any device work, and the compile
    # runs on the background executor so the next read finds it warm
    if cache.shed_cold and cache.aot_state(vid) != "none":
        cold = [key for key in call_keys if not _shape_is_warm(key)]
        if cold:
            _schedule_aot_compiles(cold)
            stats_metrics.VOLUME_SERVER_EC_SHED_COLD_SHAPE.inc(
                len(requests)
            )
            stats_metrics.VOLUME_SERVER_EC_READ_ROUTE.labels(
                route="shed_cold_shape"
            ).inc(len(requests))
            # flight recorder: the shed decision, trace-stamped — an
            # incident bundle can say "this tail read hit a cold shape"
            obs_incident.record(
                "cold_shape_shed", vid=vid, requests=len(requests),
                cold_shapes=len(cold),
            )
            raise ColdShape(
                f"vid {vid}: {len(cold)} device shape(s) still AOT-cold"
            )
    # the device-execute stage of the request trace: every dispatched
    # call's H2D/D2H bytes and compile-cache outcome annotate the span
    # (and the SeaweedFS_volumeServer_ec_device_* counters), so a slow
    # read can say "compile cliff" or "tunnel-bound fetch" by itself
    dev_span = obs_trace.span(
        "device_execute", requests=len(requests), layout=layout,
        kernel=(("sharded_" if place == "mesh" else
                 "fused_" if fused else "")
                + ("blockdiag" if groups > 1 else kernel)),
    )
    dev_calls = dev_misses = dev_h2d = dev_d2h = 0
    sub_out: list[bytes | None] = [None] * len(subs)

    # PIPELINE: dispatch device calls ahead of fetching results (jax
    # dispatch is async — each call's H2D and compute start immediately).
    # On tunneled rigs this overlaps the per-call dispatch RTT and D2H of
    # call N with the compute of call N+1 instead of paying them serially
    # per size bucket.  Aggregate un-fetched output is bounded: every
    # pending call holds its [n, fetch] result in HBM, so a huge batch
    # must drain the oldest call before dispatching more.
    pending: list[tuple] = []
    pending_bytes = 0

    def _finish(entry) -> int:
        part, arr, fetch, deltas, key, t_dispatch, wire_bytes = entry
        nbytes = int(arr.size)  # padded rows ride the fetch too
        # completion boundary BEFORE the d2h span: jax dispatch is
        # async, so without it the fetch would absorb the kernel's
        # remaining execute time and an MXU/compile regression would
        # read as "tunnel-bound fetch" in the stage histogram — the
        # blocking wait lands in device_execute, where it belongs
        arr.block_until_ready()
        # the hot-shape view's latency sample: dispatch -> result ready
        # (pipelined calls include their wait behind siblings)
        _note_call_latency(key, time.perf_counter() - t_dispatch)
        with obs_trace.span("d2h_copy", bytes=nbytes):
            out = np.asarray(arr).reshape(-1, fetch)
        stats_metrics.VOLUME_SERVER_EC_D2H_BYTES.inc(nbytes)
        if deltas is not None:  # fused: host trims the alignment delta
            for j, (sub_idx, (_, _, _, take, _)) in enumerate(part):
                d = deltas[j]
                sub_out[sub_idx] = out[j, d : d + take].tobytes()
        elif part and len(part[0]) == 3:
            # sharded: part entries carry their flat output row (the
            # call's [n_dev * n_bucket, fetch] layout is device-major,
            # with padded slots between devices); the host trims the
            # delta — backward-aligned windows fold theirs into it
            for sub_idx, (_, _, delta, take, _), row in part:
                sub_out[sub_idx] = out[row, delta : delta + take].tobytes()
        else:  # XLA fallback: delta was shifted on device iff narrowed
            bucket = part[0][1][4]
            for j, (sub_idx, (_, _, delta, take, _)) in enumerate(part):
                lo = 0 if fetch < bucket else delta
                sub_out[sub_idx] = out[j, lo : lo + take].tobytes()
        return wire_bytes

    # the ledger's device label follows placement; the workload class is
    # whatever the caller tagged (devledger.current_workload()) — the
    # serving dispatcher / scrub loop / repair handler set it at the edge
    dev_label = (
        "mesh" if place == "mesh"
        else str(int(place)) if cache.mesh is not None
        else "default"
    )
    with devledger.device(dev_label), cache.pipeline.slot() as pslot, dev_span:
        slot_wait_s = pslot.wait_s
        # the slot's preallocated arena only where device_put COPIES
        # (TPU/GPU); the CPU PJRT client zero-copies aligned numpy, so a
        # reused block would alias an asynchronously executing call's
        # input (see StagingArena)
        arena = pslot.arena if rs_tpu.on_tpu() else None
        for call, key in zip(calls, call_keys):
            kind, part, cols, pad, fetch, tile, n_bucket, deltas = call
            # H2D: stage + ship this call's packed host vector (ONE
            # int32 array per call — fused meta is a single packed row,
            # the r09 [2, N]/three-vector forms are gone).  Tiny, but on
            # a tunneled rig each transfer pays a dispatch RTT — making
            # it a named stage is what lets the stage histogram show
            # whether h2d or execute owns a regression.
            vec_np = _stage_call_vec(kind, cols, pad, arena)
            h2d_bytes = int(vec_np.nbytes)
            with obs_trace.span("h2d_copy", bytes=h2d_bytes):
                # sharding-aware staging: the vector lands directly on
                # the owning device(s) — split across the mesh for a
                # sharded call (each device receives only its own
                # requests' slots), committed to the claimed device for
                # a whole-pin, default device otherwise
                if kind == "sharded":
                    vec_sharding = NamedSharding(
                        cache.mesh, P(mesh_mod.SHARD_AXIS, None, None)
                    )
                    if cache.multiprocess:
                        # pod mesh: only THIS process's request rows are
                        # addressable here — ship exactly our lanes'
                        # slice (the local rows are contiguous in the
                        # canonical device order).  This is the only
                        # payload that crosses toward remote lanes, and
                        # it is request metadata, never survivor bytes.
                        lo = cache._local_dev_indices[0]
                        hi = cache._local_dev_indices[-1] + 1
                        dev_vec = jax.make_array_from_process_local_data(
                            vec_sharding, vec_np[lo:hi], vec_np.shape
                        )
                    else:
                        dev_vec = jax.device_put(vec_np, vec_sharding)
                elif cache.mesh is not None:
                    dev_vec = jax.device_put(
                        vec_np, cache.mesh.devices.reshape(-1)[int(place)]
                    )
                else:
                    dev_vec = jnp.asarray(vec_np)
                # the put is async too: wait it out INSIDE the span so
                # the stage measures the transfer, not the enqueue —
                # and so the arena rows are safe to reuse for the next
                # call once the copy has landed
                dev_vec.block_until_ready()
            stats_metrics.VOLUME_SERVER_EC_H2D_BYTES.inc(h2d_bytes)
            dev_h2d += h2d_bytes
            # the call key tracks the prepared matrix's shape EXACTLY
            # as retracing does: blockdiag kernels take w_true static
            # (and a_blk rows = 8*pad4(g*w_true) moves with it), while
            # the flat kernels only retrace when pad4(w_true) changes
            # a_bm's shape — keying on the shape neither misses a real
            # compile nor counts phantom ones
            dev_misses += _note_shape(key)
            t_dispatch = time.perf_counter()
            arr = _dispatch_call(
                kind, dev_vec, a_prep, survivors, len(use), w_true,
                groups, tile, fetch, kernel, interpret, key=key,
                mesh=cache.mesh if kind == "sharded" else None,
                replicate_out=cache.multiprocess,
            )
            # the padded rows ride the wire too: count what the fetch
            # actually moves, not just the useful subset (a sharded
            # call fetches every device's n_bucket rows)
            wire_rows = n_bucket * (
                cache.n_devices if kind == "sharded" else 1
            )
            pending.append(
                (part, arr, fetch, deltas, key, t_dispatch,
                 wire_rows * fetch)
            )
            pending_bytes += wire_rows * fetch
            dev_calls += 1
            dev_d2h += wire_rows * fetch
            while pending_bytes > _MAX_PENDING_OUT and len(pending) > 1:
                pending_bytes -= _finish(pending.pop(0))
        for entry in pending:
            _finish(entry)
        dev_span.annotate(
            device_calls=dev_calls, compile_misses=dev_misses,
            h2d_bytes=dev_h2d, d2h_bytes=dev_d2h,
            slot_wait_us=int(slot_wait_s * 1e6),
        )
        stats_metrics.VOLUME_SERVER_EC_DEVICE_H2D_BYTES.inc(dev_h2d)
        stats_metrics.VOLUME_SERVER_EC_DEVICE_D2H_BYTES.inc(dev_d2h)
        # busy/queue-wait are the slot's (recorded on exit); dispatches
        # and boundary bytes are this batch's
        devledger.record(dispatches=dev_calls, nbytes=dev_h2d + dev_d2h)
    outputs: list[list[bytes]] = [[] for _ in requests]
    for (idx, *_), piece in zip(subs, sub_out):
        outputs[idx].append(piece)  # subs are in offset order per request
    # throttled observed-shape save (satellite: the warm/AOT priority
    # order survives restarts) — off the device path, after the batch
    _maybe_persist_observed()
    return [b"".join(parts) for parts in outputs]


def make_batched_call(
    cache: DeviceShardCache,
    vid: int,
    requests: list[tuple[int, int, int]],
    kernel: str | None = None,
    interpret: bool | None = None,
    layout: str | None = None,
):
    """Zero-arg thunk running the ONE device call a homogeneous batch of
    requests (same size bucket, count <= COUNT_BUCKETS[-1]) maps to,
    returning the un-copied device array — bench.py profiler-times the
    serving call with this, without host copies in the measured region.
    `layout` follows the cache's active layout by default."""
    if kernel is None:
        kernel = "pallas" if rs_tpu.on_tpu() else "xla"
    if interpret is None:
        interpret = not rs_tpu.on_tpu()
    if layout is None:
        layout = cache.layout
    groups = cache.groups if layout == "blockdiag" else 1
    a_prep, survivors, row_of, use, w_true, place = _resolve_codec(
        cache, vid, requests, DATA_SHARDS, TOTAL_SHARDS, layout
    )
    if place == "mesh":
        # lane-sharded volume: the bench thunk runs the same ONE-call
        # contract through the sharded twin (the serving path's calls
        # route per-device; a homogeneous batch is one call there too)
        calls, _subs = _pack_calls_sharded(
            cache, requests, row_of, record_observed=False
        )
        if len(calls) != 1:
            raise ValueError(
                "bench batch must be one homogeneous bucket group"
            )
        kind, _p, cols, pad, fetch, tile, n_bucket, _d = calls[0]
        key = _call_key(
            kind, kernel, groups, w_true, tile, fetch, n_bucket,
            len(use), a_prep.shape, int(survivors[0].size), interpret,
            _key_place(cache, place),
        )

        def sharded_thunk():
            vec_np = _stage_call_vec(kind, cols, pad)
            sharding = NamedSharding(
                cache.mesh, P(mesh_mod.SHARD_AXIS, None, None)
            )
            if cache.multiprocess:
                lo = cache._local_dev_indices[0]
                hi = cache._local_dev_indices[-1] + 1
                vec = jax.make_array_from_process_local_data(
                    sharding, vec_np[lo:hi], vec_np.shape
                )
            else:
                vec = jax.device_put(vec_np, sharding)
            # graftlint: allow(untagged-device-dispatch): bench thunk —
            # the profiler times this measured region externally; ledger
            # tagging inside it would bill bench time to a serving class
            return _dispatch_call(
                kind, vec, a_prep, survivors, len(use), w_true, groups,
                tile, fetch, kernel, interpret, key=key, mesh=cache.mesh,
                replicate_out=cache.multiprocess,
            )

        return sharded_thunk
    subs = _plan(requests)
    buckets = {s[4] for s in subs}
    if len(buckets) != 1 or len(subs) > COUNT_BUCKETS[-1]:
        raise ValueError("bench batch must be one homogeneous bucket group")
    bucket = buckets.pop()
    part = list(enumerate(subs))
    # NOTE: deliberately NOT _pack_calls — the bench thunk keeps the
    # whole homogeneous batch in ONE device call (its contract), while
    # _pack_calls would split wide large-size batches at _max_count.
    pad = _bucket(COUNT_BUCKETS, len(part)) - len(part)
    if _use_fused(kernel, interpret):
        kind = "fused"
        cols, _deltas, fetch = _fused_vectors(part, requests, row_of)
        if groups > 1:
            fetch, tile = _blockdiag_fetch_tile(fetch, groups)
        else:
            tile = _fused_tile_for(fetch)
    else:
        kind = "xla"
        cols = _group_vectors(part, requests, row_of)
        max_take = max(s[3] for _, s in part)
        fetch = min(bucket, 1 << (max_take - 1).bit_length())
        tile = bucket

    # the staging vector is built FRESH inside the thunk: the kernels
    # DONATE it, so a captured device array would be invalid on the
    # second timed invocation — and shipping per call is exactly what
    # the serving path pays per batch, so the bench measures that too
    key = _call_key(
        kind, kernel, groups, w_true, tile, fetch,
        pad + len(part), len(use), a_prep.shape,
        int(survivors[0].size), interpret, _key_place(cache, place),
    )

    def thunk():
        vec_np = _stage_call_vec(kind, cols, pad)
        if cache.mesh is not None:
            vec = jax.device_put(
                vec_np, cache.mesh.devices.reshape(-1)[int(place)]
            )
        else:
            vec = jnp.asarray(vec_np)
        # graftlint: allow(untagged-device-dispatch): bench thunk — see
        # sharded_thunk above; the measured region stays ledger-free
        return _dispatch_call(
            kind, vec, a_prep, survivors, len(use), w_true, groups,
            tile, fetch, kernel, interpret, key=key,
        )

    return thunk


# per-segment mismatch sums stay < 2^28 < int31, so a wholesale-corrupt
# multi-GB shard cannot wrap the (x64-disabled) int32 accumulator; the
# host adds the [p, n_seg] partials with Python ints
_SCRUB_SEG = 1 << 28


@functools.partial(
    jax.jit, static_argnames=("n_lanes", "kernel", "interpret")
)
def _scrub_call(a_bm, data, parity, *, n_lanes, kernel, interpret):
    """data: tuple of 10 resident [L_pad] u8 shards; parity: tuple of 4.
    Recompute parity over the first n_lanes bytes and count mismatching
    bytes per parity shard — the ONLY thing that leaves the device is the
    [p, n_seg] int32 mismatch partials, which is what makes scrubbing the
    one serving-family op a tunneled device wins end-to-end: ~1.4 bytes
    of compute per byte held, ~0 bytes moved."""
    x = jnp.stack([d[:n_lanes] for d in data])
    out = rs_tpu.apply_matrix_device(
        a_bm, x, kernel=kernel, interpret=interpret, k_true=len(data)
    )
    rows = []
    for j in range(len(parity)):
        diff = out[j] != parity[j][:n_lanes]
        rows.append(
            jnp.stack(
                [
                    jnp.sum(diff[s : s + _SCRUB_SEG].astype(jnp.int32))
                    for s in range(0, n_lanes, _SCRUB_SEG)
                ]
            )
        )
    return jnp.stack(rows)


@functools.partial(
    jax.jit, static_argnames=("n_lanes", "groups", "kernel", "interpret")
)
def _scrub_call_blockdiag(
    a_blk, data, parity, *, n_lanes, groups, kernel, interpret
):
    """Block-diagonal scrub: the verified span splits into `groups`
    contiguous segments per shard (the host-staged segment stacking —
    slices of the same resident buffers), one apply of the blockdiag
    parity system recomputes every segment's parity, and group jg's
    output rows compare against parity segment jg.  Same contract as
    _scrub_call: only the [p, n_seg] int32 mismatch partials leave the
    device."""
    k = len(data)
    p = len(parity)
    seg = n_lanes // groups
    x = jnp.concatenate(
        [
            data[i][jg * seg : (jg + 1) * seg][None, :]
            for jg in range(groups)
            for i in range(k)
        ],
        axis=0,
    )  # [g*k, seg], segment-stacked
    out = rs_tpu.apply_matrix_device(
        a_blk, x, kernel=kernel, interpret=interpret, k_true=groups * k
    )
    rows = []
    for j in range(p):
        diff = jnp.concatenate(
            [
                out[jg * p + j] != parity[j][jg * seg : (jg + 1) * seg]
                for jg in range(groups)
            ]
        )
        rows.append(
            jnp.stack(
                [
                    jnp.sum(diff[s : s + _SCRUB_SEG].astype(jnp.int32))
                    for s in range(0, n_lanes, _SCRUB_SEG)
                ]
            )
        )
    return jnp.stack(rows)


def scrub_volume(
    cache: DeviceShardCache,
    vid: int,
    kernel: str | None = None,
    interpret: bool | None = None,
    data_shards: int = DATA_SHARDS,
    total_shards: int = TOTAL_SHARDS,
    layout: str | None = None,
) -> tuple[list[int], int]:
    """Parity scrub of a fully resident volume: -> (per-parity-shard
    mismatch byte counts, bytes verified per shard).  Raises CacheMiss
    unless ALL shards are resident.  The verified span rounds the true
    shard size UP to the lane tile (blockdiag: to groups lane tiles, so
    every segment slice stays lane-aligned) — cache buffers are
    zero-padded and parity-of-zeros is zero, so the extra lanes verify
    trivially instead of costing a per-shard tail fetch (each tiny D2H
    pays a full tunnel round-trip).  `layout` (None = cache's active
    layout) picks the kernel: blockdiag runs the scrub matmul on the
    ~157 GB/s round-3 system."""
    if kernel is None:
        kernel = "pallas" if rs_tpu.on_tpu() else "xla"
    if interpret is None:
        interpret = not rs_tpu.on_tpu()
    if layout is None:
        layout = cache.layout
    resident = cache.shard_ids(vid)
    if len(resident) < total_shards:
        raise CacheMiss(
            f"vid {vid}: {len(resident)}/{total_shards} shards resident"
        )
    sizes = {cache.shard_size(vid, s) for s in range(total_shards)}
    if len(sizes) != 1:
        raise CacheMiss(f"vid {vid}: resident shard sizes differ: {sizes}")
    true_size = sizes.pop()
    parity_m = gf256.build_matrix(data_shards, total_shards)[data_shards:]
    data = tuple(cache.get(vid, s) for s in range(data_shards))
    parity = tuple(
        cache.get(vid, s) for s in range(data_shards, total_shards)
    )
    if any(s is None for s in data + parity):
        raise CacheMiss(f"vid {vid}: shard evicted mid-scrub")
    if cache.vid_sharded(vid):
        # lane-sharded buffers are stripe-PERMUTED on device: parity is
        # byte-wise, so verifying the permuted layout is positionally
        # consistent across shards — but a true_size-bounded span would
        # cover an arbitrary stripe subset, so scrub the WHOLE padded
        # buffer (the zero padding verifies trivially: parity of zeros
        # is zero, identically placed in every shard)
        true_size = int(data[0].size)
    # scrub is scrub no matter who invoked it (the background loop, the
    # shell verb, a repair preflight) — pin the ledger class here, where
    # the dispatch happens
    t0 = time.perf_counter()
    if layout == "blockdiag":
        quant = cache.groups * LANE
        n_lanes = -(-true_size // quant) * quant
        a_blk = _prepared_blockdiag_matrix(
            parity_m.tobytes(), *parity_m.shape, cache.groups
        )
        with devledger.workload("scrub"):
            # graftlint: allow(device-sync): deliberate D2H of the tiny
            # [p, n_seg] int32 mismatch partials — the whole point of
            # scrub is that only this verdict leaves the device
            partials = np.asarray(
                _scrub_call_blockdiag(
                    a_blk, data, parity,
                    n_lanes=n_lanes, groups=cache.groups,
                    kernel=kernel, interpret=interpret,
                )
            )
    else:
        n_lanes = -(-true_size // LANE) * LANE
        a_bm = _prepared_matrix(parity_m.tobytes(), *parity_m.shape)
        with devledger.workload("scrub"):
            # graftlint: allow(device-sync): deliberate D2H of the tiny
            # [p, n_seg] int32 mismatch partials (see blockdiag branch)
            partials = np.asarray(
                _scrub_call(
                    a_bm, data, parity,
                    n_lanes=n_lanes, kernel=kernel, interpret=interpret,
                )
            )
    devledger.record(
        workload="scrub", busy_s=time.perf_counter() - t0,
        dispatches=1, nbytes=int(partials.nbytes),
    )
    stats_metrics.VOLUME_SERVER_EC_SCRUB_DISPATCH.labels(
        mode="per_volume"
    ).inc()
    return [int(row.sum(dtype=np.int64)) for row in partials], n_lanes


# --- fused multi-volume scrub megakernel -------------------------------------
#
# Per-volume scrub re-pays one device dispatch (plus a tunnel RTT on
# remote rigs) per pinned volume even though every input already sits in
# HBM.  The megakernel walks the WHOLE resident cache in one pass: every
# volume shares the same block-diagonal parity system (the per-volume
# matrices stacked block-diagonally are just the SAME cached a_blk the
# per-volume scrub uses), so V volumes stack along the LANE axis — x is
# [g*k, V*seg] with volume v's segment-stacked rows occupying its seg
# lanes — and one matmul recomputes every volume's parity at the same
# per-byte MXU cost as the per-volume loop.  (Expanding the matrix to
# V*g blocks instead would multiply the dense contraction V-fold; the
# lane stack keeps compute linear and amortizes only what is actually
# per-call: dispatch, trace, RTT.)  The per-chunk verdict reduction
# happens on device exactly as in _scrub_call: only the [V, p, n_seg]
# int32 mismatch partials come back, and the host reduces them to a
# per-volume verdict bitmap.
#
# Stacks are padded to a power-of-two volume count (repeating the first
# volume) so the compile ladder stays a handful of shapes per n_lanes
# class, not one per cache occupancy; _SCRUB_STACK_CAP bounds a single
# call's runtime and the pow2 padding waste.

_SCRUB_STACK_CAP = 32  # max volumes fused into one device call
# max stacked input bytes per fused call: the lane stack materializes
# the chunk's full (k+p)*n_lanes shard bytes AGAIN next to the resident
# copies (plus the recomputed-parity output), so a count-only cap could
# OOM a near-capacity cache during the scrub pre-pass — chunks are
# bounded by transient bytes too, not just volume count
_SCRUB_STACK_BYTES = 256 << 20


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_lanes", "groups", "vols", "k", "p", "kernel", "interpret",
    ),
)
def _scrub_all_call(
    a_blk, shards, *, n_lanes, groups, vols, k, p, kernel, interpret
):
    """shards: flat tuple of vols*(k+p) resident buffers, volume-major
    (k data then p parity per volume); a_blk the SAME per-volume
    blockdiag parity system scrub_volume applies.  One matmul over the
    lane-stacked [g*k, vols*seg] input recomputes every volume's parity
    over its first n_lanes bytes; -> [vols, p, n_seg] int32 mismatch
    partials (the only D2H)."""
    seg = n_lanes // groups
    x = jnp.stack(
        [
            # row jg*k + i: shard i's segment jg, all volumes
            # concatenated along lanes
            jnp.concatenate(
                [
                    shards[v * (k + p) + i][jg * seg : (jg + 1) * seg]
                    for v in range(vols)
                ]
            )
            for jg in range(groups)
            for i in range(k)
        ]
    )  # [groups*k, vols*seg]
    out = rs_tpu.apply_matrix_device(
        a_blk, x, kernel=kernel, interpret=interpret,
        k_true=groups * k,
    )
    rows = []
    for v in range(vols):
        vrows = []
        for j in range(p):
            diff = jnp.concatenate(
                [
                    out[jg * p + j][v * seg : (v + 1) * seg]
                    != shards[v * (k + p) + k + j][jg * seg : (jg + 1) * seg]
                    for jg in range(groups)
                ]
            )
            vrows.append(
                jnp.stack(
                    [
                        jnp.sum(diff[s : s + _SCRUB_SEG].astype(jnp.int32))
                        for s in range(0, n_lanes, _SCRUB_SEG)
                    ]
                )
            )
        rows.append(jnp.stack(vrows))
    return jnp.stack(rows)


def scrub_all_resident(
    cache: DeviceShardCache,
    kernel: str | None = None,
    interpret: bool | None = None,
    data_shards: int = DATA_SHARDS,
    total_shards: int = TOTAL_SHARDS,
    layout: str | None = None,
    vids: list[int] | None = None,
) -> tuple[dict[int, tuple[list[int], int]], dict]:
    """Parity-scrub EVERY fully resident volume (or the `vids` subset)
    in as few device passes as possible: volumes with equal verified
    spans stack into one block-diagonal megakernel call, amortizing
    dispatch + H2D over the whole cache.  -> ({vid: (per-parity-shard
    mismatch byte counts, bytes verified per shard)}, {"device_calls",
    "volumes"}).  Volumes that stop qualifying mid-pass (eviction, size
    mismatch) are silently absent from the result — the caller's
    per-volume path still owns them."""
    if kernel is None:
        kernel = "pallas" if rs_tpu.on_tpu() else "xla"
    if interpret is None:
        interpret = not rs_tpu.on_tpu()
    if layout is None:
        layout = cache.layout
    groups = cache.groups if layout == "blockdiag" else 1
    k = data_shards
    p = total_shards - data_shards
    quant = groups * LANE
    if vids is None:
        vids = sorted(cache.resident_by_vid())
    # ((n_lanes, placement), [(vid, shard tuple)]) stacks: only fully
    # resident, uniform-size volumes qualify (same rule as
    # scrub_volume).  Placement is part of the stack key: one
    # _scrub_all_call's inputs must share a device set — stacking a
    # device-0 whole-pin with a device-1 one (or a mesh-sharded volume)
    # is a jit device-mismatch ValueError, not a slow path
    stacks: dict[tuple[int, object], list[tuple[int, tuple]]] = {}
    for vid in vids:
        if cache.resident_count(vid) < total_shards:
            continue
        sizes = {cache.shard_size(vid, s) for s in range(total_shards)}
        if len(sizes) != 1 or None in sizes:
            continue
        shards = tuple(cache.get(vid, s) for s in range(total_shards))
        if any(s is None for s in shards):
            continue
        size = sizes.pop()
        if cache.vid_sharded(vid):
            # permuted stripe layout: scrub the whole padded buffer
            # (see scrub_volume — positional consistency holds, a
            # true_size-bounded span would cover an arbitrary subset)
            size = int(shards[0].size)
        n_lanes = -(-size // quant) * quant
        place = cache.placement(vid)
        stacks.setdefault((n_lanes, 0 if place is None else place), []).append(
            (vid, shards)
        )
    parity_m = gf256.build_matrix(data_shards, total_shards)[data_shards:]
    # the SAME prepared system scrub_volume uses (one cached device
    # copy): volumes stack along lanes, never into a bigger matrix
    a_blk = _prepared_blockdiag_matrix(
        parity_m.tobytes(), *parity_m.shape, groups
    )
    results: dict[int, tuple[list[int], int]] = {}
    device_calls = 0
    for (n_lanes, _place), members in sorted(
        stacks.items(), key=lambda kv: (kv[0][0], str(kv[0][1]))
    ):
        # bound the call's transient HBM (see _SCRUB_STACK_BYTES); the
        # step stays a power of two so the pow2 volume padding below
        # never re-crosses the byte cap
        fit = max(1, _SCRUB_STACK_BYTES // (n_lanes * (k + p)))
        step = min(_SCRUB_STACK_CAP, 1 << (fit.bit_length() - 1))
        for start in range(0, len(members), step):
            chunk = members[start : start + step]
            # pad to the power-of-two volume bucket by repeating the
            # first volume: compile shapes quantize to the bucket
            # ladder, and the duplicate lanes' partials are dropped
            vols = 1 << (len(chunk) - 1).bit_length()
            padded = chunk + [chunk[0]] * (vols - len(chunk))
            flat = tuple(s for _vid, shards in padded for s in shards)
            t0 = time.perf_counter()
            with devledger.workload("scrub"):
                # graftlint: allow(device-sync): deliberate D2H — the
                # [V, p, n_seg] mismatch partials are the megakernel's
                # only output, host-reduced to per-volume verdict bitmaps
                partials = np.asarray(
                    _scrub_all_call(
                        a_blk, flat, n_lanes=n_lanes, groups=groups,
                        vols=vols, k=k, p=p, kernel=kernel,
                        interpret=interpret,
                    )
                )
            devledger.record(
                workload="scrub", busy_s=time.perf_counter() - t0,
                dispatches=1, nbytes=int(partials.nbytes),
            )
            device_calls += 1
            stats_metrics.VOLUME_SERVER_EC_SCRUB_DISPATCH.labels(
                mode="megakernel"
            ).inc()
            for (vid, _shards), vol_partials in zip(chunk, partials):
                results[vid] = (
                    [int(r.sum(dtype=np.int64)) for r in vol_partials],
                    n_lanes,
                )
    return results, {"device_calls": device_calls, "volumes": len(results)}


def _warm_key(size: int, count: int) -> tuple[int, int]:
    """Map a warm-plan (size, count) to the (size_bucket, count_bucket)
    shape its ALIGNED-offset request compiles — the key space
    observed_buckets() records.  Ranking by the off=0 class (not
    size+delta) keeps boundary sizes like 2048 in their own bucket."""
    b = _bucket(SIZE_BUCKETS, min(size, MAX_TILE))
    return b, _bucket(COUNT_BUCKETS, min(count, _max_count(b)))


def _warm_grid(cache, vid, sizes, counts, total_shards, observed):
    """(missing shard, observed-first ordered [(size, count)] grid), or
    (None, []) when the volume cannot serve a degraded read at all."""
    resident = cache.shard_ids(vid)
    non_resident = [s for s in range(total_shards) if s not in resident]
    if non_resident:
        missing = non_resident[0]
        if len(resident) < DATA_SHARDS:
            return None, []
    else:
        missing = resident[-1]
        if len(resident) - 1 < DATA_SHARDS:
            return None, []
    grid = [(size, count) for size in sizes for count in counts]
    if observed is None:
        observed = observed_buckets()
    if observed:
        rank = {b: i for i, b in enumerate(observed)}
        grid.sort(key=lambda sc: rank.get(_warm_key(*sc), len(rank)))
    return missing, grid


def warm(
    cache: DeviceShardCache,
    vid: int,
    sizes: tuple[int, ...] = (4096, 65536, 1 << 20),
    counts: tuple[int, ...] = (1, 8, 64),  # single read, a batcher
    # coalesce round, and a full burst — the serving path's count shapes
    total_shards: int = TOTAL_SHARDS,
    should_stop=None,  # callable -> bool: abort between compiles
    layout: str | None = None,
    observed: list[tuple[int, int]] | None = None,
    aot: bool = True,
    wait: bool = True,
    kernel: str | None = None,
    interpret: bool | None = None,
    **kw,
) -> None:
    """Make the bucket combinations a serving path will hit compiled
    BEFORE the first real degraded read, so none pays a 20-40s TPU
    compile inline.  The wanted shard is a NON-resident one when any
    exists (the realistic degraded case), so a volume with exactly
    DATA_SHARDS survivors still warms.

    Default mode (`aot=True`) is ahead-of-time: every device-call shape
    of the grid is lowered + compiled (jax.jit(...).lower(...).compile())
    on the single-worker background executor, in observed-buckets-first
    priority order, and parked in the AOT registry _dispatch_call serves
    from — no synthetic read is ever executed.  Setting the plan also
    arms the cold-shape shed for this volume (cache.aot_state != "none"):
    a serving read racing the executor sheds to host instead of
    compiling inline.  `wait=False` returns as soon as the plan is
    queued; `wait=True` blocks until the grid is compiled and marks the
    volume "done".  `aot=False` is the legacy trace-and-execute walk
    (kept for the -ec.serving.aot.disable knob and as the
    compiled-shapes oracle in tests); it never arms the shed.

    Compiles the ACTIVE layout's ladder only (`layout`, None = the
    cache's — the other family's shapes would double the 20-40s/shape
    mount-time bill for a path the knob has switched off), and walks the
    grid OBSERVED-SHAPES-FIRST (`observed`, default this process's
    dispatch history): a re-pin under live traffic reaches
    serving-readiness for the workload's real (size, count) buckets
    before burning compiles on ladder corners nobody hits."""
    if layout is None:
        layout = cache.layout
    if kernel is None:
        kernel = "pallas" if rs_tpu.on_tpu() else "xla"
    if interpret is None:
        interpret = not rs_tpu.on_tpu()
    missing, grid = _warm_grid(
        cache, vid, sizes, counts, total_shards, observed
    )
    if missing is None or not grid:
        # no plan (unservable volume, or the CI convention warm_sizes=())
        # — aot_state stays "none", so reads keep inline compiles
        return
    if not aot:
        for size, count in grid:
            # both alignment classes: an aligned offset keeps fetch at
            # cover(size); any other offset pushes the span past it onto
            # the next ladder step (usually the 3*2^(n-1) one, see
            # _fetch_cover) — each is its own compiled shape
            for off in (0, 1):
                if should_stop is not None and should_stop():
                    return
                reqs = [(missing, off, size)] * count
                # record_observed=False: warm's own ladder walk must not
                # feed the observed-shape ranking it consults
                reconstruct_intervals(
                    cache, vid, reqs, layout=layout, kernel=kernel,
                    interpret=interpret, record_observed=False, **kw,
                )
        return
    cache._set_aot_state(vid, "warming")
    groups = cache.groups if layout == "blockdiag" else 1
    futures = []
    for size, count in grid:
        for off in (0, 1):
            if should_stop is not None and should_stop():
                # aborted (pin teardown): no plan is coming, so the
                # volume must not stay shed-armed in "warming"
                cache._set_aot_state(vid, "none")
                return
            reqs = [(missing, off, size)] * count
            try:
                calls, _subs, survivors, a_prep, use, w_true, place = (
                    _pack_calls(
                        cache, vid, reqs, kernel, interpret, layout,
                        DATA_SHARDS, total_shards, record_observed=False,
                    )
                )
            except CacheMiss:
                # evicted under the planner: nothing to warm — reset the
                # state so a later direct re-pin doesn't shed forever
                # against a plan that never ran
                cache._set_aot_state(vid, "none")
                return
            surv_len = int(survivors[0].size)
            key_place = _key_place(cache, place)
            keys = [
                _call_key(
                    kind, kernel, groups, w_true, tile, fetch, n_bucket,
                    len(use), a_prep.shape, surv_len, interpret,
                    key_place,
                )
                for kind, _p, _c, _pad, fetch, tile, n_bucket, _d in calls
            ]
            if isinstance(key_place, int) and key_place >= 2:
                # lane-sharded: the key's count bucket is the PER-DEVICE
                # width — a live batch of `count` reads lands anywhere
                # between ceil(count/n_dev) (spread) and count (every
                # hot needle in one chunk) per device — and its
                # fetch(=tile) can be any cover-ladder rung up to the
                # probe's bucket (stripe-boundary splits shrink the
                # span, backward alignment grows it to the full
                # bucket).  Compile every (fetch rung, count rung at or
                # below the probe's) so no distribution or boundary
                # placement of a warmed batch width hits a cold shape
                # (tile/fetch are key[3:5], n_bucket key[5])
                keys = list(
                    dict.fromkeys(
                        key[:3] + (f, f, cb) + key[6:]
                        for key in keys
                        for f in _sharded_fetch_rungs(key[4])
                        for cb in COUNT_BUCKETS
                        if cb <= key[5]
                    )
                )
            futures.extend(_schedule_aot_compiles(keys))
    if wait:
        for f in futures:
            f.result()
        cache._set_aot_state(vid, "done")
    elif futures:
        futures[-1].add_done_callback(
            lambda _f: cache._set_aot_state(vid, "done")
        )
    else:  # every shape already warm
        cache._set_aot_state(vid, "done")
