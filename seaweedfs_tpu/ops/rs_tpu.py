"""TPU Reed-Solomon backends: bitsliced GF(2) matmul on the MXU.

The reference's hot loop is a CPU GF(256) SIMD multiply
(klauspost/reedsolomon AVX2 nibble shuffles, called from
/root/reference/weed/storage/erasure_coding/ec_encoder.go:162-192 and the
degraded-read reconstruct at /root/reference/weed/storage/store_ec.go:339-393).
TPUs have no byte-shuffle unit, so a table-lookup port would fight the
hardware.  Instead we use the GF(2) structure of the code:

  GF(256) is an 8-dim vector space over GF(2); multiply-by-constant is a
  GF(2)-linear map (an 8x8 bit matrix).  An RS code with generator G[m,k]
  over GF(256) is therefore one GF(2) matrix A[8m, 8k], and

      out_bits[8m, B] = A[8m, 8k] @ in_bits[8k, B]   (mod 2)

  — a plain matmul with a parity reduction.  Bits are 0/1 int8 values, the
  products accumulate exactly in int32 (counts <= 8k << 2^31), and
  `count & 1` recovers the XOR.  This maps the whole codec onto the MXU
  systolic array: encode, rebuild, and degraded-read reconstruction are the
  same kernel with different matrices.

Layout (v5e sweep, experiments/kernel_variants*.py):

  * int8 operands with int32 accumulation — the v5e MXU runs int8 at twice
    the bf16 MAC rate (394 vs 197 TOPS), and every element here is a 0/1
    bit, so the narrow type is exact.
  * rows/cols permuted *bit-major* (row = bit*k_pad + shard) so the kernel
    unpacks bytes to bits with a sublane concatenation of eight masked
    planes ((x & 2^i) != 0 — int8 end to end, no widening) and repacks
    with eight static row-slices — no gathers.  The permutation is folded
    into the matrix on the host.
  * matrix cols padded to k_pad = 16 shards (so the MXU contraction dim
    8*k_pad is an exact 128 tile and every unpacked bit-plane starts on a
    sublane-tile boundary).  The input stays [k, B] in HBM; the kernel
    concatenates the k_pad-k zero rows in VMEM, which costs ~5% vs a
    pre-padded input but avoids any HBM pad copy in the pipeline.
    Head-to-head on v5e-1 (same run, useful-byte GB/s): bf16 k=10: 49;
    int8 + per-batch HBM pad: 52; int8 + VMEM concat: 67; int8
    pre-padded: 70.  Roof for this shape: one 128x128 int8 MXU pass per
    128 lanes = 1638 MACs/useful-byte -> ~120 GB/s.

Two kernels:
  "xla"    — the formulation in plain jnp; XLA materialises the bit matrix
             in HBM (8x inflation) but needs no Pallas.
  "pallas" — fused kernel: unpack -> MXU dot -> pack entirely in VMEM, so
             HBM traffic is just the k input and m output byte planes.

Round-3 findings (experiments/kernel_roof_r3.py, kernel_blockdiag_r3.py,
profiler-measured on v5e-1 — the fori-loop differencing harness used in
earlier rounds charges its own per-iteration XOR pass and dispatch
jitter to the kernel, reading ~77 GB/s for a kernel whose device-stream
execution time is 0.81 ms for 96MB = ~123 GB/s, i.e. the plain kernel
already sits AT its documented ~120 GB/s MXU roof):

  * BLOCK-DIAGONAL g=4 packing lifts the roof itself: four independent
    stripe groups fill the MXU's M dimension (A_blk [128, 320] vs a
    mostly-padding [128, 128]), cutting MACs/useful-byte from 1638 to
    ~1229 -> measured 0.656 ms / 96MB = ~152 GB/s.  The catch: inputs
    must arrive segment-stacked ([g*k, B/g]); restacking ON DEVICE costs
    more than the win (byte transposes: 58 GB/s flat-to-flat), so the
    HOST stages the layout (free — the encode pipeline writes the same
    bytes either way).  apply_matrix_blockdiag below.
  * g=8 regresses (95 GB/s): longer contraction padding + VMEM pressure.
  * Feeding the flat layout via a 3-D BlockSpec block (gather inside the
    kernel) is rejected by Mosaic (compile-helper 500) — dead end, like
    the int8-accumulate and u8-multiply routes before it.

Round-4 confirmations (bench.py reworked onto profiler device-stream
timing; four full runs on v5e-1, experiments/r4_validate.py):

  * blockdiag 156.96-156.98 GB/s and plain 120.95 GB/s, repeatable to
    +-0.02% across runs — device-stream timing is effectively exact,
    while the fori-loop differencing cross-check wobbles 66-145 GB/s
    with tunnel mood and is published only as the conservative bound.
  * Tunneled host<->device transfers pay a fixed per-ROW cost on 2-D
    arrays (~80ms/row measured): every pipeline ships FLAT 1-D buffers
    (apply_matrix_device_flat) and reshapes on device.
  * The serving-side fused gather+reconstruct pair lives in
    rs_resident.py (its header documents the Mosaic layout rules that
    shaped it); measured 1.3us/4KB needle batched, 0.30ms/1MB.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import gf256

# Lane tile for the batch dimension.  v5e sweep: 16384 is the knee for the
# int8 kernel (8192: 112, 16384: 115, 24576: 113 GB/s); VMEM footprint at
# 16384 is ~(16+4)*16K input/output + 128*16K bits ~= 2.4MB with headroom
# for double buffering.
BATCH_TILE = 16384

# Input-shard padding: k rounds up to a multiple of 16 so the unpacked bit
# planes are sublane-tile aligned and 8*k_pad is a multiple of the 128 MXU
# contraction tile.
K_ALIGN = 16


def _pad_rows(m_gf: np.ndarray) -> np.ndarray:
    """Pad the GF matrix to a multiple of 4 output rows (sublane alignment:
    8 bits * 4 rows = 32 = int8/u8 sublane tile). Zero rows produce zero
    shards that callers slice away."""
    rows = m_gf.shape[0]
    pad = (-rows) % 4
    if pad:
        m_gf = np.concatenate(
            [m_gf, np.zeros((pad, m_gf.shape[1]), dtype=np.uint8)]
        )
    return m_gf


def _pad_cols(m_gf: np.ndarray) -> np.ndarray:
    """Pad the GF matrix to a multiple of K_ALIGN input columns.  Zero
    columns multiply zero-padded input rows: no effect on the result."""
    cols = m_gf.shape[1]
    pad = (-cols) % K_ALIGN
    if pad:
        m_gf = np.concatenate(
            [m_gf, np.zeros((m_gf.shape[0], pad), dtype=np.uint8)], axis=1
        )
    return m_gf


def prepare_matrix(m_gf: np.ndarray) -> jax.Array:
    """GF(256) matrix [m,k] -> bit-major GF(2) int8 matrix
    [8*m_pad, 8*k_pad].

    a_bm[i*m_pad + p, j*k_pad + d] == bit i of (G[p,d] * 2^j), i.e.
    standard expand_to_gf2 with rows/cols permuted bit-major, rows padded
    to a multiple of 4 and cols to a multiple of K_ALIGN."""
    m_gf = _pad_cols(_pad_rows(np.asarray(m_gf, dtype=np.uint8)))
    m, k = m_gf.shape
    a_std = gf256.expand_to_gf2(m_gf)  # [8m, 8k], row p*8+i
    a_bm = (
        a_std.reshape(m, 8, k, 8).transpose(1, 0, 3, 2).reshape(8 * m, 8 * k)
    )
    return jnp.asarray(a_bm, dtype=jnp.int8)


def _unpack_bits_bitmajor(x: jax.Array, dtype=jnp.int8) -> jax.Array:
    """u8 [k, B] -> 0/1 bits [8k, B], row = bit*k + shard (concat of eight
    masked planes along sublanes).  Bit i extracts as (x & 2^i) != 0 — a
    bytewise AND + compare that stays 1-byte-wide end to end.  (The shift
    formulation needs int32 — Mosaic can't legalize sub-word shrui — and
    the 4x widening costs ~12% of kernel throughput: 65.9 -> 75.2 GB/s on
    v5e, experiments/kernel_cmp_unpack.py.)"""
    planes = [
        ((x & np.uint8(1 << i)) != 0).astype(dtype) for i in range(8)
    ]
    return jnp.concatenate(planes, axis=0)


def _pack_bits_bitmajor(counts: jax.Array, m: int) -> jax.Array:
    """int32/f32 counts [8m, B] -> u8 [m, B]: mod-2 then byte-pack via
    eight static row slices."""
    obits = counts.astype(jnp.int32) & 1
    acc = obits[0:m]
    for i in range(1, 8):
        acc = acc | (obits[i * m : (i + 1) * m] << i)
    return acc.astype(jnp.uint8)


def _check_x_rows(x: jax.Array, k_pad: int, k_true: int | None) -> None:
    """Guard matrix/input shard-count mismatches.  The matrix cols are
    padded to k_pad, so a wrong-but-smaller shard count would silently
    multiply zero columns; callers that know the matrix's true k pass it
    so the mismatch raises instead."""
    if k_true is not None and x.shape[0] != k_true:
        raise ValueError(
            f"input has {x.shape[0]} shards but matrix was built for {k_true}"
        )
    if x.shape[0] > k_pad:
        raise ValueError(
            f"input has {x.shape[0]} shards but matrix covers {k_pad}"
        )


# --- XLA kernel -------------------------------------------------------------


def _apply_xla(a_bm: jax.Array, x: jax.Array) -> jax.Array:
    m = a_bm.shape[0] // 8
    k_pad = a_bm.shape[1] // 8
    if x.shape[0] < k_pad:  # XLA fuses the row pad into the unpack
        x = jnp.pad(x, ((0, k_pad - x.shape[0]), (0, 0)))
    bits = _unpack_bits_bitmajor(x)
    counts = jnp.dot(a_bm, bits, preferred_element_type=jnp.int32)
    return _pack_bits_bitmajor(counts, m)


# --- Pallas kernel ----------------------------------------------------------


def _gf2_matmul_kernel(a_ref, x_ref, o_ref):
    m = o_ref.shape[0]
    k_pad = a_ref.shape[1] // 8
    xv = x_ref[:]
    if xv.shape[0] < k_pad:  # align shards to k_pad with a VMEM-local
        zeros = jnp.zeros((k_pad - xv.shape[0], xv.shape[1]), jnp.uint8)
        xv = jnp.concatenate([xv, zeros], axis=0)  # zero block (no HBM pad)
    bits = _unpack_bits_bitmajor(xv)
    counts = jnp.dot(a_ref[:], bits, preferred_element_type=jnp.int32)
    o_ref[:] = _pack_bits_bitmajor(counts, m)


def _tile_for(b: int) -> int:
    """Block tile: full BATCH_TILE for large batches, shrunk (128-aligned)
    for small ones so degraded reads of single needles don't pay for a 16K
    pad and interpret-mode tests stay fast."""
    return min(BATCH_TILE, max(128, -(-b // 128) * 128))


def _apply_pallas(
    a_bm: jax.Array, x: jax.Array, interpret: bool, tile: int
) -> jax.Array:
    m8, k8 = a_bm.shape
    k, b = x.shape
    m = m8 // 8
    grid = (pl.cdiv(b, tile),)
    return pl.pallas_call(
        _gf2_matmul_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((m8, k8), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((m, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, b), jnp.uint8),
        cost_estimate=pl.CostEstimate(
            flops=2 * m8 * k8 * b, bytes_accessed=k * b + m * b, transcendentals=0
        ),
        interpret=interpret,
    )(a_bm, x)


# --- jitted entry points ----------------------------------------------------


@functools.partial(
    jax.jit, static_argnames=("kernel", "interpret", "tile", "k_true")
)
def apply_matrix_device(
    a_bm: jax.Array,
    x: jax.Array,
    kernel: str = "pallas",
    interpret: bool = False,
    tile: int | None = None,
    k_true: int | None = None,
) -> jax.Array:
    """Device-resident apply: bit-major matrix [8m,8k_pad] int8, shards
    [k,B] u8 (k <= k_pad; the missing rows are treated as zeros inside the
    kernel) -> [m,B] u8.  For the pallas kernel B is padded to the block
    tile (the pad region computes garbage that is sliced off).  `tile` is
    an explicit static override (tests, tuning) — by default it is derived
    from B so the jit cache stays consistent.  `k_true` is the matrix's
    pre-padding shard count; pass it to catch shard-count mismatches that
    the column padding would otherwise absorb silently."""
    _check_x_rows(x, a_bm.shape[1] // 8, k_true)
    if kernel == "pallas":
        b = x.shape[1]
        tile = tile or _tile_for(b)
        pad = (-b) % tile
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad)))
        out = _apply_pallas(a_bm, x, interpret, tile)
        return out[:, :b] if pad else out
    if kernel == "xla":
        return _apply_xla(a_bm, x)
    raise ValueError(f"unknown TPU kernel {kernel!r}")


# --- block-diagonal variant (the encode hot path) ---------------------------

BLOCKDIAG_GROUPS = 4
BLOCKDIAG_TILE = 32768


def blockdiag_system(
    m_gf: np.ndarray, groups: int = BLOCKDIAG_GROUPS
) -> np.ndarray:
    """GF(256) matrix [m,k] -> the [groups*m, groups*k] block-diagonal
    system that encodes `groups` independent stripe segments in one
    multiply.  Shared by the single-chip prepared-matrix path and the
    mesh-sharded encode so the two can never drift."""
    m_gf = np.asarray(m_gf, dtype=np.uint8)
    m, k = m_gf.shape
    blk = np.zeros((groups * m, groups * k), dtype=np.uint8)
    for g in range(groups):
        blk[g * m : (g + 1) * m, g * k : (g + 1) * k] = m_gf
    return blk


def prepare_matrix_blockdiag(
    m_gf: np.ndarray, groups: int = BLOCKDIAG_GROUPS
) -> jax.Array:
    """GF(256) matrix [m,k] -> the block-diagonal system's prepared bit
    matrix.  The block structure is applied at the GF(256) level and then
    expanded by the standard prepare_matrix, so the column order matches
    what _unpack_bits_bitmajor produces for the STACKED input (bit-major
    over all groups*k rows — a per-group bit-major layout would compute
    garbage)."""
    return prepare_matrix(blockdiag_system(m_gf, groups))


def apply_matrix_device_blockdiag(
    a_blk: jax.Array,
    x_stacked: jax.Array,  # [groups*k, seg] u8, segment-stacked
    groups: int = BLOCKDIAG_GROUPS,
    tile: int = BLOCKDIAG_TILE,
    interpret: bool = False,
) -> jax.Array:
    """-> [>=groups*m, seg] u8 (group g's true output rows at g*m..; any
    row padding sits at the tail).  Same fused kernel as the plain path —
    only the matrix and input layout differ."""
    return apply_matrix_device(
        a_blk,
        x_stacked,
        kernel="pallas",
        interpret=interpret,
        tile=tile,
        k_true=x_stacked.shape[0],
    )


@functools.lru_cache(maxsize=16)
def _prepared_blockdiag(matrix_bytes: bytes, m: int, k: int, groups: int):
    return prepare_matrix_blockdiag(
        np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(m, k), groups
    )


def stack_segments(shards: np.ndarray, groups: int = BLOCKDIAG_GROUPS) -> np.ndarray:
    """[k, B] -> [groups*k, B/groups]: segment g of every shard becomes
    rows g*k..g*k+k-1 (the host-side staging that makes block-diagonal
    free — same bytes, different row order)."""
    k, b = shards.shape
    seg = b // groups
    return (
        shards.reshape(k, groups, seg).transpose(1, 0, 2).reshape(groups * k, seg)
    )


def unstack_segments(out: np.ndarray, m: int, groups: int = BLOCKDIAG_GROUPS) -> np.ndarray:
    """[>=groups*m, seg] -> [m, groups*seg]: group g's true rows live at
    g*m..g*m+m-1 (row padding, if any, is beyond groups*m)."""
    seg = out.shape[1]
    return (
        out[: groups * m]
        .reshape(groups, m, seg)
        .transpose(1, 0, 2)
        .reshape(m, groups * seg)
    )


def apply_matrix_blockdiag(
    m_gf: np.ndarray,
    shards: np.ndarray,
    groups: int = BLOCKDIAG_GROUPS,
    tile: int = BLOCKDIAG_TILE,
) -> np.ndarray:
    """Host-convenience block-diagonal apply (numpy in/out) — the fast
    path for bulk encode/rebuild when B divides by `groups`.  Callers
    with indivisible batches use the plain apply_matrix."""
    m_gf = np.asarray(m_gf, dtype=np.uint8)
    rows, k = m_gf.shape
    b = shards.shape[1]
    if b % groups:
        return apply_matrix(m_gf, shards)
    a_blk = _prepared_blockdiag(m_gf.tobytes(), rows, k, groups)
    x = jnp.asarray(
        np.ascontiguousarray(stack_segments(np.asarray(shards, np.uint8), groups))
    )
    out = apply_matrix_device_blockdiag(
        a_blk, x, groups=groups, tile=tile, interpret=_interpret_default()
    )
    return unstack_segments(np.asarray(out), rows, groups)


@functools.partial(
    jax.jit, static_argnames=("k", "m", "kernel", "tile", "interpret")
)
def apply_matrix_device_flat(
    a_bm: jax.Array,
    x_flat: jax.Array,
    *,
    k: int,
    m: int,
    kernel: str = "pallas",
    tile: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """1-D in / 1-D out apply: tunneled devices pay a fixed per-ROW cost
    on 2-D host<->device transfers (~80ms/row measured on this rig — a
    40-row batch took 3.3s vs 0.08s flat), so pipelines ship flat buffers
    and reshape on device, where it's free under jit.  x_flat is the
    row-major [k, B] input flattened; the result is the row-major [m, B]
    output flattened."""
    b = x_flat.size // k
    x = x_flat.reshape(k, b)
    out = apply_matrix_device(
        a_bm, x, kernel=kernel, interpret=interpret, tile=tile, k_true=k
    )
    return out[:m].reshape(-1)


def on_tpu() -> bool:
    """True on real TPU hardware (this rig's tunneled platform canonicalizes
    to "tpu", but accept its raw "axon" name too)."""
    return jax.default_backend() in ("tpu", "axon")


def _interpret_default() -> bool:
    # Pallas TPU kernels run interpreted off-TPU (CPU test mesh).
    return not on_tpu()


@functools.lru_cache(maxsize=64)
def _prepared(matrix_bytes: bytes, m: int, k: int) -> jax.Array:
    return prepare_matrix(np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(m, k))


def apply_matrix(
    m_gf: np.ndarray,
    shards: np.ndarray,
    kernel: str = "pallas",
    tile: int | None = None,
) -> np.ndarray:
    """Host-convenience apply (numpy in/out). Pipelines that care about
    staging (storage/ec/encoder.py) use apply_matrix_device directly."""
    m_gf = np.asarray(m_gf, dtype=np.uint8)
    rows, k = m_gf.shape
    a_bm = _prepared(m_gf.tobytes(), *m_gf.shape)
    x = jnp.asarray(np.ascontiguousarray(shards, dtype=np.uint8))
    out = apply_matrix_device(
        a_bm,
        x,
        kernel=kernel,
        interpret=_interpret_default(),
        tile=tile,
        k_true=k,
    )
    return np.asarray(out)[:rows]
