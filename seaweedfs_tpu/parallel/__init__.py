"""Device-mesh parallelism for EC math: the pod-scale rebuild path.

The reference scales `ec.rebuild`/degraded reads by streaming shard
intervals between hosts over per-shard gRPC (weed/storage/store_ec.go:
299-337).  The TPU-native design instead lays shards out over a device
mesh and lets XLA collectives ride ICI (SURVEY.md §2.10): each device
holds its local shard rows, computes partial GF(2) bit-counts, and one
psum over the shard axis + mod-2 yields the reconstructed bytes.
"""
from .distributed import (
    distributed_apply_matrix,
    distributed_degraded_read,
    distributed_encode_blockdiag,
    make_mesh,
    shard_parallel_apply,
    staged_apply_matrix,
)

__all__ = [
    "make_mesh",
    "distributed_apply_matrix",
    "distributed_encode_blockdiag",
    "distributed_degraded_read",
    "staged_apply_matrix",
    "shard_parallel_apply",
]
