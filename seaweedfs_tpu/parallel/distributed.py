"""Mesh-sharded GF(256) linear algebra: encode/rebuild over many chips.

Two parallel axes (SURVEY.md §2.10 mapping):

  "shard" — the RS shard dimension (the reference's 10-way striping over
            volume servers becomes a sharded array axis).  The bitsliced
            matmul out = (A @ bits(x)) mod 2 decomposes over column groups:
            each device computes partial int32 bit-counts from its local
            shard rows, one `psum` over the shard axis sums counts
            (exact: counts <= 8k per output bit), mod-2 recovers the XOR.
            This turns the reference's per-shard gRPC interval streams
            (store_ec.go:299-337) into a single ICI collective.

  "batch" — the stripe/byte dimension, embarrassingly parallel (pure data
            parallelism; no collective).

Both compose in one mesh: a (S, D) mesh reconstructs S-sharded inputs in
D-way data parallel with one psum.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
try:  # jax >= 0.8 promoted shard_map out of experimental
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import gf256
from ..ops.rs_tpu import _pack_bits_bitmajor, _unpack_bits_bitmajor

# mesh construction lives in parallel/mesh.py (ONE home for axis names
# and device ordering, shared with the r19 sharded serving layout);
# re-exported here because every bulk call site imports it from this
# module
from .mesh import make_mesh  # noqa: F401  (re-export)


def split_matrix_bitmajor(m_gf: np.ndarray, n_groups: int) -> jax.Array:
    """GF(256) matrix [m, k] -> per-group bit-major GF(2) blocks
    [n_groups, 8m, 8*(k/n_groups)] int8, group g covering input shards
    [g*k/n, (g+1)*k/n).  Each device's block is bit-major over its LOCAL
    k so the kernel's unpack/pack layout is unchanged."""
    m_gf = np.asarray(m_gf, dtype=np.uint8)
    m, k = m_gf.shape
    if k % n_groups:
        raise ValueError(f"k={k} not divisible by {n_groups} shard groups")
    k_loc = k // n_groups
    a_std = gf256.expand_to_gf2(m_gf)  # [8m, 8k], row p*8+i, col d*8+j
    # -> [8m(bit-major rows), bit j, d]
    a = a_std.reshape(m, 8, k, 8)  # [p, i, d, j]
    a_bm_rows = a.transpose(1, 0, 3, 2).reshape(8 * m, 8, k)  # [row, j, d]
    groups = []
    for g in range(n_groups):
        blk = a_bm_rows[:, :, g * k_loc : (g + 1) * k_loc]  # [8m, 8, k_loc]
        groups.append(blk.reshape(8 * m, 8 * k_loc))
    return jnp.asarray(np.stack(groups), dtype=jnp.int8)


@functools.partial(jax.jit, static_argnames=("mesh", "m_rows"))
def _distributed_apply(mesh: Mesh, a_groups: jax.Array, x: jax.Array, m_rows: int):
    """a_groups [S, 8m, 8k_loc] sharded on S; x [k, B] sharded (shard,
    batch); -> [m, B] u8 sharded on batch."""

    def kernel(a_loc, x_loc):
        bits = _unpack_bits_bitmajor(x_loc)  # [8k_loc, B_loc]
        partial = jnp.dot(
            a_loc[0], bits, preferred_element_type=jnp.int32
        )  # [8m, B_loc]
        # mod-2 BEFORE the collective: (Σ cᵢ) mod 2 == (Σ (cᵢ mod 2)) mod 2,
        # so psum'ing the int8 bit-planes is exact (sums ≤ n_shard < 128)
        # and moves 4x fewer bytes over ICI than the raw int32 counts
        pbits = (partial & 1).astype(jnp.int8)
        counts = jax.lax.psum(pbits, axis_name="shard")
        return _pack_bits_bitmajor(counts, m_rows)  # [m, B_loc]

    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P("shard", None, None), P("shard", "batch")),
        out_specs=P(None, "batch"),
    )(a_groups, x)


def distributed_apply_matrix(
    mesh: Mesh, m_gf: np.ndarray, shards, pad_rows_to: int = 4
) -> jax.Array:
    """out[i] = XOR_j m_gf[i,j] ⊗ shards[j], computed over the mesh.

    `shards` is [k, B] uint8 (host or device); k must divide over the
    mesh's shard axis and B over its batch axis.  Output rows are padded
    to a sublane-friendly multiple and sliced back."""
    m_gf = np.asarray(m_gf, dtype=np.uint8)
    rows, k = m_gf.shape
    pad = (-rows) % pad_rows_to
    if pad:
        m_gf = np.concatenate([m_gf, np.zeros((pad, k), dtype=np.uint8)])
    n_shard = mesh.shape["shard"]
    a_groups = jax.device_put(
        split_matrix_bitmajor(m_gf, n_shard),
        NamedSharding(mesh, P("shard", None, None)),
    )
    x = jax.device_put(
        jnp.asarray(shards, dtype=jnp.uint8),
        NamedSharding(mesh, P("shard", "batch")),
    )
    out = _distributed_apply(mesh, a_groups, x, rows + pad)
    return out[:rows]


def shard_parallel_apply(
    mesh: Mesh, m_gf: np.ndarray, shards
) -> np.ndarray:
    """Host-convenience wrapper returning numpy."""
    return np.asarray(distributed_apply_matrix(mesh, m_gf, shards))


def distributed_encode_blockdiag(
    mesh: Mesh, parity_m: np.ndarray, shards, groups: int = 4
) -> jax.Array:
    """Block-diagonal bulk encode over the mesh: the same g-group packing
    the single-chip fast path ships (ops/rs_tpu.py header — fills the
    MXU's M dimension, ~152 vs ~123 GB/s) expressed as one block-diagonal
    GF system and run through the generic sharded apply.  Any column
    partition of a GF matrix is valid for the shard axis, so the
    block-diagonal system needs no special shard_map treatment — the host
    stages the segment-stacked layout exactly as the single-chip path
    does."""
    from ..ops import rs_tpu

    parity_m = np.asarray(parity_m, dtype=np.uint8)
    rows = parity_m.shape[0]
    shards = np.asarray(shards, dtype=np.uint8)
    blk = rs_tpu.blockdiag_system(parity_m, groups)
    stacked = rs_tpu.stack_segments(shards, groups)  # [g*k, B/g]
    out = np.asarray(distributed_apply_matrix(mesh, blk, stacked))
    return rs_tpu.unstack_segments(out, rows, groups)


def distributed_degraded_read(
    mesh: Mesh,
    survivors: np.ndarray,  # [k, L] survivor shard bytes (k = data_shards)
    survivor_ids: list[int],
    wanted: int,  # shard id to reconstruct
    requests: list[tuple[int, int]],  # (offset, size) within the shard
    data_shards: int = 10,
    total_shards: int = 14,
) -> list[bytes]:
    """Batched degraded read over the mesh: every requested interval's
    survivor slices batch along the byte axis into ONE sharded apply (the
    pod-scale analogue of ops/rs_resident.py's serving path; replaces the
    reference's per-needle goroutine fan-in, store_ec.go:339-393)."""
    from ..ops import gf256

    rmat, use = gf256.reconstruction_matrix(
        data_shards, total_shards, survivor_ids, [wanted]
    )
    order = [survivor_ids.index(s) for s in use]
    n_batch = mesh.shape["batch"]
    tile = 128 * n_batch
    # variable-width concatenation: each request contributes only its own
    # tile-rounded span (padding every request to the burst's largest span
    # would stage/transfer mostly zeros for mixed-size bursts)
    spans = []
    col = 0
    for off, size in requests:
        lo = off - off % 128
        span = -(-(off + size - lo) // tile) * tile
        spans.append((lo, span, col))
        col += span
    x = np.zeros((len(use), col), dtype=np.uint8)
    for lo, span, c in spans:
        seg = survivors[order, lo : lo + span]
        x[:, c : c + seg.shape[1]] = seg
    out = np.asarray(distributed_apply_matrix(mesh, rmat, x))
    return [
        out[0, c + (off - lo) : c + (off - lo) + size].tobytes()
        for (off, size), (lo, _, c) in zip(requests, spans)
    ]


# ---- multi-process host staging (BASELINE config 5 / SURVEY §2.10) ---------


def staged_apply_matrix(
    mesh: Mesh,
    m_gf: np.ndarray,
    local_x: np.ndarray,
    global_b: int,
    pad_rows_to: int = 4,
):
    """Multi-process variant of distributed_apply_matrix: each PROCESS
    contributes only the input slice its own host read from its own disks
    (`jax.make_array_from_process_local_data`), the global mesh assembles
    the [k, B] logical array across hosts, and the same shard_map step
    runs with its psum riding ICI/DCN.  This is the pod-scale rebuild
    staging story: volume-server hosts feed local shard bytes straight
    into the sharded step with no central gather.

    `local_x` is this process's [k_local, b_local] portion per the
    (shard, batch) sharding; returns the [m, B] output assembled from
    THIS process's addressable output shards (replicated over the shard
    axis, so every process can reassemble the full result)."""
    m_gf = np.asarray(m_gf, dtype=np.uint8)
    rows, k = m_gf.shape
    pad = (-rows) % pad_rows_to
    if pad:
        m_gf = np.concatenate([m_gf, np.zeros((pad, k), dtype=np.uint8)])
    n_shard = mesh.shape["shard"]
    a_all = np.asarray(split_matrix_bitmajor(m_gf, n_shard))
    a_groups = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("shard", None, None)),
        a_all[_local_shard_rows(mesh)],
        a_all.shape,
    )
    x = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("shard", "batch")),
        np.ascontiguousarray(local_x),
        (k, global_b),
    )
    out = _distributed_apply(mesh, a_groups, x, rows + pad)
    # reassemble from the output shards this process can address
    cols: dict[int, np.ndarray] = {}
    for s in out.addressable_shards:
        cols[s.index[1].start or 0] = np.asarray(s.data)
    assembled = np.concatenate(
        [cols[c] for c in sorted(cols)], axis=1
    )
    return assembled[:rows]


def _local_shard_rows(mesh: Mesh) -> slice:
    """Which rows of the [S, ...] per-group matrix stack this process
    owns: the shard-axis positions of its addressable devices."""
    rows = sorted(
        {
            int(np.argwhere(mesh.devices == d)[0][0])
            for d in mesh.local_devices
        }
    )
    return slice(rows[0], rows[-1] + 1)


def _staged_worker_main(argv) -> None:
    """Worker for the two-process host-staging validation: each process
    initializes jax.distributed, stages ITS half of the input via
    make_array_from_process_local_data, runs the sharded encode, and
    asserts the full result against the numpy oracle.  Spawned by
    tests/test_parallel.py and by `python -m seaweedfs_tpu.parallel.
    distributed --staged-worker ...`."""
    import argparse
    import os

    p = argparse.ArgumentParser()
    p.add_argument("--coordinator", required=True)
    p.add_argument("--nproc", type=int, required=True)
    p.add_argument("--pid", type=int, required=True)
    p.add_argument("--devices-per-proc", type=int, default=4)
    args = p.parse_args(argv)

    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={args.devices_per_proc}"
    )
    jax.config.update("jax_platforms", "cpu")
    try:  # cross-process CPU collectives
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception as e:  # noqa: BLE001 — older jax: default impl
        import logging

        logging.getLogger("parallel").debug(
            "gloo CPU collectives unavailable (older jax?): %s", e
        )
    from . import mesh as mesh_mod

    mesh_mod.initialize_distributed(args.coordinator, args.pid, args.nproc)
    mesh = make_mesh(args.nproc, devices=mesh_mod.global_devices())

    from ..ops import rs_cpu
    from ..ops.rs import RSCodec

    rng = np.random.default_rng(42)
    k, b = 10, 1 << 20
    data = rng.integers(0, 256, size=(k, b), dtype=np.uint8)
    parity_m = np.asarray(RSCodec().matrix[k:], dtype=np.uint8)
    rows = _local_shard_rows(mesh)
    k_loc = k // args.nproc
    local = data[rows.start * k_loc : rows.stop * k_loc]
    out = staged_apply_matrix(mesh, parity_m, local, b)
    want = rs_cpu.apply_matrix_numpy(parity_m, data)
    np.testing.assert_array_equal(out, want)
    print(f"staged worker {args.pid}: ok {out.shape}")


if __name__ == "__main__":
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "--staged-worker":
        _staged_worker_main(sys.argv[2:])
