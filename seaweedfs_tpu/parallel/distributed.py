"""Mesh-sharded GF(256) linear algebra: encode/rebuild over many chips.

Two parallel axes (SURVEY.md §2.10 mapping):

  "shard" — the RS shard dimension (the reference's 10-way striping over
            volume servers becomes a sharded array axis).  The bitsliced
            matmul out = (A @ bits(x)) mod 2 decomposes over column groups:
            each device computes partial int32 bit-counts from its local
            shard rows, one `psum` over the shard axis sums counts
            (exact: counts <= 8k per output bit), mod-2 recovers the XOR.
            This turns the reference's per-shard gRPC interval streams
            (store_ec.go:299-337) into a single ICI collective.

  "batch" — the stripe/byte dimension, embarrassingly parallel (pure data
            parallelism; no collective).

Both compose in one mesh: a (S, D) mesh reconstructs S-sharded inputs in
D-way data parallel with one psum.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
try:  # jax >= 0.8 promoted shard_map out of experimental
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import gf256
from ..ops.rs_tpu import _pack_bits_bitmajor, _unpack_bits_bitmajor


def make_mesh(
    n_shard: int = 1, n_batch: int | None = None, devices=None
) -> Mesh:
    """(n_shard, n_batch) device mesh with axes ("shard", "batch")."""
    devices = devices if devices is not None else jax.devices()
    if n_batch is None:
        n_batch = len(devices) // n_shard
    devs = np.asarray(devices[: n_shard * n_batch]).reshape(n_shard, n_batch)
    return Mesh(devs, axis_names=("shard", "batch"))


def split_matrix_bitmajor(m_gf: np.ndarray, n_groups: int) -> jax.Array:
    """GF(256) matrix [m, k] -> per-group bit-major GF(2) blocks
    [n_groups, 8m, 8*(k/n_groups)] int8, group g covering input shards
    [g*k/n, (g+1)*k/n).  Each device's block is bit-major over its LOCAL
    k so the kernel's unpack/pack layout is unchanged."""
    m_gf = np.asarray(m_gf, dtype=np.uint8)
    m, k = m_gf.shape
    if k % n_groups:
        raise ValueError(f"k={k} not divisible by {n_groups} shard groups")
    k_loc = k // n_groups
    a_std = gf256.expand_to_gf2(m_gf)  # [8m, 8k], row p*8+i, col d*8+j
    # -> [8m(bit-major rows), bit j, d]
    a = a_std.reshape(m, 8, k, 8)  # [p, i, d, j]
    a_bm_rows = a.transpose(1, 0, 3, 2).reshape(8 * m, 8, k)  # [row, j, d]
    groups = []
    for g in range(n_groups):
        blk = a_bm_rows[:, :, g * k_loc : (g + 1) * k_loc]  # [8m, 8, k_loc]
        groups.append(blk.reshape(8 * m, 8 * k_loc))
    return jnp.asarray(np.stack(groups), dtype=jnp.int8)


@functools.partial(jax.jit, static_argnames=("mesh", "m_rows"))
def _distributed_apply(mesh: Mesh, a_groups: jax.Array, x: jax.Array, m_rows: int):
    """a_groups [S, 8m, 8k_loc] sharded on S; x [k, B] sharded (shard,
    batch); -> [m, B] u8 sharded on batch."""

    def kernel(a_loc, x_loc):
        bits = _unpack_bits_bitmajor(x_loc)  # [8k_loc, B_loc]
        partial = jnp.dot(
            a_loc[0], bits, preferred_element_type=jnp.int32
        )  # [8m, B_loc]
        # mod-2 BEFORE the collective: (Σ cᵢ) mod 2 == (Σ (cᵢ mod 2)) mod 2,
        # so psum'ing the int8 bit-planes is exact (sums ≤ n_shard < 128)
        # and moves 4x fewer bytes over ICI than the raw int32 counts
        pbits = (partial & 1).astype(jnp.int8)
        counts = jax.lax.psum(pbits, axis_name="shard")
        return _pack_bits_bitmajor(counts, m_rows)  # [m, B_loc]

    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P("shard", None, None), P("shard", "batch")),
        out_specs=P(None, "batch"),
    )(a_groups, x)


def distributed_apply_matrix(
    mesh: Mesh, m_gf: np.ndarray, shards, pad_rows_to: int = 4
) -> jax.Array:
    """out[i] = XOR_j m_gf[i,j] ⊗ shards[j], computed over the mesh.

    `shards` is [k, B] uint8 (host or device); k must divide over the
    mesh's shard axis and B over its batch axis.  Output rows are padded
    to a sublane-friendly multiple and sliced back."""
    m_gf = np.asarray(m_gf, dtype=np.uint8)
    rows, k = m_gf.shape
    pad = (-rows) % pad_rows_to
    if pad:
        m_gf = np.concatenate([m_gf, np.zeros((pad, k), dtype=np.uint8)])
    n_shard = mesh.shape["shard"]
    a_groups = jax.device_put(
        split_matrix_bitmajor(m_gf, n_shard),
        NamedSharding(mesh, P("shard", None, None)),
    )
    x = jax.device_put(
        jnp.asarray(shards, dtype=jnp.uint8),
        NamedSharding(mesh, P("shard", "batch")),
    )
    out = _distributed_apply(mesh, a_groups, x, rows + pad)
    return out[:rows]


def shard_parallel_apply(
    mesh: Mesh, m_gf: np.ndarray, shards
) -> np.ndarray:
    """Host-convenience wrapper returning numpy."""
    return np.asarray(distributed_apply_matrix(mesh, m_gf, shards))
