"""One home for device-mesh construction and axis naming.

Both halves of the system place work on the same physical devices:

  * the BULK plane (parallel/distributed.py) runs encode/rebuild as a
    (shard, batch) shard_map with a psum over the "shard" axis;
  * the SERVING plane (ops/rs_resident.py, r19) lane-shards resident
    EC volumes across the mesh under ``PartitionSpec("shard")`` and
    runs the batched reconstruct as one cross-device program.

Before r19 each would have built its own ``Mesh(devs, ...)`` — two
copies of the axis-naming and device-ordering conventions that MUST
agree (an AOT executable compiled against one mesh object serves calls
whose arrays were placed with another only if the two resolve to the
same devices in the same order).  This module is the single home:
`make_mesh` is the 2-D bulk constructor, `serving_mesh` the cached 1-D
serving constructor, and both use the same "shard" axis name.

Pod scale (r20): `global_serving_mesh` is the multi-controller sibling
of `serving_mesh` — same axis name, same width-1 degrade, but spanning
every process's devices after `jax.distributed.initialize` (wrapped
here as `initialize_distributed`, a no-op below 2 processes).  The two
constructors share `_serving_mesh_or_none` so the degrade rule cannot
drift between them, and every "how many devices / which host" question
the serving stack asks goes through this module: in multi-controller
mode `jax.devices()` spans the pod while `jax.local_device_count()` is
one host's slice, and sizing a budget with the wrong one silently
computes per-process capacity (graftlint GL118 pins that down).
"""
from __future__ import annotations

import functools

import numpy as np

SHARD_AXIS = "shard"
BATCH_AXIS = "batch"


def make_mesh(n_shard: int = 1, n_batch: int | None = None, devices=None):
    """(n_shard, n_batch) device mesh with axes ("shard", "batch") —
    the bulk-plane constructor (encode/rebuild psum over "shard",
    data-parallel over "batch")."""
    from jax.sharding import Mesh

    devices = devices if devices is not None else global_devices()
    if n_batch is None:
        n_batch = len(devices) // n_shard
    devs = np.asarray(devices[: n_shard * n_batch]).reshape(n_shard, n_batch)
    return Mesh(devs, axis_names=(SHARD_AXIS, BATCH_AXIS))


def local_device_count() -> int:
    """Devices addressable by this process (the LOCAL serving mesh's
    ceiling; one host's slice of a pod)."""
    import jax

    return jax.local_device_count()  # graftlint: allow(process-local-device-assumption): this module IS the helper home


def global_device_count() -> int:
    """Devices across every process of the global mesh (== local count
    in single-controller mode)."""
    import jax

    return jax.device_count()  # graftlint: allow(process-local-device-assumption): this module IS the helper home


def process_count() -> int:
    """Processes in the multi-controller job (1 = single-controller)."""
    import jax

    return jax.process_count()


def process_index() -> int:
    """This process's rank in the multi-controller job (0 when single)."""
    import jax

    return jax.process_index()


def local_devices():
    """This process's addressable devices, in jax's local order."""
    import jax

    return list(jax.local_devices())  # graftlint: allow(process-local-device-assumption): this module IS the helper home


def global_devices():
    """Every process's devices in the CANONICAL pod order — sorted by
    (process_index, id) so all processes of a multi-controller job
    agree on lane numbering (jax.devices() order is backend-dependent
    across processes; an owner-major residency layout computed against
    different orders on different hosts would scatter a volume's
    stripes inconsistently)."""
    import jax

    return sorted(jax.devices(), key=lambda d: (d.process_index, d.id))  # graftlint: allow(process-local-device-assumption): this module IS the helper home


def default_device():
    """The single-device landing spot (first local device) — the
    whole-volume / non-mesh placement target."""
    import jax

    return jax.local_devices()[0]  # graftlint: allow(process-local-device-assumption): this module IS the helper home


def device_host(dev) -> int:
    """Failure-domain id of a mesh device: the owning process index.
    One volume-server process per host in multi-controller mode, so
    process == host == the unit that dies together."""
    return int(getattr(dev, "process_index", 0))


def mesh_hosts(mesh) -> tuple[int, ...]:
    """Sorted distinct host (process) ids a serving mesh spans."""
    if mesh is None:
        return ()
    return tuple(sorted({device_host(d) for d in mesh.devices.flat}))


def _serving_mesh_or_none(devs):
    """The ONE width-1 degrade rule both serving-mesh constructors (and
    the bulk `make_mesh` wrapper below) share: a 1-wide mesh only adds
    shard_map overhead over the plain single-device path, so anything
    that resolves to fewer than 2 devices serves un-meshed (None)."""
    from jax.sharding import Mesh

    if len(devs) < 2:
        return None
    return Mesh(np.asarray(list(devs)), axis_names=(SHARD_AXIS,))


@functools.lru_cache(maxsize=8)
def serving_mesh(n_devices: int = 0):
    """Cached 1-D mesh over the first `n_devices` local devices (0 = all)
    with the single axis ("shard",) — the resident-serving layout's
    mesh.  Cached so every call site (put-time placement, the sharded
    reconstruct kernels, AOT shape compiles) shares ONE Mesh object:
    jax hashes meshes by identity-equivalent content, and handing the
    compile path a different-but-equal mesh would still fracture the
    jit cache.  Returns None when the resolved mesh would be a single
    device (`_serving_mesh_or_none`)."""
    devs = local_devices()
    if n_devices > 0:
        devs = devs[:n_devices]
    return _serving_mesh_or_none(devs)


@functools.lru_cache(maxsize=8)
def global_serving_mesh(n_devices: int = 0):
    """Cached 1-D serving mesh over EVERY process's devices in canonical
    pod order (`global_devices`), same ("shard",) axis and same width-1
    degrade as `serving_mesh`.  In a single-process job this resolves
    to exactly the devices `serving_mesh` would pick (degrade
    equality: nothing changes for existing deployments); in a
    multi-controller job it is the pod-wide residency mesh every
    process must construct IDENTICALLY for the SPMD reconstruct
    programs to line up."""
    devs = global_devices()
    if n_devices > 0:
        devs = devs[:n_devices]
    return _serving_mesh_or_none(devs)


def initialize_distributed(
    coordinator: str, process_id: int, n_processes: int
) -> bool:
    """Join the multi-controller job: `jax.distributed.initialize`
    against `coordinator` ("host:port") as process `process_id` of
    `n_processes`.  No-op (returns False) when `n_processes` <= 1 —
    single-process deployments never pay a coordinator handshake and
    `global_serving_mesh` degrades to the local mesh.  Must run before
    the first jax backend touch in the process; the caller validates
    the config (ServingConfig.validated) so a bad coordinator string
    fast-fails at startup, not here mid-handshake."""
    if n_processes <= 1:
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=n_processes,
        process_id=process_id,
    )
    return True
