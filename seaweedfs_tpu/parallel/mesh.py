"""One home for device-mesh construction and axis naming.

Both halves of the system place work on the same physical devices:

  * the BULK plane (parallel/distributed.py) runs encode/rebuild as a
    (shard, batch) shard_map with a psum over the "shard" axis;
  * the SERVING plane (ops/rs_resident.py, r19) lane-shards resident
    EC volumes across the mesh under ``PartitionSpec("shard")`` and
    runs the batched reconstruct as one cross-device program.

Before r19 each would have built its own ``Mesh(devs, ...)`` — two
copies of the axis-naming and device-ordering conventions that MUST
agree (an AOT executable compiled against one mesh object serves calls
whose arrays were placed with another only if the two resolve to the
same devices in the same order).  This module is the single home:
`make_mesh` is the 2-D bulk constructor, `serving_mesh` the cached 1-D
serving constructor, and both use the same "shard" axis name.
"""
from __future__ import annotations

import functools

import numpy as np

SHARD_AXIS = "shard"
BATCH_AXIS = "batch"


def make_mesh(n_shard: int = 1, n_batch: int | None = None, devices=None):
    """(n_shard, n_batch) device mesh with axes ("shard", "batch") —
    the bulk-plane constructor (encode/rebuild psum over "shard",
    data-parallel over "batch")."""
    import jax
    from jax.sharding import Mesh

    devices = devices if devices is not None else jax.devices()
    if n_batch is None:
        n_batch = len(devices) // n_shard
    devs = np.asarray(devices[: n_shard * n_batch]).reshape(n_shard, n_batch)
    return Mesh(devs, axis_names=(SHARD_AXIS, BATCH_AXIS))


def local_device_count() -> int:
    """Devices addressable by this process (the serving mesh's ceiling)."""
    import jax

    return jax.local_device_count()


@functools.lru_cache(maxsize=8)
def serving_mesh(n_devices: int = 0):
    """Cached 1-D mesh over the first `n_devices` local devices (0 = all)
    with the single axis ("shard",) — the resident-serving layout's
    mesh.  Cached so every call site (put-time placement, the sharded
    reconstruct kernels, AOT shape compiles) shares ONE Mesh object:
    jax hashes meshes by identity-equivalent content, and handing the
    compile path a different-but-equal mesh would still fracture the
    jit cache.  Returns None when the resolved mesh would be a single
    device — a 1-wide mesh only adds shard_map overhead over the plain
    single-device path."""
    import jax
    from jax.sharding import Mesh

    devs = jax.local_devices()
    if n_devices > 0:
        devs = devs[:n_devices]
    if len(devs) < 2:
        return None
    return Mesh(np.asarray(devs), axis_names=(SHARD_AXIS,))
