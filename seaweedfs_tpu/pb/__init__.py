"""Protobuf schemas + descriptor-driven gRPC plumbing.

Reference: weed/pb/ — 9 protos, 27.8k generated LoC.  Here: 3 condensed
protos (master, volume_server, filer) compiled with `protoc --python_out`
(see generate.sh) and a reflection layer (rpc.py) that derives client stubs
and server handlers from the descriptors, replacing grpc_tools codegen.
"""
from . import server_address
from .rpc import Stub, channel, close_all_channels, generic_handler

__all__ = ["Stub", "channel", "close_all_channels", "generic_handler", "server_address"]
