#!/bin/sh
# Regenerate *_pb2.py from the .proto schemas (plain protoc; the gRPC
# surface is derived from descriptors at runtime, see rpc.py).
cd "$(dirname "$0")" && protoc -I. --python_out=. master.proto volume_server.proto filer.proto raft.proto mq.proto
