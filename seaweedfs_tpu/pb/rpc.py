"""Descriptor-driven gRPC stubs — no grpc_tools codegen needed.

The reference ships 27.8k lines of generated Go; here the service surface
is derived at import time from the compiled FileDescriptors: `make_stub`
builds a client whose attributes are the proto method names, and
`generic_handler` wraps a servicer object (methods named after the proto
methods) for grpc.aio.Server.  Streaming-ness is read from the descriptor,
so adding an RPC to a .proto requires no further plumbing.
"""
from __future__ import annotations

import functools
import inspect
import threading

import grpc
from google.protobuf import message_factory

MAX_MESSAGE_SIZE = 32 * 1024 * 1024  # reference pb/grpc_client_server.go

GRPC_OPTIONS = [
    ("grpc.max_send_message_length", MAX_MESSAGE_SIZE),
    ("grpc.max_receive_message_length", MAX_MESSAGE_SIZE),
    ("grpc.so_reuseport", 0),  # never silently share a listener
]


def _methods(pb2_module, service_name: str):
    sd = pb2_module.DESCRIPTOR.services_by_name[service_name]
    for m in sd.methods_by_name.values():
        yield (
            m.name,
            f"/{sd.full_name}/{m.name}",
            message_factory.GetMessageClass(m.input_type),
            message_factory.GetMessageClass(m.output_type),
            m.client_streaming,
            m.server_streaming,
        )


# service name -> the role its servicer plays, for trace attribution
_SERVICE_ROLES = {
    "Seaweed": "master",
    "SeaweedFiler": "filer",
    "VolumeServer": "volume",
    "SeaweedRaft": "master",
    "SeaweedMessaging": "mq",
}


def _trace_wrap_call(call):
    """Attach the active trace id AND the remaining deadline budget as
    gRPC metadata on every outbound RPC (obs/trace.py +
    utils/faultpolicy.py contextvars) — fan-out propagation without
    touching any call site.  Explicit caller metadata wins; untraced /
    budget-less contexts add nothing.  When a deadline scope is active
    and the caller passed no explicit `timeout=`, the call gets a hard
    per-call timeout equal to the remaining budget — one hung peer can
    no longer outlive the request it serves.  Outside any scope the
    stub adds no timeout (long-lived streams like SendHeartbeat /
    KeepConnected must stay unbounded; bounded defaults are the call
    sites' job, enforced by graftlint GL114)."""

    def invoke(request, **kw):
        from ..obs import trace as obs_trace
        from ..utils import faultpolicy

        if "metadata" not in kw:
            md = (obs_trace.grpc_metadata() or ()) + (
                faultpolicy.grpc_metadata() or ()
            )
            if md:
                kw["metadata"] = md
        if "timeout" not in kw:
            rem = faultpolicy.remaining_s()
            if rem is not None:
                kw["timeout"] = max(rem, 1e-3)
        return call(request, **kw)

    return invoke


def _inbound_metadata(context) -> dict:
    try:
        return dict(context.invocation_metadata() or ())
    except Exception:  # noqa: BLE001 — context impl without metadata
        return {}


def _adopt_inbound_trace(context, role: str, method: str):
    """Adopt a trace id arriving on inbound gRPC metadata: start this
    server's own trace entry for the request (the Dapper per-process
    record, correlated by the shared id).  Returns (trace, token) —
    (None, None) when the caller sent no trace id."""
    from ..obs import trace as obs_trace

    md = _inbound_metadata(context)
    tid, psid = obs_trace.parse_trace_header(
        md.get(obs_trace.GRPC_TRACE_KEY, "")
    )
    if tid is None:
        return None, None
    return obs_trace.start_trace(
        f"grpc {method}", role, trace_id=tid, parent_span_id=psid
    )


def _adopt_inbound_deadline(context):
    """Adopt the caller's remaining deadline budget
    (`x-seaweed-deadline` metadata, ms) as this handler's ambient
    deadline — the subtract-as-you-hop half of budget propagation.
    Returns a context manager (no-op when the caller sent none; a
    default budget is never stamped here, so background streams stay
    budget-free)."""
    from ..utils import faultpolicy

    return faultpolicy.adopt_scope_from_metadata(_inbound_metadata(context))


def _trace_wrap_handler(fn, role: str, method: str):
    """Server side of the propagation: requests carrying a trace id get
    their own trace entry around the handler (unary and streaming)."""
    from ..obs import trace as obs_trace

    if inspect.isasyncgenfunction(fn):

        @functools.wraps(fn)
        async def stream_handler(request, context):
            t, token = _adopt_inbound_trace(context, role, method)
            status = "OK"
            try:
                with _adopt_inbound_deadline(context):
                    async for item in fn(request, context):
                        yield item
            except BaseException:
                status = "error"
                raise
            finally:
                obs_trace.finish_trace(t, token, status)

        return stream_handler

    @functools.wraps(fn)
    async def unary_handler(request, context):
        t, token = _adopt_inbound_trace(context, role, method)
        status = "OK"
        try:
            with _adopt_inbound_deadline(context):
                return await fn(request, context)
        except BaseException:
            status = "error"
            raise
        finally:
            obs_trace.finish_trace(t, token, status)

    return unary_handler


class Stub:
    """Client stub: one attribute per RPC, built from the descriptor."""

    def __init__(self, channel, pb2_module, service_name: str):
        for name, path, req, resp, cstream, sstream in _methods(pb2_module, service_name):
            if cstream and sstream:
                factory = channel.stream_stream
            elif cstream:
                factory = channel.stream_unary
            elif sstream:
                factory = channel.unary_stream
            else:
                factory = channel.unary_unary
            setattr(
                self,
                name,
                _trace_wrap_call(
                    factory(
                        path,
                        request_serializer=req.SerializeToString,
                        response_deserializer=resp.FromString,
                    )
                ),
            )


def generic_handler(pb2_module, service_name: str, servicer) -> grpc.GenericRpcHandler:
    """Wrap `servicer` (methods named like the proto RPCs) for a
    grpc.aio.Server.  Unimplemented methods raise UNIMPLEMENTED."""
    sd = pb2_module.DESCRIPTOR.services_by_name[service_name]
    role = _SERVICE_ROLES.get(service_name, service_name.lower())
    handlers = {}
    for name, _, req, resp, cstream, sstream in _methods(pb2_module, service_name):
        fn = getattr(servicer, name, None)
        if fn is None:
            continue
        fn = _trace_wrap_handler(fn, role, name)
        kw = dict(
            request_deserializer=req.FromString,
            response_serializer=resp.SerializeToString,
        )
        if cstream and sstream:
            handlers[name] = grpc.stream_stream_rpc_method_handler(fn, **kw)
        elif cstream:
            handlers[name] = grpc.stream_unary_rpc_method_handler(fn, **kw)
        elif sstream:
            handlers[name] = grpc.unary_stream_rpc_method_handler(fn, **kw)
        else:
            handlers[name] = grpc.unary_unary_rpc_method_handler(fn, **kw)
    return grpc.method_handlers_generic_handler(sd.full_name, handlers)


_channels: dict[str, grpc.aio.Channel] = {}


def _tls_creds():
    from ..security import tls

    cfg = tls.configured()
    return tls.channel_credentials(cfg) if cfg is not None else None


def channel(address: str) -> grpc.aio.Channel:
    """Shared aio channel per address (the reference caches one gRPC
    connection per server, pb/grpc_client_server.go) — mTLS when
    security.tls is configured, plaintext otherwise."""
    ch = _channels.get(address)
    if ch is None:
        creds = _tls_creds()
        if creds is not None:
            ch = grpc.aio.secure_channel(address, creds, options=GRPC_OPTIONS)
        else:
            ch = grpc.aio.insecure_channel(address, options=GRPC_OPTIONS)
        _channels[address] = ch
    return ch


async def evict_channel(address: str) -> None:
    """Drop AND close the cached aio channel for an address, so the next
    `channel()` call dials a genuinely fresh connection.  NOT for retry
    loops — the channel is shared per address and grpc reconnects it by
    itself (closing it under other clients' stubs livelocks them; see
    MqClient.reset).  This is the administrative path for channels that
    can never recover, e.g. after rotating TLS credentials."""
    ch = _channels.pop(address, None)
    if ch is not None:
        await ch.close()


def sync_channel(address: str) -> grpc.Channel:
    """Uncached SYNC channel honoring the TLS config — for hooks that run
    on worker threads (e.g. the volume server's remote shard reader)."""
    creds = _tls_creds()
    if creds is not None:
        return grpc.secure_channel(address, creds, options=GRPC_OPTIONS)
    return grpc.insecure_channel(address, options=GRPC_OPTIONS)


_sync_channels: dict[str, grpc.Channel] = {}
_sync_channels_lock = threading.Lock()


def sync_channel_cached(address: str) -> grpc.Channel:
    """Shared SYNC channel per address, for worker-thread hooks on HOT
    paths: the degraded-read survivor gather dials up to 10 peers per
    read, and an uncached dial pays TCP+HTTP/2 setup per shard — the
    chaos sweep's p99-during-repair found it.  Sync channels are
    thread-safe; callers must NOT close what they get here.  The cache
    drops with the async one (drop_cached_channels /
    close_all_channels), so TLS rotation keeps working."""
    with _sync_channels_lock:
        ch = _sync_channels.get(address)
        if ch is None:
            ch = sync_channel(address)
            _sync_channels[address] = ch
        return ch


def drop_cached_channels() -> None:
    """Forget cached channels (without closing: callers may hold stubs).
    Used when the TLS config changes so new dials pick it up."""
    _channels.clear()
    with _sync_channels_lock:
        _sync_channels.clear()


async def close_all_channels() -> None:
    for ch in list(_channels.values()):
        await ch.close()
    _channels.clear()
    with _sync_channels_lock:
        for ch in _sync_channels.values():
            ch.close()
        _sync_channels.clear()
