"""ServerAddress conventions (reference: weed/pb/server_address.go).

A server is addressed as `host:port` for HTTP; its gRPC listener defaults
to `port + 10000` unless an explicit `host:port.grpc_port` form is used.
"""
from __future__ import annotations

GRPC_PORT_DELTA = 10000


def parse(address: str) -> tuple[str, int, int]:
    """'host:port[.grpc]' -> (host, http_port, grpc_port)."""
    host, _, rest = address.rpartition(":")
    if "." in rest:
        port_s, grpc_s = rest.split(".", 1)
        return host, int(port_s), int(grpc_s)
    port = int(rest)
    return host, port, port + GRPC_PORT_DELTA


def http_address(address: str) -> str:
    host, port, _ = parse(address)
    return f"{host}:{port}"


def grpc_address(address: str) -> str:
    host, _, grpc_port = parse(address)
    return f"{host}:{grpc_port}"


def to_grpc_port(http_port: int) -> int:
    return http_port + GRPC_PORT_DELTA
