from .sql import QueryError, parse_select, run_select

__all__ = ["QueryError", "parse_select", "run_select"]
