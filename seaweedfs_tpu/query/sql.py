"""Minimal SQL engine for S3 Select-style queries over CSV / JSON lines.

Reference: weed/query/ (experimental SELECT support backing the s3
SelectObjectContent surface).  Grammar (case-insensitive keywords):

    SELECT <*|col[, col...]> FROM S3Object [alias]
        [WHERE <predicate> [AND <predicate>...]] [LIMIT n]

Columns: bare names (CSV header / JSON keys), `_N` positional (CSV),
or alias-qualified (`s.name`, `s._2`).  Predicates: = != <> < <= > >=
against string or numeric literals (numeric comparison when both sides
parse as numbers).  Aggregates: COUNT(*).
"""
from __future__ import annotations

import csv
import io
import json
import re


class QueryError(ValueError):
    pass


_SELECT_RE = re.compile(
    r"^\s*select\s+(?P<cols>.+?)\s+from\s+s3object(?:\s+(?:as\s+)?(?P<alias>[a-z_]\w*))?"
    r"(?:\s+where\s+(?P<where>.+?))?(?:\s+limit\s+(?P<limit>\d+))?\s*;?\s*$",
    re.IGNORECASE | re.DOTALL,
)
_PRED_RE = re.compile(
    r"^\s*(?P<col>[\w.]+)\s*(?P<op><=|>=|!=|<>|=|<|>)\s*(?P<val>'[^']*'|\"[^\"]*\"|\S+)\s*$"
)


def parse_select(expression: str) -> dict:
    m = _SELECT_RE.match(expression)
    if not m:
        raise QueryError(f"unsupported expression: {expression!r}")
    alias = m.group("alias") or ""
    cols = [c.strip() for c in m.group("cols").split(",")]
    preds = []
    if m.group("where"):
        for part in _split_and(m.group("where")):
            pm = _PRED_RE.match(part)
            if not pm:
                raise QueryError(f"unsupported predicate: {part!r}")
            val = pm.group("val")
            if val[:1] in "'\"":
                val = val[1:-1]
            preds.append((_strip_alias(pm.group("col"), alias), pm.group("op"), val))
    return {
        "columns": [_strip_alias(c, alias) for c in cols],
        "predicates": preds,
        "limit": int(m.group("limit")) if m.group("limit") else None,
    }


def _split_and(clause: str) -> list[str]:
    """Split on AND outside quoted literals ('war and peace' stays one
    token)."""
    parts, buf, quote = [], [], ""
    i, n = 0, len(clause)
    while i < n:
        ch = clause[i]
        if quote:
            buf.append(ch)
            if ch == quote:
                quote = ""
            i += 1
            continue
        if ch in "'\"":
            quote = ch
            buf.append(ch)
            i += 1
            continue
        if (
            clause[i:i + 3].lower() == "and"
            and (i == 0 or clause[i - 1].isspace())
            and (i + 3 >= n or clause[i + 3].isspace())
        ):
            parts.append("".join(buf))
            buf = []
            i += 3
            continue
        buf.append(ch)
        i += 1
    parts.append("".join(buf))
    return [p for p in (x.strip() for x in parts) if p]


def _strip_alias(col: str, alias: str) -> str:
    if alias and col.lower().startswith(alias.lower() + "."):
        return col[len(alias) + 1:]
    return col


def _compare(lhs: str, op: str, rhs: str) -> bool:
    try:
        a, b = float(lhs), float(rhs)
    except (TypeError, ValueError):
        a, b = lhs, rhs
    if op == "=":
        return a == b
    if op in ("!=", "<>"):
        return a != b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    return a >= b


def _rows_csv(data: bytes, header_mode: str):
    """header_mode: "none" | "use" (skip + name columns) | "ignore"
    (skip, positional only — AWS FileHeaderInfo semantics).  Yields
    (record_dict, star_values)."""
    text = data.decode("utf-8", errors="replace")
    reader = csv.reader(io.StringIO(text))
    header: list[str] | None = None
    skipped = header_mode == "none"
    for row in reader:
        if not row:
            continue
        if not skipped:
            skipped = True
            if header_mode == "use":
                header = row
            continue
        rec = {f"_{j + 1}": v for j, v in enumerate(row)}
        if header:
            rec.update({h: v for h, v in zip(header, row)})
        yield rec, list(row)


def _rows_json(data: bytes):
    for line in data.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            raise QueryError("malformed JSON record")
        if isinstance(obj, dict):
            rec = {k: _scalar(v) for k, v in obj.items()}
            yield rec, list(rec.values())


def _scalar(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return ""
    if isinstance(v, (dict, list)):
        return json.dumps(v)
    return str(v)


def run_select(
    expression: str,
    data: bytes,
    input_format: str = "csv",  # csv | json
    csv_header: str | bool = "use",  # none | use | ignore
    output_format: str = "csv",  # csv | json
) -> bytes:
    """Run the query; returns the serialized result records."""
    if isinstance(csv_header, bool):  # tolerate the boolean spelling
        csv_header = "use" if csv_header else "none"
    q = parse_select(expression)
    rows = (
        _rows_csv(data, csv_header)
        if input_format == "csv"
        else _rows_json(data)
    )

    is_count = len(q["columns"]) == 1 and re.fullmatch(
        r"count\(\s*\*\s*\)", q["columns"][0], re.IGNORECASE
    )
    out = io.StringIO()
    writer = csv.writer(out, lineterminator="\n") if output_format == "csv" else None
    n = 0
    for rec, star in rows:
        ok = True
        for col, op, val in q["predicates"]:
            if col not in rec or not _compare(rec[col], op, val):
                ok = False
                break
        if not ok:
            continue
        n += 1
        if is_count:
            continue
        if q["columns"] == ["*"]:
            # the raw row, once — never the positional+named union
            values = {f"_{j + 1}": v for j, v in enumerate(star)}
            if input_format == "json":
                values = rec
        else:
            missing = [c for c in q["columns"] if c not in rec]
            if missing:
                raise QueryError(f"unknown column(s): {missing}")
            values = {c: rec[c] for c in q["columns"]}
        if output_format == "csv":
            writer.writerow(list(values.values()))
        else:
            out.write(json.dumps(values) + "\n")
        if q["limit"] is not None and n >= q["limit"]:
            break
    if is_count:
        if output_format == "csv":
            writer.writerow([n])
        else:
            out.write(json.dumps({"_1": n}) + "\n")
    return out.getvalue().encode()
