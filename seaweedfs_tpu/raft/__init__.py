from .node import RaftNode

__all__ = ["RaftNode"]
