"""Raft consensus for master HA.

Reference: weed/server/raft_server.go + raft_hashicorp.go ride
hashicorp/raft; no such library exists in this image, so this is a
from-scratch implementation of the Raft paper's core: randomized-timeout
leader election, AppendEntries heartbeat + log replication with the
conflict-backoff rule, majority commit with the current-term guard
(§5.4.2), durable term/vote/log, one-at-a-time membership change, and
log-compacting snapshots (§7): past `snapshot_threshold` applied
entries the state machine's snapshot replaces the log prefix, restarts
replay O(snapshot)+tail instead of the whole history, and lagging or
joining peers catch up via InstallSnapshot (the hashicorp snapshot
store + restore plumbing the reference relies on,
raft_hashicorp.go:60-120).

All state transitions run on the asyncio loop (no thread races); RPCs
ride the same descriptor-driven grpc.aio plumbing as every other
service (pb/rpc.py).
"""
from __future__ import annotations

import asyncio
import base64
import json
import logging
import os
import random

import grpc

from ..pb import Stub, raft_pb2
from ..pb.rpc import channel

log = logging.getLogger("raft")

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class NotLeader(RuntimeError):
    def __init__(self, leader: str | None):
        super().__init__(f"not the leader (leader={leader})")
        self.leader = leader


class RaftNode:
    def __init__(
        self,
        node_id: str,  # this node's raft grpc address
        peers: list[str],  # other nodes' raft grpc addresses
        apply_fn,  # (command: dict) -> None, called in log order
        data_dir: str | None = None,
        election_timeout: tuple[float, float] = (0.4, 0.8),
        heartbeat_interval: float = 0.1,
        dial_fn=None,  # peer id -> grpc address (default: identity)
        voter: bool = True,  # False: joining server — replicate, never campaign
        snapshot_fn=None,  # () -> dict: state-machine snapshot at last_applied
        restore_fn=None,  # (dict) -> None: install a snapshot's state
        snapshot_threshold: int = 1000,  # log entries before compaction
    ):
        self.id = node_id
        self.voter = voter
        self.peers = [p for p in peers if p != node_id]
        self.dial_fn = dial_fn or (lambda a: a)
        self.apply_fn = apply_fn
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self.snapshot_threshold = snapshot_threshold
        self.data_dir = data_dir
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval

        self.state = FOLLOWER
        self.term = 0
        self.voted_for: str | None = None
        # log[0] is a sentinel at the snapshot point (term 0, index 0
        # when no snapshot); entry index i lives at log[i - snapshot_index]
        self.log: list[tuple[int, int, bytes]] = [(0, 0, b"")]
        self.snapshot_index = 0
        self.snapshot_term = 0
        self._snapshot_state: dict | None = None  # last snapshot, for peers
        self.commit_index = 0
        self.last_applied = 0
        self.leader_id: str | None = None
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}
        self._commit_waiters: dict[int, asyncio.Future] = {}
        self._election_deadline = 0.0
        self._tasks: list[asyncio.Task] = []
        self._stopped = False
        self._stub_cache: dict[str, Stub] = {}
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            self._load()

    # ------------------------------------------------------------ persistence

    def _state_path(self) -> str:
        return os.path.join(self.data_dir, "raft_state.json")

    def _log_path(self) -> str:
        return os.path.join(self.data_dir, "raft_log.jsonl")

    def _snapshot_path(self) -> str:
        return os.path.join(self.data_dir, "raft_snapshot.json")

    def _load(self) -> None:
        # snapshot FIRST: raft_state.json may hold membership/voter that
        # changed after the snapshot was taken, so its values must win
        try:
            with open(self._snapshot_path()) as f:
                snap = json.load(f)
            self._install_local_snapshot(
                snap["index"], snap["term"], snap.get("members"),
                snap["state"],
            )
        except (OSError, ValueError, KeyError):
            pass
        try:
            with open(self._state_path()) as f:
                st = json.load(f)
            self.term = st["term"]
            self.voted_for = st["voted_for"]
            if "peers" in st:  # membership changes survive restart
                self.peers = [p for p in st["peers"] if p != self.id]
            self.voter = st.get("voter", self.voter)
        except (OSError, ValueError, KeyError):
            pass
        try:
            with open(self._log_path()) as f:
                for line in f:
                    e = json.loads(line)
                    if e["i"] <= self.snapshot_index:
                        continue  # compacted away
                    self.log.append(
                        (e["t"], e["i"], base64.b64decode(e["c"]))
                    )
        except (OSError, ValueError, KeyError):
            pass

    def _install_local_snapshot(
        self, index: int, term: int, members: list[str] | None, state: dict
    ) -> None:
        """Adopt a snapshot as the new log base (shared by restart load
        and leader-pushed InstallSnapshot)."""
        self.snapshot_index = index
        self.snapshot_term = term
        self._snapshot_state = state
        self.log = [(term, index, b"")]
        self.commit_index = max(self.commit_index, index)
        self.last_applied = max(self.last_applied, index)
        if members is not None:
            self.peers = [m for m in members if not self.same_node(m, self.id)]
            if any(self.same_node(m, self.id) for m in members):
                self.voter = True
            elif self.voter and self.state != LEADER:
                # removed while partitioned and the config entry was
                # compacted away: stop campaigning (mirrors apply_config,
                # or this node would term-bump the cluster forever)
                self.voter = False
        if self.restore_fn is not None:
            try:
                self.restore_fn(state)
            except Exception:  # noqa: BLE001
                log.exception("snapshot restore failed at index %d", index)

    def _persist_snapshot(self) -> None:
        if not self.data_dir:
            return
        tmp = self._snapshot_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "index": self.snapshot_index,
                    "term": self.snapshot_term,
                    "members": [self.id] + self.peers,
                    "state": self._snapshot_state,
                },
                f,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snapshot_path())

    def _persist_state(self) -> None:
        if not self.data_dir:
            return
        tmp = self._state_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "term": self.term,
                    "voted_for": self.voted_for,
                    "peers": self.peers,
                    "voter": self.voter,
                },
                f,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._state_path())

    def _persist_log_rewrite(self) -> None:
        if not self.data_dir:
            return
        tmp = self._log_path() + ".tmp"
        with open(tmp, "w") as f:
            for t_, i, c in self.log[1:]:
                f.write(json.dumps(
                    {"t": t_, "i": i, "c": base64.b64encode(c).decode()}
                ) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._log_path())

    def _persist_log_append(self, entries) -> None:
        if not self.data_dir:
            return
        with open(self._log_path(), "a") as f:
            for t_, i, c in entries:
                f.write(json.dumps(
                    {"t": t_, "i": i, "c": base64.b64encode(c).decode()}
                ) + "\n")
            f.flush()
            os.fsync(f.fileno())

    # --------------------------------------------------------------- helpers

    @property
    def is_leader(self) -> bool:
        return self.state == LEADER

    @property
    def quorum(self) -> int:
        return (len(self.peers) + 1) // 2 + 1

    def last_log(self) -> tuple[int, int]:
        t_, i, _ = self.log[-1]
        return i, t_

    def _at(self, index: int) -> tuple[int, int, bytes]:
        """Log entry by ABSOLUTE index (the sentinel sits at
        snapshot_index)."""
        return self.log[index - self.snapshot_index]

    def _has(self, index: int) -> bool:
        return self.snapshot_index <= index <= self.last_log()[0]

    def _stub(self, peer: str) -> Stub:
        s = self._stub_cache.get(peer)
        if s is None:
            s = Stub(channel(self.dial_fn(peer)), raft_pb2, "SeaweedRaft")
            self._stub_cache[peer] = s
        return s

    def _reset_election_timer(self) -> None:
        lo, hi = self.election_timeout
        self._election_deadline = (
            asyncio.get_event_loop().time() + random.uniform(lo, hi)
        )

    def _become_follower(self, term: int, leader: str | None = None) -> None:
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._persist_state()
        self.state = FOLLOWER
        if leader:
            self.leader_id = leader
        self._reset_election_timer()

    # -------------------------------------------------------------- lifecycle

    async def start(self) -> None:
        self._reset_election_timer()
        if not self.peers and self.voter:
            # single-master deployment: win the 1-node election immediately.
            # A non-voter (raft_join) must NOT take this path even with an
            # empty peer list — self-electing would split-brain against the
            # cluster it is about to join.
            self.term += 1
            self.voted_for = self.id
            self._persist_state()
            self._become_leader()
        self._tasks.append(asyncio.create_task(self._ticker()))

    async def stop(self) -> None:
        self._stopped = True
        for t_ in self._tasks:
            t_.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        for _, fut in self._commit_waiters.values():
            if not fut.done():
                fut.cancel()
        self._commit_waiters.clear()

    async def _ticker(self) -> None:
        while not self._stopped:
            await asyncio.sleep(self.heartbeat_interval / 2)
            now = asyncio.get_event_loop().time()
            if self.state == LEADER:
                await self._replicate_all()
            elif self.voter and now >= self._election_deadline:
                await self._run_election()

    # --------------------------------------------------------------- election

    async def _run_election(self) -> None:
        self.state = CANDIDATE
        self.term += 1
        self.voted_for = self.id
        self._persist_state()
        self._reset_election_timer()
        term = self.term
        li, lt = self.last_log()
        votes = 1
        log.info("%s: starting election for term %d", self.id, term)

        async def ask(peer: str) -> bool:
            try:
                resp = await asyncio.wait_for(
                    self._stub(peer).RequestVote(
                        raft_pb2.VoteRequest(
                            term=term, candidate_id=self.id,
                            last_log_index=li, last_log_term=lt,
                        )
                    ),
                    timeout=self.heartbeat_interval * 3,
                )
            except (grpc.aio.AioRpcError, asyncio.TimeoutError):
                return False
            if resp.term > self.term:
                self._become_follower(resp.term)
                return False
            return resp.vote_granted

        results = await asyncio.gather(*(ask(p) for p in self.peers))
        if self.state != CANDIDATE or self.term != term:
            return  # a leader appeared or a newer term started meanwhile
        votes += sum(results)
        if votes >= self.quorum:
            self._become_leader()

    def _become_leader(self) -> None:
        self.state = LEADER
        self.leader_id = self.id
        li, _ = self.last_log()
        self.next_index = {p: li + 1 for p in self.peers}
        self.match_index = {p: 0 for p in self.peers}
        # no-op entry in the new term: prior-term entries can only commit
        # indirectly (§5.4.2), and this drives the commit index forward so
        # the durable log replays on a restarted single node too
        entry = (self.term, li + 1, b"")
        self.log.append(entry)
        self._persist_log_append([entry])
        if not self.peers:
            self._advance_commit()
        log.info("%s: leader for term %d", self.id, self.term)

    # ----------------------------------------------------------- membership

    def same_node(self, a: str, b: str) -> bool:
        """Node identity comparison through the dial mapping: 'host:port'
        and 'host:port.grpc' flag/advertise forms of one server must not
        read as two members."""
        return a == b or self.dial_fn(a) == self.dial_fn(b)

    def apply_config(self, members: list[str]) -> None:
        """Membership change, called when a raft_conf log entry commits.
        The entry carries the COMPLETE member list so every replica —
        including a joining server that knew nobody — converges on the
        same configuration.  One add/remove at a time keeps old and new
        quorums overlapping (the hashicorp AddVoter/RemoveServer
        discipline the reference relies on)."""
        is_member = any(self.same_node(m, self.id) for m in members)
        new_peers = [m for m in members if not self.same_node(m, self.id)]
        if self.state == LEADER:
            li, _ = self.last_log()
            for p in new_peers:
                if p not in self.next_index:
                    self.next_index[p] = li + 1
                    self.match_index[p] = 0
            for p in list(self.next_index):
                if p not in new_peers:
                    self.next_index.pop(p, None)
                    self.match_index.pop(p, None)
        self.peers = new_peers
        if is_member:
            self.voter = True  # a joining server is promoted on commit
        elif self.voter and self.state != LEADER:
            self.voter = False  # removed: stop campaigning
        self._persist_state()

    # ------------------------------------------------------------ replication

    async def propose(self, command: dict, timeout: float = 5.0) -> None:
        """Append a command and wait until it is committed AND applied.
        Raises NotLeader on followers."""
        if self.state != LEADER:
            raise NotLeader(self.leader_id)
        li, _ = self.last_log()
        index = li + 1
        term = self.term
        entry = (term, index, json.dumps(command).encode())
        self.log.append(entry)
        self._persist_log_append([entry])
        fut = asyncio.get_event_loop().create_future()
        # the waiter records its term: if another leader overwrites this
        # index, committing a DIFFERENT entry there must fail the propose,
        # not confirm it
        self._commit_waiters[index] = (term, fut)
        if not self.peers:
            self._advance_commit()
        else:
            await self._replicate_all()
        try:
            await asyncio.wait_for(fut, timeout)
        finally:
            self._commit_waiters.pop(index, None)

    async def _replicate_all(self) -> None:
        if self.peers:
            await asyncio.gather(
                *(self._replicate(p) for p in self.peers),
                return_exceptions=True,
            )
        self._advance_commit()

    async def _replicate(self, peer: str) -> None:
        ni = self.next_index.get(peer, self.snapshot_index + 1)
        if ni <= self.snapshot_index:
            if self._snapshot_state is not None:
                # the entries this peer needs are compacted away: ship
                # the snapshot instead (raft §7 InstallSnapshot)
                await self._send_snapshot(peer)
                return
            ni = self.snapshot_index + 1
        prev = self._at(ni - 1)
        entries = [
            raft_pb2.LogEntry(term=t_, index=i, command=c)
            for t_, i, c in self.log[ni - self.snapshot_index:]
        ]
        try:
            resp = await asyncio.wait_for(
                self._stub(peer).AppendEntries(
                    raft_pb2.AppendRequest(
                        term=self.term, leader_id=self.id,
                        prev_log_index=prev[1], prev_log_term=prev[0],
                        entries=entries, leader_commit=self.commit_index,
                    )
                ),
                timeout=self.heartbeat_interval * 3,
            )
        except (grpc.aio.AioRpcError, asyncio.TimeoutError):
            return
        if resp.term > self.term:
            self._become_follower(resp.term)
            return
        if self.state != LEADER:
            return
        if resp.success:
            self.match_index[peer] = resp.match_index
            self.next_index[peer] = resp.match_index + 1
        else:
            # conflict backoff; backing off TO the snapshot boundary
            # flips the next round to InstallSnapshot
            floor = self.snapshot_index if self._snapshot_state else 1
            self.next_index[peer] = max(floor, ni - 1)

    def _advance_commit(self) -> None:
        li, _ = self.last_log()
        for n in range(self.commit_index + 1, li + 1):
            if self._at(n)[0] != self.term:
                continue  # only current-term entries commit by counting (§5.4.2)
            replicated = 1 + sum(
                1 for p in self.peers if self.match_index.get(p, 0) >= n
            )
            if replicated >= self.quorum:
                self.commit_index = n
        self._apply_committed()

    def _apply_committed(self) -> None:
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            t_, i, c = self._at(self.last_applied)
            if c:
                # own_live: this node proposed the entry in its current
                # leadership — state machines can skip self-adjustments
                # that only matter for followers/replay (e.g. sequence
                # ceilings that would jump the leader's own counter)
                own_live = self.state == LEADER and t_ == self.term
                try:
                    self.apply_fn(json.loads(c), term=t_, own_live=own_live)
                except Exception:  # noqa: BLE001 — state machine must not kill raft
                    log.exception("apply failed at index %d", i)
            waiter = self._commit_waiters.get(i)
            if waiter is not None:
                wterm, fut = waiter
                if not fut.done():
                    if wterm == t_:
                        fut.set_result(None)
                    else:
                        fut.set_exception(NotLeader(self.leader_id))
        self._maybe_snapshot()

    # ------------------------------------------------------------- snapshots

    def _maybe_snapshot(self) -> None:
        if (
            self.snapshot_fn is None
            or len(self.log) - 1 <= self.snapshot_threshold
            or self.last_applied <= self.snapshot_index
        ):
            return
        self.take_snapshot()

    def take_snapshot(self) -> None:
        """Compact the log at last_applied: the state machine's snapshot
        replaces every entry at or below it (§7; the reference's
        hashicorp snapshot store role)."""
        index = self.last_applied
        term = self._at(index)[0] if index > self.snapshot_index else self.snapshot_term
        try:
            state = self.snapshot_fn()
        except Exception:  # noqa: BLE001 — never kill raft for a snapshot
            log.exception("snapshot_fn failed; keeping full log")
            return
        tail = self.log[index - self.snapshot_index + 1:]
        self.log = [(term, index, b"")] + tail
        self.snapshot_index = index
        self.snapshot_term = term
        self._snapshot_state = state
        self._persist_snapshot()
        self._persist_log_rewrite()
        log.info(
            "%s: snapshot at index %d (log now %d entries)",
            self.id, index, len(self.log) - 1,
        )

    async def _send_snapshot(self, peer: str) -> None:
        try:
            resp = await asyncio.wait_for(
                self._stub(peer).InstallSnapshot(
                    raft_pb2.SnapshotRequest(
                        term=self.term,
                        leader_id=self.id,
                        last_included_index=self.snapshot_index,
                        last_included_term=self.snapshot_term,
                        members=[self.id] + self.peers,
                        state=json.dumps(self._snapshot_state).encode(),
                    )
                ),
                timeout=self.heartbeat_interval * 10,
            )
        except (grpc.aio.AioRpcError, asyncio.TimeoutError):
            return
        if resp.term > self.term:
            self._become_follower(resp.term)
            return
        if self.state != LEADER:
            return
        self.match_index[peer] = self.snapshot_index
        self.next_index[peer] = self.snapshot_index + 1

    # ------------------------------------------------------------ rpc handlers

    async def RequestVote(self, request, context):
        if request.term > self.term:
            self._become_follower(request.term)
        granted = False
        if request.term == self.term and self.voted_for in (None, request.candidate_id):
            li, lt = self.last_log()
            up_to_date = (request.last_log_term, request.last_log_index) >= (lt, li)
            if up_to_date:
                granted = True
                self.voted_for = request.candidate_id
                self._persist_state()
                self._reset_election_timer()
        return raft_pb2.VoteResponse(term=self.term, vote_granted=granted)

    async def AppendEntries(self, request, context):
        if request.term < self.term:
            return raft_pb2.AppendResponse(term=self.term, success=False)
        self._become_follower(request.term, leader=request.leader_id)
        # log consistency check.  A prev BELOW our snapshot point is
        # consistent by construction: snapshots only cover committed
        # entries, which every legitimate leader's log matches.
        pli, plt = request.prev_log_index, request.prev_log_term
        if pli >= self.snapshot_index:
            if not self._has(pli) or self._at(pli)[0] != plt:
                return raft_pb2.AppendResponse(term=self.term, success=False)
        # append, truncating conflicts; plain appends persist by appending
        # (a full rewrite per batch would be O(n^2) across the log's life)
        truncated = False
        appended: list[tuple[int, int, bytes]] = []
        for e in request.entries:
            if e.index <= self.snapshot_index:
                continue  # already compacted into the snapshot
            if e.index <= self.last_log()[0]:
                if self._at(e.index)[0] != e.term:
                    del self.log[e.index - self.snapshot_index:]
                    truncated = True
                else:
                    continue
            entry = (e.term, e.index, bytes(e.command))
            self.log.append(entry)
            appended.append(entry)
        if truncated:
            self._persist_log_rewrite()
        elif appended:
            self._persist_log_append(appended)
        if request.leader_commit > self.commit_index:
            li, _ = self.last_log()
            self.commit_index = min(request.leader_commit, li)
            self._apply_committed()
        # match through what THIS request proved, never the follower's own
        # tail — stale extra entries here must not advance the leader
        return raft_pb2.AppendResponse(
            term=self.term,
            success=True,
            match_index=request.prev_log_index + len(request.entries),
        )

    async def InstallSnapshot(self, request, context):
        if request.term < self.term:
            return raft_pb2.SnapshotResponse(term=self.term)
        self._become_follower(request.term, leader=request.leader_id)
        if request.last_included_index <= self.snapshot_index:
            return raft_pb2.SnapshotResponse(term=self.term)  # stale
        self._install_local_snapshot(
            request.last_included_index,
            request.last_included_term,
            list(request.members) or None,
            json.loads(request.state),
        )
        # state BEFORE snapshot: _load gives raft_state.json's membership
        # precedence, so a crash between the two writes must never leave a
        # newer snapshot beside older state (pre-snapshot peers would be
        # resurrected with no config entry left in the log to fix them)
        self._persist_state()
        self._persist_snapshot()
        self._persist_log_rewrite()  # log restarts from the snapshot point
        log.info(
            "%s: installed snapshot at index %d from %s",
            self.id, self.snapshot_index, request.leader_id,
        )
        return raft_pb2.SnapshotResponse(term=self.term)
