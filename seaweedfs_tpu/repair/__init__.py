"""Self-healing repair plane: the master's autonomous EC repair loop.

ROADMAP item 3 closed: the telemetry plane (r08), the parallel rebuild
fan-out (r10), and QoS admission (r13) are joined by a scheduler that
ACTS — detecting shard loss / corruption / stale nodes, planning
prioritized rate-limited repairs, and executing them as QoS-bulk
traffic that yields to the interactive front door.
"""
from .config import RepairConfig
from .planner import PlanResult, RepairJob, plan
from .scheduler import RepairScheduler

__all__ = ["PlanResult", "RepairConfig", "RepairJob", "RepairScheduler", "plan"]
