"""Knobs for the master's self-healing repair plane (-ec.repair.* flags).

The scheduler closes the last manual loop in the pipeline: where the
reference expects a human in `weed shell` running `ec.rebuild` /
`ec.balance` when volumes degrade, these knobs bound how aggressively
the master does it autonomously — scan cadence, repair concurrency,
retry backoff, and the optional master-driven scrub sweep that feeds
corrupt-shard verdicts into the queue.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass
class RepairConfig:
    """Tunables for `RepairScheduler` (CLI: the -ec.repair.* flags)."""

    # run the autonomous repair loop at all (-ec.repair.disable); off,
    # the master still exposes the repair status plane, but only manual
    # `ec.rebuild` restores redundancy
    enabled: bool = True
    # scan cadence (-ec.repair.intervalSeconds): each cycle diffs the
    # topology's EC census against full redundancy and (re)plans the
    # queue; sub-second intervals are for tests/bench only
    interval_seconds: float = 5.0
    # concurrent repair jobs (-ec.repair.maxInflight): each job is one
    # volume's gather -> rebuild -> remount choreography; the fan-out
    # within a job is bounded separately by fanout_concurrency
    max_inflight: int = 2
    # per-RPC fan-out width inside one job (-ec.repair.fanout), passed
    # straight to the r10 gather/spread helpers
    fanout_concurrency: int = 4
    # exponential backoff for a volume whose repair FAILED
    # (-ec.repair.backoffBaseSeconds doubling up to
    # -ec.repair.backoffMaxSeconds); attempts beyond
    # -ec.repair.maxAttempts park the volume as failed until the next
    # topology change re-observes it
    backoff_base_seconds: float = 1.0
    backoff_max_seconds: float = 60.0
    max_attempts: int = 8
    # master-driven scrub sweep cadence (-ec.repair.scrubIntervalSeconds):
    # every interval, one node holding all 14 shards of each EC volume
    # verifies parity (VolumeEcShardsVerify, the r11 megakernel path when
    # resident) and corrupt verdicts enter the repair queue.  0 disables
    # the sweep — verdicts can still arrive via report_corrupt()
    scrub_interval_seconds: float = 0.0
    # breaker subordination: while ANY fresh node's telemetry reports an
    # open interactive QoS breaker, the scheduler defers new repair work
    # for this long (-ec.repair.breakerPauseSeconds) instead of adding
    # bulk shard traffic to an overloaded front door
    breaker_pause_seconds: float = 2.0

    def validated(self) -> "RepairConfig":
        if self.interval_seconds < 0:
            raise ValueError("interval_seconds must be >= 0")
        if self.max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        if self.fanout_concurrency < 1:
            raise ValueError("fanout_concurrency must be >= 1")
        if self.backoff_base_seconds <= 0:
            raise ValueError("backoff_base_seconds must be > 0")
        if self.backoff_max_seconds < self.backoff_base_seconds:
            raise ValueError("backoff_max_seconds must be >= base")
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.scrub_interval_seconds < 0:
            raise ValueError("scrub_interval_seconds must be >= 0")
        if self.breaker_pause_seconds < 0:
            raise ValueError("breaker_pause_seconds must be >= 0")
        return self
