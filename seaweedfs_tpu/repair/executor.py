"""Repair execution: one job's gather -> rebuild -> remount -> spread.

This is the r10 `ec.rebuild` fan-out (shell/command_ec.py) driven by the
master instead of a human: every borrowed shard set is pulled onto the
rebuilder CONCURRENTLY (bounded, per-RPC retry/timeout/budget via the
shared utils/faultpolicy.retry_rpc),
the missing shards are rebuilt in one VolumeEcShardsRebuild, and any
excess above the rebuilder's fair share is re-spread with the same
copy->mount->unmount->delete choreography `ec.encode` uses.

Every RPC leaves the master stamped QoS BULK (`x-seaweed-qos` gRPC
metadata, merged with the active trace id): repair traffic must be
attributable — and deniable — as background work at every hop, so it
can never masquerade as the interactive front door.
"""
from __future__ import annotations

import math

from ..pb import Stub, volume_server_pb2
from ..pb.rpc import channel
from ..shell.command_ec import (
    ec_nodes_by_freeness,
    gather_ec_shards,
    node_shards,
    spread_ec_shards,
)
from ..utils.faultpolicy import retry_rpc
from ..shell.command_env import TopoNode
from ..storage.ec import TOTAL_SHARDS
from .planner import RepairJob

QOS_METADATA_KEY = "x-seaweed-qos"
BULK = "bulk"


class BulkQosStub:
    """Stub proxy stamping every outbound RPC with the bulk QoS tier.

    The underlying descriptor stub attaches the active trace id and
    deadline budget only when no explicit metadata is passed, so this
    wrapper rebuilds the merged metadata itself: caller's -> trace id
    -> deadline budget -> the tier stamp."""

    def __init__(self, stub: Stub):
        self._stub = stub

    def __getattr__(self, name: str):
        call = getattr(self._stub, name)

        def invoke(request, **kw):
            md = list(kw.pop("metadata", ()) or ())
            from ..obs import trace as obs_trace
            from ..utils import faultpolicy

            tmd = obs_trace.grpc_metadata()
            if tmd is not None:
                md.extend(tmd)
            dmd = faultpolicy.grpc_metadata()
            if dmd is not None:
                md.extend(dmd)
            md.append((QOS_METADATA_KEY, BULK))
            return call(request, metadata=tuple(md), **kw)

        return invoke


class RepairEnv:
    """The minimal CommandEnv surface the r10 fan-out helpers need
    (`env.volume_stub`), with bulk stamping on every stub."""

    def volume_stub(self, grpc_address: str) -> BulkQosStub:
        return BulkQosStub(
            Stub(channel(grpc_address), volume_server_pb2, "VolumeServer")
        )


def shard_map_from_nodes(
    nodes: list[TopoNode],
    prefer_not: set[str] | frozenset[str] = frozenset(),
) -> tuple[dict[int, dict[int, str]], dict[int, str]]:
    """(vid -> {shard_id -> holder url}, vid -> collection) from a
    topology snapshot — the scheduler's census input.  A shard with
    several copies maps to ONE holder; any holder outside `prefer_not`
    (the stale set) wins over one inside it, so a shard already
    re-established on a fresh node counts healthy even while the stale
    original still advertises a copy."""
    shard_map: dict[int, dict[int, str]] = {}
    collections: dict[int, str] = {}
    for n in nodes:
        for s in n.ec_shards:
            collections.setdefault(s["id"], s.get("collection", ""))
            vol = shard_map.setdefault(s["id"], {})
            for sid in range(TOTAL_SHARDS):
                if not s["ec_index_bits"] >> sid & 1:
                    continue
                cur = vol.get(sid)
                if cur is None or (
                    cur in prefer_not and n.url not in prefer_not
                ):
                    vol[sid] = n.url
    return shard_map, collections


async def drop_corrupt_shards(
    env: RepairEnv, nodes: list[TopoNode], job: RepairJob
) -> list[int]:
    """Unmount + delete each corrupt shard at its holder BEFORE the
    rebuild, so the bad bytes can never be gathered as rebuild input.
    Idempotent (a re-run finds them already gone)."""
    by_url = {n.url: n for n in nodes}
    dropped: list[int] = []
    for sid, url in sorted(job.corrupt.items()):
        holder = by_url.get(url)
        if holder is None:
            continue  # the holder died since the verdict; already gone
        stub = env.volume_stub(holder.grpc_address)
        await retry_rpc(
            lambda: stub.VolumeEcShardsUnmount(
                volume_server_pb2.VolumeEcShardsUnmountRequest(
                    volume_id=job.vid, shard_ids=[sid]
                )
            ),
            f"unmount corrupt shard {job.vid}.{sid} at {url}",
            peer=holder.grpc_address,
        )
        await retry_rpc(
            lambda: stub.VolumeEcShardsDelete(
                volume_server_pb2.VolumeEcShardsDeleteRequest(
                    volume_id=job.vid, collection=job.collection,
                    shard_ids=[sid],
                )
            ),
            f"delete corrupt shard {job.vid}.{sid} at {url}",
            peer=holder.grpc_address,
        )
        dropped.append(sid)
    return dropped


async def repair_volume(
    env: RepairEnv,
    nodes: list[TopoNode],
    job: RepairJob,
    concurrency: int = 4,
    stale_nodes: set[str] | frozenset[str] = frozenset(),
) -> dict:
    """Execute one planned repair against a live topology snapshot.
    `stale_nodes` holders are never gathered from (a partitioned node
    may be dying: its copies don't count, so the rebuild regenerates
    fresh ones on live nodes).  Returns a result dict for the
    scheduler's per-volume verdict."""
    dropped = await drop_corrupt_shards(env, nodes, job)
    # census AFTER the corrupt drop: fresh holders are the preferred
    # rebuild input; stale copies are suspect but still rescuable
    shard_map, _ = shard_map_from_nodes(nodes, prefer_not=set(stale_nodes))
    holders = {
        sid: url
        for sid, url in shard_map.get(job.vid, {}).items()
        if sid not in job.corrupt and url not in stale_nodes
    }
    ranked = ec_nodes_by_freeness(
        [n for n in nodes if n.url not in stale_nodes]
    )
    if not ranked:
        raise RuntimeError(f"no volume servers to rebuild {job.vid} on")
    rebuilder = ranked[0]
    stub = env.volume_stub(rebuilder.grpc_address)
    by_url = {n.url: n for n in nodes}
    local = {
        sid for sid in node_shards(rebuilder, job.vid)
        if sid not in job.corrupt
    }
    # RESCUE pass: shards whose only copy sits on a SUSPECT (stale)
    # holder are re-established the cheap way — copied off the suspect
    # onto the rebuilder and KEPT (mounted), while the suspect still
    # answers.  A suspect that is truly dead fails the copy and the
    # job retries/backs off; a sid with no reachable holder at all is
    # regenerated by the rebuild below.
    rescue = {
        sid: url for sid, url in job.rescue.items()
        if sid not in holders and sid not in local and url in by_url
    }
    rescue_copy: dict[str, list[int]] = {}
    for sid, url in sorted(rescue.items()):
        rescue_copy.setdefault(by_url[url].grpc_address, []).append(sid)
    if rescue_copy:
        await gather_ec_shards(
            stub, job.vid, job.collection, rescue_copy,
            concurrency=concurrency,
        )
        rescued = sorted(
            sid for sids in rescue_copy.values() for sid in sids
        )
        await retry_rpc(
            lambda: stub.VolumeEcShardsMount(
                volume_server_pb2.VolumeEcShardsMountRequest(
                    volume_id=job.vid, collection=job.collection,
                    shard_ids=rescued,
                )
            ),
            f"mount rescued shards {rescued} of {job.vid}",
            peer=rebuilder.grpc_address,
        )
        local = local | set(rescued)
    else:
        rescued = []
    to_copy: dict[str, list[int]] = {}
    for sid, url in sorted(holders.items()):
        if sid in local or url == rebuilder.url:
            continue
        holder = by_url.get(url)
        if holder is None:
            continue
        to_copy.setdefault(holder.grpc_address, []).append(sid)
    if to_copy:
        await gather_ec_shards(
            stub, job.vid, job.collection, to_copy, concurrency=concurrency
        )
    resp = await retry_rpc(
        lambda: stub.VolumeEcShardsRebuild(
            volume_server_pb2.VolumeEcShardsRebuildRequest(
                volume_id=job.vid, collection=job.collection
            )
        ),
        f"rebuild missing shards of {job.vid} on {rebuilder.url}",
        peer=rebuilder.grpc_address,
    )
    rebuilt = sorted(resp.rebuilt_shard_ids)
    if rebuilt:
        await retry_rpc(
            lambda: stub.VolumeEcShardsMount(
                volume_server_pb2.VolumeEcShardsMountRequest(
                    volume_id=job.vid, collection=job.collection,
                    shard_ids=rebuilt,
                )
            ),
            f"mount rebuilt shards {rebuilt} of {job.vid}",
            peer=rebuilder.grpc_address,
        )
    # drop the shards borrowed only as rebuild input
    borrowed = [sid for sids in to_copy.values() for sid in sids]
    if borrowed:
        await retry_rpc(
            lambda: stub.VolumeEcShardsUnmount(
                volume_server_pb2.VolumeEcShardsUnmountRequest(
                    volume_id=job.vid, shard_ids=borrowed
                )
            ),
            f"unmount borrowed shards of {job.vid}",
            peer=rebuilder.grpc_address,
        )
        await retry_rpc(
            lambda: stub.VolumeEcShardsDelete(
                volume_server_pb2.VolumeEcShardsDeleteRequest(
                    volume_id=job.vid, collection=job.collection,
                    shard_ids=borrowed,
                )
            ),
            f"delete borrowed shards of {job.vid}",
            peer=rebuilder.grpc_address,
        )
    # re-spread: the rebuilder now holds its prior shards + everything
    # rebuilt; anything beyond its fair share moves to the least-loaded
    # peers so one node failure can't take out the redundancy the
    # rebuild just restored (the ec.balance instinct, applied narrowly
    # to the shards this job created)
    spread: dict[str, list[int]] = {}
    others = ranked[1:]
    created = sorted(set(rescued) | set(rebuilt))
    if created and others:
        fair = math.ceil(TOTAL_SHARDS / len(ranked))
        held = sorted(local | set(rebuilt))
        excess = len(held) - fair
        if excess > 0:
            movable = created[-excess:]
            for i, sid in enumerate(movable):
                node = others[i % len(others)]
                spread.setdefault(node.url, []).append(sid)
            targets = [
                (n, spread[n.url]) for n in others if n.url in spread
            ]
            await spread_ec_shards(
                env, job.vid, job.collection, rebuilder, targets,
                concurrency=concurrency,
            )
    return {
        "rebuilder": rebuilder.url,
        "rebuilt": rebuilt,
        "rescued": rescued,
        "dropped_corrupt": dropped,
        "spread": spread,
    }
