"""Pure repair planning: cluster EC census -> prioritized work list.

No sockets, no clocks — the scheduler feeds it a snapshot (shard
locations from the master's topology, corrupt-shard scrub verdicts,
stale nodes from the telemetry plane) and gets back `RepairJob`s in
execution order.  Keeping the policy pure is what makes the priority
rules unit-testable without a cluster:

  * a volume ONE shard from data loss (exactly DATA_SHARDS healthy
    shards left) jumps the whole queue — the next failure is
    unrecoverable, so nothing else matters more;
  * below that, most-shards-missing first (the reference operator's
    instinct in `ec.rebuild`, made explicit);
  * corrupt shards count as LOST for severity (their bytes cannot be
    trusted as rebuild input), and shards held only by STALE nodes
    count as lost too (the node may be gone; redundancy must be
    re-established elsewhere) — execution prefers fresh holders, but a
    stale node is SUSPECT, not certified dead: its shards ride the job
    as `rescue` sources, so a volume whose fresh survivors alone are
    under DATA_SHARDS can still be saved by copying off the suspect
    while it answers;
  * volumes where even fresh + stale copies can't reach DATA_SHARDS
    are flagged unrecoverable and NOT queued: burning repair attempts
    on them would starve volumes that can still be saved;
  * mesh pods are a failure domain (r20): members of one
    multi-controller pod serve a single SPMD residency mesh and
    degrade together, so a volume whose healthy survivors have
    collapsed into ONE pod is one correlated host failure from loss —
    it is escalated to critical even when the raw healthy count still
    shows slack (`node_pods` maps holder url -> pod id; clusters
    without pods pass nothing and plan exactly as before).
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..storage.ec import DATA_SHARDS, TOTAL_SHARDS


@dataclass
class RepairJob:
    """One volume's planned repair."""

    vid: int
    collection: str
    # shards to re-establish: truly absent + corrupt + stale-held
    missing: list[int]
    # the corrupt subset of `missing`, with the node still holding the
    # bad bytes (sid -> node_url): the executor drops these BEFORE the
    # rebuild so the bad shard can't be gathered as rebuild input
    corrupt: dict[int, str] = field(default_factory=dict)
    # stale-held shards (sid -> stale holder url): suspect copies the
    # executor re-establishes by COPYING onto a fresh node while the
    # suspect still answers (and may gather as rebuild input when
    # fresh survivors alone are under DATA_SHARDS)
    rescue: dict[int, str] = field(default_factory=dict)
    # healthy shard count backing the rebuild (live + uncorrupted)
    healthy: int = 0
    critical: bool = False  # one more loss = data loss
    reason: str = "shard_loss"  # shard_loss | corrupt | stale_node
    # every healthy survivor sits inside ONE mesh pod: a single
    # correlated host failure (any pod member dying) is data loss, so
    # the job escalates to critical regardless of raw healthy count
    pod_exposed: bool = False

    def sort_key(self) -> tuple:
        # critical first; then most missing; vid tiebreak for determinism
        return (not self.critical, -len(self.missing), self.vid)


@dataclass
class PlanResult:
    jobs: list[RepairJob]
    unrecoverable: list[RepairJob]
    healthy_vids: list[int]


def plan(
    shard_map: dict[int, dict[int, str]],
    collections: dict[int, str] | None = None,
    corrupt: dict[int, dict[int, str]] | None = None,
    stale_nodes: set[str] | frozenset[str] = frozenset(),
    node_pods: dict[str, str] | None = None,
) -> PlanResult:
    """`shard_map`: vid -> {shard_id -> holder url} (the master's EC
    census); `corrupt`: vid -> {shard_id -> holder url} scrub verdicts;
    `stale_nodes`: telemetry-stale holder urls; `node_pods`: holder
    url -> mesh-pod id ("", absent = not in a pod) — the r20 host
    failure domain."""
    collections = collections or {}
    corrupt = corrupt or {}
    node_pods = node_pods or {}
    jobs: list[RepairJob] = []
    dead: list[RepairJob] = []
    healthy_vids: list[int] = []
    for vid in sorted(set(shard_map) | set(corrupt)):
        shards = shard_map.get(vid, {})
        bad = dict(corrupt.get(vid, {}))
        stale_held = {
            sid: url for sid, url in shards.items()
            if url in stale_nodes and sid not in bad
        }
        healthy = [
            sid for sid in shards
            if sid not in bad and sid not in stale_held
        ]
        missing = sorted(
            sid for sid in range(TOTAL_SHARDS) if sid not in healthy
        )
        if not missing:
            healthy_vids.append(vid)
            continue
        if bad:
            reason = "corrupt"
        elif stale_held:
            reason = "stale_node"
        else:
            reason = "shard_loss"
        # pod-exposure check: the pods holding the healthy survivors.
        # All of them inside one non-"" pod = one correlated host
        # failure from loss (pod members degrade together)
        healthy_pods = {node_pods.get(shards[sid], "") for sid in healthy}
        pod_exposed = bool(
            healthy and healthy_pods != {""} and len(healthy_pods) == 1
        )
        job = RepairJob(
            vid=vid,
            collection=collections.get(vid, ""),
            missing=missing,
            corrupt=bad,
            rescue=dict(sorted(stale_held.items())),
            healthy=len(healthy),
            critical=len(healthy) <= DATA_SHARDS or pod_exposed,
            reason=reason,
            pod_exposed=pod_exposed,
        )
        if len(healthy) + len(stale_held) < DATA_SHARDS:
            dead.append(job)
        else:
            jobs.append(job)
    jobs.sort(key=RepairJob.sort_key)
    dead.sort(key=RepairJob.sort_key)
    return PlanResult(jobs=jobs, unrecoverable=dead, healthy_vids=healthy_vids)
