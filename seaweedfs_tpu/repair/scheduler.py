"""RepairScheduler: the master's autonomous EC repair loop.

Closes ROADMAP item 3: cluster health (r08), fast parallel rebuild
(r10), and QoS/breakers (r13) exist, but until now a human in `weed
shell` was the only thing that ACTED on the telemetry plane.  Each
scheduling cycle:

  1. OBSERVE — the topology's EC census (which shards exist where),
     the telemetry plane's stale nodes (heartbeats missed: their
     shards are suspect), and accumulated corrupt-shard scrub verdicts
     (the optional master-driven scrub sweep below, or ec.scrub /
     tests via report_corrupt()).
  2. PLAN — repair/planner.py: volumes one shard from data loss jump
     the queue, then most-shards-missing first; unrecoverable volumes
     are surfaced, not retried into the ground.
  3. SUBORDINATE — while any fresh node reports an open INTERACTIVE
     QoS breaker, the whole cycle defers (counted as
     backoff_total{reason="breaker_open"}): repair is bulk traffic and
     must never compete with an overloaded front door.  Every repair
     RPC is additionally stamped bulk via gRPC metadata
     (repair/executor.py).
  4. EXECUTE — at most -ec.repair.maxInflight jobs run concurrently,
     each the r10 gather/rebuild/spread fan-out; a failed job backs
     off exponentially and parks as failed after maxAttempts.

Convergence is measured: the first cycle that observes ANY missing or
corrupt shard starts the clock, and the first cycle after that where
the census is fully redundant again observes wall seconds into
`SeaweedFS_master_repair_time_to_healthy_seconds` — the recovery SLO
bench_chaos_sweep asserts.
"""
from __future__ import annotations

import asyncio
import logging
import time
from typing import Any

from .. import stats
from ..obs import incident as obs_incident
from ..pb import volume_server_pb2
from ..shell.command_env import TopoNode, topo_nodes_from_info
from ..storage.ec import DATA_SHARDS, TOTAL_SHARDS
from ..utils.tasks import spawn_logged
from . import executor, planner
from .config import RepairConfig

log = logging.getLogger("repair")


class RepairScheduler:
    """Master-side repair orchestration (one per MasterServer)."""

    def __init__(self, master, cfg: RepairConfig | None = None) -> None:
        self.master = master
        self.cfg = (cfg or RepairConfig()).validated()
        self.env = executor.RepairEnv()
        # ONE clock for every deadline (backoff, settle, breaker pause):
        # injectable so pinned-clock tests drive tick() without mixing
        # fake nows against real-monotonic stamps
        self.clock = time.monotonic
        self.paused = False
        self._inflight: dict[int, asyncio.Task] = {}
        # vid -> (attempts, monotonic time the next attempt may start)
        self._backoff: dict[int, tuple[int, float]] = {}
        self._parked: dict[int, str] = {}  # vid -> last error (failed)
        # scrub verdicts awaiting repair: vid -> {shard_id -> holder url}
        self._corrupt: dict[int, dict[int, str]] = {}
        # post-repair settle window: a completed job's mounts reach the
        # census via heartbeat deltas, so re-planning the vid before
        # ~2 pulses would launch a duplicate no-op job against the lag
        self._settle_until: dict[int, float] = {}
        # per-volume last-known state for volume.repair.status
        self._verdicts: dict[int, dict[str, Any]] = {}
        self._queue_depth = 0
        self._unhealthy_since: float | None = None
        self._breaker_deferred_until = 0.0
        self._last_scrub = 0.0
        self.last_convergence_unix: float | None = None
        self.last_time_to_healthy_s: float | None = None
        self.totals = {
            "queued": 0, "completed": 0, "failed": 0,
            "backoff_retry": 0, "backoff_breaker": 0,
        }
        self._task: asyncio.Task | None = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self.cfg.enabled and self.cfg.interval_seconds > 0:
            self._task = spawn_logged(
                self._run_forever(), log, "repair scheduler loop"
            )

    async def stop(self) -> None:
        tasks = list(self._inflight.values())
        if self._task is not None:
            tasks.append(self._task)
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        self._inflight.clear()
        stats.MASTER_REPAIR_INFLIGHT.set(0)

    async def _run_forever(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.interval_seconds)
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — one failed cycle must not
                # end the repair plane; the next cycle re-observes
                log.exception("repair cycle failed")

    # ------------------------------------------------------------- controls

    def pause(self) -> None:
        self.paused = True

    def resume(self) -> None:
        self.paused = False

    def report_corrupt(
        self, vid: int, shard_holders: dict[int, str]
    ) -> None:
        """Feed a corrupt-shard verdict (shard_id -> holder url) into
        the next planning cycle — the scrub sweep's path, and the hook
        tests / `ec.scrub` integrations use directly."""
        self._corrupt.setdefault(vid, {}).update(shard_holders)

    # ------------------------------------------------------------ the cycle

    def _breakers_open(self) -> int:
        return self.master.telemetry.breakers_open()

    async def tick(self, now: float | None = None) -> None:
        """One scheduling cycle (driven by the loop, or directly by
        tests/bench — pin `self.clock` to drive deadlines too)."""
        now = self.clock() if now is None else now
        if self.paused or not self.master.is_leader:
            return
        if now < self._breaker_deferred_until:
            return
        open_breakers = self._breakers_open()
        if open_breakers > 0:
            # repair yields to the front door: defer the WHOLE cycle
            self._breaker_deferred_until = (
                now + self.cfg.breaker_pause_seconds
            )
            self.totals["backoff_breaker"] += 1
            stats.MASTER_REPAIR_BACKOFF.labels(reason="breaker_open").inc()
            obs_incident.record(
                "repair_deferred", reason="breaker_open",
                open_breakers=open_breakers,
            )
            log.info(
                "repair deferred: %d node(s) report an open interactive "
                "QoS breaker", open_breakers,
            )
            return
        nodes = topo_nodes_from_info(self.master.topo.to_info())
        stale = self.master.telemetry.stale_node_urls()
        shard_map, collections = executor.shard_map_from_nodes(
            nodes, prefer_not=stale
        )
        result = planner.plan(
            shard_map,
            collections=collections,
            corrupt={k: dict(v) for k, v in self._corrupt.items()},
            stale_nodes=stale,
            # mesh pods as failure domains (r20): survivors collapsed
            # into one pod escalate to critical in the planner
            node_pods={
                n.url: n.mesh_pod
                for n in self.master.topo.data_nodes()
                if n.mesh_pod
            },
        )
        self._note_plan(result, now)
        if (
            self.cfg.scrub_interval_seconds > 0
            and now - self._last_scrub >= self.cfg.scrub_interval_seconds
        ):
            self._last_scrub = now
            await self._scrub_pass(nodes, shard_map)
        for job in result.jobs:
            if len(self._inflight) >= self.cfg.max_inflight:
                break
            if job.vid in self._inflight or job.vid in self._parked:
                continue
            if now < self._settle_until.get(job.vid, 0.0):
                continue  # census lag, not a fresh degradation
            attempts, next_ok = self._backoff.get(job.vid, (0, 0.0))
            if now < next_ok:
                continue
            self.totals["queued"] += 1
            stats.MASTER_REPAIR_QUEUED.inc()
            obs_incident.record(
                "repair_queued", vid=job.vid, missing=list(job.missing),
                corrupt=sorted(job.corrupt), critical=job.critical,
                reason=job.reason,
            )
            self._inflight[job.vid] = spawn_logged(
                self._run_job(job, nodes, stale),
                log,
                f"repair job for volume {job.vid}",
            )
            stats.MASTER_REPAIR_INFLIGHT.set(len(self._inflight))

    def _note_plan(self, result: planner.PlanResult, now: float) -> None:
        """Record the plan into the status plane and drive the
        time-to-healthy clock."""
        self._queue_depth = len(result.jobs)
        unhealthy = bool(result.jobs or result.unrecoverable)
        if unhealthy and self._unhealthy_since is None:
            self._unhealthy_since = now
        for job in result.jobs + result.unrecoverable:
            # repairability is the PLANNER's verdict (rescue sources
            # count), not a local healthy-count recomputation: a volume
            # under fresh quorum that stale copies can still save is
            # queued work, and the operator must not read it as lost
            unrecoverable = any(
                j.vid == job.vid for j in result.unrecoverable
            )
            attempts, next_ok = self._backoff.get(job.vid, (0, 0.0))
            v = self._verdicts.setdefault(job.vid, {})
            v.update(
                state=(
                    "unrecoverable" if unrecoverable
                    # parked/backoff survive re-planning: the status
                    # plane must keep saying WHY the volume is not
                    # being repaired, not flip back to 'queued'
                    else "failed" if job.vid in self._parked
                    else "repairing" if job.vid in self._inflight
                    else "backoff" if now < next_ok
                    else "queued"
                ),
                missing=list(job.missing),
                corrupt=sorted(job.corrupt),
                healthy_shards=job.healthy,
                critical=job.critical,
                reason=job.reason,
                attempts=attempts,
            )
        for vid in result.healthy_vids:
            if vid in self._verdicts:
                self._verdicts[vid].update(
                    state="healthy", missing=[], corrupt=[],
                    healthy_shards=TOTAL_SHARDS, critical=False,
                )
            self._corrupt.pop(vid, None)
            self._backoff.pop(vid, None)
            self._parked.pop(vid, None)
        if not unhealthy and not self._inflight:
            if self._unhealthy_since is not None:
                dt = now - self._unhealthy_since
                self._unhealthy_since = None
                self.last_time_to_healthy_s = round(dt, 3)
                self.last_convergence_unix = time.time()
                stats.MASTER_REPAIR_TIME_TO_HEALTHY.observe(dt)
                log.info(
                    "cluster re-converged to full redundancy in %.2fs", dt
                )

    async def _run_job(
        self, job: planner.RepairJob, nodes, stale: set[str]
    ) -> None:
        try:
            result = await executor.repair_volume(
                self.env, nodes, job,
                concurrency=self.cfg.fanout_concurrency,
                stale_nodes=stale,
            )
            self.totals["completed"] += 1
            stats.MASTER_REPAIR_COMPLETED.inc()
            self._backoff.pop(job.vid, None)
            self._corrupt.pop(job.vid, None)
            self._settle_until[job.vid] = self.clock() + 2.0 * max(
                1, getattr(self.master, "pulse_seconds", 1)
            )
            self._verdicts.setdefault(job.vid, {}).update(
                state="repaired", last_result=result, last_error=None,
            )
            obs_incident.record(
                "repair_completed", vid=job.vid,
                rebuilt=result.get("rebuilt"),
                rebuilder=result.get("rebuilder"),
            )
            log.info(
                "repaired ec volume %d: rebuilt %s on %s",
                job.vid, result["rebuilt"], result["rebuilder"],
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — the job's failure IS the
            # datum: it drives backoff/parking, never crashes the loop
            attempts = self._backoff.get(job.vid, (0, 0.0))[0] + 1
            delay = min(
                self.cfg.backoff_base_seconds * 2 ** (attempts - 1),
                self.cfg.backoff_max_seconds,
            )
            self._backoff[job.vid] = (attempts, self.clock() + delay)
            self._verdicts.setdefault(job.vid, {}).update(
                state="backoff", attempts=attempts, last_error=str(e),
            )
            obs_incident.record(
                "repair_failed", vid=job.vid, attempts=attempts,
                error=str(e),
                parked=bool(attempts >= self.cfg.max_attempts),
            )
            if attempts >= self.cfg.max_attempts:
                self._parked[job.vid] = str(e)
                self.totals["failed"] += 1
                stats.MASTER_REPAIR_FAILED.inc()
                self._verdicts[job.vid]["state"] = "failed"
                log.error(
                    "repair of volume %d parked after %d attempts: %s",
                    job.vid, attempts, e,
                )
            else:
                self.totals["backoff_retry"] += 1
                stats.MASTER_REPAIR_BACKOFF.labels(reason="retry").inc()
                log.warning(
                    "repair of volume %d failed (attempt %d, retry in "
                    "%.1fs): %s", job.vid, attempts, delay, e,
                )
        finally:
            self._inflight.pop(job.vid, None)
            stats.MASTER_REPAIR_INFLIGHT.set(len(self._inflight))

    # ----------------------------------------------------------- scrub pass

    async def _scrub_pass(
        self,
        nodes: list[TopoNode],
        shard_map: dict[int, dict[int, str]],
    ) -> None:
        """Master-driven parity sweep: for each EC volume with a node
        holding all 14 shards, one VolumeEcShardsVerify (bulk-stamped;
        the r11 megakernel path when the shards are device-resident).
        A single mismatching parity row localizes the corruption to
        that parity shard and enters the repair queue; a multi-row
        mismatch (corrupt DATA shard — the parity system can't name it)
        is surfaced loudly for `ec.scrub` diagnosis instead of guessing
        a shard to drop."""
        by_url = {n.url: n for n in nodes}
        for vid, shards in sorted(shard_map.items()):
            if vid in self._corrupt or vid in self._inflight:
                continue
            holders: dict[str, set[int]] = {}
            for sid, url in shards.items():
                holders.setdefault(url, set()).add(sid)
            full = sorted(
                url for url, sids in holders.items()
                if len(sids) == TOTAL_SHARDS and url in by_url
            )
            if not full:
                continue
            node = by_url[full[0]]
            try:
                r = await self.env.volume_stub(
                    node.grpc_address
                ).VolumeEcShardsVerify(
                    volume_server_pb2.VolumeEcShardsVerifyRequest(
                        volume_id=vid
                    ),
                    # bounded: a hung scrub target must not wedge the
                    # whole repair cycle (GL114)
                    timeout=600.0,
                )
            except Exception as e:  # noqa: BLE001 — a failed scrub is a
                # skipped verdict, not a dead repair plane
                log.warning("scrub of volume %d on %s failed: %s",
                            vid, node.url, e)
                continue
            mism = list(r.parity_mismatch_bytes)
            rows = [i for i, m in enumerate(mism) if m]
            if not rows:
                continue
            if len(rows) == 1:
                sid = DATA_SHARDS + rows[0]
                log.error(
                    "scrub verdict: volume %d parity shard %d corrupt "
                    "on %s (%s mismatch bytes) — scheduling repair",
                    vid, sid, node.url, mism[rows[0]],
                )
                self.report_corrupt(vid, {sid: node.url})
            else:
                self._verdicts.setdefault(vid, {}).update(
                    state="corrupt_unlocalized", scrub_mismatch=mism,
                )
                log.error(
                    "scrub verdict: volume %d has %d mismatching parity "
                    "rows on %s — a DATA shard is corrupt; run ec.scrub "
                    "/ ec.rebuild to diagnose", vid, len(rows), node.url,
                )

    # --------------------------------------------------------------- status

    def unhealthy_for(self) -> float | None:
        """Seconds the cluster has been CONTINUOUSLY under-redundant
        (None when healthy) — the live half of the time-to-healthy SLO:
        the histogram observes episodes after they end, this exposes
        the one still running so obs/slo.py can burn DURING it."""
        if self._unhealthy_since is None:
            return None
        return max(0.0, self.clock() - self._unhealthy_since)

    def status(self) -> dict[str, Any]:
        """The repair block of /cluster/health.json (and
        volume.repair.status)."""
        now = self.clock()
        return {
            "enabled": self.cfg.enabled,
            "paused": self.paused,
            "breaker_deferred": bool(now < self._breaker_deferred_until),
            "queue_depth": self._queue_depth,
            "inflight": sorted(self._inflight),
            "backoff": {
                str(vid): {
                    "attempts": attempts,
                    "next_retry_in_s": round(max(0.0, next_ok - now), 3),
                }
                for vid, (attempts, next_ok) in sorted(
                    self._backoff.items()
                )
            },
            "failed": {str(v): e for v, e in sorted(self._parked.items())},
            "totals": dict(self.totals),
            "volumes": {
                str(vid): dict(v)
                for vid, v in sorted(self._verdicts.items())
            },
            "last_convergence_unix_ms": (
                int(self.last_convergence_unix * 1e3)
                if self.last_convergence_unix is not None else None
            ),
            "last_time_to_healthy_s": self.last_time_to_healthy_s,
        }
