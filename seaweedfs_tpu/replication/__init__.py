from .sink import FilerSink
from .source import FilerSource
from .sync import FilerSync

__all__ = ["FilerSink", "FilerSource", "FilerSync"]
