"""Metadata-change notification publishers.

Reference: weed/notification/configuration.go — a MessageQueue interface
(SendMessage(key, proto)) with kafka/SQS/pub-sub/log backends, invoked
for every filer meta mutation when notifications are configured.  The
network-queue class is covered by MqNotifier publishing to the in-repo
MQ broker (mq/broker.py) — the zero-egress equivalent of the kafka
publisher (weed/notification/kafka/kafka_queue.go:1-60); the log
publisher, a local spool file, and an in-process callback round out the
local backends.
"""
from __future__ import annotations

import asyncio
import logging
import os
import struct
from collections import deque

from ..pb import filer_pb2

log = logging.getLogger("notification")


class Notifier:
    async def publish(
        self, key: str, notification: filer_pb2.EventNotification
    ) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class LogNotifier(Notifier):
    """notification.log backend."""

    async def publish(self, key, notification) -> None:
        log.info(
            "meta event %s: old=%s new=%s", key,
            notification.old_entry.name or "-",
            notification.new_entry.name or "-",
        )


class CallbackNotifier(Notifier):
    def __init__(self, fn):
        self.fn = fn

    async def publish(self, key, notification) -> None:
        r = self.fn(key, notification)
        if asyncio.iscoroutine(r):
            await r


class MqNotifier(Notifier):
    """notification.toml type `mq`: meta events go over the wire to the
    in-repo MQ broker, landing in a filer-backed partition log that
    `filer.replicate -mqBroker` consumes with committed group offsets —
    a real network queue, not an in-process hop.

    Publish semantics mirror the reference's async kafka producer
    (kafka_queue.go buffers through the client library): publish() only
    enqueues; a background task drains batches to the broker and retries
    with backoff, so a broker restart never fails filer mutations.  The
    buffer is bounded — beyond `max_buffer` the OLDEST events drop with a
    counted warning (backpressure would stall the filer's write path)."""

    def __init__(
        self,
        broker_grpc_address: str,  # comma-separated bootstrap list
        topic: str = "filer_meta",
        namespace: str = "default",
        partition_count: int = 4,
        max_buffer: int = 10000,
    ):
        from ..mq.client import MqClient

        self._addrs = [
            a.strip() for a in broker_grpc_address.split(",") if a.strip()
        ]
        self._addr_idx = 0
        self.client = MqClient(self._addrs[0])
        self.topic = MqClient.topic(topic, namespace)
        self.partition_count = partition_count
        self.max_buffer = max_buffer
        self.dropped = 0
        self._buf: deque[tuple[bytes, bytes]] = deque()
        self._configured = False
        self._task: asyncio.Task | None = None
        self._draining = False
        self._closing = False

    async def publish(self, key, notification) -> None:
        if key.startswith("/topics/"):
            # the MQ spools its partition logs through the SAME filer:
            # publishing those mutations back into the MQ would be a
            # feedback loop (every flush begets an event begets a flush)
            return
        self._buf.append((key.encode(), notification.SerializeToString()))
        over = len(self._buf) - self.max_buffer
        if over > 0:
            for _ in range(over):
                self._buf.popleft()
            self.dropped += over
            log.warning(
                "mq notifier buffer overflow: %d events dropped total",
                self.dropped,
            )
        self._maybe_spawn()

    def _maybe_spawn(self) -> None:
        """Race-free drain spawn: a publish landing while the previous
        drain is EXITING (it saw an empty buffer, but is not yet done())
        must still get a drainer, or the event sits silently until the
        next publish.  The flag flips in _drain's finally with no await
        in between, so on this single loop exactly one drainer runs and
        no buffered event is ever left without one."""
        if self._buf and not self._closing and not self._draining:
            self._draining = True
            self._task = asyncio.ensure_future(self._drain())

    async def _publish_batch(self) -> None:
        if not self._configured:
            await self.client.configure_topic(
                self.topic, self.partition_count
            )
            self._configured = True
        # take the batch OUT of the deque before awaiting: publish() may
        # run during the await and pop the deque's front on overflow —
        # popping len(batch) afterwards would then discard events that
        # were never published.  On failure the batch goes back to the
        # FRONT (order preserved), where overflow accounting can see it.
        batch = [
            self._buf.popleft() for _ in range(min(256, len(self._buf)))
        ]
        try:
            # routed: each key-hash partition goes to its OWNING broker,
            # so the notifier works unchanged against a multi-broker
            # cluster
            await self.client.publish_routed(self.topic, batch)
        except BaseException:  # incl. CancelledError: close() cancels the
            # drain mid-publish and then runs the final flush — the batch
            # must be back in the buffer for it
            self._buf.extendleft(reversed(batch))
            raise

    # bound any silently-hung RPC (half-dead channel, stalled handler):
    # a timeout surfaces as a retry with rotation instead of an unbounded
    # stall that drains nothing and logs nothing
    _PUBLISH_TIMEOUT = 10.0

    async def _drain(self) -> None:
        backoff = 0.5
        try:
            while self._buf and not self._closing:
                try:
                    await asyncio.wait_for(
                        self._publish_batch(), self._PUBLISH_TIMEOUT
                    )
                    backoff = 0.5
                except Exception as e:  # noqa: BLE001 — broker down: retry
                    log.warning(
                        "mq notify publish failed (will retry): %s", e
                    )
                    self.client.reset()
                    if len(self._addrs) > 1:
                        # rotate bootstrap brokers (kafka bootstrap-list
                        # semantics): a dead bootstrap must not stall
                        # events while other brokers live
                        from ..mq.client import MqClient

                        self._addr_idx = (
                            self._addr_idx + 1
                        ) % len(self._addrs)
                        self.client = MqClient(self._addrs[self._addr_idx])
                    await asyncio.sleep(backoff)
                    backoff = min(backoff * 2, 5.0)
        finally:
            self._draining = False
            self._maybe_spawn()  # raced with a publish after the check

    async def close(self) -> None:
        """One final best-effort flush, then stop the drain task."""
        self._closing = True
        if self._task is not None and not self._task.done():
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._buf:
            try:
                while self._buf:
                    await asyncio.wait_for(
                        self._publish_batch(), self._PUBLISH_TIMEOUT
                    )
            except Exception as e:  # noqa: BLE001
                log.warning("mq notify final flush failed: %s", e)


class FileQueueNotifier(Notifier):
    """Spool events to a local file as <u16 key len><key><u32 proto
    len><proto> records — the stand-in for an external queue."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fh = open(path, "ab")

    async def publish(self, key, notification) -> None:
        kb = key.encode()
        blob = notification.SerializeToString()
        self._fh.write(struct.pack("<H", len(kb)) + kb)
        self._fh.write(struct.pack("<I", len(blob)) + blob)
        self._fh.flush()

    def close(self) -> None:
        self._fh.close()

    @staticmethod
    def read_from(path: str, offset: int = 0):
        """Yield (next_offset, key, EventNotification) records starting at
        a byte offset; stops cleanly at a torn tail (a concurrent writer's
        half-flushed record) so pollers can resume from the SAME offset.
        The single reader of the wire format — filer.replicate and
        read_all both ride it."""
        with open(path, "rb") as f:
            f.seek(offset)
            while True:
                hdr = f.read(2)
                if len(hdr) < 2:
                    return
                (kn,) = struct.unpack("<H", hdr)
                key = f.read(kn)
                ln = f.read(4)
                if len(key) < kn or len(ln) < 4:
                    return
                (bn,) = struct.unpack("<I", ln)
                blob = f.read(bn)
                if len(blob) < bn:
                    return
                offset = f.tell()
                yield (
                    offset,
                    key.decode(),
                    filer_pb2.EventNotification.FromString(blob),
                )

    @staticmethod
    def read_all(path: str) -> list[tuple[str, filer_pb2.EventNotification]]:
        return [
            (key, ev)
            for _, key, ev in FileQueueNotifier.read_from(path)
        ]
