"""Metadata-change notification publishers.

Reference: weed/notification/configuration.go — a MessageQueue interface
(SendMessage(key, proto)) with kafka/SQS/pub-sub/log backends, invoked
for every filer meta mutation when notifications are configured.  Broker
backends need external services (zero egress here), so the shipped
implementations are the log publisher, a local spool file (length-
prefixed records an external forwarder can drain), and an in-process
callback for embedding.
"""
from __future__ import annotations

import asyncio
import logging
import os
import struct

from ..pb import filer_pb2

log = logging.getLogger("notification")


class Notifier:
    async def publish(
        self, key: str, notification: filer_pb2.EventNotification
    ) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class LogNotifier(Notifier):
    """notification.log backend."""

    async def publish(self, key, notification) -> None:
        log.info(
            "meta event %s: old=%s new=%s", key,
            notification.old_entry.name or "-",
            notification.new_entry.name or "-",
        )


class CallbackNotifier(Notifier):
    def __init__(self, fn):
        self.fn = fn

    async def publish(self, key, notification) -> None:
        r = self.fn(key, notification)
        if asyncio.iscoroutine(r):
            await r


class FileQueueNotifier(Notifier):
    """Spool events to a local file as <u16 key len><key><u32 proto
    len><proto> records — the stand-in for an external queue."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fh = open(path, "ab")

    async def publish(self, key, notification) -> None:
        kb = key.encode()
        blob = notification.SerializeToString()
        self._fh.write(struct.pack("<H", len(kb)) + kb)
        self._fh.write(struct.pack("<I", len(blob)) + blob)
        self._fh.flush()

    def close(self) -> None:
        self._fh.close()

    @staticmethod
    def read_from(path: str, offset: int = 0):
        """Yield (next_offset, key, EventNotification) records starting at
        a byte offset; stops cleanly at a torn tail (a concurrent writer's
        half-flushed record) so pollers can resume from the SAME offset.
        The single reader of the wire format — filer.replicate and
        read_all both ride it."""
        with open(path, "rb") as f:
            f.seek(offset)
            while True:
                hdr = f.read(2)
                if len(hdr) < 2:
                    return
                (kn,) = struct.unpack("<H", hdr)
                key = f.read(kn)
                ln = f.read(4)
                if len(key) < kn or len(ln) < 4:
                    return
                (bn,) = struct.unpack("<I", ln)
                blob = f.read(bn)
                if len(blob) < bn:
                    return
                offset = f.tell()
                yield (
                    offset,
                    key.decode(),
                    filer_pb2.EventNotification.FromString(blob),
                )

    @staticmethod
    def read_all(path: str) -> list[tuple[str, filer_pb2.EventNotification]]:
        return [
            (key, ev)
            for _, key, ev in FileQueueNotifier.read_from(path)
        ]
