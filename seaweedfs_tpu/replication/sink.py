"""Replication sinks: apply one filer's metadata events to a target.

Two targets, same ``apply(ev)`` surface (the reference's
weed/replication/sink/ ReplicationSink interface):

* FilerSink — another filer cluster, re-homing chunk data via the
  target's AssignVolume (reference sink/filersink/filer_sink.go, driven
  by weed/replication/replicator.go event dispatch).
* ObjectStoreSink — a storage backend from storage/backend.py, writing
  whole objects (reference sink/s3sink/s3_sink.go and sink/localsink;
  with an "s3"-type backend this IS the S3 replication sink, e2e-testable
  against the in-repo gateway).
"""
from __future__ import annotations

import logging

import grpc

from ..operation.upload import upload_data
from ..pb import Stub, filer_pb2
from ..pb.rpc import channel

log = logging.getLogger("replication.sink")


# Sinks that assemble or re-home chunk data MUST resolve manifest chunks
# (filer/manifest.expand_data_chunks) — splicing a manifest chunk's own
# bytes into an object would store serialized FileChunkManifest protos
# instead of file data.  Reference: sink/s3sink via filer.ResolveChunkManifest.


class ObjectStoreSink:
    """Mirror filer DATA into an object-store backend (s3/local).

    Event mapping (s3_sink.go CreateEntry/DeleteEntry): a file create or
    update fetches every chunk from the source and PUTs one object at the
    path-derived key; deletes remove the key; directories are skipped (no
    object-store counterpart); renames are delete+create.
    """

    def __init__(
        self,
        storage,  # storage/backend.py BackendStorage
        fetch_chunk,  # async (file_id) -> bytes, from the source cluster
        source_path: str = "/",
        key_prefix: str = "",
    ):
        self.storage = storage
        self.fetch_chunk = fetch_chunk
        self.source_path = source_path.rstrip("/")
        self.key_prefix = key_prefix.strip("/")

    def _key(self, directory: str, name: str) -> str | None:
        full = f"{directory.rstrip('/')}/{name}"
        if self.source_path and not (
            full == self.source_path or full.startswith(self.source_path + "/")
        ):
            return None
        rel = full[len(self.source_path):].strip("/")
        if not rel:
            return None
        return f"{self.key_prefix}/{rel}" if self.key_prefix else rel

    async def apply(self, ev) -> None:
        import asyncio

        n = ev.event_notification
        has_old = n.HasField("old_entry")
        has_new = n.HasField("new_entry")
        if has_old:
            old_key = self._key(ev.directory, n.old_entry.name)
            moved = has_new and n.new_parent_path and (
                n.new_parent_path != ev.directory
                or n.old_entry.name != n.new_entry.name
            )
            if old_key and (not has_new or moved):
                if n.old_entry.is_directory:
                    # directory delete/rename: sweep the whole prefix
                    # (s3_sink.go deleteDirectory semantics)
                    def sweep(prefix=old_key):
                        for k, _ in self.storage.list_keys(prefix):
                            if k == prefix or k.startswith(prefix + "/"):
                                self.storage.delete_key(k)

                    await asyncio.to_thread(sweep)
                else:
                    await asyncio.to_thread(self.storage.delete_key, old_key)
        if has_new and not n.new_entry.is_directory:
            directory = n.new_parent_path or ev.directory
            key = self._key(directory, n.new_entry.name)
            if key is None:
                return
            from ..filer.manifest import expand_data_chunks

            content = bytearray(n.new_entry.content)
            chunks = await expand_data_chunks(
                self.fetch_chunk, n.new_entry.chunks
            )
            # oldest-first by modified_ts_ns (ties: list order) so newer
            # overlapping chunks shadow older bytes, exactly like the
            # filer's interval resolution (filer/filechunks.py)
            ordered = [
                c
                for _, _, c in sorted(
                    (c.modified_ts_ns, i, c) for i, c in enumerate(chunks)
                )
            ]
            from ..filer.manifest import decoded_chunk_fetcher

            fetch_decoded = decoded_chunk_fetcher(self.fetch_chunk)
            for c in ordered:
                # decode per-chunk framing: the mirror stores FILE bytes,
                # not the zstd/AES envelopes volume servers hold
                blob = await fetch_decoded(c)
                end = c.offset + len(blob)
                if len(content) < end:
                    content.extend(b"\x00" * (end - len(content)))
                content[c.offset : end] = blob
            await asyncio.to_thread(
                self.storage.put_bytes, key, bytes(content)
            )


class FilerSink:
    def __init__(
        self,
        filer_grpc_address: str,
        fetch_chunk,  # async (file_id) -> bytes, from the source cluster
        signature: int = 0,
        collection: str = "",
        replication: str = "",
        source_path: str = "/",  # subtree on the source...
        target_path: str = "/",  # ...lands here on the target (filer_sync.go key translation)
    ):
        self.filer_grpc_address = filer_grpc_address
        self.fetch_chunk = fetch_chunk
        self.signature = signature
        self.collection = collection
        self.replication = replication
        self.source_path = source_path.rstrip("/")
        self.target_path = target_path.rstrip("/")
        self._stub_cache = None
        self._session = None  # lazy aiohttp session for target-side fetches

    def _map_dir(self, directory: str) -> str:
        if self.source_path == self.target_path:
            return directory
        if directory == self.source_path or directory.startswith(
            self.source_path + "/"
        ):
            return self.target_path + directory[len(self.source_path):]
        return directory

    def _stub(self):
        if self._stub_cache is None:
            self._stub_cache = Stub(
                channel(self.filer_grpc_address), filer_pb2, "SeaweedFiler"
            )
        return self._stub_cache

    async def _sess(self):
        import aiohttp

        if self._session is None:
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def apply(self, ev: filer_pb2.SubscribeMetadataResponse) -> None:
        """Dispatch one event (replicator.go Replicate)."""
        n = ev.event_notification
        has_old = n.HasField("old_entry")
        has_new = n.HasField("new_entry")
        if has_old and not has_new:
            await self._delete(ev.directory, n.old_entry)
        elif has_new and not has_old:
            await self._create(n.new_parent_path or ev.directory, n.new_entry)
        elif has_old and has_new:
            moved = n.new_parent_path and (
                n.new_parent_path != ev.directory
                or n.old_entry.name != n.new_entry.name
            )
            if moved:
                # rename: drop the old location, create at the new one
                await self._delete(ev.directory, n.old_entry, delete_data=False)
                await self._create(n.new_parent_path, n.new_entry)
            else:
                await self._create(ev.directory, n.new_entry)

    async def _existing_by_source(
        self, directory: str, name: str
    ) -> tuple[dict[str, filer_pb2.FileChunk], list[filer_pb2.FileChunk]]:
        """(by_source_fid, target_top_level_chunks) for the entry already
        replicated at the target — lets updates skip unchanged chunks
        (filer_sink.go UpdateEntry's chunk diff).  The target entry's
        manifests must expand first: the source_file_id-carrying children
        live INSIDE the manifest blobs, and missing them would re-upload
        every chunk of a large file on each metadata-only event."""
        try:
            resp = await self._stub().LookupDirectoryEntry(
                filer_pb2.LookupDirectoryEntryRequest(
                    directory=directory, name=name
                )
            )
        except grpc.aio.AioRpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                return {}, []
            raise
        if not resp.HasField("entry"):
            return {}, []
        top = list(resp.entry.chunks)
        chunks = top
        if any(c.is_chunk_manifest for c in chunks):
            from ..filer.manifest import expand_data_chunks, fetch_chunk_via_lookup

            sess = await self._sess()
            chunks = await expand_data_chunks(
                lambda fid: fetch_chunk_via_lookup(self._stub(), sess, fid),
                chunks,
            )
        return {c.source_file_id: c for c in chunks if c.source_file_id}, top

    async def _replicate_chunks(
        self, entry: filer_pb2.Entry, existing: dict[str, filer_pb2.FileChunk]
    ) -> list[filer_pb2.FileChunk]:
        out = []
        for c in entry.chunks:
            have = existing.get(c.file_id)
            if have is not None:
                # data already in the target cluster — keep its fid, take
                # the source's logical placement
                nc = filer_pb2.FileChunk()
                nc.CopyFrom(c)
                nc.file_id = have.file_id
                nc.source_file_id = c.file_id
                out.append(nc)
                continue
            blob = await self.fetch_chunk(c.file_id)
            a = await self._stub().AssignVolume(
                filer_pb2.AssignVolumeRequest(
                    count=1,
                    collection=self.collection,
                    replication=self.replication,
                )
            )
            if a.error:
                raise RuntimeError(f"target assign failed: {a.error}")
            await upload_data(
                f"http://{a.location.url}/{a.file_id}",
                blob,
                compress=False,
                jwt=a.auth,
            )
            nc = filer_pb2.FileChunk()
            nc.CopyFrom(c)
            nc.file_id = a.file_id
            nc.source_file_id = c.file_id
            out.append(nc)
        return out

    async def _save_blob(self, blob: bytes) -> filer_pb2.FileChunk:
        """Store a manifest blob in the TARGET cluster -> its FileChunk."""
        a = await self._stub().AssignVolume(
            filer_pb2.AssignVolumeRequest(
                count=1,
                collection=self.collection,
                replication=self.replication,
            )
        )
        if a.error:
            raise RuntimeError(f"target assign failed: {a.error}")
        await upload_data(
            f"http://{a.location.url}/{a.file_id}",
            blob,
            compress=False,
            jwt=a.auth,
        )
        return filer_pb2.FileChunk(file_id=a.file_id, size=len(blob))

    async def _create(self, directory: str, entry: filer_pb2.Entry) -> None:
        directory = self._map_dir(directory)
        existing, target_top = await self._existing_by_source(
            directory, entry.name
        )
        new_entry = filer_pb2.Entry()
        new_entry.CopyFrom(entry)
        del new_entry.chunks[:]
        # expand manifests first: replicating a manifest chunk verbatim
        # would ship a blob whose child fids point at the SOURCE cluster.
        # After re-homing, re-fold so a 100k-chunk source entry doesn't
        # become 100k inline chunks of target metadata.
        from ..filer.manifest import expand_data_chunks, maybe_manifestize_async

        flat = filer_pb2.Entry()
        flat.chunks.extend(
            await expand_data_chunks(self.fetch_chunk, entry.chunks)
        )
        replicated = await self._replicate_chunks(flat, existing)
        existing_fids = {c.file_id for c in existing.values()}
        if (
            target_top
            and len(replicated) == len(existing)
            and all(c.file_id in existing_fids for c in replicated)
        ):
            # metadata-only event: the chunk set is unchanged — keep the
            # target's own (possibly manifestized) list instead of
            # re-uploading fresh manifest blobs per attr touch
            new_entry.chunks.extend(target_top)
        else:
            new_entry.chunks.extend(
                await maybe_manifestize_async(self._save_blob, replicated)
            )
        resp = await self._stub().CreateEntry(
            filer_pb2.CreateEntryRequest(
                directory=directory,
                entry=new_entry,
                is_from_other_cluster=True,
                signatures=[self.signature] if self.signature else [],
            )
        )
        if resp.error:
            raise RuntimeError(f"sink create {directory}/{entry.name}: {resp.error}")

    async def _delete(
        self, directory: str, entry: filer_pb2.Entry, delete_data: bool = True
    ) -> None:
        try:
            await self._stub().DeleteEntry(
                filer_pb2.DeleteEntryRequest(
                    directory=self._map_dir(directory),
                    name=entry.name,
                    is_delete_data=delete_data,
                    is_recursive=True,
                    ignore_recursive_error=True,
                    is_from_other_cluster=True,
                    signatures=[self.signature] if self.signature else [],
                )
            )
        except grpc.aio.AioRpcError as e:
            if e.code() != grpc.StatusCode.NOT_FOUND:
                raise
