"""Source side of filer replication: fetch raw chunk payloads from the
cluster behind a filer.

Reference: weed/replication/source/filer_source.go (LookupFileId +
ReadPart) — chunk bytes are read straight from the source volume
servers, not through the filer's decode path, so cipher/compression
framing travels intact and the sink can store it verbatim.
"""
from __future__ import annotations

import aiohttp

from ..pb import Stub, filer_pb2
from ..pb.rpc import channel


class FilerSource:
    def __init__(self, filer_grpc_address: str):
        self.filer_grpc_address = filer_grpc_address
        self._stub_cache = None
        self._session: aiohttp.ClientSession | None = None

    def _stub(self):
        if self._stub_cache is None:
            self._stub_cache = Stub(
                channel(self.filer_grpc_address), filer_pb2, "SeaweedFiler"
            )
        return self._stub_cache

    async def _sess(self) -> aiohttp.ClientSession:
        if self._session is None:
            self._session = aiohttp.ClientSession()
        return self._session

    async def fetch_chunk(self, file_id: str) -> bytes:
        """Raw needle payload for a chunk fid (any replica)."""
        from ..filer.manifest import fetch_chunk_via_lookup

        return await fetch_chunk_via_lookup(
            self._stub(), await self._sess(), file_id
        )

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None
