"""Continuous filer-to-filer replication (the `filer.sync` command).

Reference: weed/command/filer_sync.go — subscribe to the source filer's
metadata stream from a checkpoint, apply each event through a sink, and
persist the offset in the TARGET filer's KV store so restarts resume.
For active-active sync run two FilerSyncs with the SAME signature: every
entry a sync writes carries its signature, and its own subscription
filters those events out (the reference's doSubscribeFilerMetaChanges
loop guard).
"""
from __future__ import annotations

import asyncio
import logging
import struct

import grpc

from ..pb import Stub, filer_pb2
from ..pb.rpc import channel
from .sink import FilerSink
from .source import FilerSource

log = logging.getLogger("replication.sync")


def _checkpoint_key(source: str, prefix: str) -> bytes:
    return f"filer.sync/{source}{prefix}".encode()


class FilerSync:
    def __init__(
        self,
        source_grpc_address: str,
        target_grpc_address: str,
        path_prefix: str = "/",
        target_path: str = "",  # default: same subtree on the target
        signature: int = 0,
        checkpoint_every: int = 16,
        event_retries: int = 3,
    ):
        self.source_grpc_address = source_grpc_address
        self.target_grpc_address = target_grpc_address
        self.path_prefix = path_prefix
        self.signature = signature or (hash((source_grpc_address, target_grpc_address)) & 0x7FFFFFFF)
        self.checkpoint_every = checkpoint_every
        self.event_retries = event_retries
        self.source = FilerSource(source_grpc_address)
        self.sink = FilerSink(
            target_grpc_address,
            fetch_chunk=self.source.fetch_chunk,
            signature=self.signature,
            source_path=path_prefix,
            target_path=target_path or path_prefix,
        )
        self.applied = 0
        self.skipped = 0
        self._task: asyncio.Task | None = None
        self._source_stub = None
        self._target_stub = None

    def _src(self):
        if self._source_stub is None:
            self._source_stub = Stub(
                channel(self.source_grpc_address), filer_pb2, "SeaweedFiler"
            )
        return self._source_stub

    def _tgt(self):
        if self._target_stub is None:
            self._target_stub = Stub(
                channel(self.target_grpc_address), filer_pb2, "SeaweedFiler"
            )
        return self._target_stub

    async def load_checkpoint(self) -> int:
        resp = await self._tgt().KvGet(
            filer_pb2.KvGetRequest(
                key=_checkpoint_key(self.source_grpc_address, self.path_prefix)
            )
        )
        if resp.value:
            return struct.unpack("<q", resp.value)[0]
        return 0

    async def save_checkpoint(self, ts_ns: int) -> None:
        await self._tgt().KvPut(
            filer_pb2.KvPutRequest(
                key=_checkpoint_key(self.source_grpc_address, self.path_prefix),
                value=struct.pack("<q", ts_ns),
            )
        )

    async def run(self) -> None:
        """Subscribe-apply-checkpoint loop; reconnects on stream errors."""
        since = last_ts = 0
        while True:
            try:
                since = await self.load_checkpoint()
                log.info(
                    "sync %s -> %s from ts=%d",
                    self.source_grpc_address, self.target_grpc_address, since,
                )
                pending = 0
                last_ts = since
                async for ev in self._src().SubscribeMetadata(
                    filer_pb2.SubscribeMetadataRequest(
                        client_name=f"sync-{self.signature}",
                        path_prefix=self.path_prefix,
                        since_ns=since,
                        signature=self.signature,
                    )
                ):
                    await self._apply_with_retry(ev)
                    last_ts = ev.ts_ns
                    pending += 1
                    if pending >= self.checkpoint_every:
                        await self.save_checkpoint(last_ts)
                        pending = 0
            except asyncio.CancelledError:
                if last_ts > since:
                    await self.save_checkpoint(last_ts)
                raise
            except grpc.aio.AioRpcError as e:
                log.warning("sync stream error (%s); reconnecting", e.code())
                await asyncio.sleep(1.0)

    async def _apply_with_retry(self, ev) -> None:
        """Retry transient failures; a deterministically-failing event is
        skipped (logged) so it can't wedge the stream forever — e.g. a
        create whose source chunks were purged before the sync saw it."""
        for attempt in range(self.event_retries):
            try:
                await self.sink.apply(ev)
                self.applied += 1
                return
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                if attempt == self.event_retries - 1:
                    self.skipped += 1
                    log.exception(
                        "sync event at ts=%d failed %d times; skipping",
                        ev.ts_ns, self.event_retries,
                    )
                else:
                    await asyncio.sleep(0.5 * (attempt + 1))

    def start(self) -> None:
        self._task = asyncio.create_task(self.run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        await self.source.close()
        await self.sink.close()
