"""S3 gateway (reference weed/s3api/, 12.8k LoC): SigV4 auth, bucket and
object APIs, multipart uploads — all backed by the filer namespace."""
from .auth import (
    Identity,
    IdentityAccessManagement,
    S3AuthError,
    sign_request_headers,
)
from .server import S3ApiServer, S3Error

__all__ = [
    "Identity",
    "IdentityAccessManagement",
    "S3ApiServer",
    "S3AuthError",
    "S3Error",
    "sign_request_headers",
]
