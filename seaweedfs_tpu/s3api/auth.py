"""S3 authentication: AWS Signature V4 (header + presigned query +
streaming chunk chain), legacy Signature V2 (header + presigned), POST
policy verification, and the identity/action model.

Reference: weed/s3api/auth_signature_v4.go (771 LoC — canonical request,
string-to-sign, signing-key chain), auth_signature_v2.go,
chunked_reader_v4.go, s3api_object_handlers_postpolicy.go,
auth_credentials.go (identity config, per-bucket actions).
"""
from __future__ import annotations

import base64
import calendar
import hashlib
import hmac
import time
import urllib.parse
from dataclasses import dataclass, field

UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"

ACTION_ADMIN = "Admin"
ACTION_READ = "Read"
ACTION_WRITE = "Write"
ACTION_LIST = "List"
ACTION_TAGGING = "Tagging"


# where the IAM API persists identities inside the filer (shared with
# iamapi/server.py; reference: filer_etc /etc/iam/identity.json)
IDENTITY_FILER_PATH = ("/etc/iam", "identity.json")


def scope_covers(limit: str, bucket: str) -> bool:
    """Does an action's ':bucket' scope cover this bucket?  Single source
    of truth shared by enforcement (Identity.can_do) and the ACL view
    (get_bucket_acl) so the two can't drift."""
    return not limit or limit == bucket or bucket.startswith(limit)


class S3AuthError(Exception):
    def __init__(self, code: str, message: str, status: int = 403):
        super().__init__(message)
        self.code = code
        self.status = status


@dataclass
class Identity:
    name: str
    credentials: list[tuple[str, str]] = field(default_factory=list)  # (access, secret)
    actions: list[str] = field(default_factory=list)  # "Admin", "Read:bucket", ...

    def can_do(self, action: str, bucket: str = "") -> bool:
        if ACTION_ADMIN in self.actions:
            return True
        for a in self.actions:
            base, _, limit = a.partition(":")
            # "Admin:bucket" grants every action within that bucket only
            if base != action and base != ACTION_ADMIN:
                continue
            if base == ACTION_ADMIN and not limit:
                continue  # bare Admin handled above
            if base == ACTION_ADMIN and not bucket:
                continue  # bucket-scoped admin can't do global actions
            if scope_covers(limit, bucket):
                return True
        return False


class IdentityAccessManagement:
    """Identity registry (reference auth_credentials.go).  With no
    identities configured, all requests are anonymous-allowed — matching
    the reference's behavior when no s3 config exists."""

    def __init__(self, identities: list[Identity] | None = None):
        self.identities = identities or []
        self._by_access_key: dict[str, tuple[Identity, str]] = {}
        for ident in self.identities:
            for access, secret in ident.credentials:
                self._by_access_key[access] = (ident, secret)

    @classmethod
    def from_config(cls, cfg: dict) -> "IdentityAccessManagement":
        """Parse the reference's s3.json shape:
        {"identities":[{"name","credentials":[{"accessKey","secretKey"}],
        "actions":["Admin",...]}]}"""
        idents = [
            Identity(
                name=i.get("name", ""),
                credentials=[
                    (c["accessKey"], c["secretKey"])
                    for c in i.get("credentials", [])
                ],
                actions=list(i.get("actions", [])),
            )
            for i in cfg.get("identities", [])
        ]
        return cls(idents)

    def to_config(self) -> dict:
        """Inverse of from_config (persisted by the IAM API)."""
        return {
            "identities": [
                {
                    "name": i.name,
                    "credentials": [
                        {"accessKey": a, "secretKey": s}
                        for a, s in i.credentials
                    ],
                    "actions": list(i.actions),
                }
                for i in self.identities
            ]
        }

    # -------------------------------------------------- mutation (IAM API)

    def find(self, name: str) -> Identity | None:
        return next((i for i in self.identities if i.name == name), None)

    def add_identity(self, ident: Identity) -> None:
        if self.find(ident.name) is not None:
            raise S3AuthError("EntityAlreadyExists", f"user {ident.name} exists", 409)
        self.identities.append(ident)
        for access, secret in ident.credentials:
            self._by_access_key[access] = (ident, secret)

    def remove_identity(self, name: str) -> None:
        ident = self.find(name)
        if ident is None:
            raise S3AuthError("NoSuchEntity", f"user {name} not found", 404)
        self.identities.remove(ident)
        for access, _ in ident.credentials:
            self._by_access_key.pop(access, None)

    def add_credential(self, name: str, access: str, secret: str) -> None:
        ident = self.find(name)
        if ident is None:
            raise S3AuthError("NoSuchEntity", f"user {name} not found", 404)
        ident.credentials.append((access, secret))
        self._by_access_key[access] = (ident, secret)

    def remove_credential(self, name: str, access: str) -> None:
        ident = self.find(name)
        if ident is None:
            raise S3AuthError("NoSuchEntity", f"user {name} not found", 404)
        if not any(c[0] == access for c in ident.credentials):
            # never revoke another identity's key through the wrong user
            raise S3AuthError(
                "NoSuchEntity", f"access key not owned by {name}", 404
            )
        ident.credentials = [c for c in ident.credentials if c[0] != access]
        self._by_access_key.pop(access, None)

    @property
    def enabled(self) -> bool:
        return bool(self.identities)

    def lookup(self, access_key: str) -> tuple[Identity, str]:
        try:
            return self._by_access_key[access_key]
        except KeyError:
            raise S3AuthError("InvalidAccessKeyId", f"unknown access key {access_key}")

    # ------------------------------------------------------------- verify

    def authenticate(self, request) -> Identity | None:
        """Verify an aiohttp request; returns the Identity (None =
        anonymous and auth disabled).  Raises S3AuthError on failure.

        Records `request["s3_signed"]` — True when the identity came
        from verified SigV4/V2 credentials, False when it rode the
        anonymous identity — so handlers can gate parameters AWS allows
        only on signed requests (e.g. GetObject response-* overrides)
        without re-deriving which scheme applied."""
        if not self.enabled:
            return None
        request["s3_signed"] = True
        auth_header = request.headers.get("Authorization", "")
        if auth_header.startswith("AWS4-HMAC-SHA256"):
            return self._verify_header_sig(request, auth_header)
        if request.query.get("X-Amz-Algorithm") == "AWS4-HMAC-SHA256":
            return self._verify_presigned(request)
        if auth_header.startswith("AWS "):
            return self._verify_v2_header(request, auth_header)
        if "Signature" in request.query and "AWSAccessKeyId" in request.query:
            return self._verify_v2_presigned(request)
        request["s3_signed"] = False
        anon = next((i for i in self.identities if i.name == "anonymous"), None)
        if anon is not None:
            return anon
        raise S3AuthError("AccessDenied", "no credentials provided")

    def _verify_header_sig(self, request, auth_header: str) -> Identity:
        # Authorization: AWS4-HMAC-SHA256 Credential=AK/d/r/s3/aws4_request,
        #   SignedHeaders=host;x-amz-date, Signature=hex
        try:
            fields = dict(
                kv.strip().split("=", 1)
                for kv in auth_header.split(" ", 1)[1].split(",")
            )
            credential = fields["Credential"]
            signed_headers = fields["SignedHeaders"].split(";")
            got_sig = fields["Signature"]
            access_key, datestamp, region, service, terminal = credential.split("/")
        except (KeyError, ValueError):
            raise S3AuthError("AuthorizationHeaderMalformed", "bad Authorization header")
        identity, secret = self.lookup(access_key)
        amz_date = request.headers.get("x-amz-date", "")
        _check_skew(amz_date)
        payload_hash = request.headers.get(
            "x-amz-content-sha256", UNSIGNED_PAYLOAD
        )
        canonical = _canonical_request(
            request.method,
            request.path,
            _canonical_query(request.query_string, drop_signature=False),
            {h: request.headers.get(h, "") for h in signed_headers},
            signed_headers,
            payload_hash,
        )
        expect = _signature(
            secret, datestamp, region, service, amz_date, canonical
        )
        if not hmac.compare_digest(expect, got_sig):
            raise S3AuthError("SignatureDoesNotMatch", "signature mismatch")
        if payload_hash == STREAMING_PAYLOAD:
            # the seed signature anchors each chunk's signature chain;
            # the body reader verifies every chunk against this context
            # (chunked_reader_v4.go)
            request["s3_chunk_ctx"] = (
                secret, datestamp, region, service, amz_date, got_sig,
            )
        return identity

    def _verify_presigned(self, request) -> Identity:
        q = request.query
        try:
            credential = q["X-Amz-Credential"]
            amz_date = q["X-Amz-Date"]
            expires = int(q.get("X-Amz-Expires", "900"))
            signed_headers = q["X-Amz-SignedHeaders"].split(";")
            got_sig = q["X-Amz-Signature"]
            access_key, datestamp, region, service, terminal = credential.split("/")
        except (KeyError, ValueError):
            raise S3AuthError("AuthorizationQueryParametersError", "bad presign params")
        t = time.strptime(amz_date, "%Y%m%dT%H%M%SZ")
        if time.mktime(t) + expires < time.mktime(time.gmtime()):
            raise S3AuthError("AccessDenied", "request has expired")
        identity, secret = self.lookup(access_key)
        canonical = _canonical_request(
            request.method,
            request.path,
            _canonical_query(request.query_string, drop_signature=True),
            {h: request.headers.get(h, "") for h in signed_headers},
            signed_headers,
            UNSIGNED_PAYLOAD,
        )
        expect = _signature(secret, datestamp, region, service, amz_date, canonical)
        if not hmac.compare_digest(expect, got_sig):
            raise S3AuthError("SignatureDoesNotMatch", "signature mismatch")
        return identity


    # ------------------------------------------------- signature V2 (legacy)

    def _verify_v2_header(self, request, auth_header: str) -> Identity:
        """Authorization: AWS AccessKey:Base64(HMAC-SHA1(StringToSign))
        (auth_signature_v2.go)."""
        access_key, _, got_sig = auth_header[4:].strip().partition(":")
        identity, secret = self.lookup(access_key)
        _check_skew_v2(request.headers)
        expect = _signature_v2(secret, _string_to_sign_v2(request))
        if not hmac.compare_digest(expect, got_sig):
            raise S3AuthError("SignatureDoesNotMatch", "signature mismatch")
        return identity

    def _verify_v2_presigned(self, request) -> Identity:
        """?AWSAccessKeyId=..&Expires=epoch&Signature=.. query auth."""
        q = request.query
        try:
            expires = int(q["Expires"])
        except (KeyError, ValueError):
            raise S3AuthError("AccessDenied", "bad Expires")
        if expires < time.time():
            raise S3AuthError("AccessDenied", "request has expired")
        identity, secret = self.lookup(q["AWSAccessKeyId"])
        expect = _signature_v2(
            secret, _string_to_sign_v2(request, date_value=str(expires))
        )
        if not hmac.compare_digest(expect, q["Signature"]):
            raise S3AuthError("SignatureDoesNotMatch", "signature mismatch")
        return identity

    # ---------------------------------------------------------- POST policy

    def verify_post_policy(self, fields: dict) -> Identity | None:
        """Authenticate a browser-form POST upload from its form fields
        (s3api_object_handlers_postpolicy.go).  Returns the Identity, or
        None when auth is disabled."""
        if not self.enabled:
            return None
        policy_b64 = fields.get("policy", "")
        if not policy_b64:
            raise S3AuthError("AccessDenied", "POST without policy")
        if "x-amz-signature" in fields:  # V4-signed form
            try:
                credential = fields["x-amz-credential"]
                amz_date = fields["x-amz-date"]
                got_sig = fields["x-amz-signature"]
                access_key, datestamp, region, service, _ = credential.split("/")
            except (KeyError, ValueError):
                raise S3AuthError("AccessDenied", "malformed POST credential")
            identity, secret = self.lookup(access_key)
            key = _signing_key(secret, datestamp, region, service)
            expect = hmac.new(key, policy_b64.encode(), hashlib.sha256).hexdigest()
            if not hmac.compare_digest(expect, got_sig):
                raise S3AuthError("SignatureDoesNotMatch", "policy signature mismatch")
            return identity
        if "signature" in fields and "AWSAccessKeyId" in fields:  # V2 form
            identity, secret = self.lookup(fields["AWSAccessKeyId"])
            expect = _signature_v2(secret, policy_b64)
            if not hmac.compare_digest(expect, fields["signature"]):
                raise S3AuthError("SignatureDoesNotMatch", "policy signature mismatch")
            return identity
        raise S3AuthError("AccessDenied", "POST form carries no signature")


# v2 sub-resources that participate in the canonical resource
# (auth_signature_v2.go resourceList)
_V2_SUBRESOURCES = (
    "acl", "delete", "lifecycle", "location", "logging", "notification",
    "partNumber", "policy", "requestPayment", "response-cache-control",
    "response-content-disposition", "response-content-encoding",
    "response-content-language", "response-content-type", "response-expires",
    "tagging", "torrent", "uploadId", "uploads", "versionId", "versioning",
    "versions", "website",
)


def _string_to_sign_v2(request, date_value: str | None = None) -> str:
    h = request.headers
    if date_value is None:
        # x-amz-date supersedes Date, in which case Date's slot is empty
        date_value = "" if "x-amz-date" in h else h.get("Date", "")
    amz = sorted(
        (k.lower(), v.strip())
        for k, v in h.items()
        if k.lower().startswith("x-amz-")
    )
    canonical_amz = "".join(f"{k}:{v}\n" for k, v in amz)
    sub = sorted(k for k in request.query if k in _V2_SUBRESOURCES)
    resource = request.path
    if sub:
        resource += "?" + "&".join(
            k if not request.query[k] else f"{k}={request.query[k]}"
            for k in sub
        )
    return (
        f"{request.method}\n{h.get('Content-MD5', '')}\n"
        f"{h.get('Content-Type', '')}\n{date_value}\n"
        f"{canonical_amz}{resource}"
    )


def _signature_v2(secret: str, string_to_sign: str) -> str:
    return base64.b64encode(
        hmac.new(secret.encode(), string_to_sign.encode(), hashlib.sha1).digest()
    ).decode()


def _check_skew_v2(headers) -> None:
    """The 15-minute replay window applies to V2 too; the signed Date /
    x-amz-date must be fresh (AWS RequestTimeTooSkewed semantics)."""
    raw = headers.get("x-amz-date") or headers.get("Date", "")
    for fmt in ("%a, %d %b %Y %H:%M:%S GMT", "%Y%m%dT%H%M%SZ"):
        try:
            t = time.strptime(raw, fmt)
            break
        except ValueError:
            continue
    else:
        raise S3AuthError("AccessDenied", f"bad request date {raw!r}")
    if abs(calendar.timegm(t) - time.time()) > MAX_SKEW_SECONDS:
        raise S3AuthError("RequestTimeTooSkewed", "request time too skewed")


MAX_SKEW_SECONDS = 15 * 60  # the reference's 15-minute window


def _check_skew(amz_date: str) -> None:
    try:
        t = time.strptime(amz_date, "%Y%m%dT%H%M%SZ")
    except ValueError:
        raise S3AuthError("AccessDenied", f"bad x-amz-date {amz_date!r}")
    if abs(calendar.timegm(t) - time.time()) > MAX_SKEW_SECONDS:
        raise S3AuthError("RequestTimeTooSkewed", "request time too skewed")


async def verify_payload_hash(request) -> bytes | None:
    """When the client signed a concrete payload hash, read the body and
    check it (the reference hashes the stream inline,
    auth_signature_v4.go).  Returns the consumed body so the handler can
    reuse it, or None when the payload is unsigned/streaming."""
    declared = request.headers.get("x-amz-content-sha256", "")
    if declared in ("", UNSIGNED_PAYLOAD, STREAMING_PAYLOAD) or len(declared) != 64:
        return None
    if request.method not in ("PUT", "POST"):
        return None
    body = await request.read()
    if hashlib.sha256(body).hexdigest() != declared:
        raise S3AuthError("XAmzContentSHA256Mismatch", "payload hash mismatch", 400)
    return body


def _iter_aws_chunks(data: bytes):
    """Yield (chunk_bytes, chunk_signature_hex) per frame, ending with the
    zero-length terminal frame."""
    pos = 0
    while pos < len(data):
        nl = data.find(b"\r\n", pos)
        if nl < 0:
            break
        header = data[pos:nl]
        size_hex, _, attrs = header.partition(b";")
        sig = b""
        for kv in attrs.split(b";"):
            k, _, v = kv.partition(b"=")
            if k.strip() == b"chunk-signature":
                sig = v.strip()
        try:
            size = int(size_hex, 16)
        except ValueError:
            raise S3AuthError("InvalidRequest", "bad aws-chunked framing", 400)
        start = nl + 2
        # errors="replace": a garbage signature must FAIL verification
        # (compare_digest mismatch), not 500 on the decode
        yield data[start : start + size], sig.decode(errors="replace")
        if size == 0:
            return
        pos = start + size + 2  # skip trailing \r\n


def decode_aws_chunked(data: bytes) -> bytes:
    """Strip aws-chunked framing:
    `<hex-size>;chunk-signature=<sig>\\r\\n<data>\\r\\n...0;...\\r\\n\\r\\n`
    (reference chunked_reader_v4.go) WITHOUT verifying chunk signatures —
    used only when auth is disabled (no secret to verify against)."""
    out = bytearray()
    for chunk, _sig in _iter_aws_chunks(data):
        out += chunk
    return bytes(out)


def decode_aws_chunked_verified(
    data: bytes,
    secret: str,
    datestamp: str,
    region: str,
    service: str,
    amz_date: str,
    seed_signature: str,
) -> bytes:
    """Strip aws-chunked framing AND verify every chunk signature against
    the V4 chain anchored at the request's seed signature
    (chunked_reader_v4.go getChunkSignature): each chunk signs
    AWS4-HMAC-SHA256-PAYLOAD \\n date \\n scope \\n prev_sig \\n
    sha256('') \\n sha256(chunk)."""
    key = _signing_key(secret, datestamp, region, service)
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    empty_hash = hashlib.sha256(b"").hexdigest()
    prev = seed_signature
    out = bytearray()
    saw_terminal = False
    for chunk, got_sig in _iter_aws_chunks(data):
        string_to_sign = "\n".join(
            [
                "AWS4-HMAC-SHA256-PAYLOAD",
                amz_date,
                scope,
                prev,
                empty_hash,
                hashlib.sha256(chunk).hexdigest(),
            ]
        )
        expect = hmac.new(
            key, string_to_sign.encode(), hashlib.sha256
        ).hexdigest()
        if not hmac.compare_digest(expect, got_sig):
            raise S3AuthError(
                "SignatureDoesNotMatch", "chunk signature mismatch", 403
            )
        prev = expect
        if not chunk:
            saw_terminal = True
        out += chunk
    if not saw_terminal:
        # without the signed zero-length terminal frame a truncated
        # prefix would verify — the chain must cover the WHOLE stream
        raise S3AuthError(
            "IncompleteBody", "chunked stream missing terminal frame", 400
        )
    return bytes(out)


# ------------------------------------------------------------ sigv4 pieces


def _uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "-._~" if encode_slash else "-._~/"
    return urllib.parse.quote(s, safe=safe)


def _canonical_query(query_string: str, drop_signature: bool) -> str:
    pairs = []
    for part in query_string.split("&") if query_string else []:
        if not part:
            continue
        k, _, v = part.partition("=")
        k = urllib.parse.unquote_plus(k)
        v = urllib.parse.unquote_plus(v)
        if drop_signature and k == "X-Amz-Signature":
            continue
        pairs.append((_uri_encode(k), _uri_encode(v)))
    pairs.sort()
    return "&".join(f"{k}={v}" for k, v in pairs)


def _canonical_request(
    method: str,
    path: str,
    canonical_query: str,
    headers: dict[str, str],
    signed_headers: list[str],
    payload_hash: str,
) -> str:
    names = sorted(h.lower() for h in signed_headers)
    canonical_headers = "".join(
        f"{n}:{' '.join(headers.get(n, '').split())}\n" for n in names
    )
    return "\n".join(
        [
            method,
            _uri_encode(path, encode_slash=False),
            canonical_query,
            canonical_headers,
            ";".join(names),
            payload_hash,
        ]
    )


def _signing_key(secret: str, datestamp: str, region: str, service: str) -> bytes:
    k = hmac.new(b"AWS4" + secret.encode(), datestamp.encode(), hashlib.sha256).digest()
    for piece in (region, service, "aws4_request"):
        k = hmac.new(k, piece.encode(), hashlib.sha256).digest()
    return k


def _signature(
    secret: str, datestamp: str, region: str, service: str,
    amz_date: str, canonical_request: str,
) -> str:
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    sts = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(canonical_request.encode()).hexdigest(),
        ]
    )
    return hmac.new(
        _signing_key(secret, datestamp, region, service), sts.encode(), hashlib.sha256
    ).hexdigest()


def sign_request_headers(
    method: str,
    url: str,
    headers: dict[str, str],
    payload: bytes,
    access_key: str,
    secret_key: str,
    region: str = "us-east-1",
    payload_hash: str = "",  # override: UNSIGNED-PAYLOAD / STREAMING-...
) -> dict[str, str]:
    """Client-side SigV4 header signing (used by tests and wdclient-style
    tools; the inverse of _verify_header_sig)."""
    parsed = urllib.parse.urlsplit(url)
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    datestamp = amz_date[:8]
    payload_hash = payload_hash or hashlib.sha256(payload).hexdigest()
    out = dict(headers)
    out["host"] = parsed.netloc
    out["x-amz-date"] = amz_date
    out["x-amz-content-sha256"] = payload_hash
    signed = sorted(["host", "x-amz-date", "x-amz-content-sha256"])
    canonical = _canonical_request(
        method,
        parsed.path or "/",
        _canonical_query(parsed.query, drop_signature=False),
        out,
        signed,
        payload_hash,
    )
    sig = _signature(secret_key, datestamp, region, "s3", amz_date, canonical)
    scope = f"{datestamp}/{region}/s3/aws4_request"
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}"
    )
    del out["host"]  # the HTTP client sets it
    return out
