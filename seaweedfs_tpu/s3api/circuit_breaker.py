"""S3 request circuit breaker.

Reference: weed/s3api/s3api_circuit_breaker.go — concurrent request-count
and in-flight-bytes limits, global and per-bucket, per-action, configured
in /etc/s3/circuit_breaker.json (shell: s3.circuitbreaker) and applied
live.  Exceeding any limit rejects the request with 503 SlowDown rather
than queueing, so an overloaded gateway degrades predictably.
"""
from __future__ import annotations

import json


class CircuitBreakerError(Exception):
    pass


class CircuitBreaker:
    def __init__(self):
        self.cfg: dict = {}
        # in-flight gauges: (scope, action, type) -> current value
        self._inflight: dict[tuple[str, str, str], int] = {}

    def load(self, blob: bytes) -> None:
        """Parse + validate; malformed limit values are dropped at load
        time (a bad hand-edit must not 500 every request at acquire time)."""
        cfg = json.loads(blob) if blob else {}
        for scope_cfg in [
            cfg.get("global") or {},
            *(cfg.get("buckets") or {}).values(),
        ]:
            actions = scope_cfg.get("actions")
            if not isinstance(actions, dict):
                scope_cfg.pop("actions", None)
                continue
            for key in list(actions):
                try:
                    actions[key] = int(actions[key])
                except (TypeError, ValueError):
                    del actions[key]
        self.cfg = cfg

    def _limits(self, bucket: str, action: str):
        """Yield (scope_key, limit_type, limit, cost_multiplier_key)."""
        for scope_key, scope_cfg in (
            ("", self.cfg.get("global") or {}),
            (bucket, (self.cfg.get("buckets") or {}).get(bucket) or {}),
        ):
            if not scope_cfg or scope_cfg.get("enabled") is False:
                continue
            actions = scope_cfg.get("actions") or {}
            for key, limit in actions.items():
                act, _, ltype = key.partition(":")
                if act in (action, "Total"):
                    yield scope_key, act, ltype, int(limit)

    def acquire(self, bucket: str, action: str, content_length: int | None):
        """Reserve capacity or raise; returns a release() callable.
        `content_length=None` (chunked upload) under an MB limit is
        rejected — an unbounded body must not slip past a byte cap."""
        costs = {"Count": 1, "MB": content_length}
        taken: list[tuple[tuple[str, str, str], int]] = []
        for scope, act, ltype, limit in self._limits(bucket, action):
            cost = costs.get(ltype)
            if ltype == "MB" and cost is None:
                for kk, cc in taken:
                    self._inflight[kk] -= cc
                raise CircuitBreakerError(
                    "Content-Length required under an MB limit"
                )
            if cost is None:
                continue
            limit_abs = limit * 1024 * 1024 if ltype == "MB" else limit
            k = (scope, act, ltype)
            cur = self._inflight.get(k, 0)
            if cur + cost > limit_abs:
                for kk, cc in taken:  # roll back partial reservations
                    self._inflight[kk] -= cc
                raise CircuitBreakerError(
                    f"concurrent {act}:{ltype} limit {limit} reached"
                    + (f" for bucket {scope}" if scope else "")
                )
            self._inflight[k] = cur + cost
            taken.append((k, cost))

        def release():
            for kk, cc in taken:
                self._inflight[kk] -= cc

        return release
