"""S3 request circuit breaker.

Reference: weed/s3api/s3api_circuit_breaker.go — concurrent request-count
and in-flight-bytes limits, global and per-bucket, per-action, configured
in /etc/s3/circuit_breaker.json (shell: s3.circuitbreaker) and applied
live.  Exceeding any limit rejects the request with 503 SlowDown rather
than queueing, so an overloaded gateway degrades predictably.

Trip/recover rides the SAME `serving.qos.Breaker` the volume server's
QoS admission uses (one overload policy across the S3 front door and the
EC serving queue): sustained limit-rejections trip a per-scope breaker
that fast-fails further requests without re-walking the limit table,
then half-opens after its cooldown for a probe.
"""
from __future__ import annotations

import json

from ..serving.qos import Breaker


class CircuitBreakerError(Exception):
    pass


class CircuitBreaker:
    # consecutive rejections that trip a scope + the fast-fail cooldown;
    # deliberately the Breaker's own defaults scaled for a public
    # gateway (a storm of 503s means the limit table is saturated — stop
    # paying the walk per request until the cooldown probe)
    TRIP_AFTER = 32
    RECOVER_S = 1.0

    def __init__(self):
        self.cfg: dict = {}
        # in-flight gauges: (scope, action, type) -> current value
        self._inflight: dict[tuple[str, str, str], int] = {}
        # per-(scope, action) trip/recover state ("" = global scope;
        # action is the LIMIT's action key, incl. "Total").  Keyed by
        # action so a saturated Write limit fast-fails writes without
        # 503ing reads whose own limits have free capacity.
        self._breakers: dict[tuple[str, str], Breaker] = {}

    def breaker(self, scope: str, action: str = "Total") -> Breaker:
        key = (scope, action)
        br = self._breakers.get(key)
        if br is None:
            br = self._breakers[key] = Breaker(
                trip_after=self.TRIP_AFTER, cooldown_s=self.RECOVER_S
            )
        return br

    def load(self, blob: bytes) -> None:
        """Parse + validate; malformed limit values are dropped at load
        time (a bad hand-edit must not 500 every request at acquire time)."""
        cfg = json.loads(blob) if blob else {}
        for scope_cfg in [
            cfg.get("global") or {},
            *(cfg.get("buckets") or {}).values(),
        ]:
            actions = scope_cfg.get("actions")
            if not isinstance(actions, dict):
                scope_cfg.pop("actions", None)
                continue
            for key in list(actions):
                try:
                    actions[key] = int(actions[key])
                except (TypeError, ValueError):
                    del actions[key]
        self.cfg = cfg

    def _limits(self, bucket: str, action: str):
        """Yield (scope_key, limit_type, limit, cost_multiplier_key)."""
        for scope_key, scope_cfg in (
            ("", self.cfg.get("global") or {}),
            (bucket, (self.cfg.get("buckets") or {}).get(bucket) or {}),
        ):
            if not scope_cfg or scope_cfg.get("enabled") is False:
                continue
            actions = scope_cfg.get("actions") or {}
            for key, limit in actions.items():
                act, _, ltype = key.partition(":")
                if act in (action, "Total"):
                    yield scope_key, act, ltype, int(limit)

    def acquire(self, bucket: str, action: str, content_length: int | None):
        """Reserve capacity or raise; returns a release() callable.
        `content_length=None` (chunked upload) under an MB limit is
        rejected — an unbounded body must not slip past a byte cap."""
        # fast-fail while a matching breaker is open: that LIMIT was
        # saturated trip_after times in a row — reject without walking
        # the table again until the cooldown's half-open probe.  Only
        # the request's own action (or Total) keys are consulted, so a
        # tripped Write limit never 503s reads.
        for key in (
            ("", action), ("", "Total"), (bucket, action), (bucket, "Total")
        ):
            br = self._breakers.get(key)
            if br is not None and not br.allow():
                raise CircuitBreakerError(
                    f"breaker open for {key[1]}"
                    + (f" in bucket {key[0]}" if key[0] else "")
                    + "; retry after cooldown"
                )
        costs = {"Count": 1, "MB": content_length}
        taken: list[tuple[tuple[str, str, str], int]] = []
        for scope, act, ltype, limit in self._limits(bucket, action):
            cost = costs.get(ltype)
            if ltype == "MB" and cost is None:
                for kk, cc in taken:
                    self._inflight[kk] -= cc
                # a per-request client protocol error, NOT saturation:
                # must not feed the breaker (one broken client retrying
                # chunked uploads could otherwise 503 the whole scope)
                raise CircuitBreakerError(
                    "Content-Length required under an MB limit"
                )
            if cost is None:
                continue
            limit_abs = limit * 1024 * 1024 if ltype == "MB" else limit
            k = (scope, act, ltype)
            cur = self._inflight.get(k, 0)
            if cur + cost > limit_abs:
                for kk, cc in taken:  # roll back partial reservations
                    self._inflight[kk] -= cc
                self.breaker(scope, act).record_rejection()
                raise CircuitBreakerError(
                    f"concurrent {act}:{ltype} limit {limit} reached"
                    + (f" for bucket {scope}" if scope else "")
                )
            self._inflight[k] = cur + cost
            taken.append((k, cost))
        for key in (
            ("", action), ("", "Total"), (bucket, action), (bucket, "Total")
        ):
            if key in self._breakers:
                self._breakers[key].record_success()

        def release():
            for kk, cc in taken:
                self._inflight[kk] -= cc

        return release
