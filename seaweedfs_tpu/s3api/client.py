"""Generic S3-protocol client: the outbound half of the S3 story.

The reference talks S3 as a *client* in four places — the volume tier
backend (weed/storage/backend/s3_backend/s3_backend.go:1-60), remote
storage mounts (weed/remote_storage/s3/s3_storage_client.go:1-50),
replication sinks (weed/replication/sink/s3sink/s3_sink.go), and backup
targets — all through the AWS SDK.  This module is the SDK-free
equivalent: a small synchronous client signed with this repo's own SigV4
implementation (s3api/auth.sign_request_headers), so it interoperates
with any S3 endpoint and is e2e-testable against the in-repo gateway.

Synchronous by design: every consumer (storage backends, sinks) runs on
worker threads or dedicated processes.  Callers on an asyncio loop must
wrap calls in ``asyncio.to_thread`` — especially in-process tests where
the *gateway* shares the loop.
"""
from __future__ import annotations

import http.client
import urllib.parse
import xml.etree.ElementTree as ET

from .auth import sign_request_headers

MULTIPART_THRESHOLD = 64 * 1024 * 1024
PART_SIZE = 32 * 1024 * 1024


class S3Error(OSError):
    def __init__(self, status: int, message: str):
        super().__init__(f"S3 error {status}: {message}")
        self.status = status


class S3Client:
    """Minimal S3 REST client (path-style addressing, SigV4)."""

    def __init__(
        self,
        endpoint: str,
        access_key: str = "",
        secret_key: str = "",
        region: str = "us-east-1",
        timeout: float = 60.0,
    ):
        self.https = endpoint.startswith("https://")
        if "//" in endpoint:
            endpoint = endpoint.split("//", 1)[1]
        self.endpoint = endpoint.rstrip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.timeout = timeout

    # -- transport -----------------------------------------------------------

    def _request(
        self,
        method: str,
        path: str,
        query: str = "",
        data: bytes = b"",
        headers: dict | None = None,
    ) -> tuple[int, bytes, dict]:
        scheme = "https" if self.https else "http"
        url = f"{scheme}://{self.endpoint}{path}"
        if query:
            url += f"?{query}"
        hdrs = dict(headers or {})
        if self.access_key:
            hdrs = sign_request_headers(
                method, url, hdrs, data, self.access_key, self.secret_key,
                region=self.region,
            )
        conn_cls = (
            http.client.HTTPSConnection if self.https
            else http.client.HTTPConnection
        )
        conn = conn_cls(self.endpoint, timeout=self.timeout)
        try:
            conn.request(method, path + (f"?{query}" if query else ""),
                         body=data or None, headers=hdrs)
            resp = conn.getresponse()
            body = resp.read()
            return resp.status, body, dict(resp.getheaders())
        finally:
            conn.close()

    @staticmethod
    def _key_path(bucket: str, key: str) -> str:
        return f"/{bucket}/" + urllib.parse.quote(key.lstrip("/"))

    def _check(self, status: int, body: bytes, key: str = "") -> None:
        if status == 404:
            raise FileNotFoundError(key or "not found")
        if status >= 300:
            raise S3Error(status, body[:500].decode(errors="replace"))

    # -- buckets -------------------------------------------------------------

    def create_bucket(self, bucket: str) -> None:
        status, body, _ = self._request("PUT", f"/{bucket}")
        if status == 409:  # already exists
            return
        self._check(status, body)

    def bucket_exists(self, bucket: str) -> bool:
        status, _, _ = self._request("HEAD", f"/{bucket}")
        return status < 300

    # -- objects -------------------------------------------------------------

    def put_object(self, bucket: str, key: str, data: bytes) -> None:
        status, body, _ = self._request(
            "PUT", self._key_path(bucket, key), data=data
        )
        self._check(status, body, key)

    def put_object_from_file(self, bucket: str, key: str, path: str) -> int:
        """Upload a local file; multipart above MULTIPART_THRESHOLD so a
        tier-moved 30GB .dat doesn't need one giant request (the s3_backend
        uploader's role)."""
        import os

        size = os.path.getsize(path)
        with open(path, "rb") as f:
            if size <= MULTIPART_THRESHOLD:
                self.put_object(bucket, key, f.read())
                return size
            upload_id = self._initiate_multipart(bucket, key)
            try:
                etags = []
                part = 1
                while True:
                    chunk = f.read(PART_SIZE)
                    if not chunk:
                        break
                    etags.append((part, self._upload_part(
                        bucket, key, upload_id, part, chunk
                    )))
                    part += 1
                self._complete_multipart(bucket, key, upload_id, etags)
            except Exception:
                self._abort_multipart(bucket, key, upload_id)
                raise
            return size

    def get_object(
        self, bucket: str, key: str, offset: int = 0, size: int = -1
    ) -> bytes:
        if size == 0:
            return b""  # "bytes=N--1" would be a malformed Range header
        headers = {}
        if offset or size >= 0:
            end = "" if size < 0 else str(offset + size - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        status, body, _ = self._request(
            "GET", self._key_path(bucket, key), headers=headers
        )
        self._check(status, body, key)
        return body

    def get_object_to_file(self, bucket: str, key: str, path: str) -> None:
        """Ranged chunk download to a temp file + atomic rename."""
        total = self.head_object(bucket, key)
        tmp = path + ".tmp"
        chunk = 32 * 1024 * 1024
        with open(tmp, "wb") as f:
            off = 0
            while off < total:
                n = min(chunk, total - off)
                f.write(self.get_object(bucket, key, off, n))
                off += n
        import os

        os.replace(tmp, path)

    def head_object(self, bucket: str, key: str) -> int:
        status, _, headers = self._request("HEAD", self._key_path(bucket, key))
        if status == 404:
            raise FileNotFoundError(key)
        if status >= 300:
            raise S3Error(status, "HEAD failed")
        lower = {k.lower(): v for k, v in headers.items()}
        return int(lower.get("content-length", 0))

    def delete_object(self, bucket: str, key: str) -> None:
        status, body, _ = self._request("DELETE", self._key_path(bucket, key))
        if status not in (200, 204, 404):
            self._check(status, body, key)

    def list_objects(
        self, bucket: str, prefix: str = "", max_keys: int = 1000
    ) -> list[tuple[str, int]]:
        """Full (paginated) ListObjectsV2 -> [(key, size)]."""
        out: list[tuple[str, int]] = []
        token = ""
        while True:
            q = {"list-type": "2", "max-keys": str(max_keys)}
            if prefix:
                q["prefix"] = prefix
            if token:
                q["continuation-token"] = token
            status, body, _ = self._request(
                "GET", f"/{bucket}", query=urllib.parse.urlencode(q)
            )
            self._check(status, body, bucket)
            ns = ""
            root = ET.fromstring(body)
            if root.tag.startswith("{"):
                ns = root.tag.split("}")[0] + "}"
            for c in root.findall(f"{ns}Contents"):
                out.append(
                    (
                        c.findtext(f"{ns}Key"),
                        int(c.findtext(f"{ns}Size") or 0),
                    )
                )
            if (root.findtext(f"{ns}IsTruncated") or "").lower() != "true":
                return out
            token = root.findtext(f"{ns}NextContinuationToken") or ""
            if not token:
                return out

    # -- multipart -----------------------------------------------------------

    def _initiate_multipart(self, bucket: str, key: str) -> str:
        status, body, _ = self._request(
            "POST", self._key_path(bucket, key), query="uploads"
        )
        self._check(status, body, key)
        root = ET.fromstring(body)
        ns = root.tag.split("}")[0] + "}" if root.tag.startswith("{") else ""
        upload_id = root.findtext(f"{ns}UploadId")
        if not upload_id:
            raise S3Error(status, "no UploadId in InitiateMultipartUpload")
        return upload_id

    def _upload_part(
        self, bucket: str, key: str, upload_id: str, part: int, data: bytes
    ) -> str:
        status, body, headers = self._request(
            "PUT",
            self._key_path(bucket, key),
            query=urllib.parse.urlencode(
                {"partNumber": str(part), "uploadId": upload_id}
            ),
            data=data,
        )
        self._check(status, body, key)
        lower = {k.lower(): v for k, v in headers.items()}
        return lower.get("etag", "").strip('"')

    def _complete_multipart(
        self, bucket: str, key: str, upload_id: str, etags: list[tuple[int, str]]
    ) -> None:
        root = ET.Element("CompleteMultipartUpload")
        for part, etag in etags:
            p = ET.SubElement(root, "Part")
            ET.SubElement(p, "PartNumber").text = str(part)
            ET.SubElement(p, "ETag").text = f'"{etag}"'
        status, body, _ = self._request(
            "POST",
            self._key_path(bucket, key),
            query=urllib.parse.urlencode({"uploadId": upload_id}),
            data=ET.tostring(root),
        )
        self._check(status, body, key)

    def _abort_multipart(self, bucket: str, key: str, upload_id: str) -> None:
        self._request(
            "DELETE",
            self._key_path(bucket, key),
            query=urllib.parse.urlencode({"uploadId": upload_id}),
        )
