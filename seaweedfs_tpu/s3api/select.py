"""S3 SelectObjectContent: SQL over one object, AWS event-stream reply.

Reference: the s3 surface of weed/query (experimental SELECT).  The
response rides AWS's binary event-stream framing — prelude (total len,
headers len, prelude CRC32), typed headers, payload, message CRC32 —
with Records / Stats / End events, which is what real S3 SDK clients
parse.
"""
from __future__ import annotations

import struct
import xml.etree.ElementTree as ET
import zlib

from ..query import QueryError, run_select

_HDR_STRING = 7


def _headers(pairs: dict[str, str]) -> bytes:
    out = bytearray()
    for name, value in pairs.items():
        nb, vb = name.encode(), value.encode()
        out += bytes([len(nb)]) + nb + bytes([_HDR_STRING])
        out += struct.pack(">H", len(vb)) + vb
    return bytes(out)


def event_stream_message(headers: dict[str, str], payload: bytes) -> bytes:
    hdr = _headers(headers)
    total = 12 + len(hdr) + len(payload) + 4
    prelude = struct.pack(">II", total, len(hdr))
    prelude += struct.pack(">I", zlib.crc32(prelude))
    body = prelude + hdr + payload
    return body + struct.pack(">I", zlib.crc32(body))


def records_event(payload: bytes) -> bytes:
    return event_stream_message(
        {
            ":message-type": "event",
            ":event-type": "Records",
            ":content-type": "application/octet-stream",
        },
        payload,
    )


def stats_event(scanned: int, processed: int, returned: int) -> bytes:
    xml = (
        f"<Stats><BytesScanned>{scanned}</BytesScanned>"
        f"<BytesProcessed>{processed}</BytesProcessed>"
        f"<BytesReturned>{returned}</BytesReturned></Stats>"
    ).encode()
    return event_stream_message(
        {
            ":message-type": "event",
            ":event-type": "Stats",
            ":content-type": "text/xml",
        },
        xml,
    )


def end_event() -> bytes:
    return event_stream_message(
        {":message-type": "event", ":event-type": "End"}, b""
    )


def parse_event_stream(blob: bytes):
    """Inverse of the framing (used by tests and debugging clients):
    yields (headers, payload)."""
    pos = 0
    while pos + 16 <= len(blob):
        total, hlen = struct.unpack_from(">II", blob, pos)
        headers = {}
        hpos = pos + 12
        hend = hpos + hlen
        while hpos < hend:
            nlen = blob[hpos]
            name = blob[hpos + 1: hpos + 1 + nlen].decode()
            hpos += 1 + nlen + 1  # skip type byte (always string here)
            (vlen,) = struct.unpack_from(">H", blob, hpos)
            headers[name] = blob[hpos + 2: hpos + 2 + vlen].decode()
            hpos += 2 + vlen
        payload = blob[hend: pos + total - 4]
        yield headers, payload
        pos += total


def parse_select_request(body: bytes) -> dict:
    """SelectObjectContentRequest XML -> query options."""
    try:
        root = ET.fromstring(body)
    except ET.ParseError:
        raise QueryError("malformed SelectObjectContentRequest")

    def find(path: str):
        el = root.find(path)
        if el is None:  # retry namespace-agnostic
            for e in root.iter():
                if e.tag.split("}")[-1] == path.split("/")[-1]:
                    return e
        return el

    expr_el = find("Expression")
    if expr_el is None or not (expr_el.text or "").strip():
        raise QueryError("missing Expression")
    opts = {
        "expression": expr_el.text.strip(),
        "input_format": "csv",
        "csv_header": "none",
        "output_format": "csv",
    }
    inp = find("InputSerialization")
    if inp is not None:
        for c in inp:
            ctag = c.tag.split("}")[-1]
            if ctag == "JSON":
                opts["input_format"] = "json"
            elif ctag == "CSV":
                fh = next(
                    (x for x in c if x.tag.split("}")[-1] == "FileHeaderInfo"),
                    None,
                )
                if fh is not None:
                    mode = (fh.text or "").strip().upper()
                    if mode in ("USE", "IGNORE", "NONE"):
                        opts["csv_header"] = mode.lower()
    out = find("OutputSerialization")
    if out is not None:
        for c in out:
            if c.tag.split("}")[-1] == "JSON":
                opts["output_format"] = "json"
    return opts
