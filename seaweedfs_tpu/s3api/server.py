"""S3 API gateway over the filer.

Reference: weed/s3api/s3api_server.go:93-250 (route table),
s3api_object_handlers.go, s3api_bucket_handlers.go, filer_multipart.go
(metadata-only multipart compose), s3api_object_handlers_list.go.

Objects live in the filer namespace at {buckets_path}/{bucket}/{key};
object data moves through the filer's HTTP data plane (so auto-chunking
and streaming range reads are reused), metadata ops go over the filer's
gRPC surface.  Multipart parts are staged under
{buckets_path}/{bucket}/.uploads/{uploadId}/ and completion just
concatenates the parts' chunk lists into the final entry — no data copy.
"""
from __future__ import annotations

import asyncio
import base64
import calendar
import hashlib
import json
import logging
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET

import aiohttp
import grpc
from aiohttp import web

from ..pb import Stub, filer_pb2
from ..pb.rpc import GRPC_OPTIONS, channel
from ..utils.tasks import spawn_logged
from .auth import (
    ACTION_ADMIN,
    ACTION_LIST,
    ACTION_READ,
    ACTION_WRITE,
    STREAMING_PAYLOAD,
    IdentityAccessManagement,
    S3AuthError,
    decode_aws_chunked,
    decode_aws_chunked_verified,
    verify_payload_hash,
)

log = logging.getLogger("s3api")

S3_XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"
UPLOADS_DIR = ".uploads"


class S3Error(Exception):
    def __init__(self, code: str, message: str, status: int):
        super().__init__(message)
        self.code = code
        self.status = status


ERR_NO_SUCH_BUCKET = ("NoSuchBucket", "The specified bucket does not exist", 404)
ERR_NO_SUCH_KEY = ("NoSuchKey", "The specified key does not exist", 404)
ERR_PRECONDITION = (
    "PreconditionFailed",
    "At least one of the pre-conditions you specified did not hold",
    412,
)
ERR_NO_SUCH_UPLOAD = ("NoSuchUpload", "The specified upload does not exist", 404)
ERR_BUCKET_NOT_EMPTY = ("BucketNotEmpty", "The bucket you tried to delete is not empty", 409)
ERR_BUCKET_EXISTS = ("BucketAlreadyExists", "The requested bucket name is not available", 409)

# GetObject response-* query overrides (presigned-download semantics);
# response-content-type is handled separately via resp.content_type
_RESPONSE_OVERRIDES = {
    "response-content-disposition": "Content-Disposition",
    "response-cache-control": "Cache-Control",
    "response-content-encoding": "Content-Encoding",
    "response-content-language": "Content-Language",
    "response-expires": "Expires",
}


class S3ApiServer:
    def __init__(
        self,
        filer_address: str,  # host:port (HTTP); gRPC = +10000 or explicit
        filer_grpc_address: str = "",
        ip: str = "127.0.0.1",
        port: int = 8333,
        buckets_path: str = "/buckets",
        iam: IdentityAccessManagement | None = None,
        metrics_address: str = "",  # pushgateway host:port (ref -metrics.address)
        metrics_interval_seconds: int = 15,  # ref -metrics.intervalSeconds
        direct_volume_reads: bool = True,  # GETs fetch chunks straight
        # from the volume servers (one hop less; EC chunks ride the
        # device-resident dispatcher) instead of proxying the filer
    ):
        self.metrics_address = metrics_address
        self.metrics_interval_seconds = metrics_interval_seconds
        self._metrics_push_task = None
        self.filer_address = filer_address
        host, _, p = filer_address.partition(":")
        self.filer_grpc_address = filer_grpc_address or f"{host}:{int(p) + 10000}"
        self.ip = ip
        self.port = port
        self.buckets_path = buckets_path
        self.iam = iam or IdentityAccessManagement()
        self._http_runner: web.AppRunner | None = None
        self._session: aiohttp.ClientSession | None = None
        self._stub_cache = None
        self._iam_refresh: asyncio.Task | None = None
        self.direct_volume_reads = direct_volume_reads
        # file_id volume -> (fetched_at, [volume urls]); same 10s TTL the
        # volume server uses for its EC location cache
        self._vol_loc_cache: dict[str, tuple[float, list[str]]] = {}
        from .circuit_breaker import CircuitBreaker

        self.circuit_breaker = CircuitBreaker()

    async def _load_iam_from_filer(self) -> None:
        from .auth import IDENTITY_FILER_PATH, IdentityAccessManagement

        try:
            resp = await self._stub().LookupDirectoryEntry(
                filer_pb2.LookupDirectoryEntryRequest(
                    directory=IDENTITY_FILER_PATH[0],
                    name=IDENTITY_FILER_PATH[1],
                )
            )
        except grpc.aio.AioRpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                return
            raise
        if not (resp.HasField("entry") and resp.entry.content):
            return
        import json as _json

        loaded = IdentityAccessManagement.from_config(
            _json.loads(resp.entry.content)
        )
        self.iam.identities[:] = loaded.identities
        self.iam._by_access_key.clear()
        self.iam._by_access_key.update(loaded._by_access_key)

    async def _load_cb_from_filer(self) -> None:
        try:
            resp = await self._stub().LookupDirectoryEntry(
                filer_pb2.LookupDirectoryEntryRequest(
                    directory="/etc/s3", name="circuit_breaker.json"
                )
            )
        except grpc.aio.AioRpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                # conf deleted ⇒ limits lifted (stale limits must not
                # outlive the entry that configured them)
                self.circuit_breaker.load(b"")
                return
            raise
        if resp.HasField("entry") and resp.entry.content:
            self.circuit_breaker.load(bytes(resp.entry.content))
        else:
            self.circuit_breaker.load(b"")

    async def _iam_refresh_loop(self, interval: float = 10.0) -> None:
        while True:
            await asyncio.sleep(interval)
            if self._follow_filer_iam:
                try:
                    await self._load_iam_from_filer()
                except Exception:  # noqa: BLE001 — keep old config
                    log.exception("iam refresh failed")
            try:
                await self._load_cb_from_filer()
            except Exception:  # noqa: BLE001
                log.exception("circuit breaker refresh failed")

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    def _stub(self):
        if self._stub_cache is None:
            self._stub_cache = Stub(
                channel(self.filer_grpc_address), filer_pb2, "SeaweedFiler"
            )
        return self._stub_cache

    async def start(self) -> None:
        self._session = aiohttp.ClientSession()
        # no locally-configured identities: adopt (and follow) the
        # IAM-API-managed config the filer holds, so `iam` and `s3` work
        # as separate processes (reference: s3 subscribes to filer_etc)
        self._follow_filer_iam = not self.iam.enabled
        if self._follow_filer_iam:
            await self._load_iam_from_filer()
        try:
            await self._load_cb_from_filer()
        except Exception as e:  # noqa: BLE001 — filer may not be up yet
            log.debug("initial circuit-breaker config load failed: %s", e)
        self._iam_refresh = spawn_logged(
            self._iam_refresh_loop(), log, "iam refresh loop"
        )
        app = web.Application(client_max_size=1024 * 1024 * 1024)
        from .. import obs

        # streamed object bodies prepare inside the handler; the trace
        # id must be stamped at prepare time (same rule as the filer)
        app.on_response_prepare.append(obs.response_prepare_signal)
        app.router.add_route("*", "/{tail:.*}", self._dispatch)
        self._http_runner = web.AppRunner(app)
        await self._http_runner.setup()
        site = web.TCPSite(self._http_runner, self.ip, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        from .. import stats

        self._metrics_push_task = stats.start_push_loop(
            "s3", self.url, self.metrics_address,
            self.metrics_interval_seconds,
        )
        log.info("s3 gateway listening on %s", self.port)

    async def stop(self) -> None:
        if self._metrics_push_task is not None:
            self._metrics_push_task.cancel()
            try:
                await self._metrics_push_task
            except asyncio.CancelledError:
                pass
        if self._iam_refresh is not None:
            self._iam_refresh.cancel()
            try:
                await self._iam_refresh
            except asyncio.CancelledError:
                pass
        if self._http_runner:
            await self._http_runner.cleanup()
        if self._session:
            await self._session.close()

    # -------------------------------------------------------------- routing

    async def _dispatch(self, request: web.Request) -> web.StreamResponse:
        from .. import obs, stats
        from .circuit_breaker import CircuitBreakerError

        if request.match_info["tail"] in (
            "debug/traces", "debug/stacks", "debug/incident"
        ):
            # reserved observability paths (this catch-all owns the
            # namespace; a bucket literally named "debug" loses these
            # keys to it).  The s3 port is the PUBLIC customer endpoint
            # and traces/stacks reveal internals (object keys, server
            # addresses, code paths), so unlike the admin-facing servers
            # both are opt-in only behind the SWFS_DEBUG gate — but a
            # wedged s3 gateway can still always be diagnosed with it on.
            import os

            if os.environ.get("SWFS_DEBUG") != "1":
                raise web.HTTPNotFound()
            if request.match_info["tail"] == "debug/stacks":
                from ..utils.profiling import debug_stacks_handler

                return await debug_stacks_handler(request)
            if request.match_info["tail"] == "debug/incident":
                return await obs.incident.incident_handler(request)
            return await obs.traces_handler(request)
        tid, psid = obs.parse_trace_header(
            request.headers.get(obs.TRACE_HEADER, "")
        )
        trace, token = obs.start_trace(
            f"{request.method} /{request.match_info['tail']}", "s3",
            self.url, trace_id=tid, parent_span_id=psid,
        )
        bucket = request.match_info["tail"].partition("/")[0]
        code = 500  # unhandled exceptions surface as aiohttp 500s
        try:
            # circuit breaker: concurrent count/bytes limits, global and
            # per-bucket (s3api_circuit_breaker.go Limit)
            m = request.method
            action = "Read" if m in ("GET", "HEAD") else "Write"
            # body-less methods cost 0 bytes; body methods with NO length
            # (chunked) pass None so an MB limit can reject them
            length = (
                request.content_length
                if m in ("PUT", "POST")
                else (request.content_length or 0)
            )
            try:
                release = self.circuit_breaker.acquire(bucket, action, length)
            except CircuitBreakerError as e:
                code = 503
                resp = _error_response("SlowDown", str(e), 503)
                # throttled responses are exactly the ones an operator
                # wants to correlate — echo the header here too
                obs.stamp_trace_header(resp, trace)
                return resp
            try:
                resp = await self._dispatch_authed(request)
            finally:
                release()
            code = resp.status
            obs.stamp_trace_header(resp, trace)
            return resp
        except web.HTTPException as e:
            code = e.status
            obs.stamp_trace_header(e, trace)
            raise
        finally:
            obs.finish_trace(trace, token, code)
            stats.S3_REQUEST_COUNTER.labels(
                type=request.method,
                code=str(code),
                bucket=bucket,
            ).inc()

    async def _dispatch_authed(self, request: web.Request) -> web.StreamResponse:
        # POST policy (browser form) uploads carry their auth inside the
        # form body, not the Authorization header — route them before the
        # header-based authentication
        pp_bucket, _, pp_key = request.match_info["tail"].partition("/")
        if (
            request.method == "POST"
            and pp_bucket
            and not pp_key
            and request.content_type == "multipart/form-data"
        ):
            try:
                return await self.post_object(pp_bucket, request)
            except S3Error as e:
                return _error_response(e.code, str(e), e.status)
        try:
            identity = self.iam.authenticate(request)
            body = await verify_payload_hash(request)
            if body is not None:
                request["s3_body"] = body
        except S3AuthError as e:
            return _error_response(e.code, str(e), e.status)

        tail = request.match_info["tail"]
        bucket, _, key = tail.partition("/")
        q = request.query
        m = request.method

        err = _validate_names(bucket, key)
        if err:
            return _error_response("InvalidArgument", err, 400)

        def allowed(action: str) -> bool:
            return identity is None or identity.can_do(action, bucket)

        try:
            if not bucket:
                if m == "GET":
                    return await self.list_buckets(identity)
                raise S3Error("MethodNotAllowed", "bad request", 405)
            if not key:
                bucket_action = ACTION_LIST
                if m in ("PUT", "DELETE"):
                    bucket_action = ACTION_ADMIN
                elif m == "POST" and "delete" in q:
                    bucket_action = ACTION_WRITE
                elif m == "GET" and "acl" in q:
                    # the ACL view enumerates other identities' names and
                    # access key ids — owner/admin only, not every reader
                    bucket_action = ACTION_ADMIN
                if not allowed(bucket_action):
                    raise S3Error("AccessDenied", "access denied", 403)
                if m == "GET" and "acl" in q:
                    return await self.get_bucket_acl(bucket)
                if m == "PUT" and "acl" in q:
                    # the reference leaves bucket ACL writes unimplemented
                    # (s3api_bucket_skip_handlers.go PutBucketAclHandler)
                    raise S3Error(
                        "NotImplemented", "PutBucketAcl is not implemented", 501
                    )
                if m == "GET" and "lifecycle" in q:
                    return await self.get_bucket_lifecycle(bucket)
                if m == "PUT" and "lifecycle" in q:
                    raise S3Error(
                        "NotImplemented",
                        "PutBucketLifecycle is not implemented; use "
                        "fs.configure -ttl",
                        501,
                    )
                if m == "DELETE" and "lifecycle" in q:
                    return await self.delete_bucket_lifecycle(bucket)
                if m == "GET" and "location" in q:
                    if not await self._bucket_exists(bucket):
                        raise S3Error(*ERR_NO_SUCH_BUCKET)
                    return _xml_response(_el("LocationConstraint"))
                if m == "GET" and "requestPayment" in q:
                    # GetBucketRequestPayment: always BucketOwner
                    # (reference s3api_bucket_handlers.go:352-360)
                    if not await self._bucket_exists(bucket):
                        raise S3Error(*ERR_NO_SUCH_BUCKET)
                    payment = _el("RequestPaymentConfiguration")
                    ET.SubElement(payment, "Payer").text = "BucketOwner"
                    return _xml_response(payment)
                if m == "PUT" and "requestPayment" in q:
                    # must not fall through to put_bucket (which would
                    # 409 on the existing bucket); requester-pays is not
                    # supported, like the other config-write subresources
                    raise S3Error(
                        "NotImplemented",
                        "PutBucketRequestPayment is not implemented",
                        501,
                    )
                if "object-lock" in q:
                    # bucket-level object-lock configuration is a
                    # documented no-op (reference skip handlers)
                    return web.Response(status=204)
                if m == "PUT":
                    return await self.put_bucket(bucket)
                if m == "HEAD":
                    return await self.head_bucket(bucket)
                if m == "DELETE":
                    return await self.delete_bucket(bucket)
                if m == "GET" and "uploads" in q:
                    return await self.list_multipart_uploads(bucket, q)
                if m == "GET":
                    return await self.list_objects(bucket, q)
                if m == "POST" and "delete" in q:
                    return await self.delete_multiple_objects(bucket, request)
                raise S3Error("MethodNotAllowed", "bad request", 405)
            # object-level
            if m == "POST" and "select" in q:
                # SelectObjectContent is a READ in AWS's permission model
                if not allowed(ACTION_READ):
                    raise S3Error("AccessDenied", "access denied", 403)
                return await self.select_object_content(bucket, key, request)
            write_like = m in ("PUT", "POST", "DELETE")
            if not allowed(ACTION_WRITE if write_like else ACTION_READ):
                raise S3Error("AccessDenied", "access denied", 403)
            if m == "POST" and "uploads" in q:
                return await self.create_multipart_upload(bucket, key, request)
            if m == "POST" and "uploadId" in q:
                return await self.complete_multipart_upload(bucket, key, q["uploadId"], request)
            if m == "PUT" and "partNumber" in q and "uploadId" in q:
                return await self.upload_part(bucket, key, q["uploadId"], int(q["partNumber"]), request)
            if m == "DELETE" and "uploadId" in q:
                return await self.abort_multipart_upload(bucket, q["uploadId"])
            if m == "GET" and "uploadId" in q:
                return await self.list_parts(bucket, key, q["uploadId"], q)
            if "acl" in q or "retention" in q or "legal-hold" in q:
                # documented no-ops, mirroring the reference's
                # s3api_object_skip_handlers.go (204 No Content)
                return web.Response(status=204)
            if m == "PUT" and "tagging" in q:
                return await self.put_object_tagging(bucket, key, request)
            if m == "GET" and "tagging" in q:
                return await self.get_object_tagging(bucket, key)
            if m == "DELETE" and "tagging" in q:
                return await self.delete_object_tagging(bucket, key)
            if m == "PUT" and "x-amz-copy-source" in request.headers:
                return await self.copy_object(bucket, key, request)
            if m == "PUT":
                return await self.put_object(bucket, key, request)
            if m in ("GET", "HEAD"):
                return await self.get_object(bucket, key, request)
            if m == "DELETE":
                return await self.delete_object(bucket, key)
            raise S3Error("MethodNotAllowed", "bad request", 405)
        except S3Error as e:
            return _error_response(e.code, str(e), e.status)
        except S3AuthError as e:
            # raised mid-handler, e.g. a streaming chunk signature mismatch
            # discovered while reading the body
            return _error_response(e.code, str(e), e.status)
        except grpc.aio.AioRpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                return _error_response(*ERR_NO_SUCH_KEY)
            log.exception("filer rpc failed")
            return _error_response("InternalError", e.details() or "rpc error", 500)

    # -------------------------------------------------------------- buckets

    async def _bucket_exists(self, bucket: str) -> bool:
        try:
            await self._stub().LookupDirectoryEntry(
                filer_pb2.LookupDirectoryEntryRequest(
                    directory=self.buckets_path, name=bucket
                )
            )
            return True
        except grpc.aio.AioRpcError:
            return False

    async def list_buckets(self, identity) -> web.Response:
        entries = []
        async for r in self._stub().ListEntries(
            filer_pb2.ListEntriesRequest(directory=self.buckets_path, limit=10000)
        ):
            if r.entry.is_directory:
                entries.append(r.entry)
        root = _el("ListAllMyBucketsResult")
        owner = ET.SubElement(root, "Owner")
        ET.SubElement(owner, "ID").text = identity.name if identity else "anonymous"
        buckets = ET.SubElement(root, "Buckets")
        for e in entries:
            if identity is not None and not identity.can_do(ACTION_LIST, e.name):
                continue
            b = ET.SubElement(buckets, "Bucket")
            ET.SubElement(b, "Name").text = e.name
            ET.SubElement(b, "CreationDate").text = _iso(e.attributes.crtime)
        return _xml_response(root)

    async def put_bucket(self, bucket: str) -> web.Response:
        if await self._bucket_exists(bucket):
            raise S3Error(*ERR_BUCKET_EXISTS)
        resp = await self._stub().CreateEntry(
            filer_pb2.CreateEntryRequest(
                directory=self.buckets_path,
                entry=filer_pb2.Entry(
                    name=bucket,
                    is_directory=True,
                    attributes=filer_pb2.FuseAttributes(
                        crtime=int(time.time()), file_mode=0o770
                    ),
                ),
            )
        )
        if resp.error:
            raise S3Error("InternalError", resp.error, 500)
        return web.Response(status=200, headers={"Location": f"/{bucket}"})

    async def head_bucket(self, bucket: str) -> web.Response:
        if not await self._bucket_exists(bucket):
            raise S3Error(*ERR_NO_SUCH_BUCKET)
        return web.Response(status=200)

    async def delete_bucket(self, bucket: str) -> web.Response:
        if not await self._bucket_exists(bucket):
            raise S3Error(*ERR_NO_SUCH_BUCKET)
        # only objects (files) count as content — empty directory husks
        # left by deleted keys don't exist in the S3 data model
        if await self._has_objects(f"{self.buckets_path}/{bucket}", top=True):
            raise S3Error(*ERR_BUCKET_NOT_EMPTY)
        await self._stub().DeleteEntry(
            filer_pb2.DeleteEntryRequest(
                directory=self.buckets_path,
                name=bucket,
                is_delete_data=True,
                is_recursive=True,
            )
        )
        return web.Response(status=204)

    async def _has_objects(self, directory: str, top: bool = False) -> bool:
        async for r in self._stub().ListEntries(
            filer_pb2.ListEntriesRequest(directory=directory)
        ):
            e = r.entry
            if top and e.name == UPLOADS_DIR:
                continue
            if not e.is_directory:
                return True
            if await self._has_objects(f"{directory}/{e.name}"):
                return True
        return False

    # -------------------------------------------------------------- objects

    def _object_url(self, bucket: str, key: str) -> str:
        return (
            f"http://{self.filer_address}{self.buckets_path}/{bucket}/"
            + urllib.parse.quote(key)
        )

    async def _body(self, request: web.Request):
        """Request payload for PUT/POST: the auth layer's verified bytes if
        the payload hash was signed, aws-chunked frames decoded, else the
        raw stream."""
        if "s3_body" in request:
            return request["s3_body"]
        if (
            request.headers.get("x-amz-content-sha256") == STREAMING_PAYLOAD
            or "aws-chunked" in request.headers.get("Content-Encoding", "")
        ):
            ctx = request.get("s3_chunk_ctx")
            if ctx is not None:
                return decode_aws_chunked_verified(await request.read(), *ctx)
            return decode_aws_chunked(await request.read())
        return request.content

    async def get_bucket_acl(self, bucket: str) -> web.Response:
        """Synthesize an AccessControlPolicy from the IAM identities that
        can reach this bucket (reference s3api_bucket_handlers.go
        GetBucketAclHandler — ACLs are a VIEW of identity actions, not a
        separately stored policy)."""
        if not await self._bucket_exists(bucket):
            raise S3Error(*ERR_NO_SUCH_BUCKET)
        perm_of = {
            ACTION_ADMIN: "FULL_CONTROL",
            ACTION_WRITE: "WRITE",
            ACTION_READ: "READ",
            ACTION_LIST: "READ",
        }
        from .auth import scope_covers

        root = _el("AccessControlPolicy")
        owner = ET.SubElement(root, "Owner")
        grants = ET.SubElement(root, "AccessControlList")
        for ident in self.iam.identities:
            if not ident.credentials:
                continue
            access_id = ident.credentials[0][0]
            for action in ident.actions:
                base, _, limit = action.partition(":")
                if not scope_covers(limit, bucket):
                    continue
                perm = perm_of.get(base, "")
                if not perm:
                    continue
                if base == ACTION_ADMIN and not owner.findall("ID"):
                    ET.SubElement(owner, "ID").text = access_id
                    ET.SubElement(owner, "DisplayName").text = ident.name
                g = ET.SubElement(grants, "Grant")
                grantee = ET.SubElement(g, "Grantee")
                grantee.set("xmlns:xsi", "http://www.w3.org/2001/XMLSchema-instance")
                grantee.set("xsi:type", "CanonicalUser")
                ET.SubElement(grantee, "ID").text = access_id
                ET.SubElement(grantee, "DisplayName").text = ident.name
                ET.SubElement(g, "Permission").text = perm
        return _xml_response(root)

    async def _load_filer_conf(self):
        """filer.conf fetched over the filer gRPC surface; absent or
        garbled reads as empty (shared by the lifecycle view + delete)."""
        from ..filer.path_conf import CONF_PATH, FilerConf

        d, n = CONF_PATH.rsplit("/", 1)
        try:
            resp = await self._stub().LookupDirectoryEntry(
                filer_pb2.LookupDirectoryEntryRequest(directory=d, name=n)
            )
            return FilerConf.from_bytes(bytes(resp.entry.content))
        except (grpc.aio.AioRpcError, ValueError):
            return FilerConf()

    async def get_bucket_lifecycle(self, bucket: str) -> web.Response:
        """Lifecycle as a VIEW of the filer.conf TTL rules under the
        bucket's prefix (reference GetBucketLifecycleConfigurationHandler
        + filer.ReadFilerConf)."""
        from ..storage.types import TTL

        if not await self._bucket_exists(bucket):
            raise S3Error(*ERR_NO_SUCH_BUCKET)
        conf = await self._load_filer_conf()
        prefix = f"{self.buckets_path}/{bucket}/"
        rules = []
        for loc in conf.locations:
            if not loc.location_prefix.startswith(prefix) or not loc.ttl:
                continue
            try:
                minutes = TTL.parse(loc.ttl).minutes
            except ValueError:
                continue  # a malformed stored rule must not 500 the view
            if minutes == 0:
                continue
            # sub-day TTLs round UP: hiding them would contradict the
            # DELETE handler that clears exactly these rules
            days = max(1, minutes // (60 * 24))
            rules.append((loc.location_prefix[len(prefix):], days))
        if not rules:
            raise S3Error(
                "NoSuchLifecycleConfiguration",
                "The lifecycle configuration does not exist",
                404,
            )
        root = _el("LifecycleConfiguration")
        for key_prefix, days in rules:
            rule = ET.SubElement(root, "Rule")
            ET.SubElement(rule, "Status").text = "Enabled"
            f = ET.SubElement(rule, "Filter")
            ET.SubElement(f, "Prefix").text = key_prefix
            exp = ET.SubElement(rule, "Expiration")
            ET.SubElement(exp, "Days").text = str(days)
        return _xml_response(root)

    async def delete_bucket_lifecycle(self, bucket: str) -> web.Response:
        """Clear the bucket's TTL rules from filer.conf (the inverse of
        the lifecycle view — a 204 that left the rules in place would lie
        to the next GET)."""
        from ..filer.path_conf import CONF_PATH, save_conf_entry

        if not await self._bucket_exists(bucket):
            raise S3Error(*ERR_NO_SUCH_BUCKET)
        d, n = CONF_PATH.rsplit("/", 1)
        conf = await self._load_filer_conf()
        prefix = f"{self.buckets_path}/{bucket}/"
        changed = False
        for loc in list(conf.locations):
            if loc.location_prefix.startswith(prefix) and loc.ttl:
                loc.ttl = ""
                if not (
                    loc.collection or loc.replication or loc.disk_type
                    or loc.read_only
                ):
                    conf.delete(loc.location_prefix)
                changed = True
        if changed:
            await save_conf_entry(self._stub(), d, n, conf.to_bytes())
        return web.Response(status=204)

    async def post_object(self, bucket: str, request: web.Request) -> web.Response:
        """Browser-form (POST policy) upload
        (s3api_object_handlers_postpolicy.go): multipart form with key,
        policy, signature fields and a trailing `file` part.  The policy
        document authenticates the form and constrains what it may upload."""
        if not await self._bucket_exists(bucket):
            raise S3Error(*ERR_NO_SUCH_BUCKET)
        reader = await request.multipart()
        fields: dict[str, str] = {}
        file_bytes = None
        filename = ""
        while True:
            part = await reader.next()
            if part is None:
                break
            if part.name is None:
                raise S3Error(
                    "InvalidArgument", "form part without a name", 400
                )
            if part.name == "file":
                filename = part.filename or ""
                file_bytes = await part.read(decode=False)
                break  # per the S3 spec, fields after `file` are ignored
            try:
                fields[part.name] = (await part.read(decode=False)).decode()
            except UnicodeDecodeError:
                raise S3Error(
                    "InvalidArgument",
                    f"form field {part.name!r} is not valid UTF-8",
                    400,
                )
        if file_bytes is None:
            raise S3Error("InvalidArgument", "POST form has no file field", 400)
        try:
            identity = self.iam.verify_post_policy(fields)
        except S3AuthError as e:
            return _error_response(e.code, str(e), e.status)
        key = fields.get("key", "")
        if not key:
            raise S3Error("InvalidArgument", "POST form has no key field", 400)
        key = key.replace("${filename}", filename)
        # POST policy skips the header-auth dispatch path, so it must run
        # the same traversal guard — a '../..' key after ${filename}
        # substitution would escape the authorized bucket
        err = _validate_names(bucket, key)
        if err:
            raise S3Error("InvalidArgument", err, 400)
        if fields.get("policy"):
            self._check_post_policy(fields, bucket, key, len(file_bytes))
        if identity is not None and not identity.can_do(ACTION_WRITE, bucket):
            raise S3Error("AccessDenied", "access denied", 403)
        headers = {"Content-Length": str(len(file_bytes))}
        if fields.get("Content-Type"):
            headers["Content-Type"] = fields["Content-Type"]
        async with self._session.put(
            self._object_url(bucket, key), data=file_bytes, headers=headers
        ) as r:
            if r.status >= 300:
                raise S3Error("InternalError", await r.text(), 500)
        try:
            status = int(fields.get("success_action_status", "204"))
        except ValueError:
            status = 204  # AWS ignores unparseable values
        if status not in (200, 201, 204):
            status = 204
        if status == 201:
            root = _el("PostResponse")
            ET.SubElement(root, "Bucket").text = bucket
            ET.SubElement(root, "Key").text = key
            ET.SubElement(root, "Location").text = f"/{bucket}/{key}"
            return _xml_response(root, status=201)
        return web.Response(status=status)

    def _check_post_policy(
        self, fields: dict, bucket: str, key: str, size: int
    ) -> None:
        """Enforce the signed policy document's expiration and conditions
        (policy/post-policy.go)."""
        try:
            policy = json.loads(base64.b64decode(fields["policy"]))
        except (ValueError, KeyError):
            raise S3Error("InvalidPolicyDocument", "policy is not valid JSON", 400)
        exp = str(policy.get("expiration", ""))
        if exp:
            try:
                t = time.strptime(exp.split(".")[0].rstrip("Z"), "%Y-%m-%dT%H:%M:%S")
            except ValueError:
                raise S3Error("InvalidPolicyDocument", "bad expiration", 400)
            if calendar.timegm(t) < time.time():
                raise S3Error("AccessDenied", "policy expired", 403)
        values = {"bucket": bucket, "key": key}
        for k, v in fields.items():
            values.setdefault(k.lower(), v)
        for cond in policy.get("conditions", []):
            if isinstance(cond, dict):
                items = [["eq", f"${k}", v] for k, v in cond.items()]
            else:
                items = [cond]
            for item in items:
                try:
                    op = str(item[0]).lower()
                    if op == "content-length-range":
                        lo, hi = int(item[1]), int(item[2])
                        if not lo <= size <= hi:
                            raise S3Error(
                                "EntityTooLarge"
                                if size > hi
                                else "EntityTooSmall",
                                f"size {size} outside [{lo}, {hi}]",
                                400,
                            )
                        continue
                    name = str(item[1]).lstrip("$").lower()
                    want = str(item[2])
                except (ValueError, IndexError, TypeError):
                    # malformed condition is the POLICY's fault: 400, not
                    # an unhandled 500
                    raise S3Error(
                        "InvalidPolicyDocument",
                        f"malformed policy condition {item!r}",
                        400,
                    )
                got = values.get(name, "")
                ok = (
                    got.startswith(want)
                    if op == "starts-with"
                    else got == want
                )
                if not ok:
                    raise S3Error(
                        "AccessDenied",
                        f"policy condition failed on {name}",
                        403,
                    )

    async def put_object(self, bucket: str, key: str, request: web.Request) -> web.Response:
        if not await self._bucket_exists(bucket):
            raise S3Error(*ERR_NO_SUCH_BUCKET)
        if key.endswith("/"):
            # directory marker ("create folder"): a real directory entry,
            # not a zero-byte file that would shadow the prefix
            d, n = _split_key(f"{self.buckets_path}/{bucket}/{key.rstrip('/')}")
            await self._stub().CreateEntry(
                filer_pb2.CreateEntryRequest(
                    directory=d,
                    entry=filer_pb2.Entry(
                        name=n,
                        is_directory=True,
                        attributes=filer_pb2.FuseAttributes(
                            crtime=int(time.time()), file_mode=0o770
                        ),
                    ),
                )
            )
            return web.Response(
                status=200, headers={"ETag": f'"{hashlib.md5(b"").hexdigest()}"'}
            )
        data = await self._body(request)
        from ..serving.qos import normalize_tier
        from ..server.conditional import persistable_headers

        # forward caching/presentation headers so `aws s3 cp
        # --cache-control ...` persists them like a direct filer PUT
        headers = dict(persistable_headers(request.headers))
        # write tier rides through the filer to the volume server's
        # ingest admission — a plain PUT defaults interactive, the
        # client may demote itself to bulk
        headers["X-Seaweed-QoS"] = normalize_tier(
            request.headers.get("X-Seaweed-QoS")
        )
        if request.headers.get("Content-Type"):
            headers["Content-Type"] = request.headers["Content-Type"]
        if isinstance(data, (bytes, bytearray)):
            headers["Content-Length"] = str(len(data))
        elif request.content_length is not None:
            headers["Content-Length"] = str(request.content_length)
        async with self._session.put(
            self._object_url(bucket, key), data=data, headers=headers
        ) as r:
            if r.status >= 300:
                raise S3Error("InternalError", await r.text(), 500)
            md5_b64 = r.headers.get("Content-MD5", "")
        etag = base64.b64decode(md5_b64).hex() if md5_b64 else ""
        tagging = request.headers.get("X-Amz-Tagging", "")
        amz_meta = {
            k.lower(): v
            for k, v in request.headers.items()
            if k.lower().startswith("x-amz-meta-")
        }
        if tagging or amz_meta:
            await self._set_extended(bucket, key, tagging, amz_meta)
        return web.Response(status=200, headers={"ETag": f'"{etag}"'})

    async def _set_extended(self, bucket, key, tagging: str, amz_meta: dict) -> None:
        entry = await self._get_entry(bucket, key)
        for kv in tagging.split("&"):
            if kv:
                k, _, v = kv.partition("=")
                entry.extended[f"x-amz-tag-{urllib.parse.unquote_plus(k)}"] = (
                    urllib.parse.unquote_plus(v).encode()
                )
        for k, v in amz_meta.items():
            entry.extended[k] = v.encode()
        d, n = _split_key(f"{self.buckets_path}/{bucket}/{key}")
        await self._stub().UpdateEntry(
            filer_pb2.UpdateEntryRequest(directory=d, entry=entry)
        )

    async def _get_entry(self, bucket: str, key: str) -> filer_pb2.Entry:
        d, n = _split_key(f"{self.buckets_path}/{bucket}/{key}")
        try:
            resp = await self._stub().LookupDirectoryEntry(
                filer_pb2.LookupDirectoryEntryRequest(directory=d, name=n)
            )
        except grpc.aio.AioRpcError:
            raise S3Error(*ERR_NO_SUCH_KEY)
        return resp.entry

    async def select_object_content(
        self, bucket: str, key: str, request: web.Request
    ) -> web.Response:
        """SQL over one object with the AWS event-stream reply
        (s3api/select.py; reference weed/query)."""
        from ..query import QueryError, run_select
        from .select import (
            end_event,
            parse_select_request,
            records_event,
            stats_event,
        )

        body = await self._body(request)
        if not isinstance(body, bytes):
            body = await request.read()
        entry = await self._get_entry(bucket, key)
        if entry.is_directory:
            raise S3Error(*ERR_NO_SUCH_KEY)
        async with self._session.get(self._object_url(bucket, key)) as r:
            if r.status == 404:
                raise S3Error(*ERR_NO_SUCH_KEY)
            if r.status >= 300:
                # a data-plane failure must not be scanned as object data
                raise S3Error(
                    "InternalError", f"object read failed: HTTP {r.status}", 500
                )
            data = await r.read()
        try:
            opts = parse_select_request(body)
            result = await asyncio.to_thread(
                run_select,
                opts["expression"],
                data,
                opts["input_format"],
                opts["csv_header"],
                opts["output_format"],
            )
        except QueryError as e:
            raise S3Error("InvalidRequest", str(e), 400)
        stream = b""
        if result:
            stream += records_event(result)
        stream += stats_event(len(data), len(data), len(result))
        stream += end_event()
        return web.Response(
            body=stream, content_type="application/octet-stream"
        )

    # ----------------------------------------------- direct volume reads

    async def _direct_urls(self, file_id: str) -> list[str]:
        """Volume-server URLs holding `file_id`'s volume, via the filer's
        LookupVolume gRPC (which consults the master), cached 10s."""
        vid = file_id.split(",")[0]
        now = time.time()
        cached = self._vol_loc_cache.get(vid)
        if cached and now - cached[0] < 10.0:
            return cached[1]
        resp = await self._stub().LookupVolume(
            filer_pb2.LookupVolumeRequest(volume_ids=[vid])
        )
        urls = []
        if vid in resp.locations_map:
            urls = [l.url for l in resp.locations_map[vid].locations]
        self._vol_loc_cache[vid] = (now, urls)
        return urls

    async def _fetch_view_direct(self, view, tier: str) -> bytes:
        """One ChunkView's bytes straight from a volume server.  The
        request forwards the client's QoS tier (default interactive) and
        the s3 origin tag, so the volume server's dispatcher admits it
        under the right budget and attributes it in the read_route
        series (s3_batched = this read rode the device-resident path)."""
        from .. import obs

        urls = await self._direct_urls(view.file_id)
        if not urls:
            raise RuntimeError(f"chunk {view.file_id}: no locations")
        hdr = {
            "X-Seaweed-QoS": tier,
            "X-Seaweed-Read-Origin": "s3",
            **obs.outbound_headers(),
        }
        if not (view.offset_in_chunk == 0 and view.view_size == view.chunk_size):
            hdr["Range"] = (
                f"bytes={view.offset_in_chunk}-"
                f"{view.offset_in_chunk + view.view_size - 1}"
            )
        last_err = None
        for url in urls:
            try:
                async with self._session.get(
                    f"http://{url}/{view.file_id}", headers=hdr
                ) as r:
                    if r.status >= 300:
                        raise RuntimeError(f"{url}: HTTP {r.status}")
                    data = await r.read()
                    if len(data) != view.view_size:
                        # a wrong-length 2xx (stale replica, stripped
                        # Range) stitched into a committed
                        # Content-Length stream would corrupt the
                        # object silently — treat as a failed replica
                        raise RuntimeError(
                            f"{url}: got {len(data)} bytes, "
                            f"want {view.view_size}"
                        )
                    return data
            except Exception as e:  # noqa: BLE001 — try the next replica
                last_err = e
        raise RuntimeError(f"chunk {view.file_id}: {last_err}")

    async def _get_object_direct(
        self, request: web.Request, entry: filer_pb2.Entry
    ) -> web.StreamResponse | None:
        """Serve a GET/HEAD straight from the volume servers, skipping
        the filer HTTP hop (at thousands of connections the extra proxy
        hop IS the front door's ceiling; EC-volume chunks additionally
        land on the volume server's device-resident dispatcher instead
        of a second-hand host reconstruct).  Returns None when the
        object needs the filer's richer streaming (manifest chains,
        cipher, compressed chunks, remote mounts) — the caller falls
        back to the proxy path."""
        from ..filer.filechunks import total_size, view_from_chunks
        from ..serving.qos import normalize_tier

        tier = normalize_tier(request.headers.get("X-Seaweed-QoS"))
        if any(
            c.is_chunk_manifest or bytes(c.cipher_key) or c.is_compressed
            for c in entry.chunks
        ):
            return None
        if entry.extended.get("remote.key"):
            return None  # remote-mounted: only the filer has the backend
        inline = bytes(entry.content)
        # extent-based size (max chunk offset+size), NOT the sum of
        # chunk sizes: overlapping/overwritten chunks would inflate a
        # sum and the response would be zero-padded to the wrong length
        total = max(
            total_size(entry.chunks),
            int(entry.attributes.file_size),
            len(inline),
        )
        if not entry.chunks and not inline and total > 0:
            return None  # data lives somewhere we can't see; let the filer
        offset, size, status = 0, total, 200
        headers = {
            "ETag": f'"{_entry_etag(entry)}"',
            "Accept-Ranges": "bytes",
        }
        rng = request.http_range
        if rng.start is not None or rng.stop is not None:
            start = rng.start or 0
            if start < 0:  # suffix range "bytes=-N"
                start, stop = max(total + start, 0), total
            else:
                stop = min(rng.stop if rng.stop is not None else total, total)
            if start >= stop:
                raise web.HTTPRequestRangeNotSatisfiable()
            offset, size, status = start, stop - start, 206
            headers["Content-Range"] = (
                f"bytes {start}-{start + size - 1}/{total}"
            )
        if entry.attributes.mtime:
            from ..server.conditional import format_http_date

            headers["Last-Modified"] = format_http_date(entry.attributes.mtime)
        from ..server.conditional import canonical_header, is_persisted_header

        for k, v in entry.extended.items():
            if k.startswith("x-amz-meta-"):
                headers[k] = v.decode()
            elif is_persisted_header(k):
                headers[canonical_header(k)] = v.decode("utf-8", "replace")
        content_type = entry.attributes.mime or "application/octet-stream"
        ct_override = request.query.get("response-content-type", "")
        for q, hdr in _RESPONSE_OVERRIDES.items():
            if q in request.query:
                headers[hdr] = request.query[q]
        if ct_override:
            content_type = ct_override
        headers["Content-Length"] = str(size)
        if request.method == "HEAD":
            return web.Response(
                status=status, headers=headers, content_type=content_type
            )
        # plan + fetch the FIRST piece before prepare(): the overwhelming
        # single-chunk case still falls back cleanly to the filer proxy
        # on any volume-read failure; only a multi-chunk object can fail
        # mid-stream (connection abort, like any proxy would)
        pos, stop = offset, offset + size
        pieces: list = []  # (kind, payload) lazily materialized
        if inline and pos < len(inline):
            end = min(stop, len(inline))
            pieces.append(("bytes", memoryview(inline)[pos:end]))
            pos = end
        views = (
            view_from_chunks(entry.chunks, pos, stop - pos)
            if pos < stop else []
        )
        first = None
        for v in views:
            if v.view_offset > pos:
                pieces.append(("bytes", b"\x00" * (v.view_offset - pos)))
            pieces.append(("view", v))
            pos = v.view_offset + v.view_size
        if pos < stop:
            pieces.append(("bytes", b"\x00" * (stop - pos)))
        async def piece_data(i: int) -> bytes:
            kind, payload = pieces[i]
            if kind == "bytes":
                return payload
            if first is not None and i == first[0]:
                return first[1]
            return await self._fetch_view_direct(payload, tier)

        for i, (kind, _payload) in enumerate(pieces):
            if kind == "view":
                first = (i, await piece_data(i))
                break
        resp = web.StreamResponse(status=status, headers=headers)
        resp.content_type = content_type
        await resp.prepare(request)
        # one-piece prefetch pipeline: fetch(i+1) runs while piece i
        # writes to the client, so a multi-chunk object pays
        # max(fetch, write) per piece instead of their sum
        nxt = None
        try:
            for i in range(len(pieces)):
                cur = nxt if nxt is not None else asyncio.ensure_future(
                    piece_data(i)
                )
                nxt = (
                    asyncio.ensure_future(piece_data(i + 1))
                    if i + 1 < len(pieces) else None
                )
                await resp.write(await cur)
            await resp.write_eof()
        except Exception as e:  # noqa: BLE001 — once prepared, the
            # response CANNOT fall back to the filer proxy (a second
            # response on the same socket would corrupt the payload
            # inside the first one's framing): abort the connection so
            # the client sees a truncated transfer, not silent junk
            log.debug("direct volume read aborted mid-stream: %s", e)
            if nxt is not None:
                nxt.cancel()
            if request.transport is not None:
                request.transport.abort()
        return resp

    async def get_object(self, bucket: str, key: str, request: web.Request) -> web.StreamResponse:
        if any(
            p in request.query
            for p in (*_RESPONSE_OVERRIDES, "response-content-type")
        ) and not request.get("s3_signed", True):
            # AWS rejects response-* on anonymous requests: otherwise any
            # reader could rewrite presentation headers on public
            # objects.  Checked before any backend I/O is spent.
            raise S3Error(
                "InvalidRequest",
                "response-* query parameters require a signed request",
                400,
            )
        entry = await self._get_entry(bucket, key)
        if entry.is_directory:
            raise S3Error(*ERR_NO_SUCH_KEY)
        precond = self._check_preconditions(request, entry)
        if precond is not None:
            return precond
        if self.direct_volume_reads:
            try:
                resp = await self._get_object_direct(request, entry)
                if resp is not None:
                    return resp
            except web.HTTPException:
                raise
            except Exception as e:  # noqa: BLE001 — direct path is an
                # optimization; any volume-side failure falls back to
                # the filer proxy below rather than surfacing
                log.debug(
                    "direct volume read of %s/%s fell back: %s",
                    bucket, key, e,
                )
        headers = {}
        if "Range" in request.headers:
            headers["Range"] = request.headers["Range"]
        async with self._session.request(
            request.method, self._object_url(bucket, key), headers=headers
        ) as r:
            if r.status == 404:
                raise S3Error(*ERR_NO_SUCH_KEY)
            out_headers = {
                "ETag": f'"{_entry_etag(entry)}"',
                "Accept-Ranges": "bytes",
                "Content-Length": r.headers.get("Content-Length", "0"),
                "Last-Modified": r.headers.get("Last-Modified", ""),
            }
            if r.headers.get("Content-Range"):
                out_headers["Content-Range"] = r.headers["Content-Range"]
            from ..server.conditional import (
                canonical_header,
                is_persisted_header,
            )

            for k, v in entry.extended.items():
                if k.startswith("x-amz-meta-"):
                    out_headers[k] = v.decode()
                elif is_persisted_header(k):
                    # stored caching/presentation headers ride back out
                    out_headers[canonical_header(k)] = v.decode(
                        "utf-8", "replace"
                    )
            # response-* query overrides (AWS GetObject request parameters;
            # the common use is presigned download links forcing a
            # filename/type)
            content_type_override = request.query.get(
                "response-content-type", ""
            )
            for q, hdr in _RESPONSE_OVERRIDES.items():
                if q in request.query:
                    out_headers[hdr] = request.query[q]
            resp = web.StreamResponse(status=r.status, headers=out_headers)
            resp.content_type = content_type_override or (
                r.content_type or "application/octet-stream"
            )
            await resp.prepare(request)
            if request.method != "HEAD":
                async for piece in r.content.iter_chunked(1 << 20):
                    await resp.write(piece)
            await resp.write_eof()
            return resp

    def _check_preconditions(self, request, entry):
        """AWS GetObject conditional semantics (RFC 7232 order): If-Match /
        If-Unmodified-Since fail with 412; If-None-Match /
        If-Modified-Since revalidate with 304.  Returns a ready response
        or None to proceed."""
        from ..server.conditional import (
            etag_matches,
            format_http_date,
            not_modified,
            parse_http_date,
        )

        etag = _entry_etag(entry)
        mtime = entry.attributes.mtime
        if_match = request.headers.get("If-Match", "")
        if if_match:
            # If-Match requires the STRONG comparison (RFC 7232 3.1)
            if not etag_matches(if_match, etag, weak=False):
                raise S3Error(*ERR_PRECONDITION)
        else:
            ius = request.headers.get("If-Unmodified-Since", "")
            if ius and mtime:
                since = parse_http_date(ius)
                if since is not None and int(mtime) > since:
                    raise S3Error(*ERR_PRECONDITION)
        if not_modified(request, etag, mtime):
            headers = {"ETag": f'"{etag}"'}
            if mtime:  # unset mtime must not surface as the epoch/now
                headers["Last-Modified"] = format_http_date(mtime)
            return web.Response(status=304, headers=headers)
        return None

    async def delete_object(self, bucket: str, key: str) -> web.Response:
        """S3 delete is idempotent and only removes the named object —
        never a prefix subtree that happens to share the name."""
        is_marker = key.endswith("/")
        d, n = _split_key(f"{self.buckets_path}/{bucket}/{key.rstrip('/')}")
        try:
            resp = await self._stub().LookupDirectoryEntry(
                filer_pb2.LookupDirectoryEntryRequest(directory=d, name=n)
            )
        except grpc.aio.AioRpcError:
            return web.Response(status=204)  # already gone
        entry = resp.entry
        if entry.is_directory and not is_marker:
            return web.Response(status=204)  # no object by this name
        if entry.is_directory and await self._has_objects(f"{d}/{n}"):
            return web.Response(status=204)  # marker of a non-empty prefix
        await self._stub().DeleteEntry(
            filer_pb2.DeleteEntryRequest(
                directory=d,
                name=n,
                is_delete_data=True,
                is_recursive=entry.is_directory,  # empty-marker husks only
            )
        )
        return web.Response(status=204)

    async def copy_object(self, bucket: str, key: str, request: web.Request) -> web.Response:
        src = urllib.parse.unquote(request.headers["x-amz-copy-source"]).lstrip("/")
        src_bucket, _, src_key = src.partition("/")
        src_entry = await self._get_entry(src_bucket, src_key)
        # stream data filer→filer (chunks must not be shared across entries:
        # deleting one object would free the other's data)
        headers = {}
        mime = src_entry.attributes.mime
        if request.headers.get("x-amz-metadata-directive", "COPY") == "REPLACE":
            mime = request.headers.get("Content-Type", "")
        if mime:
            headers["Content-Type"] = mime
        async with self._session.get(self._object_url(src_bucket, src_key)) as r:
            if r.status >= 300:
                raise S3Error(*ERR_NO_SUCH_KEY)
            async with self._session.put(
                self._object_url(bucket, key), data=r.content, headers=headers
            ) as w:
                if w.status >= 300:
                    raise S3Error("InternalError", await w.text(), 500)
        # carry over user metadata and tags (AWS metadata-directive COPY)
        if request.headers.get("x-amz-metadata-directive", "COPY") == "REPLACE":
            tagging = request.headers.get("X-Amz-Tagging", "")
            amz_meta = {
                k.lower(): v
                for k, v in request.headers.items()
                if k.lower().startswith("x-amz-meta-")
            }
            if tagging or amz_meta:
                await self._set_extended(bucket, key, tagging, amz_meta)
        else:
            copied = {
                k: bytes(v)
                for k, v in src_entry.extended.items()
                if k.startswith(("x-amz-meta-", "x-amz-tag-"))
            }
            if copied:
                entry = await self._get_entry(bucket, key)
                entry.extended.update(copied)
                d, _ = _split_key(f"{self.buckets_path}/{bucket}/{key}")
                await self._stub().UpdateEntry(
                    filer_pb2.UpdateEntryRequest(directory=d, entry=entry)
                )
        entry = await self._get_entry(bucket, key)
        root = _el("CopyObjectResult")
        ET.SubElement(root, "ETag").text = f'"{_entry_etag(entry)}"'
        ET.SubElement(root, "LastModified").text = _iso(entry.attributes.mtime)
        return _xml_response(root)

    async def delete_multiple_objects(self, bucket: str, request: web.Request) -> web.Response:
        body = await request.read()
        doc = ET.fromstring(body)
        ns = _ns_of(doc)
        root = _el("DeleteResult")
        quiet = doc.findtext(f"{ns}Quiet") == "true"
        for obj in doc.findall(f"{ns}Object"):
            key = obj.findtext(f"{ns}Key") or ""
            try:
                await self.delete_object(bucket, key)
                if not quiet:
                    d = ET.SubElement(root, "Deleted")
                    ET.SubElement(d, "Key").text = key
            except Exception as e:  # noqa: BLE001
                err = ET.SubElement(root, "Error")
                ET.SubElement(err, "Key").text = key
                ET.SubElement(err, "Message").text = str(e)
        return _xml_response(root)

    # ------------------------------------------------------------- listing

    async def list_objects(self, bucket: str, q) -> web.Response:
        if not await self._bucket_exists(bucket):
            raise S3Error(*ERR_NO_SUCH_BUCKET)
        v2 = q.get("list-type") == "2"
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        max_keys = int(q.get("max-keys", 1000))
        if v2:
            marker = q.get("continuation-token", "") or q.get("start-after", "")
        else:
            marker = q.get("marker", "")

        contents, prefixes, truncated, next_marker = await self._walk_keys(
            bucket, prefix, delimiter, marker, max_keys
        )

        root = _el("ListBucketResult")
        ET.SubElement(root, "Name").text = bucket
        ET.SubElement(root, "Prefix").text = prefix
        ET.SubElement(root, "MaxKeys").text = str(max_keys)
        if delimiter:
            ET.SubElement(root, "Delimiter").text = delimiter
        ET.SubElement(root, "IsTruncated").text = "true" if truncated else "false"
        ET.SubElement(root, "KeyCount" if v2 else "Marker").text = (
            str(len(contents)) if v2 else marker
        )
        if truncated:
            tag = "NextContinuationToken" if v2 else "NextMarker"
            ET.SubElement(root, tag).text = next_marker
        for key, entry in contents:
            c = ET.SubElement(root, "Contents")
            ET.SubElement(c, "Key").text = key
            ET.SubElement(c, "LastModified").text = _iso(entry.attributes.mtime)
            ET.SubElement(c, "ETag").text = f'"{_entry_etag(entry)}"'
            ET.SubElement(c, "Size").text = str(_entry_size(entry))
            ET.SubElement(c, "StorageClass").text = "STANDARD"
        for p in prefixes:
            cp = ET.SubElement(root, "CommonPrefixes")
            ET.SubElement(cp, "Prefix").text = p
        return _xml_response(root)

    async def _walk_keys(
        self, bucket: str, prefix: str, delimiter: str, marker: str, max_keys: int
    ):
        """S3 listing semantics over the filer tree.  delimiter '' (full
        recursive walk) and '/' (single level + CommonPrefixes) are
        supported — the cases every real client uses."""
        base = f"{self.buckets_path}/{bucket}"
        contents: list[tuple[str, filer_pb2.Entry]] = []
        prefixes: list[str] = []
        truncated = False
        next_marker = ""

        if delimiter == "/":
            dir_part, _, name_prefix = prefix.rpartition("/")
            directory = f"{base}/{dir_part}" if dir_part else base
            start = ""
            if dir_part == "" or marker.startswith(f"{dir_part}/"):
                start = marker[len(dir_part) :].lstrip("/").split("/")[0]
            async for r in self._stub().ListEntries(
                filer_pb2.ListEntriesRequest(
                    directory=directory,
                    prefix=name_prefix,
                    start_from_file_name=start,
                    inclusive_start_from=True,
                )
            ):
                e = r.entry
                if e.name == UPLOADS_DIR and not dir_part:
                    continue
                key = f"{dir_part}/{e.name}" if dir_part else e.name
                # list tokens: "key" for objects, "key/" for common prefixes
                token = f"{key}/" if e.is_directory else key
                if marker and token <= marker:
                    continue
                if len(contents) + len(prefixes) >= max_keys:
                    truncated = True
                    break
                if e.is_directory:
                    prefixes.append(token)
                else:
                    contents.append((key, e))
                next_marker = token
            return contents, prefixes, truncated, next_marker

        # recursive walk (no delimiter)
        async def walk(directory: str, rel: str):
            nonlocal truncated, next_marker
            async for r in self._stub().ListEntries(
                filer_pb2.ListEntriesRequest(directory=directory, limit=1 << 31)
            ):
                e = r.entry
                if e.name == UPLOADS_DIR and directory == base:
                    continue
                key = f"{rel}{e.name}"
                if truncated:
                    return
                if e.is_directory:
                    sub = f"{key}/"
                    # prune subtrees outside the prefix...
                    if prefix and not (sub.startswith(prefix) or prefix.startswith(sub)):
                        continue
                    # ...or wholly <= marker (marker bigger than, and not
                    # inside, the subtree ⇒ every sub-key sorts below it)
                    if marker and marker > sub and not marker.startswith(sub):
                        continue
                    await walk(f"{directory}/{e.name}", sub)
                else:
                    if prefix and not key.startswith(prefix):
                        continue
                    if marker and key <= marker:
                        continue
                    if len(contents) >= max_keys:
                        truncated = True
                        return
                    contents.append((key, e))
                    next_marker = key

        await walk(base, "")
        return contents, prefixes, truncated, next_marker

    # ------------------------------------------------------------ multipart

    def _uploads_dir(self, bucket: str) -> str:
        return f"{self.buckets_path}/{bucket}/{UPLOADS_DIR}"

    async def create_multipart_upload(self, bucket, key, request) -> web.Response:
        if not await self._bucket_exists(bucket):
            raise S3Error(*ERR_NO_SUCH_BUCKET)
        upload_id = uuid.uuid4().hex
        mime = request.headers.get("Content-Type", "")
        resp = await self._stub().CreateEntry(
            filer_pb2.CreateEntryRequest(
                directory=self._uploads_dir(bucket),
                entry=filer_pb2.Entry(
                    name=upload_id,
                    is_directory=True,
                    attributes=filer_pb2.FuseAttributes(
                        crtime=int(time.time()), file_mode=0o770, mime=mime
                    ),
                    extended={"key": key.encode()},
                ),
            )
        )
        if resp.error:
            raise S3Error("InternalError", resp.error, 500)
        root = _el("InitiateMultipartUploadResult")
        ET.SubElement(root, "Bucket").text = bucket
        ET.SubElement(root, "Key").text = key
        ET.SubElement(root, "UploadId").text = upload_id
        return _xml_response(root)

    async def _upload_entry(self, bucket: str, upload_id: str) -> filer_pb2.Entry:
        try:
            resp = await self._stub().LookupDirectoryEntry(
                filer_pb2.LookupDirectoryEntryRequest(
                    directory=self._uploads_dir(bucket), name=upload_id
                )
            )
            return resp.entry
        except grpc.aio.AioRpcError:
            raise S3Error(*ERR_NO_SUCH_UPLOAD)

    async def upload_part(self, bucket, key, upload_id, part_number, request) -> web.Response:
        await self._upload_entry(bucket, upload_id)
        name = f"{part_number:04d}.part"
        url = (
            f"http://{self.filer_address}{self._uploads_dir(bucket)}/"
            f"{upload_id}/{name}"
        )
        data = await self._body(request)
        # multipart parts are the batch-loader write shape: bulk tier,
        # so concurrent part floods bind at ingest admission before
        # interactive single PUTs do
        headers = {"X-Seaweed-QoS": "bulk"}
        if isinstance(data, (bytes, bytearray)):
            headers["Content-Length"] = str(len(data))
        elif request.content_length is not None:
            headers["Content-Length"] = str(request.content_length)
        async with self._session.put(url, data=data, headers=headers) as r:
            if r.status >= 300:
                raise S3Error("InternalError", await r.text(), 500)
            md5_b64 = r.headers.get("Content-MD5", "")
        etag = base64.b64decode(md5_b64).hex() if md5_b64 else ""
        return web.Response(status=200, headers={"ETag": f'"{etag}"'})

    async def complete_multipart_upload(self, bucket, key, upload_id, request) -> web.Response:
        pentry = await self._upload_entry(bucket, upload_id)
        body = await request.read()
        requested: list[tuple[int, str]] = []
        if body:
            doc = ET.fromstring(body)
            ns = _ns_of(doc)
            for part in doc.findall(f"{ns}Part"):
                num = int(part.findtext(f"{ns}PartNumber") or 0)
                etag = (part.findtext(f"{ns}ETag") or "").strip('"')
                requested.append((num, etag))
        requested.sort()

        parts: dict[int, filer_pb2.Entry] = {}
        async for r in self._stub().ListEntries(
            filer_pb2.ListEntriesRequest(
                directory=f"{self._uploads_dir(bucket)}/{upload_id}", limit=10000
            )
        ):
            if r.entry.name.endswith(".part"):
                parts[int(r.entry.name[:-5])] = r.entry
        if not parts:
            raise S3Error(*ERR_NO_SUCH_UPLOAD)
        order = [n for n, _ in requested] if requested else sorted(parts)

        final_chunks: list[filer_pb2.FileChunk] = []
        md5s = b""
        offset = 0
        for num, want_etag in requested or [(n, "") for n in order]:
            entry = parts.get(num)
            if entry is None:
                raise S3Error("InvalidPart", f"part {num} not found", 400)
            entry_md5 = bytes(entry.attributes.md5)
            if want_etag and len(want_etag) == 32 and entry_md5.hex() != want_etag:
                raise S3Error("InvalidPart", f"part {num} etag mismatch", 400)
            md5s += entry_md5
            for c in entry.chunks:
                final_chunks.append(
                    filer_pb2.FileChunk(
                        file_id=c.file_id,
                        offset=offset,
                        size=c.size,
                        modified_ts_ns=c.modified_ts_ns,
                        e_tag=c.e_tag,
                    )
                )
                offset += int(c.size)
            if entry.content:  # tiny inlined part — re-home as real content?
                raise S3Error("InternalError", "inlined part unsupported", 500)
        multipart_etag = f"{hashlib.md5(md5s).hexdigest()}-{len(order)}"

        d, n = _split_key(f"{self.buckets_path}/{bucket}/{key}")
        resp = await self._stub().CreateEntry(
            filer_pb2.CreateEntryRequest(
                directory=d,
                entry=filer_pb2.Entry(
                    name=n,
                    chunks=final_chunks,
                    attributes=filer_pb2.FuseAttributes(
                        mtime=int(time.time()),
                        crtime=int(time.time()),
                        file_mode=0o660,
                        file_size=offset,
                        mime=pentry.attributes.mime,
                    ),
                    extended={
                        **{
                            k: bytes(v)
                            for k, v in pentry.extended.items()
                            if k != "key"
                        },
                        "s3-etag": multipart_etag.encode(),
                    },
                ),
            )
        )
        if resp.error:
            raise S3Error("InternalError", resp.error, 500)
        # drop the staging dir (metadata only — chunks now belong to the key)
        await self._stub().DeleteEntry(
            filer_pb2.DeleteEntryRequest(
                directory=self._uploads_dir(bucket),
                name=upload_id,
                is_delete_data=False,
                is_recursive=True,
            )
        )
        root = _el("CompleteMultipartUploadResult")
        ET.SubElement(root, "Location").text = f"http://{self.url}/{bucket}/{key}"
        ET.SubElement(root, "Bucket").text = bucket
        ET.SubElement(root, "Key").text = key
        ET.SubElement(root, "ETag").text = f'"{multipart_etag}"'
        return _xml_response(root)

    async def abort_multipart_upload(self, bucket, upload_id) -> web.Response:
        await self._stub().DeleteEntry(
            filer_pb2.DeleteEntryRequest(
                directory=self._uploads_dir(bucket),
                name=upload_id,
                is_delete_data=True,
                is_recursive=True,
            )
        )
        return web.Response(status=204)

    async def list_multipart_uploads(self, bucket, q) -> web.Response:
        root = _el("ListMultipartUploadsResult")
        ET.SubElement(root, "Bucket").text = bucket
        try:
            async for r in self._stub().ListEntries(
                filer_pb2.ListEntriesRequest(
                    directory=self._uploads_dir(bucket), limit=1000
                )
            ):
                e = r.entry
                if not e.is_directory:
                    continue
                u = ET.SubElement(root, "Upload")
                ET.SubElement(u, "Key").text = e.extended.get("key", b"").decode()
                ET.SubElement(u, "UploadId").text = e.name
                ET.SubElement(u, "Initiated").text = _iso(e.attributes.crtime)
        except grpc.aio.AioRpcError:
            pass
        return _xml_response(root)

    async def list_parts(self, bucket, key, upload_id, q) -> web.Response:
        await self._upload_entry(bucket, upload_id)
        root = _el("ListPartsResult")
        ET.SubElement(root, "Bucket").text = bucket
        ET.SubElement(root, "Key").text = key
        ET.SubElement(root, "UploadId").text = upload_id
        async for r in self._stub().ListEntries(
            filer_pb2.ListEntriesRequest(
                directory=f"{self._uploads_dir(bucket)}/{upload_id}", limit=10000
            )
        ):
            e = r.entry
            if not e.name.endswith(".part"):
                continue
            p = ET.SubElement(root, "Part")
            ET.SubElement(p, "PartNumber").text = str(int(e.name[:-5]))
            ET.SubElement(p, "ETag").text = f'"{bytes(e.attributes.md5).hex()}"'
            ET.SubElement(p, "Size").text = str(_entry_size(e))
            ET.SubElement(p, "LastModified").text = _iso(e.attributes.mtime)
        return _xml_response(root)

    # -------------------------------------------------------------- tagging

    async def put_object_tagging(self, bucket, key, request) -> web.Response:
        entry = await self._get_entry(bucket, key)
        doc = ET.fromstring(await request.read())
        ns = _ns_of(doc)
        for k in list(entry.extended):
            if k.startswith("x-amz-tag-"):
                del entry.extended[k]
        for tag in doc.iter(f"{ns}Tag"):
            k = tag.findtext(f"{ns}Key") or ""
            v = tag.findtext(f"{ns}Value") or ""
            entry.extended[f"x-amz-tag-{k}"] = v.encode()
        d, _ = _split_key(f"{self.buckets_path}/{bucket}/{key}")
        await self._stub().UpdateEntry(
            filer_pb2.UpdateEntryRequest(directory=d, entry=entry)
        )
        return web.Response(status=200)

    async def get_object_tagging(self, bucket, key) -> web.Response:
        entry = await self._get_entry(bucket, key)
        root = _el("Tagging")
        ts = ET.SubElement(root, "TagSet")
        for k, v in entry.extended.items():
            if k.startswith("x-amz-tag-"):
                t = ET.SubElement(ts, "Tag")
                ET.SubElement(t, "Key").text = k[len("x-amz-tag-") :]
                ET.SubElement(t, "Value").text = v.decode()
        return _xml_response(root)

    async def delete_object_tagging(self, bucket, key) -> web.Response:
        entry = await self._get_entry(bucket, key)
        for k in list(entry.extended):
            if k.startswith("x-amz-tag-"):
                del entry.extended[k]
        d, _ = _split_key(f"{self.buckets_path}/{bucket}/{key}")
        await self._stub().UpdateEntry(
            filer_pb2.UpdateEntryRequest(directory=d, entry=entry)
        )
        return web.Response(status=204)


# ------------------------------------------------------------------ helpers


def _validate_names(bucket: str, key: str) -> str:
    """Reject names that would escape the bucket subtree in the filer
    namespace (the gateway authorizes per bucket, so traversal is an
    authorization bypass, not just an oddity)."""
    if bucket and not all(c.isalnum() or c in ".-_" for c in bucket):
        return f"invalid bucket name {bucket!r}"
    if bucket in (".", "..", UPLOADS_DIR):
        return f"invalid bucket name {bucket!r}"
    for seg in key.split("/"):
        if seg in (".", ".."):
            return "key must not contain '.' or '..' path segments"
    if "//" in key:
        return "key must not contain empty path segments"
    return ""


def _split_key(full_path: str) -> tuple[str, str]:
    full_path = full_path.rstrip("/")
    d, _, n = full_path.rpartition("/")
    return d or "/", n


def _entry_size(e: filer_pb2.Entry) -> int:
    return max(
        e.attributes.file_size,
        sum(int(c.size) for c in e.chunks) if e.chunks else 0,
        len(e.content),
    )


def _entry_etag(e: filer_pb2.Entry) -> str:
    s3_etag = e.extended.get("s3-etag")
    if s3_etag:
        return s3_etag.decode()
    if e.attributes.md5:
        return bytes(e.attributes.md5).hex()
    return ""


def _el(name: str) -> ET.Element:
    return ET.Element(name, xmlns=S3_XMLNS)


def _ns_of(doc: ET.Element) -> str:
    if doc.tag.startswith("{"):
        return doc.tag.split("}")[0] + "}"
    return ""


def _xml_response(root: ET.Element, status: int = 200) -> web.Response:
    body = b'<?xml version="1.0" encoding="UTF-8"?>\n' + ET.tostring(root)
    return web.Response(status=status, body=body, content_type="application/xml")


def _error_response(code: str, message: str, status: int) -> web.Response:
    root = ET.Element("Error")
    ET.SubElement(root, "Code").text = code
    ET.SubElement(root, "Message").text = message
    return _xml_response(root, status)


def _iso(ts: int) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts or 0))
