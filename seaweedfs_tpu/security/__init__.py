from .jwt import (
    JwtError,
    decode_jwt,
    encode_jwt,
    gen_volume_write_jwt,
    jwt_from_request,
    verify_volume_write_jwt,
)

__all__ = [
    "JwtError",
    "decode_jwt",
    "encode_jwt",
    "gen_volume_write_jwt",
    "jwt_from_request",
    "verify_volume_write_jwt",
]
