"""IP-whitelist Guard for the public HTTP planes.

Reference: weed/security/guard.go:52-105 — handlers wrapped by a Guard
reject requests from addresses outside `[access] white_list` (exact IPs
or CIDR ranges) in security.toml.  An empty list means open access.
"""
from __future__ import annotations

import ipaddress

from aiohttp import web


class Guard:
    def __init__(self, white_list: list[str] | None = None):
        self.networks: list[ipaddress._BaseNetwork] = []
        for item in white_list or []:
            item = item.strip()
            if not item:
                continue
            if "/" not in item:
                item += "/32" if ":" not in item else "/128"
            self.networks.append(ipaddress.ip_network(item, strict=False))

    @property
    def enabled(self) -> bool:
        return bool(self.networks)

    def allowed(self, ip: str) -> bool:
        if not self.networks:
            return True
        try:
            addr = ipaddress.ip_address(ip)
        except ValueError:
            return False
        return any(addr in net for net in self.networks)


def middleware(guard: Guard):
    """aiohttp middleware enforcing the whitelist (guard.go WhiteList)."""

    @web.middleware
    async def check(request: web.Request, handler):
        ip = request.remote or ""
        if not guard.allowed(ip):
            raise web.HTTPForbidden(text=f"request from {ip} not allowed")
        return await handler(request)

    return check


def from_security_toml(dirs=None) -> list[str]:
    """[access] white_list from security.toml."""
    from ..utils import config as config_util

    kw = {"dirs": dirs} if dirs else {}
    cfg = config_util.load_config("security", **kw)
    wl = (cfg.get("access") or {}).get("white_list") or []
    return [str(x) for x in wl]
