"""Write-authorization JWTs, wire-compatible with the reference.

Reference: /root/reference/weed/security/jwt.go:30-89 — the master signs an
HS256 JWT over the assigned fid (claim "fid", optional "exp"); the volume
server rejects writes/deletes whose token is missing, expired, mis-signed,
or signed for a different fid (volume_server_handlers.go:145-187).  The
token travels in the `Authorization: Bearer` header or a `?jwt=` query
parameter (jwt.go GetJwt).

HS256 is hmac-sha256 over base64url segments — implemented on the stdlib so
no external JWT dependency is needed.
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time


class JwtError(Exception):
    pass


def _b64url(data: bytes) -> bytes:
    return base64.urlsafe_b64encode(data).rstrip(b"=")


def _b64url_decode(data: str) -> bytes:
    pad = (-len(data)) % 4
    return base64.urlsafe_b64decode(data + "=" * pad)


_HEADER = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())


def encode_jwt(signing_key: str | bytes, claims: dict) -> str:
    """claims dict -> signed compact JWT string."""
    key = signing_key.encode() if isinstance(signing_key, str) else signing_key
    payload = _b64url(json.dumps(claims, separators=(",", ":")).encode())
    signing_input = _HEADER + b"." + payload
    sig = _b64url(hmac.new(key, signing_input, hashlib.sha256).digest())
    return (signing_input + b"." + sig).decode()


def decode_jwt(signing_key: str | bytes, token: str) -> dict:
    """Verify signature and expiry; return the claims dict."""
    key = signing_key.encode() if isinstance(signing_key, str) else signing_key
    parts = token.split(".")
    if len(parts) != 3:
        raise JwtError("malformed token")
    signing_input = (parts[0] + "." + parts[1]).encode()
    try:
        header = json.loads(_b64url_decode(parts[0]))
        claims = json.loads(_b64url_decode(parts[1]))
        sig = _b64url_decode(parts[2])
    except (ValueError, json.JSONDecodeError) as e:
        raise JwtError(f"malformed token: {e}")
    if header.get("alg") != "HS256":
        raise JwtError(f"unexpected alg {header.get('alg')!r}")
    want = hmac.new(key, signing_input, hashlib.sha256).digest()
    if not hmac.compare_digest(sig, want):
        raise JwtError("bad signature")
    exp = claims.get("exp")
    if exp is not None and time.time() > float(exp):
        raise JwtError("token expired")
    return claims


def gen_volume_write_jwt(
    signing_key: str, fid: str, expires_after_sec: int = 10
) -> str:
    """Master-side: sign a write token for one assigned fid
    (GenJwtForVolumeServer jwt.go:30-50).  Empty key -> empty token."""
    if not signing_key:
        return ""
    claims: dict = {"fid": fid}
    if expires_after_sec > 0:
        claims["exp"] = int(time.time()) + expires_after_sec
    return encode_jwt(signing_key, claims)


def jwt_from_request(request) -> str:
    """Extract the token from ?jwt= or `Authorization: Bearer ...`
    (jwt.go GetJwt)."""
    token = request.query.get("jwt", "")
    if not token:
        bearer = request.headers.get("Authorization", "")
        if len(bearer) > 7 and bearer[:7].upper() == "BEARER ":
            token = bearer[7:]
    return token


def verify_volume_write_jwt(signing_key: str, request, fid: str) -> bool:
    """Volume-server-side write guard (volume_server_handlers.go:145-187):
    token must verify and its fid claim must match the request's fid with
    any `_N` batch suffix stripped.  No signing key configured -> open."""
    if not signing_key:
        return True
    token = jwt_from_request(request)
    if not token:
        return False
    try:
        claims = decode_jwt(signing_key, token)
    except JwtError:
        return False
    sep = fid.rfind("_")
    if sep > 0:
        fid = fid[:sep]
    return claims.get("fid") == fid
