"""security.toml-driven mutual TLS for the gRPC control plane.

Reference: weed/security/tls.go — every gRPC surface (master, volume,
filer, raft, mq) loads cert/key/CA from security.toml and requires
verified client certificates; clients present their own cert from the
same file.  Mirrored here as process-global state (the reference's
security.toml is process-global too): `configure()` once at startup,
after which `add_port()` binds secure listeners and pb/rpc.py's channel
helpers hand out mTLS channels.

security.toml shape (see command/scaffold.py):

    [tls]
    ca   = "/etc/seaweedfs/ca.crt"
    cert = "/etc/seaweedfs/server.crt"
    key  = "/etc/seaweedfs/server.key"
"""
from __future__ import annotations

import dataclasses
import os

import grpc


@dataclasses.dataclass(frozen=True)
class TlsConfig:
    ca: str  # CA bundle path (verifies peers both ways)
    cert: str  # this process's certificate path
    key: str  # this process's private key path

    def read(self) -> tuple[bytes, bytes, bytes]:
        with open(self.ca, "rb") as f:
            ca = f.read()
        with open(self.cert, "rb") as f:
            cert = f.read()
        with open(self.key, "rb") as f:
            key = f.read()
        return ca, cert, key


_config: TlsConfig | None = None


def configure(cfg: TlsConfig | None) -> None:
    """Set (or clear) the process-wide TLS config.  Existing cached
    channels are dropped so new dials pick up the change."""
    global _config
    _config = cfg
    from ..pb import rpc

    rpc.drop_cached_channels()


def configured() -> TlsConfig | None:
    return _config


def from_security_toml(dirs=None) -> TlsConfig | None:
    """[tls] section of security.toml -> TlsConfig (None when absent)."""
    from ..utils import config as config_util

    kw = {"dirs": dirs} if dirs else {}
    cfg = config_util.load_config("security", **kw)
    section = cfg.get("tls") or {}
    present = {k for k in ("ca", "cert", "key") if section.get(k)}
    if len(present) == 3:
        return TlsConfig(
            ca=section["ca"], cert=section["cert"], key=section["key"]
        )
    if present:
        # a half-filled section must FAIL, not silently serve plaintext
        # while the operator believes mTLS is on
        missing = {"ca", "cert", "key"} - present
        raise ValueError(
            f"security.toml [tls] is missing {sorted(missing)} — set all "
            "of ca/cert/key or none"
        )
    return None


def server_credentials(cfg: TlsConfig) -> grpc.ServerCredentials:
    ca, cert, key = cfg.read()
    return grpc.ssl_server_credentials(
        [(key, cert)],
        root_certificates=ca,
        require_client_auth=True,  # mutual TLS, like the reference
    )


def channel_credentials(cfg: TlsConfig) -> grpc.ChannelCredentials:
    ca, cert, key = cfg.read()
    return grpc.ssl_channel_credentials(
        root_certificates=ca, private_key=key, certificate_chain=cert
    )


def add_port(server, address: str) -> int:
    """Bind a gRPC server port — secure when TLS is configured, insecure
    otherwise.  The one call every server's start() makes."""
    if _config is not None:
        return server.add_secure_port(address, server_credentials(_config))
    return server.add_insecure_port(address)


def generate_test_pki(directory: str, hosts=("127.0.0.1", "localhost")) -> TlsConfig:
    """Self-signed CA + one server/client cert for tests and scaffolding
    (the reference points users at openssl; in-process generation keeps
    the e2e TLS test hermetic)."""
    import datetime
    import ipaddress as ipa

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    os.makedirs(directory, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc)

    def make_key():
        return rsa.generate_private_key(public_exponent=65537, key_size=2048)

    ca_key = make_key()
    ca_name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "seaweedfs-test-ca")]
    )
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_name)
        .issuer_name(ca_name)
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), True)
        .sign(ca_key, hashes.SHA256())
    )

    leaf_key = make_key()
    san = []
    for h in hosts:
        try:
            san.append(x509.IPAddress(ipa.ip_address(h)))
        except ValueError:
            san.append(x509.DNSName(h))
    leaf_cert = (
        x509.CertificateBuilder()
        .subject_name(
            x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, hosts[-1])])
        )
        .issuer_name(ca_name)
        .public_key(leaf_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(x509.SubjectAlternativeName(san), False)
        .sign(ca_key, hashes.SHA256())
    )

    paths = {}
    for name, data in (
        ("ca.crt", ca_cert.public_bytes(serialization.Encoding.PEM)),
        ("server.crt", leaf_cert.public_bytes(serialization.Encoding.PEM)),
        (
            "server.key",
            leaf_key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption(),
            ),
        ),
    ):
        p = os.path.join(directory, name)
        with open(p, "wb") as f:
            f.write(data)
        paths[name] = p
    return TlsConfig(
        ca=paths["ca.crt"], cert=paths["server.crt"], key=paths["server.key"]
    )
