"""Servers: master + volume (+ filer, gateways) on asyncio/grpc.aio.

Reference: weed/server/ (10.2k LoC).  Each server is a plain class with
async start()/stop(); the `weed server` all-in-one launcher lives in
cluster.py.
"""
from .filer import FilerServer
from .master import MasterServer
from .volume import VolumeServer

__all__ = ["FilerServer", "MasterServer", "VolumeServer"]
