"""In-process cluster launcher — the `weed server` equivalent.

Reference: weed/command/server.go boots master+volume(+filer) in one
process; here `LocalCluster` does the same on one asyncio loop, and is
what the e2e tests and the benchmark harness drive.
"""
from __future__ import annotations

import asyncio

from .filer import FilerServer
from .master import MasterServer
from .volume import VolumeServer


class LocalCluster:
    def __init__(
        self,
        n_volume_servers: int = 1,
        dirs_per_server: int = 1,
        base_dir: str = "/tmp/seaweedfs-tpu",
        max_volume_count: int = 16,
        volume_size_limit_mb: int = 1024,
        pulse_seconds: int = 1,
        ec_backend: str = "auto",
        data_centers: list[str] | None = None,
        racks: list[str] | None = None,
        with_filer: bool = False,
        filer_kwargs: dict | None = None,
        with_s3: bool = False,
        s3_kwargs: dict | None = None,
        with_webdav: bool = False,
        with_iam: bool = False,
        jwt_signing_key: str = "",
        tier_backends: dict | None = None,  # default: local backend in base_dir/tier
        disk_types: list[str] | None = None,  # per-directory, all servers
        master_kwargs: dict | None = None,
        volume_kwargs: dict | None = None,  # extra VolumeServer kwargs,
        # all servers (e.g. ec_ingest=IngestConfig(backend="xla"))
    ):
        import os

        self.master = MasterServer(
            port=0, volume_size_limit_mb=volume_size_limit_mb,
            pulse_seconds=pulse_seconds,
            jwt_signing_key=jwt_signing_key,
            **(master_kwargs or {}),
        )
        self.jwt_signing_key = jwt_signing_key
        self.with_filer = with_filer or with_s3 or with_webdav or with_iam
        self.with_webdav = with_webdav
        self.webdav = None
        self.with_iam = with_iam
        self.iam_server = None
        self.filer_kwargs = filer_kwargs or {}
        self.filer: FilerServer | None = None
        self.with_s3 = with_s3
        self.s3_kwargs = s3_kwargs or {}
        self.s3 = None
        self.base_dir = base_dir
        if tier_backends is None:
            tier_backends = {
                "local.default": {
                    "type": "local",
                    "dir": os.path.join(base_dir, "tier"),
                }
            }
        self._specs = []
        for i in range(n_volume_servers):
            dirs = [
                os.path.join(base_dir, f"vs{i}", f"d{j}")
                for j in range(dirs_per_server)
            ]
            self._specs.append(
                dict(
                    directories=dirs,
                    max_volume_counts=max_volume_count,
                    pulse_seconds=pulse_seconds,
                    ec_backend=ec_backend,
                    data_center=(data_centers or ["dc1"])[i % len(data_centers or ["dc1"])],
                    rack=(racks or ["r1"])[i % len(racks or ["r1"])],
                    tier_backends=tier_backends,
                    disk_types=disk_types,
                    **(volume_kwargs or {}),
                )
            )
        self.volume_servers: list[VolumeServer] = []

    async def start(self) -> None:
        await self.master.start()
        for spec in self._specs:
            vs = VolumeServer(
                masters=[self.master.url],
                port=0,
                grpc_port=0,
                jwt_signing_key=self.jwt_signing_key,
                **spec,
            )
            # master http port == grpc port resolution needs master.grpc_port;
            # VolumeServer resolves host:port -> grpc via +10000, so pass the
            # explicit grpc address form
            vs.masters = [f"{self.master.ip}:{self.master.port}.{self.master.grpc_port}"]
            await vs.start()
            self.volume_servers.append(vs)
        await self.wait_for_nodes(len(self.volume_servers))
        if self.with_filer:
            self.filer = FilerServer(
                masters=[self.master.advertise_url], port=0, grpc_port=0,
                **self.filer_kwargs,
            )
            await self.filer.start()
        if self.with_s3:
            from ..s3api import S3ApiServer

            self.s3 = S3ApiServer(
                filer_address=self.filer.url,
                filer_grpc_address=f"{self.filer.ip}:{self.filer.grpc_port}",
                port=0,
                **self.s3_kwargs,
            )
            await self.s3.start()
        if self.with_iam:
            from ..iamapi import IamApiServer

            # share the S3 gateway's IAM registry so policy changes take
            # effect immediately in-process (the reference shares the
            # filer-stored config the same way)
            self.iam_server = IamApiServer(
                filer_address=self.filer.url,
                filer_grpc_address=f"{self.filer.ip}:{self.filer.grpc_port}",
                port=0,
                iam=self.s3.iam if self.s3 is not None else None,
            )
            await self.iam_server.start()
        if self.with_webdav:
            from .webdav import WebDavServer

            self.webdav = WebDavServer(
                filer_address=self.filer.url,
                filer_grpc_address=f"{self.filer.ip}:{self.filer.grpc_port}",
                port=0,
            )
            await self.webdav.start()

    async def wait_for_nodes(self, n: int, timeout: float = 10.0) -> None:
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            if len(self.master.topo.data_nodes()) >= n:
                return
            await asyncio.sleep(0.05)
        raise TimeoutError(f"only {len(self.master.topo.data_nodes())}/{n} nodes joined")

    async def stop(self) -> None:
        if self.iam_server is not None:
            await self.iam_server.stop()
        if self.webdav is not None:
            await self.webdav.stop()
        if self.s3 is not None:
            await self.s3.stop()
        if self.filer is not None:
            await self.filer.stop()
        for vs in self.volume_servers:
            await vs.stop()
        await self.master.stop()
        from ..pb.rpc import close_all_channels

        await close_all_channels()
