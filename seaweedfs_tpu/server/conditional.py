"""Conditional GET/HEAD evaluation (If-None-Match / If-Modified-Since ->
304), the reference's checkPreconditions at
weed/server/filer_server_handlers_read.go:60-80 and the needle ETag check
at volume_server_handlers_read.go:160-175: If-None-Match wins when
present; If-Modified-Since only consulted otherwise.
"""
from __future__ import annotations

import calendar
import time


def _canonical_etag(tag: str) -> str:
    tag = tag.strip()
    if tag.startswith("W/"):
        tag = tag[2:]
    return tag.strip('"')


def not_modified(request, etag: str, mtime: int | float | None) -> bool:
    """True when the client's validators prove its cached copy is current.

    `etag` is the response's ETag value (quoted or not — canonicalized
    here); `mtime` is the entity's last-modified unix time (None/0 =
    unknown)."""
    inm = request.headers.get("If-None-Match", "")
    if inm:
        ours = _canonical_etag(etag)
        return any(
            _canonical_etag(candidate) in ("*", ours)
            for candidate in inm.split(",")
        )
    ims = request.headers.get("If-Modified-Since", "")
    if ims and mtime:
        try:
            # timegm, not mktime: the header is GMT by definition and the
            # server's local timezone/DST must not skew the comparison
            since = calendar.timegm(
                time.strptime(ims, "%a, %d %b %Y %H:%M:%S GMT")
            )
        except ValueError:
            return False
        return int(mtime) <= since
    return False
