"""Conditional GET/HEAD evaluation (If-None-Match / If-Modified-Since ->
304), the reference's checkPreconditions at
weed/server/filer_server_handlers_read.go:60-80 and the needle ETag check
at volume_server_handlers_read.go:160-175: If-None-Match wins when
present; If-Modified-Since only consulted otherwise.
"""
from __future__ import annotations

import calendar
import time


def _canonical_etag(tag: str) -> str:
    tag = tag.strip()
    if tag.startswith("W/"):
        tag = tag[2:]
    return tag.strip('"')


def format_http_date(mtime: int | float) -> str:
    """unix seconds -> IMF-fixdate (the one formatter every server path
    shares)."""
    return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime(mtime))


def parse_http_date(value: str) -> int | None:
    """IMF-fixdate -> unix seconds, or None when unparseable.  timegm, not
    mktime: the header is GMT by definition and the server's local
    timezone/DST must not skew comparisons."""
    try:
        return calendar.timegm(
            time.strptime(value, "%a, %d %b %Y %H:%M:%S GMT")
        )
    except ValueError:
        return None


def etag_matches(header_value: str, ours: str, weak: bool = True) -> bool:
    """Does any candidate in an If-(None-)Match header match our ETag?

    weak=True is RFC 7232's weak comparison (If-None-Match); weak=False is
    the STRONG comparison If-Match requires — a W/ candidate never
    matches."""
    ours_c = _canonical_etag(ours)
    for candidate in header_value.split(","):
        candidate = candidate.strip()
        if candidate == "*":
            return True
        if not weak and candidate.startswith("W/"):
            continue
        if _canonical_etag(candidate) == ours_c:
            return True
    return False


PERSISTED_HEADERS = ("Cache-Control", "Expires", "Content-Disposition")


def canonical_header(name: str) -> str:
    """HTTP header names are case-insensitive; canonicalize like Go's
    textproto (Cache-Control, Seaweed-Origin) so matching and storage
    never depend on the client's spelling."""
    return "-".join(p.capitalize() for p in name.split("-"))


def is_persisted_header(name: str) -> bool:
    ck = canonical_header(name)
    return ck in PERSISTED_HEADERS or ck.startswith("Seaweed-")


def persistable_headers(headers) -> dict[str, str]:
    """The upload headers an entry should carry and replay on reads
    (reference SaveAmzMetaData shape): caching/presentation headers plus
    Seaweed-* pairs, keys canonicalized.  ONE predicate shared by the
    filer write path, its read replay, and the S3 gateway's forward."""
    out: dict[str, str] = {}
    for k, v in headers.items():
        if is_persisted_header(k):
            out[canonical_header(k)] = v
    return out


def content_disposition(request, filename: str) -> str | None:
    """`inline; filename=...` for named entities, `attachment` when the
    ?dl= query flag asks for a download (reference
    adjustHeaderContentDisposition, server/common.go:268-282)."""
    if not filename:
        return None
    import urllib.parse

    kind = "inline"
    dl = request.query.get("dl", "")
    if dl.lower() in ("1", "true", "yes"):
        kind = "attachment"
    quoted = urllib.parse.quote(filename)
    return f'{kind}; filename="{quoted}"'


def not_modified(request, etag: str, mtime: int | float | None) -> bool:
    """True when the client's validators prove its cached copy is current.

    `etag` is the response's ETag value (quoted or not — canonicalized
    here); `mtime` is the entity's last-modified unix time (None/0 =
    unknown)."""
    inm = request.headers.get("If-None-Match", "")
    if inm:
        return etag_matches(inm, etag, weak=True)
    ims = request.headers.get("If-Modified-Since", "")
    if ims and mtime:
        since = parse_http_date(ims)
        if since is None:
            return False
        return int(mtime) <= since
    return False
