"""pb <-> internal dataclass conversions shared by master and volume servers."""
from __future__ import annotations

from ..pb import master_pb2
from ..storage.store import EcShardMessage, HeartbeatState, VolumeMessage
from ..topology.node import DataNode


def volume_msg_to_pb(v: VolumeMessage) -> master_pb2.VolumeInformationMessage:
    return master_pb2.VolumeInformationMessage(
        id=v.id,
        size=v.size,
        collection=v.collection,
        file_count=v.file_count,
        delete_count=v.delete_count,
        deleted_byte_count=v.deleted_byte_count,
        read_only=v.read_only,
        replica_placement=v.replica_placement,
        version=v.version,
        ttl=v.ttl,
        disk_type=v.disk_type,
        modified_at_second=v.modified_at_second,
    )


def volume_msg_from_pb(p: master_pb2.VolumeInformationMessage) -> VolumeMessage:
    return VolumeMessage(
        id=p.id,
        size=p.size,
        collection=p.collection,
        file_count=p.file_count,
        delete_count=p.delete_count,
        deleted_byte_count=p.deleted_byte_count,
        read_only=p.read_only,
        replica_placement=p.replica_placement,
        version=p.version,
        ttl=p.ttl,
        disk_type=p.disk_type,
        modified_at_second=p.modified_at_second,
    )


def ec_msg_to_pb(e: EcShardMessage) -> master_pb2.VolumeEcShardInformationMessage:
    return master_pb2.VolumeEcShardInformationMessage(
        id=e.id,
        collection=e.collection,
        ec_index_bits=e.ec_index_bits,
        disk_type=e.disk_type,
    )


def ec_msg_from_pb(p: master_pb2.VolumeEcShardInformationMessage) -> EcShardMessage:
    return EcShardMessage(
        id=p.id,
        collection=p.collection,
        ec_index_bits=p.ec_index_bits,
        disk_type=p.disk_type,
    )


def heartbeat_state_from_pb(hb: master_pb2.Heartbeat) -> HeartbeatState:
    return HeartbeatState(
        volumes=[volume_msg_from_pb(v) for v in hb.volumes],
        ec_shards=[ec_msg_from_pb(e) for e in hb.ec_shards],
        max_volume_counts=dict(hb.max_volume_counts),
        has_no_volumes=hb.has_no_volumes,
        has_no_ec_shards=hb.has_no_ec_shards,
    )


def node_to_location(n: DataNode) -> master_pb2.Location:
    return master_pb2.Location(
        url=n.url,
        public_url=n.public_url,
        grpc_port=n.grpc_port,
        data_center=n.rack.data_center.name if n.rack else "",
        # r20: holder's multi-controller pod — degraded-read gathers
        # hedge pod-anti-affine (pod members stall together)
        mesh_pod=getattr(n, "mesh_pod", ""),
    )
