"""FilerServer: the namespace tier's HTTP + gRPC host.

Reference: weed/server/filer_server.go, filer_server_handlers_read.go (261),
filer_server_handlers_write_autochunk.go:25-130, filer_grpc_server.go (368),
filer_grpc_server_rename.go, filer_grpc_server_sub_meta.go.

One asyncio process:
  - aiohttp data plane on /{path}: POST/PUT auto-chunking uploads (body is
    split into maxMB chunks, each assigned+uploaded to volume servers),
    GET/HEAD streaming reads with Range support and directory listings,
    DELETE with recursive.
  - grpc.aio `SeaweedFiler` service: entry CRUD, AtomicRenameEntry,
    AssignVolume proxy, metadata subscription (replay + live tail).
  - a MasterClient subscription for vid→location lookups and leader
    tracking (the reference filer does the same, filer.go:35-75).
"""
from __future__ import annotations

import asyncio
import base64
import hashlib
import json
import logging
import os
import time

import aiohttp
import grpc
from aiohttp import web

from ..filer import (
    Attr,
    Entry,
    Filer,
    FilerError,
    MODE_DIR,
    MemoryStore,
    NotEmptyError,
    NotFoundError,
    SqliteStore,
    etag_of_chunks,
    maybe_manifestize,
    new_full_path,
    view_from_chunks,
)
from .. import obs, stats
from ..utils import faultpolicy
from ..operation.assign import assign as assign_rpc
from ..operation.delete import delete_files
from ..operation.upload import upload_data
from ..pb import Stub, channel, filer_pb2, generic_handler, master_pb2, server_address
from ..security import tls as tls_mod
from ..security import guard as guard_mod
from ..pb.rpc import GRPC_OPTIONS
from ..wdclient import MasterClient

log = logging.getLogger("filer")

# per-chunk-fetch fallback timeout when the request carries no deadline
# budget (the front door stamps one by default; this bounds direct
# callers) — generous for a 4MB chunk off a loaded peer, finite always
_CHUNK_FETCH_TIMEOUT_S = 30.0


class FilerServer:
    def __init__(
        self,
        masters: list[str],
        store=None,
        ip: str = "127.0.0.1",
        port: int = 8888,
        grpc_port: int = 0,
        max_mb: int = 4,
        collection: str = "",
        replication: str = "",
        data_center: str = "",
        rack: str = "",
        meta_log_path: str | None = None,
        save_inside_limit: int = 0,  # inline files <= this many bytes in metadata
        dir_buckets: str = "/buckets",
        metrics_port: int | None = 0,  # 0 = auto-assign; None = disabled
        cipher: bool = False,  # AES-GCM encrypt chunks at rest (util/cipher.go)
        compress_chunks: bool = True,  # zstd compressible chunks (util/compression.go)
        chunk_cache_mb: int = 64,
        chunk_cache_dir: str | None = None,
        notifier=None,  # replication.notification.Notifier
        upload_parallelism: int = 4,  # concurrent chunk uploads per file
        white_list: list[str] | None = None,  # [access] white_list guard
        metrics_address: str = "",  # pushgateway host:port (ref -metrics.address)
        metrics_interval_seconds: int = 15,  # ref -metrics.intervalSeconds
    ):
        self.metrics_address = metrics_address
        self.metrics_interval_seconds = metrics_interval_seconds
        self._metrics_push_task = None
        self.masters = masters
        self.guard = guard_mod.Guard(white_list)
        self.ip = ip
        self.port = port
        self.grpc_port = grpc_port or (port + 10000 if port else 0)
        self.max_mb = max_mb
        self.collection = collection
        self.replication = replication
        self.data_center = data_center
        self.rack = rack
        self.save_inside_limit = save_inside_limit
        self.dir_buckets = dir_buckets
        self.metrics_port = metrics_port
        self.cipher = cipher
        self.compress_chunks = compress_chunks
        self.upload_parallelism = max(1, upload_parallelism)
        from ..filer.chunk_cache import ChunkCache

        self.chunk_cache = ChunkCache(
            mem_limit_bytes=chunk_cache_mb * 1024 * 1024,
            disk_dir=chunk_cache_dir,
        )
        self.filer = Filer(
            store if store is not None else MemoryStore(),
            delete_file_ids_fn=self._delete_file_ids,
            meta_log_path=meta_log_path,
            notifier=notifier,
            fetch_manifest_fn=lambda c: self._fetch_chunk_decoded(
                c.file_id, bytes(c.cipher_key), c.is_compressed
            ),
        )
        self.master_client = MasterClient(
            masters,
            client_type="filer",
            client_address=f"{ip}:{port}",
            data_center=data_center,
        )
        self._grpc_server: grpc.aio.Server | None = None
        self._http_runner: web.AppRunner | None = None
        self._metrics_runner: web.AppRunner | None = None
        self._session: aiohttp.ClientSession | None = None
        self._conf_cache = None
        self._conf_cache_ts = 0.0

    # -------------------------------------------------- path storage rules

    def _filer_conf(self):
        """Cached /etc/seaweedfs/filer.conf (filer_conf.go); the 2s TTL
        bounds staleness after a live fs.configure edit without a store
        read per request."""
        from ..filer.path_conf import CONF_PATH, FilerConf

        now = time.time()
        if self._conf_cache is not None and now - self._conf_cache_ts < 2.0:
            return self._conf_cache
        try:
            blob = bytes(self.filer.find_entry(CONF_PATH).content)
            conf = FilerConf.from_bytes(blob)
        except Exception:  # noqa: BLE001 — absent/garbled conf = no rules
            conf = FilerConf()
        self._conf_cache = conf
        self._conf_cache_ts = now
        return conf

    def _conf_rule(self, path: str):
        return self._filer_conf().match(path)

    def _check_writable(self, path: str) -> None:
        """Raise 403 when a filer.conf rule marks the path read-only —
        shared by HTTP writes AND the gRPC mutation surface so FUSE /
        S3 multipart / replication clients can't bypass a quota lock."""
        from ..filer.path_conf import CONF_PATH

        if path == CONF_PATH:
            return  # editing the conf itself must never be locked out
        rule = self._conf_rule(path)
        if rule and rule.read_only:
            raise web.HTTPForbidden(
                text=f"{rule.location_prefix} is read-only (filer.conf)"
            )

    # ----------------------------------------------------------- lifecycle

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    async def start(self) -> None:
        self._session = aiohttp.ClientSession()
        self._grpc_server = grpc.aio.server(options=GRPC_OPTIONS)
        self._grpc_server.add_generic_rpc_handlers(
            [generic_handler(filer_pb2, "SeaweedFiler", self)]
        )
        self.grpc_port = tls_mod.add_port(
            self._grpc_server, f"{self.ip}:{self.grpc_port}"
        )
        await self._grpc_server.start()

        app = web.Application(
            client_max_size=1024 * 1024 * 1024,
            middlewares=(
                [guard_mod.middleware(self.guard)] if self.guard.enabled else []
            ),
        )
        # streamed file bodies prepare inside the handler, so the trace
        # id must be stamped at prepare time (obs/trace.py)
        app.on_response_prepare.append(obs.response_prepare_signal)
        app.router.add_route("*", "/{path:.*}", self._http_dispatch)
        self._http_runner = web.AppRunner(app)
        await self._http_runner.setup()
        site = web.TCPSite(self._http_runner, self.ip, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]

        # /metrics on its own port: the data app's catch-all route owns the
        # whole namespace, so a filer path "/metrics" must stay a file path
        # (the reference also serves metrics on a dedicated -metricsPort).
        if self.metrics_port is not None:
            mapp = web.Application()
            mapp.router.add_get("/metrics", stats.metrics_handler)
            # traces ride the metrics port for the same reason metrics
            # do: the data app's catch-all owns the whole namespace, so
            # a filer path "/debug/traces" must stay a file path
            mapp.router.add_get("/debug/traces", obs.traces_handler)
            # the filer's flight-recorder ring rides the metrics port
            # too (co-hosted roles share one ring, like the registry)
            mapp.router.add_get(
                "/debug/incident", obs.incident.incident_handler
            )
            if os.environ.get("SWFS_DEBUG") == "1":
                # thread-stack dumps for a wedged filer (same opt-in
                # gate as the other roles' /debug/stacks)
                from ..utils.profiling import debug_stacks_handler

                mapp.router.add_get("/debug/stacks", debug_stacks_handler)
            self._metrics_runner = web.AppRunner(mapp)
            await self._metrics_runner.setup()
            msite = web.TCPSite(self._metrics_runner, self.ip, self.metrics_port)
            await msite.start()
            self.metrics_port = msite._server.sockets[0].getsockname()[1]

        # advertise the explicit grpc form when the +10000 convention
        # doesn't hold (dynamic test ports) so shells can dial us
        if self.grpc_port == self.port + 10000:
            self.master_client.client_address = f"{self.ip}:{self.port}"
        else:
            self.master_client.client_address = (
                f"{self.ip}:{self.port}.{self.grpc_port}"
            )
        await self.master_client.start()
        self._metrics_push_task = stats.start_push_loop(
            "filer", self.url, self.metrics_address,
            self.metrics_interval_seconds,
        )
        log.info("filer listening http=%s grpc=%s", self.port, self.grpc_port)

    async def stop(self) -> None:
        if self._metrics_push_task is not None:
            self._metrics_push_task.cancel()
            try:
                await self._metrics_push_task
            except asyncio.CancelledError:
                pass
        await self.master_client.stop()
        if self._grpc_server:
            await self._grpc_server.stop(0.5)
        if self._http_runner:
            await self._http_runner.cleanup()
        if self._metrics_runner:
            await self._metrics_runner.cleanup()
        if self._session:
            await self._session.close()
        # async notifiers (MqNotifier) hold buffered events + a drain
        # task: flush and stop them before the process exits
        notifier = getattr(self.filer.meta_log, "notifier", None)
        close = getattr(notifier, "close", None)
        if close is not None:
            import inspect

            r = close()
            if inspect.isawaitable(r):
                await r
        self.filer.shutdown()

    # -------------------------------------------------- chunk data movement

    async def _delete_file_ids(self, fids: list[str]) -> None:
        await delete_files(self.master_client.current_master, fids)

    async def _assign(self, count: int = 1, collection: str = "", replication: str = "",
                      ttl: str = "", data_center: str = ""):
        return await assign_rpc(
            self.master_client.current_master,
            count=count,
            collection=collection or self.collection,
            replication=replication or self.replication,
            ttl=ttl,
            data_center=data_center or self.data_center,
        )

    async def _upload_chunk(
        self, data: bytes, offset: int, filename: str,
        collection: str = "", replication: str = "", ttl: str = "",
        mime: str = "", qos_tier: str = "",
    ) -> filer_pb2.FileChunk:
        # compress-then-encrypt; chunk.size stays the logical (plaintext)
        # length so the interval algebra never sees wire sizes
        payload = data
        is_compressed = False
        cipher_key = b""
        if self.compress_chunks:
            from ..utils.compression import maybe_compress

            ext = "." + filename.rsplit(".", 1)[-1] if "." in filename else ""
            payload, is_compressed = maybe_compress(payload, mime, ext)
        if self.cipher:
            from ..utils.cipher import encrypt, gen_cipher_key

            cipher_key = gen_cipher_key()
            payload = encrypt(payload, cipher_key)
        a = await self._assign(1, collection, replication, ttl)
        # carry the write tier and remaining deadline budget to the
        # volume server's ingest admission (the doomed upload is refused
        # there, before any bytes hit the .dat)
        hdr = dict(faultpolicy.outbound_headers())
        if qos_tier:
            hdr["X-Seaweed-QoS"] = qos_tier
        result = await upload_data(
            f"http://{a.url}/{a.fid}",
            payload,
            filename=filename,
            compress=False,
            jwt=a.auth,
            headers=hdr,
        )
        return filer_pb2.FileChunk(
            file_id=a.fid,
            offset=offset,
            size=len(data),
            modified_ts_ns=time.time_ns(),
            e_tag=result.get("eTag", ""),
            cipher_key=cipher_key,
            is_compressed=is_compressed,
        )

    async def _lookup_urls(self, file_id: str) -> list[str]:
        vid = int(file_id.split(",")[0])
        locs = await self.master_client.lookup_or_fetch(vid)
        return [f"http://{l.url}/{file_id}" for l in locs]

    async def _cache_get(self, file_id: str) -> bytes | None:
        # the disk tier blocks; keep it off the event loop
        if self.chunk_cache.disk_dir:
            return await asyncio.to_thread(self.chunk_cache.get, file_id)
        return self.chunk_cache.get(file_id)

    async def _cache_put(self, file_id: str, blob: bytes) -> None:
        if self.chunk_cache.disk_dir:
            await asyncio.to_thread(self.chunk_cache.put, file_id, blob)
        else:
            self.chunk_cache.put(file_id, blob)

    async def _fetch_chunk_decoded(
        self, file_id: str, cipher_key: bytes, is_compressed: bool
    ) -> bytes:
        """Whole chunk, decrypted/decompressed, through the chunk cache.
        Cipher and compressed chunks can't be range-read, so they always
        come through here (the reference streams them whole too)."""
        blob = await self._cache_get(file_id)
        if blob is not None:
            return blob
        raw = await self._fetch_whole(file_id)
        if cipher_key:
            from ..utils.cipher import decrypt

            raw = decrypt(raw, cipher_key)
        if is_compressed:
            from ..utils.compression import decompress

            raw = decompress(raw)
        await self._cache_put(file_id, raw)
        return raw

    async def _fetch_view(self, view) -> bytes:
        """One ChunkView's bytes from a volume server (Range read)."""
        if view.cipher_key or view.is_gzipped:
            blob = await self._fetch_chunk_decoded(
                view.file_id, view.cipher_key, view.is_gzipped
            )
            return blob[
                view.offset_in_chunk: view.offset_in_chunk + view.view_size
            ]
        cached = await self._cache_get(view.file_id)
        if cached is not None:
            return cached[
                view.offset_in_chunk: view.offset_in_chunk + view.view_size
            ]
        urls = await self._lookup_urls(view.file_id)
        if not urls:
            raise web.HTTPInternalServerError(
                text=f"chunk {view.file_id}: no locations"
            )
        last_err = None
        for url in urls:
            hdr = {**obs.outbound_headers(), **faultpolicy.outbound_headers()}
            if not (view.offset_in_chunk == 0 and view.view_size == view.chunk_size):
                hdr["Range"] = (
                    f"bytes={view.offset_in_chunk}-"
                    f"{view.offset_in_chunk + view.view_size - 1}"
                )
            try:
                with obs.span(
                    "chunk_fetch", file_id=view.file_id,
                    bytes=view.view_size,
                ):
                    async with self._session.get(
                        url, headers=hdr,
                        # hard per-fetch timeout from the remaining
                        # request budget (a hung volume server must not
                        # pin this filer read past its deadline)
                        timeout=aiohttp.ClientTimeout(
                            total=faultpolicy.rpc_timeout_s(
                                _CHUNK_FETCH_TIMEOUT_S, what="chunk_fetch"
                            )
                        ),
                    ) as r:
                        if r.status >= 300:
                            raise RuntimeError(f"{url}: HTTP {r.status}")
                        data = await r.read()
                if view.is_full_chunk:
                    await self._cache_put(view.file_id, data)
                return data
            except Exception as e:  # noqa: BLE001 — try the next replica
                last_err = e
        raise web.HTTPInternalServerError(text=f"chunk {view.file_id}: {last_err}")

    async def _fetch_whole(self, file_id: str) -> bytes:
        urls = await self._lookup_urls(file_id)
        last_err: Exception | None = None
        for url in urls:
            try:
                with obs.span("chunk_fetch", file_id=file_id):
                    async with self._session.get(
                        url,
                        headers={
                            **obs.outbound_headers(),
                            **faultpolicy.outbound_headers(),
                        },
                        timeout=aiohttp.ClientTimeout(
                            total=faultpolicy.rpc_timeout_s(
                                _CHUNK_FETCH_TIMEOUT_S, what="chunk_fetch"
                            )
                        ),
                    ) as r:
                        if r.status < 300:
                            return await r.read()
                        last_err = RuntimeError(f"{url}: HTTP {r.status}")
            except Exception as e:  # noqa: BLE001 — try the next replica
                last_err = e
        raise RuntimeError(f"{file_id}: unreachable ({last_err})")

    async def _resolve_views(self, chunks, offset: int, size: int):
        """view_from_chunks with async manifest resolution."""
        from ..filer.manifest import resolve_chunk_manifest

        has_manifest = any(c.is_chunk_manifest for c in chunks)
        if has_manifest:
            blobs: dict[str, bytes] = {}
            for c in chunks:
                if c.is_chunk_manifest:
                    blobs[c.file_id] = await self._fetch_chunk_decoded(
                        c.file_id, bytes(c.cipher_key), c.is_compressed
                    )

            def lookup(fid):
                if fid not in blobs:
                    raise KeyError(fid)
                return blobs[fid]

            chunks, _ = resolve_chunk_manifest(lookup, chunks, offset, offset + size)
        return view_from_chunks(chunks, offset, size)

    # ------------------------------------------------------- HTTP handlers

    async def _http_dispatch(self, request: web.Request) -> web.StreamResponse:
        # manual trace scope (the catch-all route owns the namespace, so
        # the obs middleware's path exclusions don't apply here): adopt
        # an inbound trace id or start one, echo it on the response, and
        # record the filer-side spans for the fan-out this request does
        tid, psid = obs.parse_trace_header(
            request.headers.get(obs.TRACE_HEADER, "")
        )
        trace, token = obs.start_trace(
            f"{request.method} /{request.match_info['path']}", "filer",
            self.url, trace_id=tid, parent_span_id=psid,
        )
        status = 500
        try:
            # the filer is a deadline front door too: adopt the inbound
            # budget or stamp the default, so the chunk fetches below
            # ride one continuous budget (utils/faultpolicy.py)
            with faultpolicy.request_scope(request.headers):
                resp = await self._http_dispatch_inner(request)
            status = resp.status
            obs.stamp_trace_header(resp, trace)
            return resp
        except web.HTTPException as e:
            status = e.status
            obs.stamp_trace_header(e, trace)
            raise
        except faultpolicy.DeadlineExceeded as e:
            status = 504
            timeout = web.HTTPGatewayTimeout(text=str(e))
            obs.stamp_trace_header(timeout, trace)  # correlate the shed
            raise timeout
        finally:
            obs.finish_trace(trace, token, status)

    async def _http_dispatch_inner(
        self, request: web.Request
    ) -> web.StreamResponse:
        try:
            if request.method in ("GET", "HEAD"):
                with stats.time_request(
                    stats.FILER_REQUEST_COUNTER, stats.FILER_REQUEST_HISTOGRAM, "get"
                ):
                    return await self.h_get(request)
            if request.method in ("POST", "PUT"):
                with stats.time_request(
                    stats.FILER_REQUEST_COUNTER, stats.FILER_REQUEST_HISTOGRAM, "post"
                ):
                    return await self.h_write(request)
            if request.method == "DELETE":
                with stats.time_request(
                    stats.FILER_REQUEST_COUNTER, stats.FILER_REQUEST_HISTOGRAM, "delete"
                ):
                    return await self.h_delete(request)
        except web.HTTPException:
            raise
        except NotFoundError:
            raise web.HTTPNotFound()
        except (FilerError, NotEmptyError) as e:
            raise web.HTTPConflict(text=str(e))
        raise web.HTTPMethodNotAllowed(request.method, ["GET", "POST", "PUT", "DELETE"])

    def _req_path(self, request: web.Request) -> tuple[str, bool]:
        p = "/" + request.match_info["path"]
        return p.rstrip("/") or "/", p.endswith("/") and p != "/"

    async def h_get(self, request: web.Request) -> web.StreamResponse:
        path, _ = self._req_path(request)
        entry = self.filer.find_entry(path)  # NotFoundError → 404
        if entry.is_directory:
            return await self._list_dir(request, path)
        return await self._stream_file(request, entry)

    async def _list_dir(self, request: web.Request, path: str) -> web.Response:
        q = request.query
        limit = int(q.get("limit", 100))
        last = q.get("lastFileName", "")
        prefix = q.get("namePattern", "").rstrip("*")
        entries = self.filer.list_directory_entries(
            path, start_file_name=last, limit=limit + 1, prefix=prefix
        )
        more = len(entries) > limit
        entries = entries[:limit]
        from . import ui

        if ui.wants_html(request):
            # browser directory listing (reference filer_ui/filer.html)
            return web.Response(
                text=ui.render_filer_listing(path, entries, limit, more),
                content_type="text/html",
            )
        return web.json_response(
            {
                "Path": path,
                "Entries": [_entry_json(e) for e in entries],
                "Limit": limit,
                "LastFileName": entries[-1].name if entries else "",
                "ShouldDisplayLoadMore": more,
            }
        )

    async def _stream_file(self, request: web.Request, entry: Entry) -> web.StreamResponse:
        total = entry.size()
        mime = entry.attr.mime or "application/octet-stream"
        from .conditional import format_http_date

        headers = {
            "Accept-Ranges": "bytes",
            "Last-Modified": format_http_date(entry.attr.mtime),
        }
        if entry.chunks:
            headers["ETag"] = f'"{etag_of_chunks(entry.chunks)}"'
        if entry.attr.md5:
            headers["Content-MD5"] = base64.b64encode(entry.attr.md5).decode()

        from .conditional import content_disposition, not_modified

        # replay stored caching/presentation headers (an explicit stored
        # Content-Disposition wins over the synthesized filename one, like
        # the reference's early return in adjustHeaderContentDisposition)
        from .conditional import canonical_header, is_persisted_header

        for xk, xv in entry.extended.items():
            if is_persisted_header(xk):
                headers[canonical_header(xk)] = xv.decode("utf-8", "replace")
        if "Content-Disposition" not in headers:
            cd = content_disposition(request, entry.name)
            if cd:
                headers["Content-Disposition"] = cd
        if not_modified(request, headers.get("ETag", ""), entry.attr.mtime):
            return web.Response(status=304, headers=headers)

        offset, size, status = 0, total, 200
        rng = request.http_range
        if rng.start is not None or rng.stop is not None:
            start = rng.start or 0
            if start < 0:  # suffix range "bytes=-N"
                start, stop = max(total + start, 0), total
            else:
                stop = min(rng.stop if rng.stop is not None else total, total)
            if start >= stop:
                raise web.HTTPRequestRangeNotSatisfiable()
            offset, size, status = start, stop - start, 206
            headers["Content-Range"] = f"bytes {start}-{start + size - 1}/{total}"

        if request.method == "HEAD":
            headers["Content-Length"] = str(size)
            return web.Response(status=status, headers=headers, content_type=mime)

        resp = web.StreamResponse(status=status, headers={**headers, "Content-Length": str(size)})
        resp.content_type = mime
        await resp.prepare(request)
        pos = offset
        stop = offset + size
        if entry.content and pos < len(entry.content):
            # inlined head (appends may have added chunks past it)
            end = min(stop, len(entry.content))
            await resp.write(bytes(entry.content[pos:end]))
            pos = end
        if pos < stop and not entry.chunks and entry.extended.get("remote.key"):
            # remote-mounted entry with no cached chunks: read through the
            # storage backend (filer_server_handlers_read.go remote path)
            from ..storage import backend as backend_mod

            backend_name = entry.extended.get("remote.backend", b"").decode()
            btype, _, bid = backend_name.partition(".")
            try:
                storage = backend_mod.get_backend(btype, bid or "default")
            except KeyError:
                # config was registered via remote.configure into our own
                # KV (shells run in other processes) — lazy-load it
                try:
                    cfg = self.filer.store.kv_get(
                        f"remote.conf/{backend_name}".encode()
                    )
                    backend_mod.configure(json.loads(cfg))
                except NotFoundError:
                    raise web.HTTPBadGateway(
                        text=f"storage backend {backend_name} not configured"
                    )
                storage = backend_mod.get_backend(btype, bid or "default")
            rkey = entry.extended["remote.key"].decode()
            piece = 1 << 16
            while pos < stop:
                n = min(piece, stop - pos)
                blob = await asyncio.to_thread(storage.pread, rkey, n, pos)
                if not blob:
                    break
                await resp.write(blob)
                pos += len(blob)
        if pos < stop:
            views = await self._resolve_views(entry.chunks, pos, stop - pos)
            for v in views:
                if v.view_offset > pos:  # hole → zeros
                    await resp.write(b"\x00" * (v.view_offset - pos))
                await resp.write(await self._fetch_view(v))
                pos = v.view_offset + v.view_size
            if pos < stop:
                await resp.write(b"\x00" * (stop - pos))
        await resp.write_eof()
        return resp

    async def h_write(self, request: web.Request) -> web.Response:
        path, had_slash = self._req_path(request)
        q = request.query
        self._check_writable(path)
        # mkdir: POST to a path ending in "/" with no content-type
        if (
            request.method == "POST"
            and had_slash
            and not request.headers.get("Content-Type")
        ):
            await self.filer.create_entry(
                Entry(
                    full_path=path,
                    attr=Attr(
                        mtime=int(time.time()), crtime=int(time.time()),
                        mode=0o770 | MODE_DIR,
                    ),
                )
            )
            return web.json_response({"name": path}, status=201)

        chunk_size = int(q.get("maxMB", self.max_mb)) * 1024 * 1024
        rule = self._conf_rule(path)
        collection = q.get("collection") or (
            rule.collection if rule else ""
        ) or self.collection
        replication = q.get("replication") or (
            rule.replication if rule else ""
        ) or self.replication
        ttl_str = q.get("ttl") or (rule.ttl if rule else "")
        try:
            from ..storage.types import TTL

            ttl_sec = TTL.parse(ttl_str).minutes * 60
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        is_append = q.get("op") == "append"

        filename = ""
        content_type = request.headers.get("Content-Type", "")
        reader = request.content
        if request.method == "POST" and content_type.startswith("multipart/"):
            mp = await request.multipart()
            part = await mp.next()
            if part is None:
                raise web.HTTPBadRequest(text="empty multipart body")
            filename = part.filename or ""
            content_type = part.headers.get("Content-Type", "")
            reader = part
        if content_type == "application/octet-stream":
            content_type = ""

        # if POSTing to a directory, the file lands inside it
        if had_slash and filename:
            path = new_full_path(path, filename)
        elif filename and path != "/":
            try:
                if self.filer.find_entry(path).is_directory:
                    path = new_full_path(path, filename)
            except NotFoundError:
                pass

        md5 = hashlib.md5()
        small_content = b""
        offset = 0
        buf = bytearray()
        eof = False
        # chunk uploads run in a bounded parallel window — the volume
        # servers take them concurrently, so a big file's wall clock is
        # ~window× better than the strictly sequential loop (the
        # reference uploads chunks via a worker pool the same way)
        tasks: list[asyncio.Task] = []
        upload_name = filename or path.rsplit("/", 1)[-1]
        # write tier rides the same header the read path uses; the s3
        # gateway stamps it (multipart parts = bulk), direct PUTs may too
        qos_tier = request.headers.get("X-Seaweed-QoS", "")

        def launch(data: bytes, off: int) -> None:
            tasks.append(
                asyncio.create_task(
                    self._upload_chunk(
                        data, off, upload_name,
                        collection, replication, ttl_str, mime=content_type,
                        qos_tier=qos_tier,
                    )
                )
            )

        async def abort_uploads() -> None:
            """Cancel in-flight chunk tasks and GC whatever landed."""
            for t_ in tasks:
                if not t_.done():
                    t_.cancel()
            results = await asyncio.gather(*tasks, return_exceptions=True)
            fids = [
                r.file_id for r in results
                if isinstance(r, filer_pb2.FileChunk)
            ]
            if fids:
                await self._delete_file_ids(fids)

        try:
            while not eof:
                while len(buf) < chunk_size and not eof:
                    piece = await reader.read(min(chunk_size - len(buf), 1 << 20))
                    if not piece:
                        eof = True
                    else:
                        buf.extend(piece)
                data = bytes(buf)
                buf.clear()
                if not data and offset > 0:
                    break
                md5.update(data)
                if (
                    eof
                    and offset == 0
                    and len(data) <= self.save_inside_limit
                    and not is_append
                ):
                    small_content = data
                    offset = len(data)
                    break
                if not data:  # empty file: an entry with no chunks
                    break
                launch(data, offset)
                offset += len(data)
                # bound read-ahead: at most `upload_parallelism` chunk
                # buffers in flight (wait only on PENDING tasks — done
                # ones would make FIRST_COMPLETED a hot spin)
                while True:
                    pending = [t_ for t_ in tasks if not t_.done()]
                    if len(pending) < self.upload_parallelism:
                        break
                    await asyncio.wait(
                        pending, return_when=asyncio.FIRST_COMPLETED
                    )
                # a failed chunk aborts the upload NOW, not after the
                # remaining gigabytes have been read and uploaded
                failed = next(
                    (
                        t_ for t_ in tasks
                        if t_.done() and not t_.cancelled() and t_.exception()
                    ),
                    None,
                )
                if failed is not None:
                    raise failed.exception()

            results = await asyncio.gather(*tasks)
        except asyncio.CancelledError:
            await abort_uploads()
            raise
        except Exception as e:  # noqa: BLE001 — client abort, chunk failure
            await abort_uploads()
            raise web.HTTPInternalServerError(text=f"chunk upload failed: {e}")
        chunks = list(results)

        if is_append:
            entry = await self.filer.append_chunks(path, chunks)
            size = entry.size()
        else:
            # fold huge chunk lists into manifests before saving metadata
            if len(chunks) > 1000:
                chunks = await self._manifestize_async(
                    chunks, collection, replication
                )
            now = int(time.time())
            mode = int(q.get("mode", "0660"), 8)
            # persist caching/presentation headers + Seaweed-* pairs with
            # the entry; reads replay them (reference autochunk
            # SaveAmzMetaData shape, write_autochunk.go:245-258)
            from .conditional import persistable_headers

            extended = {
                k: v.encode()
                for k, v in persistable_headers(request.headers).items()
            }
            entry = Entry(
                full_path=path,
                attr=Attr(
                    mtime=now, crtime=now, mode=mode,
                    uid=0, gid=0, mime=content_type,
                    ttl_sec=ttl_sec, md5=md5.digest(), file_size=offset,
                ),
                chunks=chunks,
                content=small_content,
                extended=extended,
            )
            old_chunks = []
            try:
                old = self.filer.find_entry(path)
                # overwriting a hard-linked name rewrites the SHARED
                # content: inherit the id so every other name sees the new
                # data, and the replaced chunks are safe to GC exactly
                # because all names now point at the replacement
                entry.hard_link_id = old.hard_link_id
                old_chunks = list(old.chunks)
            except NotFoundError:
                pass
            await self.filer.create_entry(entry)
            if old_chunks:
                await self.filer.delete_unused_chunks(old_chunks, chunks)
            size = offset

        return web.json_response(
            {"name": path.rsplit("/", 1)[-1], "size": size},
            status=201,
            headers={"Content-MD5": base64.b64encode(md5.digest()).decode()},
        )

    async def _manifestize_async(self, chunks, collection, replication):
        """Async wrapper: pre-upload manifest blobs then fold the list."""
        from ..filer.manifest import maybe_manifestize_async

        return await maybe_manifestize_async(
            lambda blob: self._upload_chunk(
                blob, 0, "manifest", collection, replication
            ),
            chunks,
        )

    async def h_delete(self, request: web.Request) -> web.Response:
        path, _ = self._req_path(request)
        q = request.query
        try:
            await self.filer.delete_entry_meta_and_data(
                path,
                is_recursive=q.get("recursive") == "true",
                ignore_recursive_error=q.get("ignoreRecursiveError") == "true",
                is_delete_data=q.get("skipChunkDeletion") != "true",
            )
        except NotFoundError:
            raise web.HTTPNotFound()
        except NotEmptyError as e:
            raise web.HTTPConflict(text=str(e))
        return web.Response(status=204)

    # -------------------------------------------------------- gRPC service

    async def LookupDirectoryEntry(self, request, context):
        try:
            entry = self.filer.find_entry(
                new_full_path(request.directory, request.name)
            )
        except NotFoundError:
            await context.abort(grpc.StatusCode.NOT_FOUND, "not found")
        return filer_pb2.LookupDirectoryEntryResponse(entry=entry.to_pb())

    async def ListEntries(self, request, context):
        remaining = request.limit or (1 << 31)
        start = request.start_from_file_name
        inclusive = request.inclusive_start_from
        while remaining > 0:
            ask = min(remaining, 1024)
            batch = self.filer.list_directory_entries(
                request.directory,
                start_file_name=start,
                include_start=inclusive,
                limit=ask,
                prefix=request.prefix,
            )
            for e in batch:
                yield filer_pb2.ListEntriesResponse(entry=e.to_pb())
            if len(batch) < ask:
                return
            remaining -= len(batch)
            start, inclusive = batch[-1].name, False

    async def CreateEntry(self, request, context):
        try:
            self._check_writable(
                f"{request.directory.rstrip('/')}/{request.entry.name}"
            )
        except web.HTTPForbidden as e:
            return filer_pb2.CreateEntryResponse(error=e.text)
        entry = Entry.from_pb(request.directory, request.entry)
        old = None
        try:
            old = self.filer.find_entry(entry.full_path)
        except NotFoundError:
            pass
        try:
            await self.filer.create_entry(
                entry,
                o_excl=request.o_excl,
                is_from_other_cluster=request.is_from_other_cluster,
                signatures=list(request.signatures),
            )
        except FilerError as e:
            return filer_pb2.CreateEntryResponse(error=str(e))
        if old is not None and old.chunks:
            if old.hard_link_id and old.hard_link_id != entry.hard_link_id:
                # the name detached from its link group: drop ONE ref;
                # the shared chunks live on for the other names
                self.filer._release_hard_link(old)
            else:
                await self.filer.delete_unused_chunks(
                    old.chunks, entry.chunks
                )
        return filer_pb2.CreateEntryResponse()

    async def UpdateEntry(self, request, context):
        try:
            self._check_writable(
                f"{request.directory.rstrip('/')}/{request.entry.name}"
            )
        except web.HTTPForbidden as e:
            await context.abort(grpc.StatusCode.PERMISSION_DENIED, e.text)
        entry = Entry.from_pb(request.directory, request.entry)
        old = None
        try:
            old = self.filer.find_entry(entry.full_path)
        except NotFoundError:
            pass
        await self.filer.update_entry(
            old, entry, signatures=list(request.signatures)
        )
        if old is not None:
            if old.hard_link_id and old.hard_link_id != entry.hard_link_id:
                self.filer._release_hard_link(old)  # name left the group
            else:
                await self.filer.delete_unused_chunks(
                    old.chunks, entry.chunks
                )
        return filer_pb2.UpdateEntryResponse()

    async def AppendToEntry(self, request, context):
        try:
            self._check_writable(
                f"{request.directory.rstrip('/')}/{request.entry_name}"
            )
        except web.HTTPForbidden as e:
            await context.abort(grpc.StatusCode.PERMISSION_DENIED, e.text)
        await self.filer.append_chunks(
            new_full_path(request.directory, request.entry_name),
            list(request.chunks),
        )
        return filer_pb2.AppendToEntryResponse()

    async def DeleteEntry(self, request, context):
        try:
            await self.filer.delete_entry_meta_and_data(
                new_full_path(request.directory, request.name),
                is_recursive=request.is_recursive,
                ignore_recursive_error=request.ignore_recursive_error,
                is_delete_data=request.is_delete_data,
                signatures=list(request.signatures),
            )
        except NotFoundError:
            return filer_pb2.DeleteEntryResponse()
        except NotEmptyError as e:
            return filer_pb2.DeleteEntryResponse(error=str(e))
        return filer_pb2.DeleteEntryResponse()

    async def AtomicRenameEntry(self, request, context):
        try:
            # renames must not GROW a read-only subtree (moving OUT of one
            # is allowed — quota locks block growth, not shrinkage)
            self._check_writable(
                f"{request.new_directory.rstrip('/')}/{request.new_name}"
            )
        except web.HTTPForbidden as e:
            await context.abort(grpc.StatusCode.PERMISSION_DENIED, e.text)
        try:
            await self.filer.atomic_rename(
                request.old_directory,
                request.old_name,
                request.new_directory,
                request.new_name,
                signatures=list(request.signatures),
            )
        except NotFoundError:
            await context.abort(grpc.StatusCode.NOT_FOUND, "source not found")
        return filer_pb2.AtomicRenameEntryResponse()

    async def AssignVolume(self, request, context):
        rule = self._conf_rule(request.path) if request.path else None
        if rule and rule.read_only:
            return filer_pb2.AssignVolumeResponse(
                error=f"{rule.location_prefix} is read-only (filer.conf)"
            )
        try:
            a = await self._assign(
                max(request.count, 1),
                request.collection or (rule.collection if rule else ""),
                request.replication or (rule.replication if rule else ""),
                _seconds_to_ttl(request.ttl_sec)
                or (rule.ttl if rule else ""),
                request.data_center,
            )
        except Exception as e:  # noqa: BLE001
            return filer_pb2.AssignVolumeResponse(error=str(e))
        return filer_pb2.AssignVolumeResponse(
            file_id=a.fid,
            count=a.count,
            auth=a.auth,
            collection=request.collection or self.collection,
            replication=request.replication or self.replication,
            location=filer_pb2.Location(
                url=a.url, public_url=a.public_url, grpc_port=a.grpc_port
            ),
        )

    async def LookupVolume(self, request, context):
        resp = filer_pb2.LookupVolumeResponse()
        for vid_str in request.volume_ids:
            vid = int(vid_str.split(",")[0])
            locs = await self.master_client.lookup_or_fetch(vid)
            resp.locations_map[vid_str].CopyFrom(
                filer_pb2.Locations(
                    locations=[
                        filer_pb2.Location(
                            url=l.url, public_url=l.public_url, grpc_port=l.grpc_port
                        )
                        for l in locs
                    ]
                )
            )
        return resp

    async def CollectionList(self, request, context):
        stub = self._master_stub()
        resp = await stub.CollectionList(
            master_pb2.CollectionListRequest(
                include_normal_volumes=request.include_normal_volumes,
                include_ec_volumes=request.include_ec_volumes,
            ),
            timeout=30.0,  # master metadata round-trip (GL114)
        )
        return filer_pb2.CollectionListResponse(
            collections=[filer_pb2.Collection(name=c.name) for c in resp.collections]
        )

    async def DeleteCollection(self, request, context):
        stub = self._master_stub()
        await stub.CollectionDelete(
            master_pb2.CollectionDeleteRequest(name=request.collection),
            timeout=60.0,  # deletes fan out to volume servers (GL114)
        )
        return filer_pb2.DeleteCollectionResponse()

    async def Statistics(self, request, context):
        stub = self._master_stub()
        resp = await stub.Statistics(
            master_pb2.StatisticsRequest(
                replication=request.replication,
                collection=request.collection,
                ttl=request.ttl,
                disk_type=request.disk_type,
            ),
            timeout=30.0,  # master metadata round-trip (GL114)
        )
        return filer_pb2.StatisticsResponse(
            total_size=resp.total_size,
            used_size=resp.used_size,
            file_count=resp.file_count,
        )

    async def GetFilerConfiguration(self, request, context):
        return filer_pb2.GetFilerConfigurationResponse(
            masters=self.masters,
            replication=self.replication,
            collection=self.collection,
            max_mb=self.max_mb,
            dir_buckets=self.dir_buckets,
        )

    async def SubscribeMetadata(self, request, context):
        async for ev in self.filer.meta_log.subscribe(
            since_ns=request.since_ns, path_prefix=request.path_prefix
        ):
            sigs = ev.event_notification.signatures
            if request.signature and request.signature in sigs:
                continue  # originated from this subscriber — loop guard
            yield ev

    async def KvGet(self, request, context):
        try:
            value = self.filer.store.kv_get(bytes(request.key))
        except NotFoundError:
            return filer_pb2.KvGetResponse()
        return filer_pb2.KvGetResponse(value=value)

    async def KvPut(self, request, context):
        self.filer.store.kv_put(bytes(request.key), bytes(request.value))
        return filer_pb2.KvPutResponse()

    def _master_stub(self):
        return Stub(
            channel(server_address.grpc_address(self.master_client.current_master)),
            master_pb2,
            "Seaweed",
        )


def _seconds_to_ttl(sec: int) -> str:
    """Seconds → the master's TTL string units (m/h/d/w; rounds up to a
    minute — the reference's needle.SecondsToTTL does the same)."""
    if sec <= 0:
        return ""
    if sec % 86400 == 0:
        return f"{sec // 86400}d"
    if sec % 3600 == 0:
        return f"{sec // 3600}h"
    return f"{max(1, (sec + 59) // 60)}m"


def _entry_json(e: Entry) -> dict:
    return {
        "FullPath": e.full_path,
        "Mtime": e.attr.mtime,
        "Crtime": e.attr.crtime,
        "Mode": e.attr.mode,
        "Uid": e.attr.uid,
        "Gid": e.attr.gid,
        "Mime": e.attr.mime,
        "TtlSec": e.attr.ttl_sec,
        "FileSize": e.size(),
        "IsDirectory": e.is_directory,
        "Md5": base64.b64encode(e.attr.md5).decode() if e.attr.md5 else "",
    }
