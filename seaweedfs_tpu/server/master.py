"""MasterServer: topology brain + HTTP admin + gRPC services.

Reference: weed/server/master_server.go (410), master_grpc_server.go (409),
master_grpc_server_volume.go (324), master_server_handlers*.go (341).

One asyncio process hosting:
  - gRPC `Seaweed` service: SendHeartbeat (bidi: volume servers),
    KeepConnected (bidi: filers/shells/mounts get VolumeLocation pushes),
    Assign / LookupVolume / LookupEcVolume / VolumeList / admin locks
  - aiohttp admin+data endpoints: /dir/assign, /dir/lookup, /dir/status,
    /vol/grow, /vol/vacuum, /col/delete, /submit
  - automatic volume growth when a layout runs out of writable volumes
    (the reference's vgCh channel → here an asyncio queue consumed by
    a grower task)
  - periodic vacuum scan driving the volume servers' compact protocol

Single-master deployment (the reference supports the same); raft HA is
layered on in server/raft.py.
"""
from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from dataclasses import dataclass, field

import grpc
from aiohttp import web

from .. import stats
from ..pb import Stub, generic_handler, master_pb2, raft_pb2, server_address, volume_server_pb2
from ..pb.rpc import GRPC_OPTIONS, channel
from ..security import gen_volume_write_jwt
from ..security import tls as tls_mod
from ..security import guard as guard_mod
from ..storage import types as t
from ..utils.tasks import spawn_logged
from ..topology import (
    MemorySequencer,
    NoFreeSpace,
    Topology,
    VolumeGrowOption,
    target_count_per_request,
)
from ..topology.node import DataNode
from .conversions import (
    ec_msg_from_pb,
    heartbeat_state_from_pb,
    node_to_location,
    volume_msg_from_pb,
)

log = logging.getLogger("master")


@dataclass
class AdminLock:
    """Exclusive admin lock leased to one shell at a time
    (LeaseAdminToken master_grpc_server_admin.go)."""

    token: int = 0
    ts_ns: int = 0
    client: str = ""
    message: str = ""

    LEASE_NS = 60 * 1_000_000_000

    def is_held(self) -> bool:
        return self.token != 0 and time.time_ns() - self.ts_ns < self.LEASE_NS


class MasterServer:
    def __init__(
        self,
        ip: str = "127.0.0.1",
        port: int = 9333,
        grpc_port: int = 0,
        volume_size_limit_mb: int = 30 * 1024,
        default_replication: str = "000",
        pulse_seconds: int = 5,
        garbage_threshold: float = 0.3,
        sequencer: MemorySequencer | None = None,
        auto_vacuum: bool = False,
        jwt_signing_key: str = "",
        jwt_expires_sec: int = 10,
        peers: list[str] | None = None,  # other masters' advertise urls
        meta_dir: str | None = None,  # durable raft state directory
        raft_join: bool = False,  # start as non-voter until cluster.raft.add
        raft_snapshot_threshold: int = 1000,  # log entries before compaction
        white_list: list[str] | None = None,  # [access] white_list guard
        metrics_address: str = "",  # pushgateway host:port (ref -metrics.address)
        metrics_interval_seconds: int = 15,  # ref -metrics.intervalSeconds
        ec_repair=None,  # repair.RepairConfig | None (-ec.repair.* flags)
        obs_slo=None,  # obs.SloConfig | None (-obs.slo.* flags)
        obs_incident=None,  # obs.IncidentConfig | None (-obs.incident.*)
    ):
        self.metrics_address = metrics_address
        self.metrics_interval_seconds = metrics_interval_seconds
        self.raft_join = raft_join
        self.guard = guard_mod.Guard(white_list)
        self.raft_snapshot_threshold = raft_snapshot_threshold
        self.ip = ip
        self.port = port
        self.grpc_port = grpc_port or (port + 10000 if port else 0)
        self.default_replication = default_replication
        self.pulse_seconds = pulse_seconds
        self.garbage_threshold = garbage_threshold
        self.auto_vacuum = auto_vacuum
        self.vacuum_disabled = False
        self.jwt_signing_key = jwt_signing_key
        self.jwt_expires_sec = jwt_expires_sec
        self.topo = Topology(
            volume_size_limit=volume_size_limit_mb * 1024 * 1024,
            sequencer=sequencer,
            pulse_seconds=pulse_seconds,
        )
        # heartbeat-carried telemetry aggregated into /cluster/health.json
        # and the SeaweedFS_cluster_* series; a node missing 2 pulse
        # intervals is flagged stale (stats/cluster.py)
        self.telemetry = stats.ClusterTelemetry(pulse_seconds)
        # self-healing repair plane (repair/scheduler.py): watches the
        # EC census + telemetry for missing/corrupt shards and drives
        # prioritized, QoS-subordinated ec.rebuild fan-outs; its loop
        # starts in start() and only acts while this master leads
        from ..repair import RepairScheduler

        self.repair = RepairScheduler(self, ec_repair)
        # incident plane (obs/slo.py + obs/incident.py): declared SLOs
        # evaluated against the telemetry plane every pulse; a sustained
        # burn (fast window trips, slow window confirms) fires the
        # bundler, which snapshots every fresh node's flight recorder +
        # trace ring into one correlated bundle under -obs.incident.dir
        from .. import obs

        if obs_incident is not None:
            obs.incident.configure(obs_incident)
        self.slo = obs.SloEngine(obs_slo, self.telemetry, self.repair)
        self.incident = obs.IncidentBundler(
            self.telemetry.fresh_node_urls, self._health_doc,
            timeline_fn=self.telemetry.timeline,
            skew_ms_fn=self.telemetry.clock_skew_ms,
        )
        # tail-forensics retention for the master's own requests
        # (assign/lookup paths have tails too), built in start()
        self.tailstore = None
        self.slo.on_violation.append(self._on_slo_violation)
        self._incident_captures: set = set()
        self._subscribers: dict[object, asyncio.Queue] = {}
        self._grow_queue: asyncio.Queue = asyncio.Queue()
        self._growing: set[tuple] = set()
        self.locks: dict[str, AdminLock] = {}
        self.peers = peers or []
        self.meta_dir = meta_dir
        # (client_type, address) -> joined-at ns; fed by KeepConnected
        # streams (reference weed/cluster/cluster.go membership)
        self.cluster_nodes: dict[tuple[str, str], int] = {}
        self.raft = None  # RaftNode once started (raft/node.py)
        self._seq_committed = 0  # highest raft-replicated sequence ceiling
        self._grpc_server: grpc.aio.Server | None = None
        self._http_runner: web.AppRunner | None = None
        self._tasks: list[asyncio.Task] = []

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    @property
    def grpc_url(self) -> str:
        return f"{self.ip}:{self.grpc_port}"

    @property
    def advertise_url(self) -> str:
        """host:port[.grpc] — explicit grpc form when the +10000 convention
        doesn't hold (dynamically-assigned test ports)."""
        if self.grpc_port == self.port + 10000:
            return self.url
        return f"{self.ip}:{self.port}.{self.grpc_port}"

    # ------------------------------------------------------------------ lifecycle

    async def start(self) -> None:
        self._grpc_server = grpc.aio.server(options=GRPC_OPTIONS)
        self._grpc_server.add_generic_rpc_handlers(
            [generic_handler(master_pb2, "Seaweed", self)]
        )
        # raft RPCs delegate through self so the handler can register
        # before the RaftNode exists (ports are only known after bind)
        self._grpc_server.add_generic_rpc_handlers(
            [generic_handler(raft_pb2, "SeaweedRaft", self)]
        )
        self.grpc_port = tls_mod.add_port(
            self._grpc_server, f"{self.ip}:{self.grpc_port}"
        )
        await self._grpc_server.start()

        from .. import obs

        app = web.Application(
            client_max_size=256 * 1024 * 1024,
            middlewares=(
                [guard_mod.middleware(self.guard)] if self.guard.enabled else []
            ) + [obs.middleware("master")],
        )
        app.router.add_get("/", self.h_ui)
        app.router.add_route("*", "/dir/assign", self.h_assign)
        app.router.add_route("*", "/dir/lookup", self.h_lookup)
        app.router.add_get("/dir/status", self.h_dir_status)
        app.router.add_route("*", "/vol/grow", self.h_grow)
        app.router.add_route("*", "/vol/vacuum", self.h_vacuum)
        app.router.add_route("*", "/col/delete", self.h_col_delete)
        app.router.add_post("/submit", self.h_submit)
        app.router.add_get("/cluster/status", self.h_cluster_status)
        app.router.add_get("/cluster/health.json", self.h_cluster_health)
        app.router.add_get("/metrics", stats.metrics_handler)
        # refresh the SeaweedFS_cluster_* gauges from the telemetry plane
        # at scrape time (the volume server refreshes its store gauges
        # through the same hook)
        app[stats.metrics.metrics_collect_key()] = self.telemetry.refresh_gauges
        app.router.add_get("/debug/traces", obs.traces_handler)
        # tail-forensics plane: cross-node critical-path assembly (fans
        # out to every fresh node's /debug/traces, reconciles clocks
        # against the heartbeat skew estimates) + this master's own
        # tail ring (volume.trace.why / cluster.tail read these)
        app.router.add_get(
            "/debug/critpath",
            obs.critpath_handler(
                node_urls_fn=self.telemetry.fresh_node_urls,
                skew_ms_fn=self.telemetry.clock_skew_ms,
            ),
        )
        app.router.add_get("/debug/tail", self.h_debug_tail)
        # the assembled cluster flight timeline (heartbeat-shipped node
        # samples, clock-aligned) — ?window=<seconds> trims the tail
        app.router.add_get("/debug/timeline", self.h_debug_timeline)
        # the master's own flight-recorder ring (repair + SLO events);
        # volume servers serve the same endpoint for the fan-out
        app.router.add_get("/debug/incident", obs.incident.incident_handler)
        app.router.add_post("/cluster/incident/dump", self.h_incident_dump)
        if os.environ.get("SWFS_DEBUG") == "1":
            # stack dumps reveal internals; opt-in only (the reference
            # gates pprof handlers the same way)
            from ..utils.profiling import debug_stacks_handler

            app.router.add_get("/debug/stacks", debug_stacks_handler)
        self._http_runner = web.AppRunner(app)
        await self._http_runner.setup()
        site = web.TCPSite(self._http_runner, self.ip, self.port)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        self.port = port

        from ..obs import tailstore as tailstore_mod
        from ..obs import trace as obs_trace_mod

        if obs_trace_mod.CONFIG.tail_enabled:
            self.tailstore = tailstore_mod.TailStore(node=self.url).install()

        from ..raft import RaftNode

        others = [
            p for p in self.peers
            if server_address.http_address(p) != self.url
        ]
        self.raft = RaftNode(
            self.advertise_url,
            others,
            apply_fn=self._apply_raft,
            data_dir=self.meta_dir,
            dial_fn=server_address.grpc_address,
            voter=not self.raft_join,
            snapshot_fn=self._raft_snapshot,
            restore_fn=self._raft_restore,
            snapshot_threshold=self.raft_snapshot_threshold,
        )
        await self.raft.start()

        self._tasks.append(
            spawn_logged(self._grower_loop(), log, "volume grower loop")
        )
        if self.slo.specs:
            self._tasks.append(
                spawn_logged(self._slo_loop(), log, "slo evaluation loop")
            )
        self.repair.start()
        if self.auto_vacuum:
            self._tasks.append(
                spawn_logged(self._vacuum_loop(), log, "auto-vacuum loop")
            )
        push = stats.start_push_loop(
            "master", self.url, self.metrics_address,
            self.metrics_interval_seconds,
        )
        if push is not None:
            self._tasks.append(push)
        log.info(
            "master up http=%s grpc=%s peers=%s", self.url, self.grpc_url,
            others,
        )

    async def stop(self) -> None:
        await self.repair.stop()
        if self.raft is not None:
            await self.raft.stop()
        captures = list(self._incident_captures)
        for t_ in self._tasks + captures:
            t_.cancel()
        await asyncio.gather(
            *self._tasks, *captures, return_exceptions=True
        )
        if self._grpc_server:
            await self._grpc_server.stop(0.1)
        if self._http_runner:
            await self._http_runner.cleanup()
        if self.tailstore is not None:
            # unhook the finished-trace tap: the process-global observer
            # list outlives this server (co-hosted roles, test restarts)
            self.tailstore.uninstall()

    # ------------------------------------------------------------------ gRPC

    # ------------------------------------------------------------------ raft

    @property
    def is_leader(self) -> bool:
        return self.raft is None or self.raft.is_leader

    @property
    def leader_advertise(self) -> str:
        if self.raft is None or self.raft.leader_id is None:
            return self.advertise_url
        return self.raft.leader_id

    def _raft_snapshot(self) -> dict:
        """State-machine snapshot at the raft apply point: the allocation
        ceilings every future leader must start past (membership is
        carried by the raft layer itself).  Reference analogue: the
        hashicorp snapshot of MaxVolumeId state, raft_hashicorp.go."""
        return {
            "max_vid": self.topo.max_volume_id,
            "seq_ceiling": self._seq_committed,
        }

    def _raft_restore(self, st: dict) -> None:
        self.topo.max_volume_id = max(
            self.topo.max_volume_id, int(st.get("max_vid", 0))
        )
        ceiling = int(st.get("seq_ceiling", 0))
        if ceiling:
            self.topo.sequencer.set_max(ceiling)
            self._seq_committed = max(self._seq_committed, ceiling)

    def _apply_raft(self, cmd: dict, term: int = 0, own_live: bool = False) -> None:
        """Raft state machine: allocation ceilings replicated so any
        future leader starts past every id ever handed out (the reference
        replicates MaxVolumeIdCommand the same way, topology.go)."""
        op = cmd.get("op")
        if op == "max_vid":
            self.topo.max_volume_id = max(self.topo.max_volume_id, cmd["vid"])
        elif op == "seq":
            if not own_live:
                # followers / restart replay jump past the ceiling; the
                # live proposer keeps minting from its lower counter so
                # the 10k batch isn't burned per proposal
                self.topo.sequencer.set_max(cmd["ceiling"])
            self._seq_committed = max(self._seq_committed, cmd["ceiling"])
        elif op == "raft_conf":
            if self.raft is not None:
                self.raft.apply_config(cmd["members"])

    async def RequestVote(self, request, context):
        if self.raft is None:
            await context.abort(grpc.StatusCode.UNAVAILABLE, "raft not up")
        return await self.raft.RequestVote(request, context)

    async def AppendEntries(self, request, context):
        if self.raft is None:
            await context.abort(grpc.StatusCode.UNAVAILABLE, "raft not up")
        return await self.raft.AppendEntries(request, context)

    def _leader_stub(self) -> Stub:
        return Stub(
            channel(server_address.grpc_address(self.leader_advertise)),
            master_pb2,
            "Seaweed",
        )

    async def _proxy_to_leader(self, method: str, request):
        """Followers forward control-plane calls: only the leader holds
        topology state, since volume servers heartbeat to it alone
        (masterclient proxyToMaster in the reference)."""
        if self.leader_advertise == self.advertise_url:
            raise RuntimeError("no raft leader elected yet")
        return await getattr(self._leader_stub(), method)(request)

    async def _replicate_seq_ceiling(self) -> None:
        """After minting fids: make sure a crash/failover can't re-mint
        them.  Batched — most assigns find the ceiling already covers."""
        if self.raft is None or not self.raft.peers:
            return
        seq = self.topo.sequencer
        peek = getattr(seq, "peek", None)
        if peek is None:
            return  # snowflake ids are collision-free without consensus
        if seq.peek() <= self._seq_committed:
            return
        ceiling = seq.peek() + 10_000
        await self.raft.propose({"op": "seq", "ceiling": ceiling})

    async def SendHeartbeat(self, request_iterator, context):
        """Followers close the stream with a leader hint so volume
        servers re-dial the leader (the only master holding topology).
        """
        if not self.is_leader:
            yield master_pb2.HeartbeatResponse(
                volume_size_limit=self.topo.volume_size_limit,
                leader=self.leader_advertise,
            )
            return
        async for resp in self._send_heartbeat_leader(request_iterator, context):
            yield resp

    async def _send_heartbeat_leader(self, request_iterator, context):
        """Volume-server registration stream (master_grpc_server.go:61-170)."""
        node: DataNode | None = None
        try:
            async for hb in request_iterator:
                if hb.offset_bytes and hb.offset_bytes != t.OFFSET_SIZE:
                    # the needle-map offset width is a deployment-wide
                    # mode: .idx/.ecx written in one mode are garbage in
                    # the other, so reject the mismatch LOUDLY instead of
                    # letting the cluster mix formats
                    await context.abort(
                        grpc.StatusCode.FAILED_PRECONDITION,
                        f"offset width mismatch: volume server uses "
                        f"{hb.offset_bytes}-byte needle-map offsets, "
                        f"master uses {t.OFFSET_SIZE} (check "
                        f"-volumeSizeLimitMB / -offset.bytes)",
                    )
                if node is None:
                    node = self.topo.get_or_create_node(
                        hb.data_center,
                        hb.rack,
                        hb.ip,
                        hb.port,
                        hb.public_url,
                        hb.grpc_port,
                    )
                    log.info("volume server joined: %s", node.url)
                # pod membership (r20, getattr-guarded for pre-r20
                # servers): members of one jax.distributed pod serve a
                # single SPMD residency mesh and degrade together, so
                # the topology tree treats the pod id as a rack-like
                # failure domain (placement + repair planning)
                node.mesh_pod = str(getattr(hb, "mesh_pod", ""))
                stats.MASTER_RECEIVED_HEARTBEATS.labels(type="total").inc()
                # every pulse refreshes freshness; the payload (absent on
                # pre-telemetry servers) feeds the cluster health plane
                self.telemetry.observe(
                    node.url,
                    hb.telemetry if hb.HasField("telemetry") else None,
                    mesh_pod=node.mesh_pod,
                )
                if hb.volumes or hb.has_no_volumes or hb.ec_shards or hb.has_no_ec_shards:
                    new_v, del_v, new_ec, del_ec = self.topo.sync_node(
                        node, heartbeat_state_from_pb(hb)
                    )
                    self._broadcast_location(node, new_v, del_v, new_ec, del_ec)
                if hb.new_volumes or hb.deleted_volumes or hb.new_ec_shards or hb.deleted_ec_shards:
                    self.topo.incremental_sync_node(
                        node,
                        [volume_msg_from_pb(v) for v in hb.new_volumes],
                        [volume_msg_from_pb(v) for v in hb.deleted_volumes],
                        [ec_msg_from_pb(e) for e in hb.new_ec_shards],
                        [ec_msg_from_pb(e) for e in hb.deleted_ec_shards],
                    )
                    self._broadcast_location(
                        node,
                        [v.id for v in hb.new_volumes],
                        [v.id for v in hb.deleted_volumes],
                        [e.id for e in hb.new_ec_shards],
                        [e.id for e in hb.deleted_ec_shards],
                    )
                yield master_pb2.HeartbeatResponse(
                    volume_size_limit=self.topo.volume_size_limit,
                    leader=self.advertise_url,
                )
        finally:
            if node is not None:
                # stream broke: the server is gone; drop its volumes and
                # tell every subscribed client (phantom cleanup :63-94)
                dead_vids = list(node.volumes)
                dead_ec = list(node.ec_shards)
                self.topo.unregister_node(node)
                self._broadcast_location(node, [], dead_vids, [], dead_ec)
                # keep the node's last telemetry snapshot (flagged
                # disconnected; age takes it stale) — health.json should
                # show what a dead node last looked like, not erase it
                self.telemetry.disconnect(node.url)
                log.info("volume server left: %s", node.url)

    async def KeepConnected(self, request_iterator, context):
        """Client subscription stream: pushes VolumeLocation deltas
        (master_grpc_server.go broadcastToClients)."""
        if not self.is_leader:
            # hint then close: the wdclient re-dials the leader
            yield master_pb2.KeepConnectedResponse(leader=self.leader_advertise)
            return
        q: asyncio.Queue = asyncio.Queue()
        key = object()
        self._subscribers[key] = q
        # send current full location map first
        for n in self.topo.data_nodes():
            loc = master_pb2.VolumeLocation(
                url=n.url,
                public_url=n.public_url,
                grpc_port=n.grpc_port,
                data_center=n.rack.data_center.name if n.rack else "",
                new_vids=sorted(set(list(n.volumes) + list(n.ec_shards))),
                new_ec_vids=sorted(n.ec_shards),
            )
            yield master_pb2.KeepConnectedResponse(
                volume_location=loc, leader=self.advertise_url
            )

        registered: tuple[str, str] | None = None
        registered_ts = 0

        async def drain_requests():
            nonlocal registered, registered_ts
            try:
                async for req in request_iterator:
                    # first request names the client: track cluster
                    # membership for cluster.ps (reference cluster.go)
                    if registered is None and req.client_address:
                        registered = (req.client_type, req.client_address)
                        registered_ts = time.time_ns()
                        self.cluster_nodes[registered] = registered_ts
            except Exception as e:  # noqa: BLE001
                # a broken keep-connected stream is routine (client
                # restart, network blip) but must not vanish silently:
                # the telemetry plane reads liveness off these streams
                from .. import obs

                cur = obs.current()
                log.debug(
                    "keep-connected drain from %s ended (trace=%s): %s",
                    registered, cur[0].trace_id if cur else "-", e,
                )
            finally:
                q.put_nowait(None)

        drainer = asyncio.create_task(drain_requests())
        try:
            while True:
                item = await q.get()
                if item is None:
                    break
                yield item
        finally:
            drainer.cancel()
            self._subscribers.pop(key, None)
            if (
                registered is not None
                # a reconnect may have re-registered under the same key;
                # only the stream that owns the entry may remove it
                and self.cluster_nodes.get(registered) == registered_ts
            ):
                self.cluster_nodes.pop(registered, None)

    async def ListClusterNodes(self, request, context):
        # membership registers on the leader (clients follow leader hints)
        proxied = await self._maybe_proxy("ListClusterNodes", request, context)
        if proxied is not None:
            return proxied
        resp = master_pb2.ListClusterNodesResponse()
        for (ctype, addr), ts in sorted(self.cluster_nodes.items()):
            if request.client_type and ctype != request.client_type:
                continue
            resp.cluster_nodes.append(
                master_pb2.ClusterNodeInfo(
                    address=addr, client_type=ctype, created_at_ns=ts
                )
            )
        return resp

    def _broadcast_location(
        self,
        node: DataNode,
        new_vids: list[int],
        deleted_vids: list[int],
        new_ec_vids: list[int] = (),
        deleted_ec_vids: list[int] = (),
    ) -> None:
        if not (new_vids or deleted_vids or new_ec_vids or deleted_ec_vids):
            return
        msg = master_pb2.KeepConnectedResponse(
            volume_location=master_pb2.VolumeLocation(
                url=node.url,
                public_url=node.public_url,
                grpc_port=node.grpc_port,
                data_center=node.rack.data_center.name if node.rack else "",
                new_vids=sorted(set(new_vids) | set(new_ec_vids)),
                deleted_vids=sorted(set(deleted_vids) | set(deleted_ec_vids)),
                new_ec_vids=sorted(set(new_ec_vids)),
                deleted_ec_vids=sorted(set(deleted_ec_vids)),
            ),
            leader=self.advertise_url,
        )
        for q in self._subscribers.values():
            q.put_nowait(msg)

    async def Assign(self, request, context):
        if not self.is_leader:
            try:
                return await self._proxy_to_leader("Assign", request)
            except Exception as e:  # noqa: BLE001
                return master_pb2.AssignResponse(error=str(e))
        try:
            option = self._grow_option(
                request.collection,
                request.replication,
                request.ttl,
                request.data_center,
                request.rack,
                request.data_node,
                request.disk_type,
            )
        except ValueError as e:
            return master_pb2.AssignResponse(error=str(e))
        count = int(request.count) or 1
        for attempt in range(4):
            try:
                fid, n, nodes = self.topo.pick_for_write(count, option)
                await self._replicate_seq_ceiling()
                return master_pb2.AssignResponse(
                    fid=fid,
                    count=n,
                    location=node_to_location(nodes[0]),
                    replicas=[node_to_location(x) for x in nodes[1:]],
                    auth=gen_volume_write_jwt(
                        self.jwt_signing_key, fid, self.jwt_expires_sec
                    ),
                )
            except LookupError:
                grown = await self._grow_now(option)
                if not grown and attempt < 3:
                    # a concurrent assign may be growing this layout right
                    # now (_grow_now dedups by key) — give it a beat and
                    # retry the pick instead of failing the burst
                    await asyncio.sleep(0.25)
        return master_pb2.AssignResponse(error="no writable volumes and growth failed")

    async def _maybe_proxy(self, name: str, request, context):
        """None when leader (caller handles locally); else the response
        proxied from the leader."""
        if self.is_leader:
            return None
        try:
            return await self._proxy_to_leader(name, request)
        except grpc.aio.AioRpcError as e:
            await context.abort(e.code(), e.details())
        except RuntimeError as e:
            await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))

    def _redirect_if_follower(self, request: web.Request) -> None:
        if self.is_leader:
            return
        if self.leader_advertise == self.advertise_url:
            # mid-election (or partitioned minority): redirecting to
            # ourselves would loop — tell the client to retry instead
            raise web.HTTPServiceUnavailable(text="no raft leader elected yet")
        leader = server_address.http_address(self.leader_advertise)
        raise web.HTTPTemporaryRedirect(f"http://{leader}{request.path_qs}")

    async def LookupVolume(self, request, context):
        proxied = await self._maybe_proxy("LookupVolume", request, context)
        if proxied is not None:
            return proxied
        resp = master_pb2.LookupVolumeResponse()
        for vof in request.volume_or_file_ids:
            entry = resp.volume_id_locations.add(volume_or_file_id=vof)
            try:
                vid_s = vof.split(",")[0]
                nodes = self.topo.lookup_volume(request.collection, int(vid_s))
                if not nodes:
                    entry.error = f"volume {vid_s} not found"
                else:
                    entry.locations.extend(node_to_location(n) for n in nodes)
                    if "," in vof:
                        # full-fid lookups get a write token so clients can
                        # delete/overwrite (master_grpc_server_volume.go
                        # LookupVolume auth)
                        entry.auth = gen_volume_write_jwt(
                            self.jwt_signing_key, vof, self.jwt_expires_sec
                        )
            except ValueError:
                entry.error = f"bad volume id {vof!r}"
        return resp

    async def LookupEcVolume(self, request, context):
        proxied = await self._maybe_proxy("LookupEcVolume", request, context)
        if proxied is not None:
            return proxied
        locs = self.topo.lookup_ec_shards(request.volume_id)
        resp = master_pb2.LookupEcVolumeResponse(volume_id=request.volume_id)
        if locs is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND, f"ec volume {request.volume_id} not found"
            )
        for sid, nodes in enumerate(locs.locations):
            if nodes:
                e = resp.shard_id_locations.add(shard_id=sid)
                e.locations.extend(node_to_location(n) for n in nodes)
        return resp

    async def Statistics(self, request, context):
        proxied = await self._maybe_proxy("Statistics", request, context)
        if proxied is not None:
            return proxied
        total = used = files = 0
        for n in self.topo.data_nodes():
            for v in n.volumes.values():
                if request.collection and v.collection != request.collection:
                    continue
                used += v.size
                files += v.file_count
            total += n.max_volume_count() * self.topo.volume_size_limit
        return master_pb2.StatisticsResponse(
            total_size=total, used_size=used, file_count=files
        )

    async def CollectionList(self, request, context):
        proxied = await self._maybe_proxy("CollectionList", request, context)
        if proxied is not None:
            return proxied
        return master_pb2.CollectionListResponse(
            collections=[
                master_pb2.Collection(name=c) for c in sorted(self.topo.collections)
                if c
            ]
        )

    async def CollectionDelete(self, request, context):
        proxied = await self._maybe_proxy("CollectionDelete", request, context)
        if proxied is not None:
            return proxied
        vids = set()
        for col_name, vl in self.topo.layouts():
            if col_name == request.name:
                vids.update(vl.vid2location)
        for vid in vids:
            for node in self.topo.lookup_volume(request.name, vid):
                stub = self._volume_stub(node)
                try:
                    await stub.VolumeDelete(
                        volume_server_pb2.VolumeDeleteRequest(volume_id=vid)
                    )
                except grpc.aio.AioRpcError as e:
                    log.warning("delete %d on %s failed: %s", vid, node.url, e)
        self.topo.collections.pop(request.name, None)
        return master_pb2.CollectionDeleteResponse()

    async def VolumeList(self, request, context):
        proxied = await self._maybe_proxy("VolumeList", request, context)
        if proxied is not None:
            return proxied
        return master_pb2.VolumeListResponse(
            topology_info_json=json.dumps(self.topo.to_info()),
            volume_size_limit_mb=self.topo.volume_size_limit // (1024 * 1024),
        )

    async def LeaseAdminToken(self, request, context):
        proxied = await self._maybe_proxy("LeaseAdminToken", request, context)
        if proxied is not None:
            return proxied
        lock = self.locks.setdefault(request.lock_name, AdminLock())
        now = time.time_ns()
        if lock.is_held() and lock.token != request.previous_token:
            await context.abort(
                grpc.StatusCode.ABORTED,
                f"lock {request.lock_name} held by {lock.client}: {lock.message}",
            )
        lock.token = now
        lock.ts_ns = now
        lock.client = request.client_name
        lock.message = request.message
        return master_pb2.LeaseAdminTokenResponse(token=now, lock_ts_ns=now)

    async def ReleaseAdminToken(self, request, context):
        proxied = await self._maybe_proxy("ReleaseAdminToken", request, context)
        if proxied is not None:
            return proxied
        lock = self.locks.get(request.lock_name)
        if lock and lock.token == request.previous_token:
            lock.token = 0
        return master_pb2.ReleaseAdminTokenResponse()

    async def VacuumVolume(self, request, context):
        proxied = await self._maybe_proxy("VacuumVolume", request, context)
        if proxied is not None:
            return proxied
        await self._vacuum_pass(
            request.garbage_threshold or self.garbage_threshold,
            request.volume_id or 0,
        )
        return master_pb2.VacuumVolumeResponse()

    async def DisableVacuum(self, request, context):
        """volume.vacuum.disable (reference master_grpc_server_volume.go
        DisableVacuum): stops the periodic scan AND manual passes until
        re-enabled."""
        proxied = await self._maybe_proxy("DisableVacuum", request, context)
        if proxied is not None:
            return proxied
        self.vacuum_disabled = True
        return master_pb2.DisableVacuumResponse()

    async def EnableVacuum(self, request, context):
        proxied = await self._maybe_proxy("EnableVacuum", request, context)
        if proxied is not None:
            return proxied
        self.vacuum_disabled = False
        return master_pb2.EnableVacuumResponse()

    async def PauseRepair(self, request, context):
        """volume.repair.pause: quiesce the autonomous repair loop
        (planned maintenance, debugging) — detection keeps running via
        the status plane, but no new repair jobs start."""
        proxied = await self._maybe_proxy("PauseRepair", request, context)
        if proxied is not None:
            return proxied
        self.repair.pause()
        return master_pb2.PauseRepairResponse()

    async def ResumeRepair(self, request, context):
        proxied = await self._maybe_proxy("ResumeRepair", request, context)
        if proxied is not None:
            return proxied
        self.repair.resume()
        return master_pb2.ResumeRepairResponse()

    # -------------------------------------------------- raft administration

    async def RaftListClusterServers(self, request, context):
        """cluster.raft.ps (reference master_grpc_server_raft.go)."""
        resp = master_pb2.RaftListClusterServersResponse()
        if self.raft is None:
            resp.cluster_servers.append(
                master_pb2.ClusterServer(id=self.advertise_url, is_leader=True)
            )
            return resp
        resp.term = self.raft.term
        for sid in [self.raft.id, *self.raft.peers]:
            resp.cluster_servers.append(
                master_pb2.ClusterServer(
                    id=sid, is_leader=sid == self.raft.leader_id
                )
            )
        return resp

    async def RaftAddServer(self, request, context):
        """Single-server joint-free membership add, replicated through the
        log so every node (and any future leader) converges on the new
        peer set."""
        proxied = await self._maybe_proxy("RaftAddServer", request, context)
        if proxied is not None:
            return proxied
        if self.raft is None:
            await context.abort(
                grpc.StatusCode.FAILED_PRECONDITION, "raft not enabled"
            )
        members = [self.raft.id, *self.raft.peers]
        if not any(self.raft.same_node(m, request.id) for m in members):
            members.append(request.id)
        await self.raft.propose(
            {"op": "raft_conf", "members": sorted(members)}
        )
        return master_pb2.RaftAddServerResponse()

    async def RaftRemoveServer(self, request, context):
        proxied = await self._maybe_proxy("RaftRemoveServer", request, context)
        if proxied is not None:
            return proxied
        if self.raft is None:
            await context.abort(
                grpc.StatusCode.FAILED_PRECONDITION, "raft not enabled"
            )
        if self.raft.same_node(request.id, self.raft.id):
            await context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                "cannot remove the current leader; transfer leadership first",
            )
        members = sorted(
            m
            for m in [self.raft.id, *self.raft.peers]
            if not self.raft.same_node(m, request.id)
        )
        await self.raft.propose({"op": "raft_conf", "members": members})
        return master_pb2.RaftRemoveServerResponse()

    # ------------------------------------------------------------------ growth

    def _grow_option(
        self,
        collection: str = "",
        replication: str = "",
        ttl: str = "",
        data_center: str = "",
        rack: str = "",
        data_node: str = "",
        disk_type: str = "",
    ) -> VolumeGrowOption:
        return VolumeGrowOption(
            collection=collection,
            replica_placement=t.ReplicaPlacement.parse(
                replication or self.default_replication
            ),
            ttl=t.TTL.parse(ttl or ""),
            disk_type=disk_type or "hdd",
            preferred_data_center=data_center,
            preferred_rack=rack,
            preferred_node=data_node,
        )

    async def _grow_now(self, option: VolumeGrowOption, count: int = 0) -> list[int]:
        """Synchronously grow volumes for an assign that found nothing
        writable (AutomaticGrowByType volume_growth.go:60-110)."""
        key = (option.collection, str(option.replica_placement), str(option.ttl))
        if key in self._growing:
            await asyncio.sleep(0.05)
            return []
        self._growing.add(key)
        try:
            count = count or target_count_per_request(option.replica_placement)
            allocations: list[tuple[DataNode, int]] = []

            def plan(node, vid, opt):
                allocations.append((node, vid))

            try:
                vids = self.topo.grow_volumes(option, count, plan)
            except NoFreeSpace as e:
                log.warning("growth failed: %s", e)
                return []
            # replicate the ceiling BEFORE creating the volumes: a leader
            # failover after this point starts past every allocated vid
            if self.raft is not None and self.raft.peers:
                try:
                    await self.raft.propose(
                        {"op": "max_vid", "vid": self.topo.max_volume_id}
                    )
                except Exception as e:  # noqa: BLE001 — lost leadership mid-grow
                    log.warning("vid reservation not committed: %s", e)
                    return []
            ok_vids = set(vids)
            for node, vid in allocations:
                stub = self._volume_stub(node)
                try:
                    await stub.AllocateVolume(
                        volume_server_pb2.AllocateVolumeRequest(
                            volume_id=vid,
                            collection=option.collection,
                            replication=str(option.replica_placement),
                            ttl=str(option.ttl),
                            disk_type=option.disk_type,
                        )
                    )
                except grpc.aio.AioRpcError as e:
                    log.warning("allocate %d on %s failed: %s", vid, node.url, e)
                    ok_vids.discard(vid)
            # register immediately so the triggering assign can succeed;
            # heartbeat deltas will confirm
            for node, vid in allocations:
                if vid in ok_vids:
                    from ..storage.store import VolumeMessage

                    self.topo.incremental_sync_node(
                        node,
                        [
                            VolumeMessage(
                                id=vid,
                                size=0,
                                collection=option.collection,
                                file_count=0,
                                delete_count=0,
                                deleted_byte_count=0,
                                read_only=False,
                                replica_placement=option.replica_placement.to_byte(),
                                version=3,
                                ttl=int.from_bytes(option.ttl.to_bytes(), "big"),
                                disk_type=option.disk_type,
                            )
                        ],
                        [],
                    )
            return sorted(ok_vids)
        finally:
            self._growing.discard(key)

    async def _grower_loop(self) -> None:
        while True:
            option = await self._grow_queue.get()
            await self._grow_now(option)

    def _volume_stub(self, node: DataNode) -> Stub:
        return Stub(channel(node.grpc_url), volume_server_pb2, "VolumeServer")

    # ---------------------------------------------------------- incident plane

    async def _slo_loop(self) -> None:
        """Evaluate the declared SLOs once per telemetry pulse — the
        judging half of the observability loop (obs/slo.py).  Runs only
        while this master leads: heartbeat telemetry lands on the
        leader alone, so a follower's windows would judge silence."""
        while True:
            await asyncio.sleep(self.pulse_seconds)
            if not self.is_leader:
                continue
            try:
                self.slo.evaluate()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — one failed evaluation
                # must not end the judge; the next pulse re-samples
                log.exception("slo evaluation failed")

    def _on_slo_violation(self, verdict: dict) -> None:
        """Rising-edge hook from the SLO engine: record the verdict
        into the master's own flight recorder, then capture an incident
        bundle in the background (the evaluate() caller must not block
        on a cluster-wide fan-out)."""
        from .. import obs

        obs.incident.record("slo_violation", **verdict)
        # the bundle should cover the burn AND its lead-up: second-scale
        # test/bench windows would otherwise capture only the last pulse
        # or two of traces (the rings bound the cost either way)
        window = max(self.slo.cfg.slow_window_seconds, 30.0)
        # registry set, not _tasks: captures self-discard on completion,
        # so a flapping SLO can't grow the master's task list forever
        spawn_logged(
            self.incident.capture(verdict, window_s=window),
            log,
            f"incident bundle for {verdict.get('slo')}",
            registry=self._incident_captures,
        )

    def _health_doc(self) -> dict:
        """The /cluster/health.json document — telemetry plane + repair
        + slo blocks; also what every incident bundle embeds."""
        doc = self.telemetry.health()
        doc["repair"] = self.repair.status()
        doc["slo"] = self.slo.status()
        return doc

    async def h_incident_dump(self, request: web.Request) -> web.Response:
        """POST /cluster/incident/dump: operator-triggered incident
        bundle (shell `cluster.incident.dump`) — same fan-out and
        bundle shape as an SLO fire, skipping only the rate limit.
        ?window=S overrides the capture window (default: the slow SLO
        window)."""
        self._redirect_if_follower(request)
        from ..obs import incident as obs_incident

        if not obs_incident.CONFIG.dir:
            return web.json_response(
                {"error": "incident bundling disabled: set -obs.incident.dir"},
                status=503,
            )
        import math

        try:
            window = float(
                request.query.get(
                    "window", self.slo.cfg.slow_window_seconds
                )
            )
        except ValueError:
            window = math.nan
        if not math.isfinite(window) or window <= 0:
            # nan/-5 would silently produce an EMPTY bundle (every
            # since-comparison false) — the operator's manual capture
            # must fail loudly instead of capturing nothing
            return web.json_response(
                {"error": "window must be a positive number of seconds"},
                status=400,
            )
        summary = await self.incident.capture(
            {"slo": "manual", "latency": False},
            window_s=window,
            trigger="manual",
            force=True,
        )
        return web.json_response(summary)

    # ------------------------------------------------------------------ vacuum

    async def _vacuum_loop(self) -> None:
        while True:
            await asyncio.sleep(self.pulse_seconds * 3)
            try:
                await self._vacuum_pass(self.garbage_threshold)
            except Exception:
                log.exception("vacuum pass failed")

    async def _vacuum_pass(self, threshold: float, only_vid: int = 0) -> int:
        """Drive Check → Compact → Commit over gRPC
        (topology_vacuum.go:220-269)."""
        if self.vacuum_disabled:
            return 0
        done = 0
        for _, vl in self.topo.layouts():
            for vid, loc in list(vl.vid2location.items()):
                if only_vid and vid != only_vid:
                    continue
                nodes = list(loc.nodes)
                if not nodes:
                    continue
                ratios = []
                for n in nodes:
                    try:
                        r = await self._volume_stub(n).VacuumVolumeCheck(
                            volume_server_pb2.VacuumVolumeCheckRequest(volume_id=vid)
                        )
                        ratios.append(r.garbage_ratio)
                    except grpc.aio.AioRpcError:
                        ratios.append(0.0)
                if not only_vid and (not ratios or min(ratios) <= threshold):
                    continue
                vl.set_readonly(vid, True)
                try:
                    ok = True
                    for n in nodes:
                        try:
                            async for _ in self._volume_stub(n).VacuumVolumeCompact(
                                volume_server_pb2.VacuumVolumeCompactRequest(volume_id=vid)
                            ):
                                pass
                        except grpc.aio.AioRpcError:
                            ok = False
                    for n in nodes:
                        verb = "VacuumVolumeCommit" if ok else "VacuumVolumeCleanup"
                        try:
                            await getattr(self._volume_stub(n), verb)(
                                getattr(volume_server_pb2, verb + "Request")(volume_id=vid)
                            )
                        except grpc.aio.AioRpcError:
                            pass
                    done += ok
                finally:
                    vl.set_readonly(vid, False)
        return done

    # ------------------------------------------------------------------ HTTP

    async def h_assign(self, request: web.Request) -> web.Response:
        self._redirect_if_follower(request)
        params = {**request.query, **(await request.post() if request.method == "POST" else {})}
        req = master_pb2.AssignRequest(
            count=int(params.get("count", 1)),
            replication=params.get("replication", ""),
            collection=params.get("collection", ""),
            ttl=params.get("ttl", ""),
            data_center=params.get("dataCenter", ""),
            rack=params.get("rack", ""),
            data_node=params.get("dataNode", ""),
            disk_type=params.get("disk", ""),
        )
        resp = await self.Assign(req, None)
        if resp.error:
            return web.json_response({"error": resp.error}, status=404)
        out = {
            "fid": resp.fid,
            "url": resp.location.url,
            "publicUrl": resp.location.public_url,
            "count": resp.count,
        }
        if resp.auth:
            out["auth"] = resp.auth
        return web.json_response(out)

    async def h_lookup(self, request: web.Request) -> web.Response:
        self._redirect_if_follower(request)
        vof = request.query.get("volumeId", "")
        collection = request.query.get("collection", "")
        resp = await self.LookupVolume(
            master_pb2.LookupVolumeRequest(
                volume_or_file_ids=[vof], collection=collection
            ),
            None,
        )
        entry = resp.volume_id_locations[0]
        if entry.error:
            return web.json_response(
                {"volumeOrFileId": vof, "error": entry.error}, status=404
            )
        return web.json_response(
            {
                "volumeOrFileId": vof,
                "locations": [
                    {"url": l.url, "publicUrl": l.public_url} for l in entry.locations
                ],
            }
        )

    async def h_ui(self, request: web.Request) -> web.Response:
        """Operator status page (reference master_server_handlers_ui.go +
        master_ui/master.html); browsers get HTML, everyone else the
        /dir/status JSON."""
        from . import ui

        if not ui.wants_html(request):
            return await self.h_dir_status(request)
        cluster = {
            "IsLeader": self.is_leader,
            "Leader": server_address.http_address(self.leader_advertise),
            "Peers": self.peers,
            "MaxVolumeId": self.topo.max_volume_id,
        }
        return web.Response(
            text=ui.render_master(cluster, self.topo.to_info()),
            content_type="text/html",
        )

    async def h_dir_status(self, request: web.Request) -> web.Response:
        self._redirect_if_follower(request)
        return web.json_response(
            {"Topology": self.topo.to_info(), "Version": "seaweedfs-tpu"}
        )

    async def h_cluster_status(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "IsLeader": self.is_leader,
                "Leader": server_address.http_address(self.leader_advertise),
                "Peers": self.peers,
                "MaxVolumeId": self.topo.max_volume_id,
            }
        )

    async def h_cluster_health(self, request: web.Request) -> web.Response:
        """Aggregated cluster health from heartbeat telemetry: per-node
        freshness/staleness, HBM budget/used/headroom, dispatcher state,
        the EC residency map, and merged per-stage p50/p99 estimates.
        Telemetry lands on the leader (volume servers heartbeat to it
        alone), so followers redirect like every control-plane handler."""
        self._redirect_if_follower(request)
        # telemetry plane + the repair plane's live view + the SLO
        # engine's verdicts, one document (_health_doc — the incident
        # bundler embeds the same)
        return web.json_response(self._health_doc())

    async def h_debug_timeline(self, request: web.Request) -> web.Response:
        """GET /debug/timeline[?window=S]: the clock-aligned cluster
        flight timeline assembled from heartbeat-shipped node samples.
        Lands on the leader with the rest of the telemetry plane."""
        self._redirect_if_follower(request)
        window = request.query.get("window")
        try:
            window_s = float(window) if window else None
        except ValueError:
            return web.json_response(
                {"error": f"bad window: {window!r}"}, status=400
            )
        return web.json_response(self.telemetry.timeline(window_s=window_s))

    async def h_debug_tail(self, request: web.Request) -> web.Response:
        """GET /debug/tail: the master's own tail ring (route stats +
        pinned slow/incident span trees; ?id= resolves one full tree).
        Per-node by design — cluster.tail fans out over every node's
        endpoint, like the incident bundler does for /debug/traces."""
        from .. import obs

        if self.tailstore is None:
            return web.json_response(
                {"error": "tail retention disabled (-obs.tail.disable)"},
                status=404,
            )
        return await obs.tail_handler(self.tailstore)(request)

    async def h_grow(self, request: web.Request) -> web.Response:
        self._redirect_if_follower(request)
        params = request.query
        try:
            option = self._grow_option(
                params.get("collection", ""),
                params.get("replication", ""),
                params.get("ttl", ""),
                params.get("dataCenter", ""),
            )
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        count = int(params.get("count", 0))
        vids = await self._grow_now(option, count)
        if not vids:
            return web.json_response({"error": "growth failed"}, status=500)
        return web.json_response({"count": len(vids), "vids": vids})

    async def h_vacuum(self, request: web.Request) -> web.Response:
        self._redirect_if_follower(request)
        threshold = float(
            request.query.get("garbageThreshold", self.garbage_threshold)
        )
        n = await self._vacuum_pass(threshold)
        return web.json_response({"vacuumed": n})

    async def h_col_delete(self, request: web.Request) -> web.Response:
        self._redirect_if_follower(request)
        name = request.query.get("collection", "")
        await self.CollectionDelete(
            master_pb2.CollectionDeleteRequest(name=name), None
        )
        return web.json_response({"deleted": name})

    async def h_submit(self, request: web.Request) -> web.Response:
        self._redirect_if_follower(request)
        """One-shot upload: assign + proxy the body to the volume server
        (master_server_handlers.go submit)."""
        from ..operation.upload import upload_multipart_body

        params = request.query
        resp = await self.Assign(
            master_pb2.AssignRequest(
                count=1,
                replication=params.get("replication", ""),
                collection=params.get("collection", ""),
                ttl=params.get("ttl", ""),
            ),
            None,
        )
        if resp.error:
            return web.json_response({"error": resp.error}, status=500)
        body = await request.read()
        result = await upload_multipart_body(
            f"http://{resp.location.url}/{resp.fid}",
            body,
            content_type=request.content_type,
            jwt=resp.auth,
        )
        result["fid"] = resp.fid
        result["fileUrl"] = f"{resp.location.public_url}/{resp.fid}"
        return web.json_response(result)
