"""Read-only master follower.

Reference: weed/command/master_follower.go — a lookup-only master that
does NOT join raft: it mirrors the leader's volume locations over the
KeepConnected stream (wdclient vidMap) and serves /dir/lookup (HTTP) and
LookupVolume (gRPC) locally, offloading read traffic from the leader.
Assign and every other control-plane verb proxy to the real leader.
"""
from __future__ import annotations

import asyncio
import logging

import grpc
from aiohttp import web

from ..pb import Stub, channel, generic_handler, master_pb2, server_address
from ..security import tls as tls_mod
from ..pb.rpc import GRPC_OPTIONS
from ..wdclient import MasterClient

log = logging.getLogger("master-follower")


class MasterFollowerServer:
    def __init__(
        self,
        masters: list[str],
        ip: str = "127.0.0.1",
        port: int = 9334,
        grpc_port: int = 0,
    ):
        self.masters = masters
        self.ip = ip
        self.port = port
        self.grpc_port = grpc_port or (port + 10000 if port else 0)
        self.master_client = MasterClient(
            masters, client_type="master_follower",
            client_address=f"{ip}:{port}",
        )
        self._grpc_server: grpc.aio.Server | None = None
        self._http_runner: web.AppRunner | None = None

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    @property
    def advertise_url(self) -> str:
        return f"{self.ip}:{self.port}.{self.grpc_port}"

    async def start(self) -> None:
        self._grpc_server = grpc.aio.server(options=GRPC_OPTIONS)
        self._grpc_server.add_generic_rpc_handlers(
            [generic_handler(master_pb2, "Seaweed", self)]
        )
        self.grpc_port = tls_mod.add_port(
            self._grpc_server, f"{self.ip}:{self.grpc_port}"
        )
        await self._grpc_server.start()

        app = web.Application()
        app.router.add_get("/dir/lookup", self.h_lookup)
        app.router.add_get("/cluster/status", self.h_cluster_status)
        self._http_runner = web.AppRunner(app)
        await self._http_runner.setup()
        site = web.TCPSite(self._http_runner, self.ip, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]

        await self.master_client.start()
        log.info(
            "master follower on %s (following %s)", self.url, self.masters
        )

    async def stop(self) -> None:
        await self.master_client.stop()
        if self._grpc_server:
            await self._grpc_server.stop(grace=0.5)
        if self._http_runner:
            await self._http_runner.cleanup()

    # ---------------------------------------------------------------- reads

    def _lookup(self, vof: str):
        vid = int(str(vof).split(",")[0])
        return self.master_client.vid_map.lookup(vid)

    async def LookupVolume(self, request, context):
        resp = master_pb2.LookupVolumeResponse()
        for vof in request.volume_or_file_ids:
            entry = resp.volume_id_locations.add()
            entry.volume_or_file_id = str(vof)
            try:
                locs = self._lookup(vof)
            except ValueError:
                entry.error = f"invalid volume id {vof!r}"
                continue
            if not locs:
                entry.error = f"volume {vof} not found"
                continue
            for l in locs:
                entry.locations.add(url=l.url, public_url=l.public_url or l.url)
        return resp

    async def h_lookup(self, request: web.Request) -> web.Response:
        vof = request.query.get("volumeId", "")
        try:
            locs = self._lookup(vof)
        except ValueError:
            locs = []
        if not locs:
            return web.json_response(
                {"volumeOrFileId": vof, "error": "not found"}, status=404
            )
        return web.json_response(
            {
                "volumeOrFileId": vof,
                "locations": [
                    {"url": l.url, "publicUrl": l.public_url or l.url}
                    for l in locs
                ],
            }
        )

    async def h_cluster_status(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "IsLeader": False,
                "Leader": self.master_client.current_master,
                "Peers": self.masters,
            }
        )

    # ------------------------------------------------- control-plane proxy

    def _leader_stub(self) -> Stub:
        return Stub(
            channel(
                server_address.grpc_address(self.master_client.current_master)
            ),
            master_pb2,
            "Seaweed",
        )

    async def Assign(self, request, context):
        return await self._leader_stub().Assign(request)

    async def Statistics(self, request, context):
        return await self._leader_stub().Statistics(request)

    async def VolumeList(self, request, context):
        return await self._leader_stub().VolumeList(request)

    async def ListClusterNodes(self, request, context):
        return await self._leader_stub().ListClusterNodes(request)
