"""Server status pages: the operator-facing HTML the reference renders
from weed/server/master_ui/ + volume_server_ui/ + filer_ui/ templates
(master_server_handlers_ui.go:1-35 etc.).  Plain tables, no assets, no
JS — `curl -H 'Accept: text/html'` or a browser both read it.
"""
from __future__ import annotations

import html
import time
import urllib.parse

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{title}</title><style>
body {{ font-family: sans-serif; margin: 2em; color: #222; }}
h1 {{ font-size: 1.4em; }} h2 {{ font-size: 1.1em; margin-top: 1.5em; }}
table {{ border-collapse: collapse; margin: 0.5em 0; }}
th, td {{ border: 1px solid #bbb; padding: 0.25em 0.7em; text-align: left; }}
th {{ background: #eee; }}
.muted {{ color: #777; font-size: 0.9em; }}
</style></head><body>
<h1>{title}</h1>
<p class="muted">seaweedfs-tpu &middot; rendered {now}</p>
{body}
</body></html>"""


def _esc(v) -> str:
    return html.escape(str(v))


def _table(headers: list[str], rows: list[list]) -> str:
    head = "".join(f"<th>{_esc(h)}</th>" for h in headers)
    body = "".join(
        "<tr>" + "".join(f"<td>{_esc(c)}</td>" for c in row) + "</tr>"
        for row in rows
    )
    return f"<table><tr>{head}</tr>{body}</table>"


def _page(title: str, body: str) -> str:
    return _PAGE.format(
        title=_esc(title), now=time.strftime("%Y-%m-%d %H:%M:%S"), body=body
    )


def wants_html(request) -> bool:
    return "text/html" in request.headers.get("Accept", "")


def render_master(cluster: dict, topo_info: dict) -> str:
    """Cluster status + the topology tree with per-node volume layouts."""
    body = ["<h2>Cluster</h2>"]
    body.append(
        _table(
            ["leader", "this node is leader", "peers", "max volume id"],
            [[
                cluster.get("Leader", ""),
                cluster.get("IsLeader", False),
                ", ".join(cluster.get("Peers", []) or []) or "-",
                cluster.get("MaxVolumeId", 0),
            ]],
        )
    )
    body.append("<h2>Topology</h2>")
    rows = []
    for dc in topo_info.get("data_centers", []):
        for rack in dc.get("racks", []):
            for node in rack.get("nodes", []):
                vols = node.get("volumes", [])
                ec = node.get("ec_shards", [])
                size = sum(v.get("size", 0) for v in vols)
                rows.append([
                    dc.get("id", ""),
                    rack.get("id", ""),
                    node.get("id", ""),
                    len(vols),
                    sum(v.get("file_count", 0) for v in vols),
                    f"{size / 1e6:.1f} MB",
                    len(ec),
                    node.get("max_volume_counts", ""),
                ])
    body.append(
        _table(
            ["data center", "rack", "node", "volumes", "files", "size",
             "ec shards", "slots"],
            rows,
        )
    )
    vol_rows = []
    for dc in topo_info.get("data_centers", []):
        for rack in dc.get("racks", []):
            for node in rack.get("nodes", []):
                for v in node.get("volumes", []):
                    vol_rows.append([
                        v.get("id", ""),
                        v.get("collection", "") or "-",
                        node.get("id", ""),
                        f"{v.get('size', 0) / 1e6:.1f} MB",
                        v.get("file_count", 0),
                        v.get("delete_count", 0),
                        "ro" if v.get("read_only") else "rw",
                        v.get("replica_placement", 0),
                    ])
    body.append("<h2>Volumes</h2>")
    body.append(
        _table(
            ["id", "collection", "node", "size", "files", "deleted",
             "mode", "replication"],
            sorted(vol_rows, key=lambda r: (r[0], r[2])),
        )
    )
    return _page("seaweedfs-tpu master", "".join(body))


def render_volume(
    url: str, disks: list[dict], volumes: list[dict], ec_shards: list[dict]
) -> str:
    body = ["<h2>Disks</h2>"]
    body.append(
        _table(
            ["directory", "disk type", "max volumes", "volumes", "ec shards"],
            [[
                d.get("dir", ""), d.get("disk_type", ""),
                d.get("max_volume_count", 0), d.get("volumes", 0),
                d.get("ec_shards", 0),
            ] for d in disks],
        )
    )
    body.append("<h2>Volumes</h2>")
    body.append(
        _table(
            ["id", "collection", "size", "files", "deleted",
             "deleted bytes", "mode", "ttl", "version"],
            [[
                v.get("id", ""), v.get("collection", "") or "-",
                f"{v.get('size', 0) / 1e6:.1f} MB", v.get("file_count", 0),
                v.get("delete_count", 0), v.get("deleted_byte_count", 0),
                "ro" if v.get("read_only") else "rw",
                v.get("ttl", 0) or "-", v.get("version", ""),
            ] for v in sorted(volumes, key=lambda v: v.get("id", 0))],
        )
    )
    body.append("<h2>EC shards</h2>")
    body.append(
        _table(
            ["volume", "collection", "shards held", "resident in HBM"],
            [[
                s.get("id", ""), s.get("collection", "") or "-",
                s.get("shard_ids", ""), s.get("resident", "-") or "-",
            ] for s in ec_shards],
        )
    )
    return _page(f"seaweedfs-tpu volume server {url}", "".join(body))


def render_filer_listing(
    path: str, entries: list, limit: int, has_more: bool
) -> str:
    rows = []
    for e in entries:
        name = e.name + ("/" if e.is_directory else "")
        # percent-encode the segment: names with %, ?, # or spaces must
        # not be parsed as URL syntax by the browser
        href = (
            urllib.parse.quote(path.rstrip("/"))
            + "/"
            + urllib.parse.quote(e.name)
        )
        rows.append([
            f'<a href="{_esc(href)}">{_esc(name)}</a>',
            "-" if e.is_directory else e.attr.file_size,
            time.strftime(
                "%Y-%m-%d %H:%M:%S", time.localtime(e.attr.mtime or 0)
            ),
            f"{e.attr.mode & 0o7777:o}",
        ])
    body = [f"<h2>{_esc(path.rstrip('/') or '/')}</h2>"]
    # the name cell is pre-escaped html (an anchor): render raw
    head = "".join(
        f"<th>{h}</th>" for h in ("name", "size", "modified", "mode")
    )
    trs = "".join(
        "<tr><td>" + r[0] + "</td>"
        + "".join(f"<td>{_esc(c)}</td>" for c in r[1:])
        + "</tr>"
        for r in rows
    )
    body.append(f"<table><tr>{head}</tr>{trs}</table>")
    if has_more:
        body.append(
            f'<p class="muted">showing first {limit}; pass ?limit= for more</p>'
        )
    return _page("seaweedfs-tpu filer", "".join(body))
