"""VolumeServer: storage engine host — HTTP data plane + gRPC admin/EC.

Reference: weed/server/volume_server.go (23-53), volume_server_handlers*.go,
volume_grpc_admin.go (351), volume_grpc_vacuum.go (111), volume_grpc_copy.go
(401), volume_grpc_erasure_coding.go (446), volume_grpc_client_to_master.go.

One asyncio process per storage node:
  - aiohttp: GET/HEAD/POST/PUT/DELETE on /vid,fid — reads serve normal
    volumes, EC volumes (with remote-shard + degraded reconstruction
    fallbacks), or redirect to a peer; writes fan out to replicas
    (store_replicate.go:24-120)
  - grpc.aio `VolumeServer` service: volume lifecycle, the 4-step vacuum
    protocol, file copy streams, and all nine EC RPCs (SURVEY.md §2.2)
  - a heartbeat task streaming full + delta state to the master
    (volume_grpc_client_to_master.go:50-92)

Blocking storage/kernel work runs via asyncio.to_thread; the degraded EC
read's remote-shard hook uses synchronous gRPC stubs since it already runs
on a worker thread.
"""
from __future__ import annotations

import asyncio
import json
import logging
import os
import random
import time

import grpc
from aiohttp import web

from ..pb import Stub, generic_handler, master_pb2, volume_server_pb2
from ..pb.rpc import GRPC_OPTIONS, channel
from ..storage import types as t
from ..storage import vacuum as vacuum_mod
from ..storage.disk_location import DiskLocation
from ..storage.ec import (
    TOTAL_SHARDS,
    ec_base_name,
    find_dat_file_size,
    to_ext,
    write_dat_file,
    write_idx_file_from_ec_index,
)
from .. import obs, stats
from ..serving import EcReadDispatcher, ServingConfig
from ..security import verify_volume_write_jwt
from ..security import tls as tls_mod
from ..security import guard as guard_mod
from ..storage.needle import CrcError, Needle
from ..storage.store import Store
from ..utils import faultpolicy
from ..utils.tasks import spawn_logged
from ..storage.volume import CookieMismatch, NotFoundError, Volume, VolumeReadOnly
from .conversions import ec_msg_to_pb, volume_msg_to_pb

log = logging.getLogger("volume")

_EC_LOCATION_TTL = 10.0  # seconds; reference refreshes at 11s (store_ec.go:254)
# per-call bounds for the degraded-read fan-out when no request budget
# is tighter: one shard interval off a healthy peer is milliseconds, so
# these are generous — but FINITE, which is the whole point (r18)
_SHARD_READ_TIMEOUT_S = 10.0
_EC_LOOKUP_TIMEOUT_S = 5.0


class ByteLimiter:
    """Bound total in-flight bytes (the reference's inFlightUploadData /
    inFlightDownloadData cond-var throttles, volume_server.go:23-53).
    Admission is FIFO so an oversize request (> limit, which runs alone)
    can't be starved by a stream of small ones.  limit<=0 disables."""

    def __init__(self, limit_bytes: int, timeout: float = 30.0):
        self.limit = limit_bytes
        self.timeout = timeout
        self.in_flight = 0
        self._cond = asyncio.Condition()
        from collections import deque

        self._queue: deque = deque()

    def __call__(self, n: int) -> "_ByteLease":
        return _ByteLease(self, n)


class _ByteLease:
    def __init__(self, limiter: ByteLimiter, n: int):
        self.limiter = limiter
        self.n = n

    async def __aenter__(self):
        lim = self.limiter
        if lim.limit <= 0:
            return self
        ticket = object()
        async with lim._cond:
            lim._queue.append(ticket)

            def my_turn():
                return lim._queue[0] is ticket and (
                    lim.in_flight + self.n <= lim.limit
                    or lim.in_flight == 0  # oversize requests run alone
                )

            try:
                await asyncio.wait_for(
                    lim._cond.wait_for(my_turn), lim.timeout
                )
            except asyncio.TimeoutError:
                lim._queue.remove(ticket)
                lim._cond.notify_all()
                raise web.HTTPTooManyRequests(
                    text="too many in-flight bytes; retry later"
                )
            lim._queue.popleft()
            lim.in_flight += self.n
            lim._cond.notify_all()  # the next ticket may also fit
        return self

    async def __aexit__(self, *exc):
        lim = self.limiter
        if lim.limit <= 0:
            return
        async with lim._cond:
            lim.in_flight -= self.n
            lim._cond.notify_all()


class VolumeServer:
    def __init__(
        self,
        masters: list[str],
        directories: list[str],
        ip: str = "127.0.0.1",
        port: int = 8080,
        grpc_port: int = 0,
        public_url: str = "",
        max_volume_counts: int | list[int] = 8,
        data_center: str = "",
        rack: str = "",
        pulse_seconds: int = 5,
        ec_backend: str = "auto",
        read_mode: str = "proxy",  # local | proxy | redirect
        jwt_signing_key: str = "",
        tier_backends: dict | None = None,  # storage/backend.py configure()
        index_kind: str = "memory",  # memory | sqlite (ref -index flag)
        client_max_size_mb: int = 256,
        concurrent_upload_limit_mb: int = 0,  # 0 = unlimited
        concurrent_download_limit_mb: int = 0,
        disk_types: list[str] | None = None,  # per-directory (ref -disk flag)
        ec_device_cache_mb: int = 0,  # >0: pin mounted EC shards in HBM
        white_list: list[str] | None = None,  # [access] white_list guard
        fix_jpg_orientation: bool = False,  # ref -images.fix.orientation
        metrics_address: str = "",  # pushgateway host:port (ref -metrics.address)
        metrics_interval_seconds: int = 15,  # ref -metrics.intervalSeconds
        ec_scrub_interval_seconds: int = 0,  # >0: periodic parity scrub
        ec_serving=None,  # serving.ServingConfig | None (-ec.serving.* flags)
        ec_ingest=None,  # ingest.IngestConfig | None (-ec.ingest.* flags)
        ec_scrub_megakernel: bool = True,  # fuse resident scrubs into one
        # device pass per cycle (-ec.scrub.megakernel.disable)
    ):
        self.metrics_address = metrics_address
        self.metrics_interval_seconds = metrics_interval_seconds
        self.ec_scrub_interval_seconds = ec_scrub_interval_seconds
        self.ec_scrub_megakernel = ec_scrub_megakernel
        self.fix_jpg_orientation = fix_jpg_orientation
        self.guard = guard_mod.Guard(white_list)
        if tier_backends:
            from ..storage import backend as backend_mod

            backend_mod.configure(tier_backends)
        # validate the serving config BEFORE the Store exists: the cache
        # must carry the configured layout/pipeline shape from birth —
        # Store.__init__ spawns pin/warm threads for on-disk EC volumes
        # immediately, and a warm racing a late layout assignment would
        # burn its 20-40s/shape budget compiling the wrong ladder
        ec_serving = (ec_serving or ServingConfig()).validated()
        self.ec_serving = ec_serving
        device_cache = None
        if ec_device_cache_mb > 0:
            from ..ops.rs_resident import DeviceShardCache

            device_cache = DeviceShardCache(
                budget_bytes=ec_device_cache_mb << 20,
                layout=ec_serving.layout,
                # pod-scale mesh residency (-ec.serving.mesh.*): lane-
                # shard resident volumes across the local device mesh;
                # None keeps the single-device layout
                mesh_devices=(
                    ec_serving.mesh_devices if ec_serving.mesh else None
                ),
                mesh_min_shard_bytes=ec_serving.mesh_min_shard_mb << 20,
                # multi-controller pod mesh (-ec.mesh.*): residency
                # spans every process's devices; the caller already ran
                # parallel.mesh.initialize_distributed before the first
                # jax touch (command/volume.py)
                global_mesh=ec_serving.multiprocess,
            )
            device_cache.pipeline.set_slots(ec_serving.pipeline_slots)
            # -ec.serving.aot.disable: inline compiles instead of the
            # cold-shape shed (warm() also keys its mode off this)
            device_cache.shed_cold = ec_serving.aot
        if isinstance(max_volume_counts, int):
            max_volume_counts = [max_volume_counts] * len(directories)
        if disk_types is None:
            disk_types = ["hdd"] * len(directories)
        if len(disk_types) != len(directories) or len(max_volume_counts) != len(
            directories
        ):
            raise ValueError(
                "disk_types / max_volume_counts must match directories 1:1"
            )
        self.store = Store(
            [
                DiskLocation(
                    d, max_volume_count=c, disk_type=dt,
                    needle_map_kind=(
                        {"sqlite": "persistent", "native": "native"}.get(
                            index_kind
                        )
                    ),
                )
                for d, c, dt in zip(directories, max_volume_counts, disk_types)
            ],
            ip=ip,
            port=port,
            public_url=public_url,
            ec_backend=ec_backend,
            ec_device_cache=device_cache,
        )
        self.masters = masters
        self.ip = ip
        self.port = port
        self.grpc_port = grpc_port or (port + 10000 if port else 0)
        self.data_center = data_center
        self.rack = rack
        self.pulse_seconds = pulse_seconds
        self.read_mode = read_mode
        self.jwt_signing_key = jwt_signing_key
        self.current_master = masters[0] if masters else ""
        self.client_max_size_mb = client_max_size_mb
        self.upload_limiter = ByteLimiter(concurrent_upload_limit_mb << 20)
        self.download_limiter = ByteLimiter(concurrent_download_limit_mb << 20)
        self._pending_compacts: dict[int, tuple[str, str, int, str | None]] = {}
        self._ec_locations: dict[int, tuple[float, dict[int, list[str]]]] = {}
        # peer grpc addr -> mesh pod id, refreshed with _ec_locations:
        # the hedged gather's pod anti-affinity signal (r20)
        self._ec_location_pods: dict[int, dict[str, str]] = {}
        self.ec_dispatcher = EcReadDispatcher(
            self.store, self._remote_shard_reader, ec_serving
        )
        # heat-tiered residency ladder (serving/tiering.py, -ec.tier.*):
        # only meaningful with a device cache; the dispatcher feeds the
        # heat signal, the QoS controller gates swap churn under
        # overload, and the tier loop below runs the rebalance cycles
        self.tiering = None
        if device_cache is not None and ec_serving.tier:
            from ..serving.tiering import TieringController

            self.tiering = TieringController(self.store, ec_serving)
            self.tiering.attach_qos(self.ec_dispatcher.qos)
            self.ec_dispatcher.tiering = self.tiering
        # streaming ingest plane (ingest/, -ec.ingest.*): QoS write-tier
        # admission + whole-upload deadline doom at the door, per-volume
        # pipelines stream-encoding stripe rows as appends land, group-
        # commit fsync.  Write heat feeds the same HeatTracker the read
        # path feeds, so a freshly written volume enters the tiering
        # ladder already warm.
        from ..ingest import IngestConfig, IngestPlane

        ec_ingest = (ec_ingest or IngestConfig()).validated()
        self.ingest = None
        if ec_ingest.enabled:
            self.ingest = IngestPlane(
                ec_ingest,
                heat=self.tiering.heat if self.tiering is not None else None,
            )
        self.store.ingest = self.ingest
        # stage-digest shipping state: deltas against _stage_snapshot
        # accrue in _digest_backlog until the heartbeat that carried
        # them is ACKED (the master answers every heartbeat in order),
        # so a stream break re-ships instead of silently dropping the
        # lost pulse's observations from the cluster's merged digests
        self._stage_snapshot: dict = {}
        self._digest_backlog: dict = {}  # stage -> [buckets, count, sum_s]
        self._digest_shipped: dict = {}  # the outstanding shipment's content
        self._digest_inflight_at: int | None = None  # its heartbeat seq
        self._hb_sent = 0  # per-stream counters (reset on reconnect)
        self._hb_acked = 0
        # flight-timeline shipping state (obs/timeline.py): samples
        # accrue in the backlog until the heartbeat that carried them is
        # ACKed — same protocol as the stage digests above; reships
        # after a stream break are safe because the master dedupes
        # samples by (node, t)
        self.timeline = None  # TimelineSampler, built in start()
        # tail-forensics retention (obs/tailstore.py), built in start():
        # pins the full span tree of p99-exceeding / incident-flagged
        # requests and feeds SeaweedFS_critpath_seconds per route
        self.tailstore = None
        self._timeline_backlog: list[dict] = []
        self._timeline_shipped = 0  # leading backlog entries in flight
        self._timeline_inflight_at: int | None = None
        self._grpc_server: grpc.aio.Server | None = None
        self._http_runner: web.AppRunner | None = None
        self._tasks: list[asyncio.Task] = []
        self._stopping = False
        # chaos-harness hook (loadgen/chaos.py): True stops the pulse
        # loop from sending WITHOUT breaking the stream — the master
        # sees missed heartbeats and flags the node stale, which is the
        # partition signal the repair scheduler watches (a broken
        # stream would instead unregister the node immediately)
        self.heartbeat_pause = False
        # chaos-harness NETWORK faults on the VolumeEcShardRead servicer
        # (loadgen/chaos.py; r18 tail-tolerance sweep): the gray-failure
        # injectors fast faults can't model.  hang = accept the RPC then
        # never answer; stall_after_chunks = answer N chunks then hang
        # mid-stream; delay_s = fixed added latency before the first
        # byte; fail_pct = probability of an immediate UNAVAILABLE (the
        # flaky-dial model).  Never set outside tests/bench.
        self.fault_shard_read_hang = False
        self.fault_shard_read_stall_after: int | None = None
        self.fault_shard_read_delay_s = 0.0
        self.fault_shard_read_fail_pct = 0.0

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    @property
    def grpc_url(self) -> str:
        return f"{self.ip}:{self.grpc_port}"

    # ------------------------------------------------------------------ lifecycle

    async def start(self, heartbeat: bool = True) -> None:
        self._grpc_server = grpc.aio.server(options=GRPC_OPTIONS)
        self._grpc_server.add_generic_rpc_handlers(
            [generic_handler(volume_server_pb2, "VolumeServer", self)]
        )
        self.grpc_port = tls_mod.add_port(
            self._grpc_server, f"{self.ip}:{self.grpc_port}"
        )
        await self._grpc_server.start()

        app = web.Application(
            client_max_size=self.client_max_size_mb * 1024 * 1024,
            middlewares=(
                [guard_mod.middleware(self.guard)] if self.guard.enabled else []
            ) + [obs.middleware("volume")],
        )
        app.router.add_get("/status", self.h_status)
        app.router.add_get("/metrics", stats.metrics_handler)
        app.router.add_get("/debug/traces", obs.traces_handler)
        # tail-forensics plane: this node's own critical-path view
        # (local ring + tail pins only — cross-node assembly lives on
        # the master) and the tail ring's route stats / pinned trees
        app.router.add_get("/debug/critpath", self.h_debug_critpath)
        app.router.add_get("/debug/tail", self.h_debug_tail)
        # incident plane: this node's flight-recorder ring + trace
        # window (the master's bundle fan-out target) and the live
        # per-shape device dispatch view (volume.device.status -hot)
        app.router.add_get("/debug/incident", obs.incident.incident_handler)
        app.router.add_get("/debug/device/hot", obs.device_hot_handler)
        # this node's flight-timeline ring (obs/timeline.py): the local
        # view of what the master assembles cluster-wide
        app.router.add_get("/debug/timeline", self.h_debug_timeline)
        # this node's device-time ledger: per-workload busy/dispatch/
        # bytes attribution (shell volume.device.attribution)
        app.router.add_get(
            "/debug/device/attribution", self.h_debug_device_attribution
        )
        if os.environ.get("SWFS_DEBUG") == "1":
            # stack dumps reveal internals; opt-in only (the reference
            # gates pprof handlers the same way)
            from ..utils.profiling import debug_stacks_handler

            app.router.add_get("/debug/stacks", debug_stacks_handler)
            # on-demand device profiling (obs/profile.py): wraps
            # jax.profiler around the live serving loop — same opt-in
            # gate as the stack dumps (it reveals internals AND costs
            # device attention)
            app.router.add_get("/debug/profile", obs.profile_handler)
        app[stats.metrics.metrics_collect_key()] = self._collect_metrics
        app.router.add_route("*", "/{fid:.*}", self.h_needle)
        self._http_runner = web.AppRunner(app)
        await self._http_runner.setup()
        site = web.TCPSite(self._http_runner, self.ip, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        self.store.port = self.port
        if self.store.public_url == f"{self.ip}:0":
            self.store.public_url = self.url

        # spawn_logged: a heartbeat/sweep/scrub loop dying early must
        # log AT death with its spawn trace, not sit silent until stop()
        # gathers the corpse (GL111 hardening)
        if heartbeat and self.masters:
            self._tasks.append(
                spawn_logged(self._heartbeat_forever(), log, "heartbeat loop")
            )
        self._tasks.append(
            spawn_logged(self._ttl_sweep_forever(), log, "ttl sweep loop")
        )
        if self.ec_scrub_interval_seconds > 0:
            self._tasks.append(
                spawn_logged(self._ec_scrub_forever(), log, "ec scrub loop")
            )
        if (
            self.tiering is not None
            and self.ec_dispatcher.cfg.tier_interval_seconds > 0
        ):
            self._tasks.append(
                spawn_logged(self._tier_loop_forever(), log, "ec tier loop")
            )
        from ..obs import timeline as timeline_mod
        from ..obs import trace as obs_trace_mod

        if obs_trace_mod.CONFIG.timeline_enabled:
            self.timeline = timeline_mod.TimelineSampler(
                node=self.url
            ).install()
            self._tasks.append(
                spawn_logged(
                    self._timeline_forever(), log, "timeline sampler loop"
                )
            )
        if obs_trace_mod.CONFIG.tail_enabled:
            from ..obs import tailstore as tailstore_mod

            self.tailstore = tailstore_mod.TailStore(node=self.url).install()
        push = stats.start_push_loop(
            "volumeServer", self.url, self.metrics_address,
            self.metrics_interval_seconds, collect=self._collect_metrics,
        )
        if push is not None:
            self._tasks.append(push)
        log.info("volume server up http=%s grpc=%s", self.url, self.grpc_url)

    async def _timeline_forever(self) -> None:
        """~1s flight-timeline sampling (-obs.timeline.intervalSeconds):
        each tick snapshots the ledger/QoS/ingest counters into one
        clock-aligned sample; the heartbeat builder drains the ring's
        new suffix into its ACK-gated backlog."""
        from ..obs import trace as obs_trace_mod

        interval = obs_trace_mod.CONFIG.timeline_interval_seconds
        while not self._stopping:
            await asyncio.sleep(interval)
            try:
                self.timeline.sample()
            except Exception:  # noqa: BLE001 — sampling must never die
                log.exception("timeline sample failed")

    async def h_debug_timeline(self, request: web.Request) -> web.Response:
        window = request.query.get("window")
        samples = (
            self.timeline.snapshot(float(window) if window else None)
            if self.timeline is not None
            else []
        )
        return web.json_response({"node": self.url, "samples": samples})

    async def h_debug_critpath(self, request: web.Request) -> web.Response:
        """GET /debug/critpath?id=: critical-path attribution from THIS
        node's local view (ring + tail pins).  No cluster fan-out here —
        a volume server only ever holds its own hops; the master's
        endpoint stitches the cross-node DAG."""
        return await obs.critpath_handler()(request)

    async def h_debug_tail(self, request: web.Request) -> web.Response:
        """GET /debug/tail: the tail ring's per-route stats + pinned
        slow/incident span trees (?id= resolves one full tree)."""
        if self.tailstore is None:
            return web.json_response(
                {"error": "tail retention disabled (-obs.tail.disable)"},
                status=404,
            )
        return await obs.tail_handler(self.tailstore)(request)

    async def h_debug_device_attribution(
        self, request: web.Request
    ) -> web.Response:
        """GET /debug/device/attribution: the device-time ledger — busy
        seconds / dispatches / bytes / queue-wait per workload class,
        with the per-device breakdown (shell volume.device.attribution)."""
        from ..obs import devledger

        return web.json_response({
            "node": self.url,
            "enabled": devledger.LEDGER.enabled,
            "total_busy_seconds": devledger.LEDGER.total_busy_s(),
            "workloads": devledger.LEDGER.snapshot(),
        })

    async def _ec_scrub_forever(self) -> None:
        """Periodic parity scrub of every locally-complete EC volume
        (-ec.scrub.intervalSeconds): the background repair loop around
        VolumeEcShardsVerify.  Device-resident volumes scrub in HBM at
        ~zero payload cost; file-backed volumes stream through the CPU
        kernel.  Corruption is logged loudly and surfaced as a gauge —
        detection, not auto-repair (ec.rebuild is the repair verb)."""
        from ..storage.ec.layout import TOTAL_SHARDS

        # (location dir, vid) -> last KNOWN verdict.  A scrub that ERRORS
        # keeps the previous verdict: a transiently unreadable volume
        # that was corrupt last cycle must not auto-resolve the alert.
        verdicts: dict[tuple[str, int], bool] = {}

        def _record(key: tuple[str, int], r: dict) -> None:
            # ONE home for the verdict+alert bookkeeping so the
            # megakernel and per-volume branches can never report
            # corruption differently
            bad = sum(r["parity_mismatch_bytes"])
            verdicts[key] = bool(bad)
            if bad:
                log.error(
                    "ec volume %d FAILED parity scrub: %s mismatch "
                    "bytes (backend=%s) — run ec.rebuild",
                    key[1], r["parity_mismatch_bytes"], r["backend"],
                )

        while not self._stopping:
            await asyncio.sleep(self.ec_scrub_interval_seconds)
            seen: set[tuple[str, int]] = set()
            # megakernel pre-pass: every fully resident volume scrubs in
            # ONE fused device pass (block-diagonally stacked parity
            # systems) instead of one dispatch per volume; the loop
            # below consumes its verdicts and only scrubs the rest
            # (file-backed or unpinned copies) individually
            mega: dict = {}
            if self.ec_scrub_megakernel:
                try:
                    mega = await asyncio.to_thread(
                        self.store.scrub_all_resident
                    )
                except Exception:  # noqa: BLE001 — fall back per-volume
                    log.exception("ec scrub megakernel pass failed")
            for loc in self.store.locations:
                # per-location EcVolume objects: a vid mounted in two
                # locations is two independent shard sets, each scrubbed
                for vid, ev in list(loc.ec_volumes.items()):
                    key = (loc.directory, vid)
                    seen.add(key)
                    if len(ev.shards) < TOTAL_SHARDS:
                        # locally incomplete (normal spread placement):
                        # nothing to verify here; don't burn a thread
                        # hop per cycle finding that out
                        verdicts.pop(key, None)
                        continue
                    r = mega.get(vid)
                    if r is not None and r["dir"] == loc.directory:
                        # the fused pass already verified THIS location's
                        # pinned bytes
                        _record(key, r)
                        continue
                    try:
                        r = await asyncio.to_thread(self.store.scrub_ec, ev)
                    except FileNotFoundError:
                        verdicts.pop(key, None)  # shards went away
                        continue
                    except Exception:  # noqa: BLE001 — transient IO /
                        # unmount mid-scrub: keep the last verdict
                        log.exception("ec scrub failed for volume %d", vid)
                        continue
                    _record(key, r)
            for key in list(verdicts):
                if key not in seen:  # unmounted since last cycle
                    del verdicts[key]
            stats.VOLUME_SERVER_SCRUB_CORRUPT_GAUGE.set(
                sum(verdicts.values())
            )

    async def _tier_loop_forever(self) -> None:
        """The residency ladder's rebalance loop
        (-ec.tier.intervalSeconds): each cycle re-ranks volumes by
        decayed read heat and makes at most a couple of ladder moves —
        promotion pins (host-RAM bytes first) + AOT pre-warm, demotion
        through the claim/evict release path, host-tier staging.  The
        blocking pin/stage IO runs on a worker thread so the event loop
        keeps serving."""
        interval = self.ec_dispatcher.cfg.tier_interval_seconds
        while not self._stopping:
            await asyncio.sleep(interval)
            try:
                moves = await asyncio.to_thread(self.tiering.rebalance)
                if moves:
                    log.info(
                        "tier rebalance: %s",
                        " ".join(f"{kind}:{vid}" for kind, vid in moves),
                    )
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — one failed cycle must
                # not end the ladder; the next cycle retries
                log.exception("tier rebalance failed")

    async def _ttl_sweep_forever(self, interval: float = 60.0) -> None:
        while not self._stopping:
            await asyncio.sleep(interval)
            try:
                await asyncio.to_thread(self.sweep_expired_ttl_volumes)
            except Exception:  # noqa: BLE001
                log.exception("ttl sweep failed")

    def sweep_expired_ttl_volumes(self, grace: float = 0.1) -> list[int]:
        """Delete volumes whose TTL fully lapsed since their last write
        (the reference expires whole TTL volumes the same way,
        store_vacuum/volume ttl handling).  Returns deleted vids."""
        deleted = []
        now = time.time()
        for loc in self.store.locations:
            for vid, v in list(loc.volumes.items()):
                ttl_min = v.super_block.ttl.minutes
                if not ttl_min or v.is_tiered:
                    # tiered guard must be is_tiered: keep_local tiering
                    # leaves remote_dat None but still owns a remote copy
                    continue
                try:
                    last_write = os.path.getmtime(v.dat_path)
                except OSError:
                    continue
                if last_write + ttl_min * 60 * (1 + grace) >= now:
                    continue
                # close the write window before deleting: mark readonly
                # (pushed to the master immediately so assigns stop), then
                # re-check — a write that raced the first mtime read keeps
                # the volume for its records' full TTL
                try:
                    self.store.mark_volume_readonly(vid, True)
                except Exception:  # noqa: BLE001 — volume may be mid-delete
                    continue
                if os.path.getmtime(v.dat_path) != last_write:
                    continue
                log.info("ttl volume %d expired; deleting", vid)
                self.store.delete_volume(vid)
                deleted.append(vid)
        return deleted

    async def kill(self) -> None:
        """Abrupt stop — the in-process analogue of SIGKILL for the
        chaos harness (loadgen/chaos.py): the HTTP/gRPC endpoints
        vanish and the heartbeat stream breaks mid-pulse (so the master
        unregisters the node's shards), but the store stays OPEN — a
        SIGKILLed process doesn't get to flush or unmount either, and
        `revive()` must bring the same on-disk state back."""
        self._stopping = True
        for t_ in self._tasks:
            t_.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()
        if self._grpc_server:
            await self._grpc_server.stop(0)
            self._grpc_server = None
        if self._http_runner:
            await self._http_runner.cleanup()
            self._http_runner = None

    async def revive(self) -> None:
        """Restart after `kill()` on the same ports (fids cached by
        clients keep resolving) with the same store."""
        self._stopping = False
        self.heartbeat_pause = False
        await self.start()

    async def stop(self) -> None:
        self._stopping = True
        for t_ in self._tasks:
            t_.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        if self._grpc_server:
            await self._grpc_server.stop(0.1)
        if self._http_runner:
            await self._http_runner.cleanup()
        # zero the occupancy/queue gauges: the registry outlives this
        # server (co-hosted roles, in-process restarts), and a restarted
        # server must not report the dead instance's last occupancy
        # until its first batch
        self.ec_dispatcher.shutdown()
        if self.timeline is not None:
            # unhook the finished-trace tap: the process-global observer
            # list outlives this server (co-hosted roles, test restarts)
            self.timeline.uninstall()
        if self.tailstore is not None:
            self.tailstore.uninstall()
        if self.ingest is not None:
            # joins encode workers + the group-commit flusher
            await asyncio.to_thread(self.ingest.close)
        # off the loop: close() joins pin/warm threads that may sit in a
        # 20-40s jit compile — blocking here would freeze every other
        # coroutine in the process (co-hosted servers, in-flight HTTP)
        await asyncio.to_thread(self.store.close)

    # ------------------------------------------------------------------ heartbeat

    @staticmethod
    def _fold_digest(dst: dict, stage, buckets, count, dsum, sign=1) -> None:
        rec = dst.setdefault(stage, [[0] * len(buckets), 0, 0.0])
        rec[0] = [a + sign * b for a, b in zip(rec[0], buckets)]
        rec[1] += sign * count
        rec[2] += sign * dsum

    def _build_telemetry(self) -> master_pb2.VolumeServerTelemetry:
        """One pulse's telemetry payload: device-cache occupancy, the
        serving dispatcher's live state, and the stage-histogram delta
        since the previous pulse (pb StageDigest — fixed buckets, so the
        master merges without raw samples).

        Digest delivery is ack-gated: each pulse's delta joins the
        backlog, the backlog ships only while no earlier shipment is
        unconfirmed, and a shipment is confirmed (removed from the
        backlog) once its heartbeat's response arrives — responses are
        1:1 and ordered.  A broken stream re-ships the unconfirmed
        backlog on reconnect, so observations survive blips; the rare
        cost is one pulse's digest double-counted when the master
        applied a heartbeat whose response the break ate (and a
        follower's hint response during leader churn can false-ack one
        shipment) — a bounded skew, versus guaranteed loss."""
        tel = master_pb2.VolumeServerTelemetry()
        # wall clock at build time: the master differences it against
        # its own receive time for the per-node skew estimate the
        # tail-forensics assembler reconciles span timestamps with
        tel.wall_clock_unix_ms = int(time.time() * 1e3)
        # pod rank (r20): which member of the multi-controller mesh this
        # node is — cluster.health keys its per-host pod rows on it
        tel.mesh_process_id = self.ec_serving.mesh_process_id
        tel.mesh_process_count = self.ec_serving.mesh_process_count
        cache = self.store.ec_device_cache
        if cache is not None:
            n_resident, n_bytes = cache.stats()
            tel.device_budget_bytes = cache.budget
            tel.device_used_bytes = n_bytes
            tel.device_resident_shards = n_resident
            tel.device_evictions = cache.evictions
            tel.device_pin_claims = cache.pin_claims
            # per-device breakdown (r19 mesh layout): index-ordered so
            # the master can show which chip a lopsided mesh is full on
            tel.device_bytes_per_device.extend(
                d["used_bytes"] for d in cache.device_stats()
            )
            for vid, sids in cache.resident_by_vid().items():
                tel.resident_shards_by_volume[vid] = len(sids)
        g = stats.REGISTRY.get_sample_value
        tel.compile_hits = int(
            g("SeaweedFS_volumeServer_ec_device_compile_total",
              {"result": "hit"}) or 0
        )
        tel.compile_misses = int(
            g("SeaweedFS_volumeServer_ec_device_compile_total",
              {"result": "miss"}) or 0
        )
        # persistent-compile-cache outcome: a node silently recompiling
        # every restart is an operator-visible column, not a lost log
        from ..ops import rs_resident

        tel.compile_cache_enabled = bool(
            rs_resident.compile_cache_status()["enabled"]
        )
        # residency-ladder state (serving/tiering.py): census from the
        # last rebalance + cumulative promotion/demotion counters, so
        # cluster.health can show where each node's working set lives
        # and whether its ladder is thrashing
        if self.tiering is not None:
            tel.tier_hbm_volumes = self.tiering.last_sizes.get("hbm", 0)
            tel.tier_host_volumes = self.tiering.last_sizes.get("host", 0)
            tel.tier_promotions = sum(self.tiering.promotions.values())
            tel.tier_demotions = sum(self.tiering.demotions.values())
            hc = self.tiering.host_cache
            tel.tier_host_bytes = hc.bytes_used if hc is not None else 0
        tel.dispatcher_queue_depth = self.ec_dispatcher.queue_depth
        tel.dispatcher_inflight = self.ec_dispatcher.inflight
        # INTERACTIVE admission breaker state: the master's repair
        # scheduler defers bulk repair traffic while any node reports
        # an open front-door breaker (serving/qos.py Breaker.OPEN)
        from ..serving import qos as qos_mod

        tel.qos_breaker_open = bool(
            self.ec_dispatcher.qos.breaker_state(qos_mod.INTERACTIVE)
            == qos_mod.Breaker.OPEN
        )
        tel.dispatcher_shed = int(
            g("SeaweedFS_volumeServer_ec_batch_fallback_total") or 0
        )
        # error-rate SLO raw counters (obs/slo.py): admitted EC reads
        # (batched+native partitions admissions — the re-route counts
        # like shed_cold_shape ride on top and must not double-count)
        # and total sheds.  With QoS enabled, every coalescer-saturation
        # fallback ALSO lands in qos_shed{queue_budget} via saturated(),
        # so the qos series alone is the complete shed count — adding
        # dispatcher_shed on top would double-count saturation and
        # inflate the error-rate burn; only the -ec.qos.disable config
        # (fixed at construction) leaves the fallback counter as the
        # sole record.
        tel.ec_reads_total = sum(
            int(
                g("SeaweedFS_volumeServer_ec_read_route_total",
                  {"route": r}) or 0
            )
            for r in ("batched", "native")
        )
        qos_sheds = sum(
            int(
                g("SeaweedFS_volumeServer_ec_qos_shed_total",
                  {"tier": t_, "reason": r_}) or 0
            )
            for t_ in ("interactive", "bulk")
            for r_ in ("queue_budget", "deadline", "breaker_open")
        )
        tel.ec_reads_shed_total = (
            qos_sheds if self.ec_dispatcher.cfg.qos
            else tel.dispatcher_shed
        )
        # double-buffered batch pipeline: last window's device-busy /
        # wall ratio + cumulative staged bytes, so cluster.health can
        # show per-node overlap next to queue/occupancy
        tel.overlap_fraction = float(
            g("SeaweedFS_volumeServer_ec_overlap_fraction") or 0.0
        )
        tel.ec_h2d_bytes = int(
            g("SeaweedFS_volumeServer_ec_h2d_bytes_total") or 0
        )
        tel.ec_d2h_bytes = int(
            g("SeaweedFS_volumeServer_ec_d2h_bytes_total") or 0
        )
        # streaming ingest plane (ingest/): write bytes admitted, rows
        # encoded online split device/host, door sheds, group-commit
        # fsyncs, live pipelines, and seals that skipped the offline
        # encode — cluster.health rolls these up next to the read plane
        if self.ingest is not None:
            ing = self.ingest.snapshot()
            tel.ingest_bytes_total = int(
                g("SeaweedFS_volumeServer_ingest_bytes_total") or 0
            )
            tel.ingest_rows_device = int(ing["rows_device"])
            tel.ingest_rows_host = int(ing["rows_host"])
            tel.ingest_shed_total = sum(ing["sheds"].values())
            tel.ingest_fsyncs_total = int(
                g("SeaweedFS_volumeServer_ingest_fsyncs_total") or 0
            )
            tel.ingest_active_pipelines = int(ing["pipelines"])
            tel.ingest_streamed_seals = int(
                g("SeaweedFS_volumeServer_ingest_seals_total",
                  {"path": "streamed"}) or 0
            )
        snap = stats.metrics.stage_histogram_snapshot()
        for stage, buckets, count, dsum in stats.metrics.stage_digest_deltas(
            self._stage_snapshot, snap
        ):
            self._fold_digest(self._digest_backlog, stage, buckets, count, dsum)
        self._stage_snapshot = snap
        if (
            self._digest_inflight_at is not None
            and self._hb_acked >= self._digest_inflight_at
        ):
            # the shipment's heartbeat was answered: the master applied
            # it — retire exactly what was shipped from the backlog
            for stage, (buckets, count, dsum) in self._digest_shipped.items():
                self._fold_digest(
                    self._digest_backlog, stage, buckets, count, dsum, sign=-1
                )
            self._digest_backlog = {
                s: rec for s, rec in self._digest_backlog.items() if rec[1] > 0
            }
            self._digest_shipped = {}
            self._digest_inflight_at = None
        if self._digest_inflight_at is None and self._digest_backlog:
            for stage, (buckets, count, dsum) in sorted(
                self._digest_backlog.items()
            ):
                d = tel.stage_digests.add()
                d.stage = stage
                d.bucket_counts.extend(buckets)
                d.count = count
                d.sum_seconds = dsum
            self._digest_shipped = {
                s: (list(b), c, ds)
                for s, (b, c, ds) in self._digest_backlog.items()
            }
            # pulses() bumps _hb_sent right after this build, so the
            # heartbeat carrying this shipment is number _hb_sent + 1
            self._digest_inflight_at = self._hb_sent + 1
        # flight-timeline samples ride the same ACK gate: fold the
        # ring's new suffix into the backlog, retire the in-flight
        # shipment once its heartbeat is answered, ship the backlog only
        # while nothing is unconfirmed.  The backlog is capped at one
        # ring's worth — a long partition drops the OLDEST unshipped
        # samples (bounded memory; the local /debug/timeline ring still
        # has them until they age out).
        if self.timeline is not None:
            self._timeline_backlog.extend(self.timeline.take_new())
            drop = len(self._timeline_backlog) - self.timeline.capacity
            if drop > 0:
                del self._timeline_backlog[:drop]
                self._timeline_shipped = max(0, self._timeline_shipped - drop)
            if (
                self._timeline_inflight_at is not None
                and self._hb_acked >= self._timeline_inflight_at
            ):
                del self._timeline_backlog[: self._timeline_shipped]
                self._timeline_shipped = 0
                self._timeline_inflight_at = None
            if self._timeline_inflight_at is None and self._timeline_backlog:
                tel.timeline_samples_json.extend(
                    json.dumps(s, separators=(",", ":"))
                    for s in self._timeline_backlog
                )
                self._timeline_shipped = len(self._timeline_backlog)
                self._timeline_inflight_at = self._hb_sent + 1
        return tel

    def _identity_heartbeat(self) -> master_pb2.Heartbeat:
        """Who-am-i header + this pulse's telemetry, no volume state:
        what keeps the master's health plane fresh when nothing about
        the volumes changed between pulses."""
        hb = master_pb2.Heartbeat(
            ip=self.ip, port=self.port,
            public_url=self.store.public_url, grpc_port=self.grpc_port,
            data_center=self.data_center, rack=self.rack,
            # pod membership: the coordinator address IS the pod id —
            # every member of one jax.distributed job shares it, and the
            # master treats it as a rack-like failure domain
            mesh_pod=(
                self.ec_serving.mesh_coordinator
                if self.ec_serving.multiprocess else ""
            ),
        )
        hb.telemetry.CopyFrom(self._build_telemetry())
        return hb

    def _full_heartbeat(self) -> master_pb2.Heartbeat:
        hs = self.store.collect_heartbeat()
        hb = self._identity_heartbeat()
        hb.has_no_volumes = hs.has_no_volumes
        hb.has_no_ec_shards = hs.has_no_ec_shards
        hb.offset_bytes = t.OFFSET_SIZE
        for k, v in hs.max_volume_counts.items():
            hb.max_volume_counts[k] = v
        hb.volumes.extend(volume_msg_to_pb(v) for v in hs.volumes)
        hb.ec_shards.extend(ec_msg_to_pb(e) for e in hs.ec_shards)
        return hb

    def _delta_heartbeat(self) -> master_pb2.Heartbeat | None:
        new_v, del_v, new_ec, del_ec = self.store.drain_deltas()
        if not (new_v or del_v or new_ec or del_ec):
            return None
        hb = self._identity_heartbeat()
        hb.new_volumes.extend(volume_msg_to_pb(v) for v in new_v)
        hb.deleted_volumes.extend(volume_msg_to_pb(v) for v in del_v)
        hb.new_ec_shards.extend(ec_msg_to_pb(e) for e in new_ec)
        hb.deleted_ec_shards.extend(ec_msg_to_pb(e) for e in del_ec)
        return hb

    async def _heartbeat_forever(self) -> None:
        i = 0
        while not self._stopping:
            master = self.masters[i % len(self.masters)]
            i += 1
            try:
                await self._heartbeat_stream(master)
            except asyncio.CancelledError:
                # stop() cancelled us: propagate so the awaited task
                # reads CANCELLED instead of silently "done"
                raise
            except Exception as e:
                log.debug("heartbeat to %s failed: %s", master, e)
            await asyncio.sleep(min(self.pulse_seconds, 1))

    async def _heartbeat_stream(self, master: str) -> None:
        """One connected session: full heartbeat, then deltas + periodic
        re-sync (doHeartbeat volume_grpc_client_to_master.go:92+)."""
        from ..pb import server_address

        stub = Stub(channel(server_address.grpc_address(master)), master_pb2, "Seaweed")

        async def pulses():
            hb = self._full_heartbeat()
            self._hb_sent += 1
            yield hb
            n = 0
            while not self._stopping:
                await asyncio.sleep(
                    0.05 if not self.store.new_volumes.empty()
                    or not self.store.new_ec_shards.empty()
                    else self.pulse_seconds
                )
                while self.heartbeat_pause and not self._stopping:
                    # chaos partition: stay connected, stop pulsing —
                    # the master's staleness window does the rest
                    await asyncio.sleep(0.05)
                hb = self._delta_heartbeat()
                n += 1
                if hb is None:
                    # no state deltas: periodic full re-sync, otherwise a
                    # telemetry-only pulse — the master's health plane
                    # (staleness marking, HBM headroom, stage digests)
                    # needs EVERY pulse, not just state changes
                    hb = (
                        self._full_heartbeat() if n % 4 == 0
                        else self._identity_heartbeat()
                    )
                self._hb_sent += 1
                yield hb

        try:
            # graftlint: allow(unbounded-rpc): the heartbeat stream IS
            # the liveness signal — deliberately unbounded; a wedged
            # master surfaces as a broken stream and a redial
            async for resp in stub.SendHeartbeat(pulses()):
                self._hb_acked += 1
                if resp.volume_size_limit:
                    self.store.volume_size_limit = resp.volume_size_limit
                if resp.leader:
                    self.current_master = resp.leader
        finally:
            # per-stream bookkeeping dies with the stream; an
            # unconfirmed digest shipment stays in the backlog and
            # re-ships on the next connection
            self._hb_sent = 0
            self._hb_acked = 0
            self._digest_shipped = {}
            self._digest_inflight_at = None
            # unconfirmed timeline samples stay in the backlog and
            # re-ship whole on the next connection (master dedupes by t)
            self._timeline_shipped = 0
            self._timeline_inflight_at = None

    # ------------------------------------------------------------------ HTTP data plane

    async def h_status(self, request: web.Request) -> web.Response:
        infos = await asyncio.to_thread(self.store.volume_infos)
        from . import ui

        if ui.wants_html(request):
            # operator page (reference volume_server_ui/ index.html)
            disks = [
                {
                    "dir": loc.directory,
                    "disk_type": loc.disk_type,
                    "max_volume_count": loc.max_volume_count,
                    "volumes": len(loc.volumes),
                    "ec_shards": sum(
                        len(ev.shards) for ev in loc.ec_volumes.values()
                    ),
                }
                for loc in self.store.locations
            ]
            cache = self.store.ec_device_cache
            resident = (
                cache.resident_by_vid() if cache is not None else {}
            )
            ec = [
                {
                    "id": ev.id,
                    "collection": ev.collection,
                    "shard_ids": ",".join(
                        str(s) for s in sorted(ev.shards)
                    ),
                    "resident": ",".join(
                        str(s) for s in resident.get(ev.id, [])
                    ),
                }
                for loc in self.store.locations
                for ev in loc.ec_volumes.values()
            ]
            return web.Response(
                text=ui.render_volume(
                    self.url, disks, [vars(i) for i in infos], ec
                ),
                content_type="text/html",
            )
        return web.json_response(
            {
                "Version": "seaweedfs-tpu",
                "Volumes": [vars(i) for i in infos],
            }
        )

    async def h_needle(self, request: web.Request) -> web.StreamResponse:
        if request.method in ("GET", "HEAD"):
            with stats.time_request(
                stats.VOLUME_SERVER_REQUEST_COUNTER,
                stats.VOLUME_SERVER_REQUEST_HISTOGRAM,
                "get",
            ):
                return await self.h_read(request)
        if request.method in ("POST", "PUT"):
            self._check_write_jwt(request)
            with stats.time_request(
                stats.VOLUME_SERVER_REQUEST_COUNTER,
                stats.VOLUME_SERVER_REQUEST_HISTOGRAM,
                "post",
            ):
                return await self.h_write(request)
        if request.method == "DELETE":
            self._check_write_jwt(request)
            with stats.time_request(
                stats.VOLUME_SERVER_REQUEST_COUNTER,
                stats.VOLUME_SERVER_REQUEST_HISTOGRAM,
                "delete",
            ):
                return await self.h_delete(request)
        raise web.HTTPMethodNotAllowed(request.method, ["GET", "POST", "PUT", "DELETE"])

    def _check_write_jwt(self, request: web.Request) -> None:
        """Reject unauthorized writes/deletes when a signing key is
        configured (volume_server_handlers.go:33-120 write guard)."""
        raw_fid = request.match_info["fid"].strip("/").split(".")[0]
        if not verify_volume_write_jwt(self.jwt_signing_key, request, raw_fid):
            raise web.HTTPUnauthorized(text="missing or invalid write jwt")

    def _collect_metrics(self) -> None:
        """Refresh volume/EC gauges from store state at scrape time
        (reference gauges set on mount/unmount, ec_shard.go:46).  The gauge
        is cleared first so deleted collections drop to absent instead of
        reporting stale counts; with several in-process volume servers
        sharing the registry (LocalCluster), a scrape reflects the server
        that answered it — separate processes (the deployed shape) each
        have their own registry, like the reference."""
        stats.VOLUME_SERVER_VOLUME_GAUGE.clear()
        by_key: dict[tuple[str, str], int] = {}
        for loc in self.store.locations:
            for v in loc.volumes.values():
                key = (v.collection, "volume")
                by_key[key] = by_key.get(key, 0) + 1
            for ev in loc.ec_volumes.values():
                key = (ev.collection, "ec_shards")
                by_key[key] = by_key.get(key, 0) + len(ev.shards)
        for (collection, kind), count in by_key.items():
            stats.VOLUME_SERVER_VOLUME_GAUGE.labels(
                collection=collection, type=kind
            ).set(count)
        cache = self.store.ec_device_cache
        # always set (zero when cache-less): on a shared registry
        # (LocalCluster) a skipped set would leave another server's
        # resident counts standing as if they were this server's
        n_resident, n_bytes = cache.stats() if cache is not None else (0, 0)
        stats.VOLUME_SERVER_RESIDENT_SHARD_GAUGE.set(n_resident)
        stats.VOLUME_SERVER_RESIDENT_BYTES_GAUGE.set(n_bytes)

    def _parse_fid(self, request: web.Request) -> tuple[int, int, int]:
        fid = request.match_info["fid"].strip("/")
        return t.parse_fid(fid)  # raises ValueError

    async def h_read(self, request: web.Request) -> web.StreamResponse:
        """(GetOrHeadHandler volume_server_handlers_read.go:31-235)"""
        try:
            vid, nid, cookie = self._parse_fid(request)
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        v = self.store.find_volume(vid)
        ev = self.store.find_ec_volume(vid) if v is None else None
        if v is None and ev is None:
            return await self._read_remote(request, vid)
        # lease BEFORE the disk read so the throttle bounds memory; the
        # index knows the size up front for normal volumes (EC locates
        # during the read itself — those lease 0 and stay unthrottled)
        read_deleted = request.query.get("readDeleted") == "true"
        size_hint = 0
        if v is not None:
            loc = v.nm.get(nid)
            size_hint = loc[1] if loc else 0
            if loc is None and read_deleted:
                # forensic reads must stay under the memory throttle too
                # (a 16-byte header pread on a rare path)
                size_hint = (
                    await asyncio.to_thread(v.deleted_needle_size, nid) or 0
                )
        serving_cfg = self.ec_dispatcher.cfg
        async with self.download_limiter(size_hint):
            try:
                if v is not None:
                    n = await asyncio.to_thread(
                        self.store.read_needle,
                        vid,
                        nid,
                        cookie,
                        read_deleted,
                        serving_cfg.zero_copy,
                    )
                else:
                    # the serving dispatcher routes per volume: resident
                    # volumes coalesce into pipelined device-resident
                    # reconstruct batches; unpinned/cache-less volumes
                    # (whose concurrent disk reads must not serialize
                    # behind a batch queue) take the native path inside.
                    # QoS tier + origin ride in on headers (the S3
                    # gateway's direct path and the load harness set
                    # them; absent = interactive front-door traffic)
                    n = await self.ec_dispatcher.read(
                        vid, nid, cookie,
                        tier=request.headers.get("X-Seaweed-QoS", ""),
                        origin=request.headers.get(
                            "X-Seaweed-Read-Origin", ""
                        ),
                    )
            except (NotFoundError, KeyError):
                raise web.HTTPNotFound()
            except CookieMismatch:
                raise web.HTTPForbidden()
            except CrcError:
                raise web.HTTPInternalServerError(
                    text="data corruption: CRC mismatch"
                )
            except ValueError:
                # the volume was destroyed under us (TTL sweep / admin
                # delete closed the dat file mid-read)
                raise web.HTTPNotFound(text="volume is gone")
            # TTL'd needles expire at read time even before the volume
            # sweep removes the whole volume (GetOrHeadHandler's ttl check)
            if v is not None:
                ttl_min = v.super_block.ttl.minutes
                if (
                    ttl_min
                    and n.last_modified
                    and n.last_modified + ttl_min * 60 < time.time()
                ):
                    raise web.HTTPNotFound(text="needle expired")
            return await self._respond_needle(request, n)

    async def _respond_needle(
        self, request: web.Request, n: Needle
    ) -> web.StreamResponse:
        headers = {"Etag": f'"{n.etag}"', "Accept-Ranges": "bytes"}
        if n.last_modified:
            from .conditional import format_http_date

            headers["Last-Modified"] = format_http_date(n.last_modified)
        ct = n.mime.decode() if n.mime else "application/octet-stream"
        is_image = ct.startswith("image/")
        resize = is_image and (
            "width" in request.query or "height" in request.query
        )
        crop = is_image and any(
            f"crop_{k}" in request.query for k in ("x1", "y1", "x2", "y2")
        )
        if resize or crop:
            try:
                rw = int(request.query.get("width") or 0)
                rh = int(request.query.get("height") or 0)
                cx1 = int(request.query.get("crop_x1") or 0)
                cy1 = int(request.query.get("crop_y1") or 0)
                cx2 = int(request.query.get("crop_x2") or 0)
                cy2 = int(request.query.get("crop_y2") or 0)
            except ValueError:
                raise web.HTTPBadRequest(
                    text="width/height/crop_* must be integers"
                )
            rmode = request.query.get("mode", "")
            # processed variants must not share the original's cache
            # identity; the crop suffix only appears when cropping so
            # resize-only Etags stay stable across versions
            variant = f"{n.etag}-{rw}x{rh}{rmode}"
            if crop:
                variant += f"-{cx1},{cy1},{cx2},{cy2}"
            headers["Etag"] = f'"{variant}"'
        from .conditional import content_disposition, not_modified

        cd = content_disposition(
            request, n.name.decode("utf-8", "replace") if n.name else ""
        )
        if cd:
            headers["Content-Disposition"] = cd
        if not_modified(request, headers["Etag"], n.last_modified):
            # BEFORE decompress/resize: a 304 exists to skip the body work;
            # keep the validators so caches can refresh their entry
            return web.Response(status=304, headers=headers)
        copied = 0  # response-path bytes COPIED serving this request
        body = n.data  # memoryview on the zero-copy parse, else bytes
        if isinstance(body, bytes) and body:
            # the copying parse already materialized the payload once —
            # that copy is exactly what the zero-copy path removes, so
            # it is what the counter measures
            copied += len(body)
        if n.is_compressed:
            # transforms need pixels: never hand gzip bytes to crop/resize
            # (they would pass through untouched yet carry the variant
            # Etag, poisoning caches with the original under that identity)
            if not (resize or crop) and "gzip" in request.headers.get(
                "Accept-Encoding", ""
            ):
                headers["Content-Encoding"] = "gzip"
            else:
                import gzip as _gz

                body = _gz.decompress(body)
                copied += len(body)
        if crop:
            # reference order: crop first, then resize (volume_server_
            # handlers_read.go shouldCropImages + shouldResizeImages)
            from ..images import cropped

            body = await asyncio.to_thread(
                cropped, bytes(body), cx1, cy1, cx2, cy2
            )
            copied += len(body)
        if resize:
            from ..images import resized

            body = await asyncio.to_thread(resized, bytes(body), rw, rh, rmode)
            copied += len(body)
        if request.method == "HEAD":
            stats.VOLUME_SERVER_RESPONSE_COPY_BYTES.inc(copied)
            return web.Response(
                status=200, headers={**headers, "Content-Length": str(len(body))},
                content_type=ct,
            )
        # range support
        status = 200
        rng = request.http_range
        if rng.start is not None or rng.stop is not None:
            start = rng.start or 0
            if start < 0:  # suffix range "bytes=-N": last N bytes
                start, stop = max(len(body) + start, 0), len(body)
            else:
                stop = min(
                    rng.stop if rng.stop is not None else len(body),
                    len(body),
                )
            if start >= stop:
                # a 206 with an empty body and end<start Content-Range
                # would read as "object ends here" to resuming clients
                raise web.HTTPRequestRangeNotSatisfiable(
                    headers={"Content-Range": f"bytes */{len(body)}"}
                )
            # memoryview slice = zero-copy window; a bytes slice copies
            part = memoryview(body)[start:stop] if isinstance(
                body, memoryview
            ) else body[start:stop]
            if isinstance(part, bytes):
                copied += len(part)
            headers["Content-Range"] = f"bytes {start}-{start + len(part) - 1}/{len(body)}"
            body = part
            status = 206
        stats.VOLUME_SERVER_RESPONSE_COPY_BYTES.inc(copied)
        return await self._send_body(request, status, body, headers, ct)

    # streamed-write chunk; also the threshold below which a body rides
    # web.Response (a small body sits in the socket buffer regardless of
    # how slowly the client drains — nothing worth bounding)
    _STREAM_CHUNK = 64 * 1024

    async def _send_body(
        self,
        request: web.Request,
        status: int,
        body,
        headers: dict,
        ct: str,
    ) -> web.StreamResponse:
        """Write a read response body.  Large bodies stream in chunks
        (memoryview windows — no further copies) under a per-response
        stall budget scaled by size, the way r06 bounded mount reads: a
        dribbling client that can't drain within the budget is
        disconnected (counted in response_stall_aborts_total) instead of
        holding the download byte-lease and the needle buffers open."""
        cfg = self.ec_dispatcher.cfg
        budget = cfg.stall_budget_for(len(body))
        if len(body) <= self._STREAM_CHUNK or budget <= 0:
            return web.Response(
                status=status, body=body, headers=headers, content_type=ct
            )
        resp = web.StreamResponse(
            status=status,
            headers={**headers, "Content-Length": str(len(body))},
        )
        resp.content_type = ct
        loop = asyncio.get_running_loop()
        deadline = loop.time() + budget
        mv = memoryview(body)
        try:
            await resp.prepare(request)
            for off in range(0, len(mv), self._STREAM_CHUNK):
                remaining = deadline - loop.time()
                if remaining <= 0:
                    raise asyncio.TimeoutError
                # write() returns once the chunk is buffered; it only
                # awaits when the transport is above its high-water mark
                # — i.e. exactly when the client is the bottleneck
                await asyncio.wait_for(
                    resp.write(mv[off : off + self._STREAM_CHUNK]),
                    timeout=remaining,
                )
            await resp.write_eof()
        except ConnectionResetError:
            # the client went away on its own (churn, cancel): not a
            # stall — nothing to abort, nothing to count as dribbling
            log.debug("client disconnected mid-response")
        except asyncio.TimeoutError:
            stats.VOLUME_SERVER_RESPONSE_STALL_ABORTS.inc()
            # flight recorder: the abort decision, trace-stamped — an
            # incident bundle joins "this client got cut off" with the
            # request trace that was dribbling
            obs.incident.record(
                "stall_abort", bytes=len(mv), budget_s=round(budget, 1)
            )
            log.warning(
                "read response stalled past its %.1fs budget "
                "(%d bytes); disconnecting slow client", budget, len(mv),
            )
            if request.transport is not None:
                # abort, not close: close() flushes the transport's
                # buffered backlog first, which a dribbling client would
                # keep draining for minutes — the budget's whole point
                # is to stop paying for this socket NOW
                request.transport.abort()
        return resp

    async def _read_remote(self, request: web.Request, vid: int) -> web.StreamResponse:
        """Volume not local: proxy to or redirect at a peer holding it
        (volume_server_handlers_read.go:65-120)."""
        locations = await self._lookup_volume_locations(vid)
        locations = [u for u in locations if u != self.url]
        if not locations:
            raise web.HTTPNotFound(text=f"volume {vid} not found anywhere")
        target = locations[0]
        if self.read_mode == "redirect":
            raise web.HTTPMovedPermanently(
                f"http://{target}{request.path_qs}"
            )
        import aiohttp

        # forward the read-semantics headers (conditionals, Range) and hand
        # the peer's validators back, so proxied reads revalidate exactly
        # like local ones
        fwd = {
            k: request.headers[k]
            for k in (
                "Range",
                "If-None-Match",
                "If-Modified-Since",
                "Accept-Encoding",
            )
            if k in request.headers
        }
        # the peer records its own spans under the same trace id
        fwd.update(obs.outbound_headers())
        # auto_decompress=False: the relay must pass the holder's bytes
        # VERBATIM — transparent gunzip would serve decompressed data
        # still labeled Content-Encoding: gzip
        async with aiohttp.ClientSession(auto_decompress=False) as s:
            async with s.get(
                f"http://{target}{request.path_qs}", headers=fwd
            ) as r:
                body = await r.read()
                back = {
                    k: r.headers[k]
                    for k in (
                        "Etag",
                        "Last-Modified",
                        "Accept-Ranges",
                        "Content-Range",
                        "Content-Encoding",
                        "Content-Disposition",
                    )
                    if k in r.headers
                }
                return web.Response(
                    status=r.status, body=body, headers=back,
                    content_type=r.content_type or "application/octet-stream",
                )

    async def _lookup_volume_locations(self, vid: int) -> list[str]:
        if not self.masters:
            return []
        from ..pb import server_address

        stub = Stub(
            channel(server_address.grpc_address(self.current_master)),
            master_pb2,
            "Seaweed",
        )
        try:
            resp = await stub.LookupVolume(
                master_pb2.LookupVolumeRequest(volume_or_file_ids=[str(vid)]),
                timeout=10.0,  # master metadata round-trip (GL114)
            )
        except grpc.aio.AioRpcError:
            return []
        out = []
        for e in resp.volume_id_locations:
            out.extend(l.url for l in e.locations)
        return out

    async def h_write(self, request: web.Request) -> web.Response:
        """(PostHandler volume_server_handlers_write.go) — parse upload,
        append locally, fan out to replicas unless this IS a replica write.

        The ingest plane's front door: the write rides one deadline
        budget end to end (r18 request_scope), and admission happens
        BEFORE any body byte is buffered — a QoS write-tier shed or a
        doomed upload (content_length at the floor rate overruns the
        remaining budget) is refused at the door instead of discovered
        at fsync."""
        try:
            vid, nid, cookie = self._parse_fid(request)
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        if not self.store.has_volume(vid):
            raise web.HTTPNotFound(text=f"volume {vid} not local")
        tier = request.headers.get("X-Seaweed-QoS", "")
        # The doom projection only binds against a deadline the CLIENT
        # propagated: the server-stamped default budget is a backstop
        # for in-flight work, not a contract the uploader agreed to —
        # dooming an undeadlined large body against it would refuse
        # uploads the client is happy to wait for.
        client_ms = faultpolicy.parse_deadline_ms(
            request.headers.get(faultpolicy.DEADLINE_HEADER, "")
        )
        with faultpolicy.request_scope(request.headers):
            if self.ingest is None:
                return await self._h_write_admitted(request, vid, nid, cookie, tier)
            shed = self.ingest.admit(
                tier,
                request.content_length or 0,
                faultpolicy.remaining_s() if client_ms is not None else None,
            )
            if shed == "deadline":
                raise web.HTTPGatewayTimeout(
                    text="upload cannot finish within its deadline budget"
                )
            if shed is not None:
                err = web.HTTPTooManyRequests(
                    text=f"write admission shed ({shed})"
                )
                err.headers["Retry-After"] = "1"
                raise err
            t0 = time.monotonic()
            try:
                return await self._h_write_admitted(
                    request, vid, nid, cookie, tier
                )
            finally:
                self.ingest.complete(tier, time.monotonic() - t0)

    async def _h_write_admitted(
        self, request: web.Request, vid: int, nid: int, cookie: int, tier: str
    ) -> web.Response:
        # lease BEFORE buffering the body, or the throttle bounds nothing;
        # chunked uploads (no Content-Length) pass a 0 lease
        async with self.upload_limiter(request.content_length or 0):
            body = await request.read()
            name, mime, data, compressed = self._parse_upload(
                request.headers.get("Content-Type", ""), body
            )
            if (
                self.fix_jpg_orientation
                and not compressed
                and (
                    mime == b"image/jpeg"
                    or (name or b"").lower().endswith((b".jpg", b".jpeg"))
                )
            ):
                # turn pixels upright at ingest (reference needle.go:104
                # images.FixJpgOrientation, behind -images.fix.orientation)
                from ..images.orientation import fix_orientation

                data = await asyncio.to_thread(fix_orientation, data)
            from ..storage.needle import FLAG_IS_COMPRESSED

            n = Needle(
                id=nid,
                cookie=cookie,
                data=data,
                name=name,
                mime=mime,
                last_modified=int(time.time()),
                flags=FLAG_IS_COMPRESSED if compressed else 0,
            )
            is_replicate = request.query.get("type") == "replicate"
            v = self.store.find_volume(vid)
            existed = v is not None and v.has(nid)
            try:
                size = await asyncio.to_thread(self.store.write_needle, vid, n)
            except VolumeReadOnly:
                raise web.HTTPConflict(text=f"volume {vid} is read-only")
            if self.ingest is not None and v is not None:
                # post-append hook on the worker thread: write heat,
                # stage newly completed stripe rows (the arena wait is
                # the plane's backpressure, landing on THIS writer), and
                # park on the group commit when durability is on
                await asyncio.to_thread(self.ingest.on_write, v, size, tier)
            if not is_replicate:
                err, acked = await self._replicate(
                    request, vid, body_override=body
                )
                if err:
                    # un-commit so replicas can't diverge silently
                    # (store_replicate.go deletes on fan-out failure):
                    # tombstone the fresh needle locally AND on peers that
                    # acked — but only for CREATES; rolling back an
                    # overwrite would destroy the prior durable version,
                    # so overwrite divergence is left to fix.replication
                    if not existed:
                        try:
                            await asyncio.to_thread(
                                self.store.delete_needle, vid, nid, cookie
                            )
                        except Exception:  # noqa: BLE001 — best effort
                            log.exception("rollback of %d,%x failed", vid, nid)
                        await self._rollback_acked(request, acked)
                    raise web.HTTPInternalServerError(
                        text=f"replication failed: {err}"
                    )
        return web.json_response({"name": name.decode() or "", "size": size, "eTag": n.etag})

    @staticmethod
    def _parse_upload(
        content_type: str, body: bytes
    ) -> tuple[bytes, bytes, bytes, bool]:
        """multipart/form-data or raw body -> (filename, mime, data,
        is_gzipped) (needle_parse_upload.go).  Parses from the cached raw
        bytes so the identical body can be re-posted to replicas."""
        if content_type.startswith("multipart/"):
            import email
            import email.policy

            msg = email.message_from_bytes(
                b"Content-Type: " + content_type.encode() + b"\r\n\r\n" + body,
                policy=email.policy.HTTP,
            )
            for part in msg.iter_parts():
                data = part.get_payload(decode=True) or b""
                fname = (part.get_filename() or "").encode()
                pmime = (part.get_content_type() or "").encode()
                if part.get("Content-Type") is None or pmime == b"application/octet-stream":
                    pmime = b""
                gz = part.get("Content-Encoding") == "gzip"
                return fname, pmime, data, gz
            return b"", b"", b"", False
        ct = content_type.split(";")[0].strip()
        mime = ct.encode() if ct and ct != "application/octet-stream" else b""
        return b"", mime, body, False

    async def _replicate(
        self, request: web.Request, vid: int, body_override
    ) -> tuple[str | None, list[str]]:
        """Fan the original request out to every replica
        (DistributedOperation store_replicate.go:60).  Returns
        (error_summary_or_None, peers_that_acked)."""
        v = self.store.find_volume(vid)
        if v is None or v.super_block.replica_placement.copy_count <= 1:
            return None, []
        locations = await self._lookup_volume_locations(vid)
        peers = [u for u in locations if u != self.url]
        if not peers:
            return "no replica locations known", []
        import aiohttp

        body = body_override if body_override is not None else await request.read()
        sep = "&" if request.query_string else ""
        qs = f"?{request.query_string}{sep}type=replicate"
        errors = []
        acked: list[str] = []

        headers = {"Content-Type": request.headers.get("Content-Type", "")}
        if request.headers.get("Authorization"):
            # replicas validate the same master-issued write jwt
            headers["Authorization"] = request.headers["Authorization"]

        async def one(peer):
            try:
                async with aiohttp.ClientSession() as s:
                    async with s.request(
                        request.method,
                        f"http://{peer}{request.path}{qs}",
                        data=body,
                        headers=headers,
                    ) as r:
                        if r.status >= 300:
                            errors.append(f"{peer}: HTTP {r.status}")
                        else:
                            acked.append(peer)
            except Exception as e:
                errors.append(f"{peer}: {e}")

        await asyncio.gather(*(one(p) for p in peers))
        return ("; ".join(errors) if errors else None), acked

    async def _rollback_acked(
        self, request: web.Request, acked: list[str]
    ) -> None:
        """Best-effort delete of the fresh needle on replicas that took
        the failed fan-out's write."""
        if not acked:
            return
        import aiohttp

        headers = {}
        if request.headers.get("Authorization"):
            headers["Authorization"] = request.headers["Authorization"]
        async with aiohttp.ClientSession() as s:
            for peer in acked:
                try:
                    await s.delete(
                        f"http://{peer}{request.path}?type=replicate",
                        headers=headers,
                    )
                except Exception:  # noqa: BLE001
                    log.warning("rollback delete on %s failed", peer)

    async def h_delete(self, request: web.Request) -> web.Response:
        try:
            vid, nid, cookie = self._parse_fid(request)
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        is_replicate = request.query.get("type") == "replicate"
        v = self.store.find_volume(vid)
        if v is None:
            ev = self.store.find_ec_volume(vid)
            if ev is None:
                raise web.HTTPNotFound()
            await asyncio.to_thread(self.store.delete_ec_needle, vid, nid)
            return web.json_response({"size": 0})
        try:
            size = await asyncio.to_thread(self.store.delete_needle, vid, nid, cookie)
        except CookieMismatch:
            raise web.HTTPForbidden()
        if not is_replicate:
            await self._replicate(request, vid, body_override=b"")
        return web.json_response({"size": size})

    # ------------------------------------------------------------------ EC remote reads

    def _remote_shard_reader(self, vid: int):
        """Sync hook: shard_id, offset, size -> bytes|None, fetching from a
        peer found via master LookupEcVolume (store_ec.go:238-337).  Both the
        location lookup and the shard fetch happen lazily INSIDE the hook,
        which runs on a to_thread worker — sync gRPC on the event-loop
        thread would deadlock against our own servers.  Every fetch
        carries a hard per-call timeout (the remaining deadline budget,
        capped at _SHARD_READ_TIMEOUT_S): a peer that accepts the RPC
        and never answers — the gray failure bench_netchaos_sweep
        injects — frees this worker thread at the timeout instead of
        pinning it forever.  `read.peer_of` exposes the shard's primary
        holder so the hedged gather can key its latency EWMAs per peer."""

        def read(shard_id: int, offset: int, size: int):
            try:
                timeout = faultpolicy.rpc_timeout_s(
                    _SHARD_READ_TIMEOUT_S, what="remote_shard_read"
                )
            except faultpolicy.DeadlineExceeded:
                return None  # doomed: the gather's verdict tells the truth
            locations = self._cached_ec_locations(vid)
            for addr in locations.get(shard_id, []):
                try:
                    # cached per-address channel: the survivor gather
                    # hits up to 10 peers per degraded read, and a
                    # fresh dial per shard was the p99 cliff the chaos
                    # sweep measured (channels are thread-safe; never
                    # closed here)
                    from ..pb.rpc import sync_channel_cached

                    ch = sync_channel_cached(addr)
                    stub = Stub(ch, volume_server_pb2, "VolumeServer")
                    chunks = []
                    for resp in stub.VolumeEcShardRead(
                        volume_server_pb2.VolumeEcShardReadRequest(
                            volume_id=vid, shard_id=shard_id, offset=offset, size=size
                        ),
                        timeout=timeout,
                    ):
                        if resp.is_deleted:
                            return None
                        chunks.append(resp.data)
                    return b"".join(chunks)
                except grpc.RpcError:
                    continue
            return None

        def peer_of(shard_id: int):
            return next(
                iter(self._cached_ec_locations(vid).get(shard_id, ())), None
            )

        def pod_of(shard_id: int):
            # the primary holder's mesh pod ("" = not in a pod): the
            # hedged gather prefers spares OUTSIDE a slow peer's pod —
            # pod members serve one SPMD mesh and stall together, so a
            # same-pod hedge buys nothing (r20)
            peer = peer_of(shard_id)
            if peer is None:
                return ""
            return self._ec_location_pods.get(vid, {}).get(peer, "")

        read.peer_of = peer_of
        read.pod_of = pod_of
        return read

    def _cached_ec_locations(self, vid: int) -> dict[int, list[str]]:
        now = time.time()
        cached = self._ec_locations.get(vid)
        if cached and now - cached[0] < _EC_LOCATION_TTL:
            return cached[1]
        locs: dict[int, list[str]] = {}
        if self.masters:
            from ..pb import server_address

            try:
                from ..pb.rpc import sync_channel_cached

                ch = sync_channel_cached(
                    server_address.grpc_address(self.current_master)
                )
                stub = Stub(ch, master_pb2, "Seaweed")
                # FIXED timeout, not the ambient budget: this refresh
                # fills a process-level cache serving MANY requests, so
                # it must not ride (or be refused by) whichever dying
                # request happened to trigger it
                resp = stub.LookupEcVolume(
                    master_pb2.LookupEcVolumeRequest(volume_id=vid),
                    timeout=_EC_LOOKUP_TIMEOUT_S,
                )
                pods: dict[str, str] = {}
                for e in resp.shard_id_locations:
                    addrs = []
                    for l in e.locations:
                        if l.url == self.url:
                            continue
                        addr = f"{l.url.rsplit(':', 1)[0]}:{l.grpc_port}"
                        addrs.append(addr)
                        if l.mesh_pod:
                            pods[addr] = l.mesh_pod
                    locs[e.shard_id] = addrs
                self._ec_location_pods[vid] = pods
            except grpc.RpcError:
                # unreachable master: keep serving the STALE snapshot
                # rather than poisoning the cache with an empty map for
                # a full TTL (no remote candidates = every degraded
                # read fails for 2s — the netchaos sweep caught this).
                # Re-stamp the timestamp so a down master costs ONE
                # blocking lookup per TTL, not one per call.
                if cached:
                    self._ec_locations[vid] = (now, cached[1])
                    return cached[1]
        self._ec_locations[vid] = (now, locs)
        return locs

    # ------------------------------------------------------------------ gRPC: lifecycle

    async def AllocateVolume(self, request, context):
        await asyncio.to_thread(
            self.store.add_volume,
            request.volume_id,
            request.collection,
            request.replication or "000",
            request.ttl or "",
            3,
            request.disk_type or "",
        )
        return volume_server_pb2.AllocateVolumeResponse()

    async def VolumeMount(self, request, context):
        await asyncio.to_thread(self.store.mount_volume, request.volume_id)
        return volume_server_pb2.VolumeMountResponse()

    async def VolumeUnmount(self, request, context):
        await asyncio.to_thread(self.store.unmount_volume, request.volume_id)
        return volume_server_pb2.VolumeUnmountResponse()

    async def VolumeDelete(self, request, context):
        try:
            await asyncio.to_thread(self.store.delete_volume, request.volume_id)
        except NotFoundError:
            pass
        return volume_server_pb2.VolumeDeleteResponse()

    async def VolumeMarkReadonly(self, request, context):
        self.store.mark_volume_readonly(request.volume_id, True)
        return volume_server_pb2.VolumeMarkReadonlyResponse()

    async def VolumeMarkWritable(self, request, context):
        self.store.mark_volume_readonly(request.volume_id, False)
        return volume_server_pb2.VolumeMarkWritableResponse()

    async def VolumeConfigure(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            return volume_server_pb2.VolumeConfigureResponse(error="not found")
        try:
            await asyncio.to_thread(
                v.update_replica_placement,
                t.ReplicaPlacement.parse(request.replication),
            )
        except (ValueError, VolumeReadOnly) as e:
            return volume_server_pb2.VolumeConfigureResponse(error=str(e))
        return volume_server_pb2.VolumeConfigureResponse()

    async def VolumeStatus(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            await context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        info = v.info()
        return volume_server_pb2.VolumeStatusResponse(
            is_read_only=v.read_only,
            volume_size=info.size,
            file_count=info.file_count,
            file_deleted_count=info.delete_count,
            compact_revision=v.super_block.compaction_revision,
            version=v.version,
            ttl=str(v.super_block.ttl),
            replication=str(v.super_block.replica_placement),
        )

    async def DeleteCollection(self, request, context):
        for loc in self.store.locations:
            for vid, v in list(loc.volumes.items()):
                if v.collection == request.collection:
                    await asyncio.to_thread(self.store.delete_volume, vid)
        return volume_server_pb2.DeleteCollectionResponse()

    async def VolumeServerStatus(self, request, context):
        return volume_server_pb2.VolumeServerStatusResponse(
            data_dirs=[l.directory for l in self.store.locations],
            volume_count=sum(len(l.volumes) for l in self.store.locations),
            ec_shard_count=sum(
                ev.shard_bits().count()
                for l in self.store.locations
                for ev in l.ec_volumes.values()
            ),
        )

    async def VolumeServerLeave(self, request, context):
        self._stopping = True
        for t_ in self._tasks:
            t_.cancel()
        return volume_server_pb2.VolumeServerLeaveResponse()

    # ------------------------------------------------------------------ gRPC: vacuum

    async def VacuumVolumeCheck(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            await context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        return volume_server_pb2.VacuumVolumeCheckResponse(
            # tiered volumes must not be vacuum candidates: compaction
            # would clash with the remote .dat the .vif records
            garbage_ratio=0.0 if v.is_tiered else v.garbage_ratio
        )

    async def VacuumVolumeCompact(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            await context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        cpd, cpx, snap, shadow = await asyncio.to_thread(vacuum_mod.compact, v)
        self._pending_compacts[request.volume_id] = (cpd, cpx, snap, shadow)
        yield volume_server_pb2.VacuumVolumeCompactResponse(
            processed_bytes=os.path.getsize(cpd)
        )

    async def VacuumVolumeCommit(self, request, context):
        v = self.store.find_volume(request.volume_id)
        pending = self._pending_compacts.pop(request.volume_id, None)
        if v is None or pending is None:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION, "no pending compact")
        await asyncio.to_thread(vacuum_mod.commit, v, *pending)
        return volume_server_pb2.VacuumVolumeCommitResponse(is_read_only=v.read_only)

    async def VacuumVolumeCleanup(self, request, context):
        pending = self._pending_compacts.pop(request.volume_id, None)
        if pending:
            cpd, cpx, _, shadow = pending
            for p in (cpd, cpx, shadow):
                if p and os.path.exists(p):
                    os.remove(p)
        return volume_server_pb2.VacuumVolumeCleanupResponse()

    # ------------------------------------------------------------------ gRPC: tail sync

    async def VolumeTierMoveDatToRemote(self, request, context):
        """Upload the .dat to a backend, keep serving reads from it
        (volume_grpc_tier.go)."""
        try:
            size = await asyncio.to_thread(
                self.store.tier_move_to_remote,
                request.volume_id,
                request.destination_backend_name,
                request.keep_local_dat_file,
            )
        except (NotFoundError, ValueError, KeyError, OSError) as e:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        yield volume_server_pb2.VolumeTierMoveDatToRemoteResponse(
            processed=size, processedPercentage=100.0
        )

    async def VolumeTierMoveDatFromRemote(self, request, context):
        try:
            size = await asyncio.to_thread(
                self.store.tier_move_from_remote,
                request.volume_id,
                request.keep_remote_dat_file,
            )
        except (NotFoundError, ValueError, KeyError, OSError) as e:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        yield volume_server_pb2.VolumeTierMoveDatFromRemoteResponse(
            processed=size, processedPercentage=100.0
        )

    async def VolumeTailSender(self, request, context):
        """Stream records appended after since_ns; with a nonzero idle
        timeout, drain that many idle seconds then end the stream
        (volume_grpc_tail.go VolumeTailSender)."""
        v = self.store.find_volume(request.volume_id)
        if v is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND, f"volume {request.volume_id} not found"
            )
        chunk_limit = 256 * 1024
        since_ns = request.since_ns
        draining = request.idle_timeout_seconds
        # position once by timestamp, then follow appends by byte offset —
        # a cursor that advances even for v1/v2 records (no timestamps)
        # and never re-reads the index per poll
        pos = await asyncio.to_thread(v.find_offset_since, since_ns)
        while True:
            advanced = False
            for offset, hdr, rest, n in v.scan_records(pos):
                pos = offset + len(hdr) + len(rest)
                advanced = True
                if 0 < n.append_at_ns <= since_ns:
                    continue  # initial positioning backs up one record
                for i in range(0, max(len(rest), 1), chunk_limit):
                    part = rest[i : i + chunk_limit]
                    yield volume_server_pb2.VolumeTailSenderResponse(
                        needle_header=hdr,
                        needle_body=part,
                        is_last_chunk=i + chunk_limit >= len(rest),
                    )
            if not advanced:
                # no new data: keepalive + drain countdown
                yield volume_server_pb2.VolumeTailSenderResponse(is_last_chunk=True)
                if request.idle_timeout_seconds > 0:
                    draining -= 1
                    if draining <= 0:
                        return
            else:
                draining = request.idle_timeout_seconds
            await asyncio.sleep(1)

    async def VolumeTailReceiver(self, request, context):
        """Pull another server's appends into the local volume — how a new
        or stale replica catches up (volume_grpc_tail.go
        VolumeTailReceiver)."""
        from ..operation.tail_volume import tail_volume_from_source

        v = self.store.find_volume(request.volume_id)
        if v is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND, f"volume {request.volume_id} not found"
            )

        async def write(n):
            await asyncio.to_thread(self.store.write_needle, request.volume_id, n)

        await tail_volume_from_source(
            request.source_volume_server,
            request.volume_id,
            request.since_ns,
            int(request.idle_timeout_seconds),
            write,
            version=v.version,
        )
        return volume_server_pb2.VolumeTailReceiverResponse()

    # ------------------------------------------------------------------ gRPC: copy

    async def CopyFile(self, request, context):
        """Stream a volume/EC file to a puller (volume_grpc_copy.go
        CopyFile)."""
        v = self.store.find_volume(request.volume_id)
        if v is not None:
            base = Volume.base_name(v.dir, v.id, v.collection)
        else:
            base = await asyncio.to_thread(
                self.store._ec_base, request.volume_id, request.collection
            )
            if base is None:
                if request.ignore_source_file_not_found:
                    return
                await context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        path = base + request.ext
        if not os.path.exists(path):
            if request.ignore_source_file_not_found:
                return
            await context.abort(grpc.StatusCode.NOT_FOUND, f"{path} not found")
        stop = request.stop_offset or os.path.getsize(path)
        chunk = 1024 * 1024
        # open + the 1MB reads go through to_thread: a multi-GB shard
        # copy must not stall the event loop (heartbeats, EC reads)
        # between its disk reads
        from ..utils.aiofile import open_in_thread

        async with open_in_thread(path, "rb") as f:
            sent = 0
            while sent < stop:
                buf = await asyncio.to_thread(f.read, min(chunk, stop - sent))
                if not buf:
                    break
                sent += len(buf)
                yield volume_server_pb2.CopyFileResponse(file_content=buf)

    async def _pull_file(self, source_grpc: str, vid: int, collection: str, ext: str,
                         dest_path: str, ignore_missing: bool = False) -> bool:
        stub = Stub(channel(source_grpc), volume_server_pb2, "VolumeServer")
        tmp = dest_path + ".tmp"
        got_any = False
        from ..utils.aiofile import open_in_thread

        try:
            async with open_in_thread(tmp, "wb") as f:
                async for resp in stub.CopyFile(
                    volume_server_pb2.CopyFileRequest(
                        volume_id=vid,
                        collection=collection,
                        ext=ext,
                        ignore_source_file_not_found=ignore_missing,
                    ),
                    # whole-shard pulls ship tens of MB: heavy but
                    # FINITE, so a hung source frees the copier (GL114)
                    timeout=600.0,
                ):
                    got_any = True
                    await asyncio.to_thread(f.write, resp.file_content)
        except grpc.aio.AioRpcError:
            if os.path.exists(tmp):
                os.remove(tmp)
            if ignore_missing:
                return False
            raise
        if got_any or not ignore_missing:
            os.replace(tmp, dest_path)
            return True
        os.remove(tmp)
        return False

    async def VolumeCopy(self, request, context):
        """Pull .dat/.idx of a volume from a peer and mount it
        (volume_grpc_copy.go VolumeCopy).  `disk_type` pins the copy onto a
        matching DiskLocation (volume.tier.move's hdd→ssd path)."""
        loc = self.store._pick_location(request.disk_type or "")
        if loc is None:
            await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, "no free slots")
        base = Volume.base_name(loc.directory, request.volume_id, request.collection)
        n = 0
        for ext in (".dat", ".idx"):
            await self._pull_file(
                request.source_data_node, request.volume_id, request.collection,
                ext, base + ext,
            )
            n += os.path.getsize(base + ext)
        await asyncio.to_thread(self.store.mount_volume, request.volume_id)
        yield volume_server_pb2.VolumeCopyResponse(processed_bytes=n)

    async def ReadNeedleBlob(self, request, context):
        try:
            n = await asyncio.to_thread(
                self.store.read_needle, request.volume_id, request.needle_id
            )
        except (NotFoundError, KeyError):
            await context.abort(grpc.StatusCode.NOT_FOUND, "needle not found")
        return volume_server_pb2.ReadNeedleBlobResponse(
            needle_blob=n.data, cookie=n.cookie,
            last_modified=n.last_modified,
        )

    async def WriteNeedleBlob(self, request, context):
        """Append one needle to a local replica — volume.check.disk's sync
        path (reference volume_grpc_read_write.go WriteNeedleBlob)."""
        n = Needle(
            id=request.needle_id,
            cookie=request.cookie,
            data=request.needle_blob,
            last_modified=request.last_modified or int(time.time()),
        )
        try:
            await asyncio.to_thread(self.store.write_needle, request.volume_id, n)
        except NotFoundError:
            await context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        except VolumeReadOnly as e:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        return volume_server_pb2.WriteNeedleBlobResponse()

    # ------------------------------------------------------------------ gRPC: erasure coding

    async def VolumeEcShardsGenerate(self, request, context):
        """volume_grpc_erasure_coding.go:38-81 — the TPU encode entry."""
        try:
            await asyncio.to_thread(self.store.ec_generate, request.volume_id)
        except NotFoundError:
            await context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        return volume_server_pb2.VolumeEcShardsGenerateResponse()

    async def VolumeEcShardsRebuild(self, request, context):
        try:
            rebuilt = await asyncio.to_thread(
                self.store.ec_rebuild, request.volume_id, request.collection,
                request.fsync,
            )
        except (NotFoundError, ValueError) as e:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        return volume_server_pb2.VolumeEcShardsRebuildResponse(
            rebuilt_shard_ids=rebuilt
        )

    async def VolumeEcShardsCopy(self, request, context):
        """Pull shard files (+ sidecars) from source_data_node
        (volume_grpc_erasure_coding.go:126-177)."""
        loc = self.store._pick_location()
        if loc is None:
            await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, "no free slots")
        base = ec_base_name(loc.directory, request.volume_id, request.collection)
        for sid in request.shard_ids:
            await self._pull_file(
                request.source_data_node, request.volume_id, request.collection,
                to_ext(sid), base + to_ext(sid),
            )
        if request.copy_ecx_file:
            await self._pull_file(
                request.source_data_node, request.volume_id, request.collection,
                ".ecx", base + ".ecx",
            )
        if request.copy_ecj_file:
            await self._pull_file(
                request.source_data_node, request.volume_id, request.collection,
                ".ecj", base + ".ecj", ignore_missing=True,
            )
        if request.copy_vif_file:
            await self._pull_file(
                request.source_data_node, request.volume_id, request.collection,
                ".vif", base + ".vif", ignore_missing=True,
            )
        return volume_server_pb2.VolumeEcShardsCopyResponse()

    async def VolumeEcShardsDelete(self, request, context):
        await asyncio.to_thread(
            self.store.delete_ec_shards,
            request.volume_id,
            list(request.shard_ids),
            request.collection,
        )
        return volume_server_pb2.VolumeEcShardsDeleteResponse()

    async def VolumeEcShardsMount(self, request, context):
        try:
            await asyncio.to_thread(
                self.store.mount_ec_shards,
                request.volume_id,
                list(request.shard_ids),
                request.collection,
            )
        except NotFoundError as e:
            await context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        return volume_server_pb2.VolumeEcShardsMountResponse()

    async def VolumeEcShardsUnmount(self, request, context):
        await asyncio.to_thread(
            self.store.unmount_ec_shards, request.volume_id, list(request.shard_ids)
        )
        return volume_server_pb2.VolumeEcShardsUnmountResponse()

    async def VolumeEcShardRead(self, request, context):
        """Stream raw shard bytes (volume_grpc_erasure_coding.go:309-375)."""
        # chaos network faults (loadgen/chaos.py): the gray failures the
        # r18 fault-policy layer exists to survive — callers must carry
        # per-call timeouts (graftlint GL114) and hedge around us
        if self.fault_shard_read_fail_pct > 0 and (
            random.random() < self.fault_shard_read_fail_pct
        ):
            await context.abort(
                grpc.StatusCode.UNAVAILABLE, "chaos: flaky dial"
            )
        if self.fault_shard_read_delay_s > 0:
            await asyncio.sleep(self.fault_shard_read_delay_s)
        if self.fault_shard_read_hang:
            await asyncio.Event().wait()  # hold until the caller times out
        ev = self.store.find_ec_volume(request.volume_id)
        if ev is None or request.shard_id not in ev.shards:
            await context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"ec volume {request.volume_id} shard {request.shard_id} not here",
            )
        if request.file_key:
            from ..storage.ec.volume import NeedleNotFound, search_sorted_index

            try:
                _, _, size = await asyncio.to_thread(
                    search_sorted_index, ev._ecx.fileno(), ev.ecx_size, request.file_key
                )
                if t.size_is_deleted(size):
                    yield volume_server_pb2.VolumeEcShardReadResponse(is_deleted=True)
                    return
            except NeedleNotFound:
                pass
        remaining = request.size
        offset = request.offset
        chunk = 1024 * 1024
        sent_chunks = 0
        while remaining > 0:
            stall_after = self.fault_shard_read_stall_after
            if stall_after is not None and sent_chunks >= stall_after:
                # chaos: mid-stream stall — bytes stop flowing but the
                # stream stays open (the half-answered gray failure)
                await asyncio.Event().wait()
            buf = await asyncio.to_thread(
                self.store.read_ec_shard_interval,
                request.volume_id,
                request.shard_id,
                offset,
                min(chunk, remaining),
            )
            if not buf:
                break
            yield volume_server_pb2.VolumeEcShardReadResponse(data=buf)
            sent_chunks += 1
            offset += len(buf)
            remaining -= len(buf)

    async def VolumeEcBlobDelete(self, request, context):
        try:
            await asyncio.to_thread(
                self.store.delete_ec_needle, request.volume_id, request.file_key
            )
        except NotFoundError:
            pass
        return volume_server_pb2.VolumeEcBlobDeleteResponse()

    async def VolumeEcShardsVerify(self, request, context):
        """Parity scrub of a mounted EC volume (device-resident when the
        shard cache holds the whole volume, else the CPU kernel over the
        shard files) — the repair-loop verify pass as a first-class RPC.

        `all_resident=True` ignores volume_id and scrubs EVERY fully
        device-resident volume on this node in one fused megakernel pass
        (per-volume parity systems stacked block-diagonally — a handful
        of device dispatches for the whole cache); the per-volume
        verdicts come back in `volumes`."""
        if getattr(request, "all_resident", False):
            results = await asyncio.to_thread(self.store.scrub_all_resident)
            # per-volume seconds are span-apportioned slices of the one
            # shared pass, so their sum IS the pass wall
            wall = sum(r["seconds"] for r in results.values())
            return volume_server_pb2.VolumeEcShardsVerifyResponse(
                backend="device_megakernel",
                seconds=wall,
                volumes=[
                    volume_server_pb2.EcVolumeScrubResult(
                        volume_id=vid,
                        parity_mismatch_bytes=r["parity_mismatch_bytes"],
                        backend=r["backend"],
                        bytes_verified=r["bytes_verified"],
                        seconds=r["seconds"],
                    )
                    for vid, r in sorted(results.items())
                ],
            )
        try:
            result = await asyncio.to_thread(
                self.store.scrub_ec_volume, request.volume_id
            )
        except NotFoundError as e:
            await context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except FileNotFoundError as e:
            # degraded volume (missing shard files) and not fully
            # resident: scrub needs all 14 inputs — tell the caller
            # cleanly instead of an UNKNOWN traceback
            await context.abort(
                grpc.StatusCode.FAILED_PRECONDITION, str(e)
            )
        return volume_server_pb2.VolumeEcShardsVerifyResponse(
            parity_mismatch_bytes=result["parity_mismatch_bytes"],
            backend=result["backend"],
            seconds=result["seconds"],
            bytes_verified=result["bytes_verified"],
        )

    async def VolumeEcShardsToVolume(self, request, context):
        """Decode EC shards back into a normal .dat/.idx volume
        (volume_grpc_erasure_coding.go:407-446)."""
        ev = self.store.find_ec_volume(request.volume_id)
        if ev is None:
            await context.abort(grpc.StatusCode.NOT_FOUND, "ec volume not found")
        base = ev.base_name

        def decode():
            dat_size = find_dat_file_size(base)
            write_dat_file(base, dat_size)
            write_idx_file_from_ec_index(base)

        await asyncio.to_thread(decode)
        await asyncio.to_thread(self.store.mount_volume, request.volume_id)
        return volume_server_pb2.VolumeEcShardsToVolumeResponse()
