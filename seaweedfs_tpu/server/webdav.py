"""WebDAV server over the filer (RFC 4918 class 1 + 2).

Reference: weed/server/webdav_server.go wraps golang.org/x/net/webdav
with a filer-backed FileSystem (Mkdir/OpenFile/RemoveAll/Rename/Stat at
webdav_server.go:161-386); there is no such protocol library here, so
this module speaks the WebDAV HTTP methods directly and maps them onto
the same filer surface: metadata over the filer's gRPC API, file bytes
through the filer's HTTP data plane (reusing auto-chunking and streaming
range reads, like the S3 gateway does).

Supported: OPTIONS, PROPFIND (depth 0/1/infinity), PROPPATCH (no-op
207), MKCOL, GET, HEAD, PUT, DELETE, COPY, MOVE, LOCK/UNLOCK (in-memory
lock table — enough for Windows/macOS clients that demand class 2).
"""
from __future__ import annotations

import logging
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from datetime import datetime, timezone

import aiohttp
import grpc
from aiohttp import web

from ..pb import Stub, filer_pb2
from ..pb.rpc import channel

log = logging.getLogger("webdav")

DAV_NS = "DAV:"


def _dav(tag: str) -> str:
    return f"{{{DAV_NS}}}{tag}"


def _http_date(ts: int) -> str:
    return datetime.fromtimestamp(ts or 0, tz=timezone.utc).strftime(
        "%a, %d %b %Y %H:%M:%S GMT"
    )


def _iso_date(ts: int) -> str:
    return datetime.fromtimestamp(ts or 0, tz=timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ"
    )


class WebDavServer:
    def __init__(
        self,
        filer_address: str,  # host:port (HTTP); gRPC = +10000 or explicit
        filer_grpc_address: str = "",
        ip: str = "127.0.0.1",
        port: int = 7333,
        root: str = "/",
    ):
        self.filer_address = filer_address
        host, _, p = filer_address.partition(":")
        self.filer_grpc_address = filer_grpc_address or f"{host}:{int(p) + 10000}"
        self.ip = ip
        self.port = port
        self.root = root.rstrip("/") or ""
        self._runner: web.AppRunner | None = None
        self._session: aiohttp.ClientSession | None = None
        self._stub_cache = None
        self._locks: dict[str, str] = {}  # path -> lock token

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    def _stub(self):
        if self._stub_cache is None:
            self._stub_cache = Stub(
                channel(self.filer_grpc_address), filer_pb2, "SeaweedFiler"
            )
        return self._stub_cache

    async def start(self) -> None:
        self._session = aiohttp.ClientSession()
        app = web.Application(client_max_size=1024 * 1024 * 1024)
        app.router.add_route("*", "/{tail:.*}", self._dispatch)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.ip, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        log.info("webdav listening on %s", self.port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()
        if self._session:
            await self._session.close()

    # ------------------------------------------------------------- routing

    def _path(self, request: web.Request) -> str:
        p = urllib.parse.unquote(request.path)
        p = "/" + p.strip("/")
        return self.root + ("" if p == "/" else p) or "/"

    async def _dispatch(self, request: web.Request) -> web.StreamResponse:
        handler = getattr(self, f"h_{request.method.lower()}", None)
        if handler is None:
            return web.Response(status=405, headers={"Allow": self._allow()})
        try:
            return await handler(request)
        except grpc.aio.AioRpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                return web.Response(status=404)
            log.exception("webdav %s %s", request.method, request.path)
            return web.Response(status=500, text=str(e))

    @staticmethod
    def _allow() -> str:
        return (
            "OPTIONS, GET, HEAD, PUT, DELETE, PROPFIND, PROPPATCH, MKCOL, "
            "COPY, MOVE, LOCK, UNLOCK"
        )

    # ------------------------------------------------------------ metadata

    async def _lookup(self, path: str) -> filer_pb2.Entry | None:
        if path == "/":
            e = filer_pb2.Entry(name="/", is_directory=True)
            return e
        d, _, name = path.rpartition("/")
        try:
            resp = await self._stub().LookupDirectoryEntry(
                filer_pb2.LookupDirectoryEntryRequest(
                    directory=d or "/", name=name
                )
            )
        except grpc.aio.AioRpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                return None
            raise
        return resp.entry if resp.HasField("entry") else None

    async def _list(self, directory: str) -> list[filer_pb2.Entry]:
        from ..filer.client import list_all_entries

        return await list_all_entries(self._stub(), directory)

    # ------------------------------------------------------------- methods

    async def h_options(self, request: web.Request) -> web.Response:
        return web.Response(
            status=200,
            headers={
                "DAV": "1, 2",
                "Allow": self._allow(),
                "MS-Author-Via": "DAV",
            },
        )

    async def h_propfind(self, request: web.Request) -> web.Response:
        path = self._path(request)
        entry = await self._lookup(path)
        if entry is None:
            return web.Response(status=404)
        depth = request.headers.get("Depth", "infinity")
        ms = ET.Element(_dav("multistatus"))
        self._prop_response(ms, path, entry)
        if entry.is_directory and depth != "0":
            await self._propfind_children(
                ms, path, recursive=(depth == "infinity")
            )
        body = ET.tostring(ms, encoding="utf-8", xml_declaration=True)
        return web.Response(
            status=207, body=body, content_type="application/xml"
        )

    async def _propfind_children(
        self, ms: ET.Element, path: str, recursive: bool
    ) -> None:
        for child in await self._list(path if path != "/" else "/"):
            child_path = (path.rstrip("/") or "") + "/" + child.name
            self._prop_response(ms, child_path, child)
            if recursive and child.is_directory:
                await self._propfind_children(ms, child_path, recursive=True)

    def _prop_response(
        self, ms: ET.Element, path: str, entry: filer_pb2.Entry
    ) -> None:
        rel = path[len(self.root):] if self.root and path.startswith(self.root) else path
        href = urllib.parse.quote(rel or "/")
        if entry.is_directory and not href.endswith("/"):
            href += "/"
        resp = ET.SubElement(ms, _dav("response"))
        ET.SubElement(resp, _dav("href")).text = href
        stat = ET.SubElement(resp, _dav("propstat"))
        prop = ET.SubElement(stat, _dav("prop"))
        ET.SubElement(prop, _dav("displayname")).text = (
            entry.name if entry.name != "/" else ""
        )
        rtype = ET.SubElement(prop, _dav("resourcetype"))
        attrs = entry.attributes
        if entry.is_directory:
            ET.SubElement(rtype, _dav("collection"))
        else:
            size = attrs.file_size or sum(
                c.size for c in entry.chunks
            ) or len(entry.content)
            ET.SubElement(prop, _dav("getcontentlength")).text = str(size)
            ET.SubElement(prop, _dav("getcontenttype")).text = (
                attrs.mime or "application/octet-stream"
            )
            ET.SubElement(prop, _dav("getetag")).text = f'"{attrs.mtime:x}-{size:x}"'
        ET.SubElement(prop, _dav("getlastmodified")).text = _http_date(attrs.mtime)
        ET.SubElement(prop, _dav("creationdate")).text = _iso_date(
            attrs.crtime or attrs.mtime
        )
        sl = ET.SubElement(prop, _dav("supportedlock"))
        le = ET.SubElement(sl, _dav("lockentry"))
        ET.SubElement(ET.SubElement(le, _dav("lockscope")), _dav("exclusive"))
        ET.SubElement(ET.SubElement(le, _dav("locktype")), _dav("write"))
        ET.SubElement(stat, _dav("status")).text = "HTTP/1.1 200 OK"

    async def h_proppatch(self, request: web.Request) -> web.Response:
        path = self._path(request)
        if await self._lookup(path) is None:
            return web.Response(status=404)
        # accept-and-ignore (dead properties aren't stored; the reference's
        # x/net/webdav handler does the same for unsupported live props)
        ms = ET.Element(_dav("multistatus"))
        resp = ET.SubElement(ms, _dav("response"))
        ET.SubElement(resp, _dav("href")).text = urllib.parse.quote(request.path)
        stat = ET.SubElement(resp, _dav("propstat"))
        ET.SubElement(stat, _dav("prop"))
        ET.SubElement(stat, _dav("status")).text = "HTTP/1.1 200 OK"
        body = ET.tostring(ms, encoding="utf-8", xml_declaration=True)
        return web.Response(status=207, body=body, content_type="application/xml")

    async def h_mkcol(self, request: web.Request) -> web.Response:
        path = self._path(request)
        if await self._lookup(path) is not None:
            return web.Response(status=405)
        d, _, name = path.rpartition("/")
        parent = await self._lookup(d or "/")
        if parent is None or not parent.is_directory:
            return web.Response(status=409)
        import time

        now = int(time.time())
        await self._stub().CreateEntry(
            filer_pb2.CreateEntryRequest(
                directory=d or "/",
                entry=filer_pb2.Entry(
                    name=name,
                    is_directory=True,
                    attributes=filer_pb2.FuseAttributes(
                        file_mode=0o770 | 0x80000000, mtime=now, crtime=now
                    ),
                ),
            )
        )
        return web.Response(status=201)

    # data plane: proxy through the filer's HTTP handlers so chunking,
    # range reads, and manifest resolution live in one place
    async def h_get(self, request: web.Request) -> web.StreamResponse:
        return await self._proxy_read(request, "GET")

    async def h_head(self, request: web.Request) -> web.StreamResponse:
        return await self._proxy_read(request, "HEAD")

    async def _proxy_read(
        self, request: web.Request, method: str
    ) -> web.StreamResponse:
        path = self._path(request)
        entry = await self._lookup(path)
        if entry is None:
            return web.Response(status=404)
        if entry.is_directory:
            return web.Response(status=405)
        headers = {}
        if "Range" in request.headers:
            headers["Range"] = request.headers["Range"]
        async with self._session.request(
            method,
            f"http://{self.filer_address}{urllib.parse.quote(path)}",
            headers=headers,
        ) as upstream:
            resp = web.StreamResponse(status=upstream.status)
            for h in (
                "Content-Type",
                "Content-Length",
                "Content-Range",
                "Accept-Ranges",
                "Last-Modified",
                "ETag",
            ):
                if h in upstream.headers:
                    resp.headers[h] = upstream.headers[h]
            await resp.prepare(request)
            async for chunk in upstream.content.iter_chunked(64 * 1024):
                await resp.write(chunk)
            await resp.write_eof()
            return resp

    async def h_put(self, request: web.Request) -> web.Response:
        path = self._path(request)
        d, _, _ = path.rpartition("/")
        parent = await self._lookup(d or "/")
        if parent is None:
            return web.Response(status=409)
        if self._lock_conflict(path, request):
            return web.Response(status=423)
        existed = await self._lookup(path) is not None
        headers = {}
        if request.content_type and request.content_type != "application/octet-stream":
            headers["Content-Type"] = request.content_type
        async with self._session.put(
            f"http://{self.filer_address}{urllib.parse.quote(path)}",
            data=request.content,
            headers=headers,
        ) as upstream:
            if upstream.status >= 300:
                return web.Response(status=upstream.status)
        return web.Response(status=204 if existed else 201)

    async def h_delete(self, request: web.Request) -> web.Response:
        path = self._path(request)
        if await self._lookup(path) is None:
            return web.Response(status=404)
        if self._lock_conflict(path, request):
            return web.Response(status=423)
        d, _, name = path.rpartition("/")
        await self._stub().DeleteEntry(
            filer_pb2.DeleteEntryRequest(
                directory=d or "/",
                name=name,
                is_delete_data=True,
                is_recursive=True,
                ignore_recursive_error=True,
            )
        )
        self._locks.pop(path, None)
        return web.Response(status=204)

    def _destination(self, request: web.Request) -> str | None:
        dest = request.headers.get("Destination")
        if not dest:
            return None
        parsed = urllib.parse.urlparse(dest)
        return self.root + "/" + urllib.parse.unquote(parsed.path).strip("/")

    async def h_move(self, request: web.Request) -> web.Response:
        src = self._path(request)
        dst = self._destination(request)
        if dst is None:
            return web.Response(status=400, text="missing Destination")
        if await self._lookup(src) is None:
            return web.Response(status=404)
        if self._lock_conflict(src, request) or self._lock_conflict(dst, request):
            return web.Response(status=423)
        dst_exists = await self._lookup(dst) is not None
        if dst_exists:
            if request.headers.get("Overwrite", "T").upper() == "F":
                return web.Response(status=412)
            dd, _, dn = dst.rpartition("/")
            await self._stub().DeleteEntry(
                filer_pb2.DeleteEntryRequest(
                    directory=dd or "/", name=dn, is_delete_data=True,
                    is_recursive=True, ignore_recursive_error=True,
                )
            )
        sd, _, sn = src.rpartition("/")
        dd, _, dn = dst.rpartition("/")
        await self._stub().AtomicRenameEntry(
            filer_pb2.AtomicRenameEntryRequest(
                old_directory=sd or "/", old_name=sn,
                new_directory=dd or "/", new_name=dn,
            )
        )
        return web.Response(status=204 if dst_exists else 201)

    async def h_copy(self, request: web.Request) -> web.Response:
        src = self._path(request)
        dst = self._destination(request)
        if dst is None:
            return web.Response(status=400, text="missing Destination")
        entry = await self._lookup(src)
        if entry is None:
            return web.Response(status=404)
        dst_exists = await self._lookup(dst) is not None
        if dst_exists and request.headers.get("Overwrite", "T").upper() == "F":
            return web.Response(status=412)
        await self._copy_tree(src, dst, entry)
        return web.Response(status=204 if dst_exists else 201)

    async def _copy_tree(
        self, src: str, dst: str, entry: filer_pb2.Entry
    ) -> None:
        if entry.is_directory:
            if await self._lookup(dst) is None:
                d, _, name = dst.rpartition("/")
                await self._stub().CreateEntry(
                    filer_pb2.CreateEntryRequest(
                        directory=d or "/",
                        entry=filer_pb2.Entry(
                            name=name, is_directory=True,
                            attributes=entry.attributes,
                        ),
                    )
                )
            for child in await self._list(src):
                await self._copy_tree(
                    f"{src}/{child.name}", f"{dst}/{child.name}", child
                )
            return
        # files: stream through the filer data plane (fresh chunks, so the
        # copy owns its data like the reference's webdav PUT-on-read does)
        async with self._session.get(
            f"http://{self.filer_address}{urllib.parse.quote(src)}"
        ) as upstream:
            if upstream.status >= 300:
                raise web.HTTPBadGateway(
                    text=f"COPY source read failed: HTTP {upstream.status}"
                )
            async with self._session.put(
                f"http://{self.filer_address}{urllib.parse.quote(dst)}",
                data=upstream.content,
                headers={
                    "Content-Type": entry.attributes.mime
                    or "application/octet-stream"
                },
            ) as put_resp:
                if put_resp.status >= 300:
                    raise web.HTTPBadGateway(
                        text=f"COPY destination write failed: HTTP {put_resp.status}"
                    )

    # --------------------------------------------------------------- locks

    def _lock_conflict(self, path: str, request: web.Request) -> bool:
        token = self._locks.get(path)
        if token is None:
            return False
        supplied = request.headers.get("If", "") + request.headers.get(
            "Lock-Token", ""
        )
        return token not in supplied

    async def h_lock(self, request: web.Request) -> web.Response:
        path = self._path(request)
        if self._lock_conflict(path, request):
            return web.Response(status=423)
        token = self._locks.get(path) or f"opaquelocktoken:{uuid.uuid4()}"
        self._locks[path] = token
        prop = ET.Element(_dav("prop"))
        ld = ET.SubElement(prop, _dav("lockdiscovery"))
        al = ET.SubElement(ld, _dav("activelock"))
        ET.SubElement(ET.SubElement(al, _dav("locktype")), _dav("write"))
        ET.SubElement(ET.SubElement(al, _dav("lockscope")), _dav("exclusive"))
        ET.SubElement(al, _dav("depth")).text = request.headers.get("Depth", "0")
        ET.SubElement(al, _dav("timeout")).text = "Second-3600"
        lt = ET.SubElement(al, _dav("locktoken"))
        ET.SubElement(lt, _dav("href")).text = token
        body = ET.tostring(prop, encoding="utf-8", xml_declaration=True)
        return web.Response(
            status=200,
            body=body,
            content_type="application/xml",
            headers={"Lock-Token": f"<{token}>"},
        )

    async def h_unlock(self, request: web.Request) -> web.Response:
        path = self._path(request)
        token = request.headers.get("Lock-Token", "").strip("<>")
        if self._locks.get(path) and self._locks[path] != token:
            return web.Response(status=409)
        self._locks.pop(path, None)
        return web.Response(status=204)
