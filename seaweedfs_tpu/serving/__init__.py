"""Continuous-batching dispatch for the device-resident EC read path.

BENCH_r05 measured the resident serving path at 417 reads/s against a
same-run tunnel ceiling of 3259 — 13% utilization — while the native CPU
path peaked at 2091.  In that window the binding constraint was dispatch
software, not bytes: each coalesced batch ran to completion (device call
+ D2H + per-needle HTTP responses) before the next batch dispatched, so
the device idled through every tunnel round-trip.  This package grafts
the inference-serving fix — continuous batching — onto the storage read
path:

  * `Coalescer` packs concurrent needle reads for the same resident
    EcVolume into wide `read_needles_batch` calls (tunable max batch
    width and a µs-scale max-wait admission window);
  * `EcReadDispatcher` keeps several batches in flight (bounded depth):
    batch N+1 dispatches while batch N's reconstructed bytes are still
    riding the tunnel back, and saturation falls back to the native
    per-read path instead of queuing unboundedly;
  * per-batch Prometheus series (stats/metrics.py) make batch width,
    queue wait, device occupancy, and fallbacks dashboard-visible.

Reference path being outperformed: the per-needle goroutine fan-in of
weed/storage/store_ec.go:339-393.
"""
from .config import ServingConfig
from .coalescer import Coalescer, ReadRequest
from .dispatcher import EcReadDispatcher
from .qos import Breaker, QosController, normalize_tier
from .tiering import HeatTracker, HostShardCache, TieringController

__all__ = [
    "Breaker",
    "Coalescer",
    "EcReadDispatcher",
    "HeatTracker",
    "HostShardCache",
    "QosController",
    "ReadRequest",
    "ServingConfig",
    "TieringController",
    "normalize_tier",
]
