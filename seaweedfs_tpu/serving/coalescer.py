"""Admission queue packing concurrent EC needle reads into batches.

Pure bookkeeping — no asyncio scheduling, no device calls — so the
packing, saturation, and FIFO-ordering rules are unit-testable without a
cluster.  The dispatcher owns timing (admission window, pipelining); the
coalescer owns what rides in each batch.
"""
from __future__ import annotations

import asyncio
from dataclasses import dataclass


@dataclass
class ReadRequest:
    """One queued EC needle read awaiting a batch slot."""

    vid: int
    nid: int
    cookie: int | None
    future: asyncio.Future
    enqueued: float  # loop.time() at admission, for the queue-wait series
    # (trace, parent_span_id) captured at admission: the drain task that
    # serves this request runs outside the request's context, so the
    # trace must ride the queue with the request (obs/trace.py)
    obs_ctx: object | None = None
    # QoS tier this request was admitted under (serving/qos.py): the
    # drain loop must credit the SAME tier's budget back at take time
    tier: str = "interactive"


class Coalescer:
    """Bounded FIFO queue that packs requests into per-volume batches.

    `offer` admits a request unless the queue is saturated (backpressure:
    the caller falls back to the native path).  `take` removes up to
    `max_batch` requests in arrival order and groups them by volume id —
    each group becomes one `read_needles_batch` device call.  Grouping at
    take-time (not offer-time) keeps admission O(1) and lets a multi-
    volume burst still fill wide batches per volume.
    """

    def __init__(self, max_batch: int, max_queue: int):
        # invariants (max_batch >= 1, max_queue >= max_batch) are
        # enforced by ServingConfig.validated() — one validation layer,
        # no silent clamping here
        self.max_batch = max_batch
        self.max_queue = max_queue
        self._queue: list[ReadRequest] = []

    def __len__(self) -> int:
        return len(self._queue)

    @property
    def saturated(self) -> bool:
        return len(self._queue) >= self.max_queue

    def offer(self, req: ReadRequest) -> bool:
        """Admit `req`; False when saturated (nothing is enqueued)."""
        if self.saturated:
            return False
        self._queue.append(req)
        return True

    def take(self) -> dict[int, list[ReadRequest]]:
        """Remove up to `max_batch` oldest requests, grouped by vid.

        The slice is atomic with respect to the event loop (no awaits),
        so concurrent drain tasks never see the same request twice."""
        batch, self._queue = (
            self._queue[: self.max_batch],
            self._queue[self.max_batch :],
        )
        by_vid: dict[int, list[ReadRequest]] = {}
        for req in batch:
            by_vid.setdefault(req.vid, []).append(req)
        return by_vid
