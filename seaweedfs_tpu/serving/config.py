"""Knobs for the continuous-batching EC serving dispatcher.

Defaults are sized from this rig's measured artifacts: COUNT_BUCKETS in
ops/rs_resident.py tops out at 256 (a wider coalesce would hit an
uncompiled shape), the round-5 sweep showed `max_inflight=2` leaving the
device idle through tunnel round-trips, and an admission window needs to
be far below the ~ms batch service time to be free.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass
class ServingConfig:
    """Tunables for `EcReadDispatcher` (CLI: the -ec.serving.* flags)."""

    # route EC reads of resident volumes through the batching dispatcher;
    # False serves every read on the native per-read path
    enabled: bool = True
    # widest coalesced batch; matches COUNT_BUCKETS[-1] so a full batch
    # is one already-warm device shape
    max_batch: int = 256
    # admission window: when a dispatch slot frees and the queue holds a
    # partial batch, wait this long for the batch to fill before
    # dispatching.  Only applied once a drain loop is already hot (the
    # first batch after idle dispatches immediately), so a lone request
    # never waits.  0 disables the window.
    max_wait_us: int = 200
    # pipelined batches in flight: batch N+1's device dispatch overlaps
    # batch N's D2H + response fan-out.  Round 5 measured depth 2 leaving
    # the resident path at 13% of the tunnel ceiling; bench.py sweeps
    # 2/4/8 and publishes the curve
    max_inflight: int = 4
    # backpressure: queued requests beyond this fall back to the native
    # per-read path (counted in the fallback metric) instead of growing
    # the queue without bound
    max_queue: int = 2048
    # resident shard layout the reconstruct kernels serve through:
    # "blockdiag" is the ~157 GB/s round-3 g=4 system (default — the
    # host stages the segment layout for free at pin time), "flat" the
    # plain kernel kept as fallback (-ec.serving.layout)
    layout: str = "blockdiag"
    # double-buffered device staging: 2 slots let batch N+1 pack and
    # ship while batch N executes (only N's D2H blocks N); False = one
    # slot, the serial baseline bench.py's overlap-off axis measures
    overlap: bool = True
    # AOT serving grid + cold-shape shed (-ec.serving.aot.disable):
    # warm plans compile ahead-of-time on a background executor, and a
    # read that would hit a still-cold device shape is served on the
    # host path (shed_cold_shape route) instead of stalling the
    # dispatcher 20-40s behind an inline compile.  False restores the
    # legacy trace-and-execute warm and inline compiles.
    aot: bool = True

    @property
    def max_wait_s(self) -> float:
        return self.max_wait_us / 1e6

    @property
    def pipeline_slots(self) -> int:
        return 2 if self.overlap else 1

    def validated(self) -> "ServingConfig":
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        if self.max_queue < self.max_batch:
            raise ValueError("max_queue must be >= max_batch")
        if self.max_wait_us < 0:
            raise ValueError("max_wait_us must be >= 0")
        if self.layout not in ("flat", "blockdiag"):
            raise ValueError("layout must be 'flat' or 'blockdiag'")
        return self
