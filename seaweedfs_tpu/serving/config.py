"""Knobs for the continuous-batching EC serving dispatcher.

Defaults are sized from this rig's measured artifacts: COUNT_BUCKETS in
ops/rs_resident.py tops out at 256 (a wider coalesce would hit an
uncompiled shape), the round-5 sweep showed `max_inflight=2` leaving the
device idle through tunnel round-trips, and an admission window needs to
be far below the ~ms batch service time to be free.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass
class ServingConfig:
    """Tunables for `EcReadDispatcher` (CLI: the -ec.serving.* flags)."""

    # route EC reads of resident volumes through the batching dispatcher;
    # False serves every read on the native per-read path
    # (-ec.serving.disable)
    enabled: bool = True
    # widest coalesced batch; matches COUNT_BUCKETS[-1] so a full batch
    # is one already-warm device shape (-ec.serving.maxBatch)
    max_batch: int = 256
    # admission window: when a dispatch slot frees and the queue holds a
    # partial batch, wait this long for the batch to fill before
    # dispatching.  Only applied once a drain loop is already hot (the
    # first batch after idle dispatches immediately), so a lone request
    # never waits.  0 disables the window.  (-ec.serving.maxWaitUs)
    max_wait_us: int = 200
    # pipelined batches in flight: batch N+1's device dispatch overlaps
    # batch N's D2H + response fan-out.  Round 5 measured depth 2 leaving
    # the resident path at 13% of the tunnel ceiling; bench.py sweeps
    # 2/4/8 and publishes the curve (-ec.serving.maxInflight)
    max_inflight: int = 4
    # backpressure: queued requests beyond this fall back to the native
    # per-read path (counted in the fallback metric) instead of growing
    # the queue without bound (-ec.serving.maxQueue)
    max_queue: int = 2048
    # resident shard layout the reconstruct kernels serve through:
    # "blockdiag" is the ~157 GB/s round-3 g=4 system (default — the
    # host stages the segment layout for free at pin time), "flat" the
    # plain kernel kept as fallback (-ec.serving.layout)
    layout: str = "blockdiag"
    # double-buffered device staging: 2 slots let batch N+1 pack and
    # ship while batch N executes (only N's D2H blocks N); False = one
    # slot, the serial baseline bench.py's overlap-off axis measures
    # (-ec.serving.overlap.disable)
    overlap: bool = True
    # AOT serving grid + cold-shape shed (-ec.serving.aot.disable):
    # warm plans compile ahead-of-time on a background executor, and a
    # read that would hit a still-cold device shape is served on the
    # host path (shed_cold_shape route) instead of stalling the
    # dispatcher 20-40s behind an inline compile.  False restores the
    # legacy trace-and-execute warm and inline compiles.
    aot: bool = True
    # pod-scale mesh residency (-ec.serving.mesh.disable): lane-shard
    # resident volumes across the local device mesh under
    # PartitionSpec("shard") so a volume's resident capacity is the
    # WHOLE mesh's HBM, not one chip's, and batched reconstruct lane
    # work runs 1/n per device.  False pins volumes whole onto the
    # default device (the pre-r19 layout).  Only takes effect when >1
    # local device is visible.
    mesh: bool = True
    # devices the serving mesh may span (-ec.serving.mesh.devices):
    # 0 = every local device, n = the first n
    mesh_devices: int = 0
    # volumes whose shard files are smaller than this pin whole onto
    # the least-loaded mesh device instead of lane-sharding
    # (-ec.serving.mesh.minShardMB): spreading a tiny volume across the
    # mesh buys no capacity and pays cross-device dispatch per batch
    mesh_min_shard_mb: int = 8
    # multi-controller pod mesh (-ec.mesh.coordinator /
    # -ec.mesh.processId / -ec.mesh.processCount): when processCount > 1
    # this volume server joins a single global mesh via
    # jax.distributed.initialize(coordinator, ...) as process
    # `processId`, and residency lane-shards across EVERY process's
    # devices (parallel.mesh.global_serving_mesh) instead of this
    # host's slice.  processCount == 1 (the default) never touches the
    # coordinator and degrades to the local serving mesh — nothing
    # changes for existing single-process deployments.  Validation is
    # startup-time (validated() below): a bad coordinator string or an
    # out-of-range processId must fail the process before it takes
    # traffic, not the first dispatch.
    mesh_coordinator: str = ""
    mesh_process_id: int = 0
    mesh_process_count: int = 1
    # zero-copy response writes (-ec.serving.zerocopy.disable): needle
    # payloads stay memoryviews over the reconstruct/pread buffers all
    # the way into the aiohttp body write; False restores the legacy
    # bytes-materializing path (the r13 load bench's comparison axis).
    # SeaweedFS_volumeServer_response_copy_bytes_total measures the
    # difference.
    zero_copy: bool = True
    # QoS admission control (-ec.qos.disable): per-tier queue budgets,
    # deadline-aware shedding, and a trip/recover breaker in front of
    # the coalescer (serving/qos.py).  False = the pre-r13 single
    # shared queue with only the max_queue backstop.
    qos: bool = True
    # per-tier queue budgets: how many requests of each tier may sit in
    # the coalescer at once (-ec.qos.interactiveQueue / -ec.qos.bulkQueue).
    # The defaults PARTITION max_queue (1792 + 256 = 2048), so a tier
    # budget always binds before the global backstop and bulk can never
    # crowd the front door out of the queue.
    qos_interactive_queue: int = 1792
    qos_bulk_queue: int = 256
    # deadline budgets (ms): a request whose ESTIMATED queue wait (EWMA
    # of recent per-needle service time x queue depth / pipeline width)
    # already exceeds its tier deadline sheds to the host path at
    # admission instead of timing out inside the queue.  0 disables
    # deadline shedding for the tier (-ec.qos.interactiveDeadlineMs /
    # -ec.qos.bulkDeadlineMs).
    qos_interactive_deadline_ms: int = 2000
    qos_bulk_deadline_ms: int = 20000
    # breaker: this many CONSECUTIVE sheds trip a tier's breaker
    # (fast-fail to host) for recoverSeconds, then half-open probe
    # (-ec.qos.tripAfter / -ec.qos.recoverSeconds)
    qos_trip_after: int = 64
    qos_recover_seconds: float = 1.0
    # heat-tiered residency ladder (serving/tiering.py): HBM -> pinned
    # host-RAM reconstruct cache -> disk, driven by decayed per-volume
    # read heat fed from the dispatcher's admission accounting.
    # -ec.tier.disable turns the ladder off (residency falls back to
    # the manual pin/unpin + blind LRU budget eviction).
    tier: bool = True
    # rebalance cadence of the volume server's tier loop
    # (-ec.tier.intervalSeconds); 0 disables the loop — rebalance() can
    # still be driven manually (tests, bench)
    tier_interval_seconds: float = 5.0
    # pinned host-RAM warm tier budget (-ec.tier.hostCacheMB); 0
    # disables the host tier, so demotions fall straight to disk
    tier_host_cache_mb: int = 0
    # heat decay half-life (-ec.tier.halfLifeSeconds): popularity is an
    # exponentially-decayed read counter, so idle volumes cool to zero
    tier_half_life_seconds: float = 60.0
    # hysteresis, promotion side (-ec.tier.promoteRatio): a swap needs
    # the candidate to out-heat the coldest eligible resident by this
    # factor — the demotion threshold sits promoteRatio BELOW the
    # promotion threshold, so equally hot volumes never flap
    tier_promote_ratio: float = 1.5
    # hysteresis, time side (-ec.tier.minResidencySeconds): a promoted
    # volume is not swap-eligible before this age; over-budget pressure
    # demotions ignore it (staying over budget would re-trigger the
    # blind LRU eviction the ladder replaces)
    tier_min_residency_seconds: float = 10.0
    # QoS weight of bulk-tier reads in the heat signal
    # (-ec.tier.bulkWeight): a background scan must not out-heat the
    # interactive front door's hot set
    tier_bulk_weight: float = 0.25
    # slow-client guard: per-response stall budget for streamed bodies =
    # stall_budget_seconds + body_bytes / (stall_min_rate_kbps KB/s); a
    # client draining slower than that is disconnected so it can't hold
    # the download byte-lease + needle buffers open
    # (-ec.qos.stallBudgetSeconds / -ec.qos.stallMinRateKBps, 0 budget
    # disables the guard)
    stall_budget_seconds: float = 30.0
    stall_min_rate_kbps: int = 64

    @property
    def max_wait_s(self) -> float:
        return self.max_wait_us / 1e6

    @property
    def multiprocess(self) -> bool:
        """True when this server is one member of a multi-controller
        pod mesh (residency spans hosts)."""
        return self.mesh_process_count > 1

    def stall_budget_for(self, nbytes: int) -> float:
        """Total seconds a streamed response of `nbytes` may take before
        the dribbling client is disconnected (0 = unbounded)."""
        if self.stall_budget_seconds <= 0:
            return 0.0
        return self.stall_budget_seconds + nbytes / (
            max(1, self.stall_min_rate_kbps) * 1024.0
        )

    @property
    def pipeline_slots(self) -> int:
        return 2 if self.overlap else 1

    def validated(self) -> "ServingConfig":
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        if self.max_queue < self.max_batch:
            raise ValueError("max_queue must be >= max_batch")
        if self.max_wait_us < 0:
            raise ValueError("max_wait_us must be >= 0")
        if self.layout not in ("flat", "blockdiag"):
            raise ValueError("layout must be 'flat' or 'blockdiag'")
        if self.mesh_devices < 0:
            raise ValueError("mesh_devices must be >= 0 (0 = all local)")
        if self.mesh_min_shard_mb < 0:
            raise ValueError("mesh_min_shard_mb must be >= 0")
        if self.mesh_process_count < 1:
            raise ValueError("mesh_process_count must be >= 1")
        if self.mesh_process_count > 1:
            # multi-controller: the coordinator handshake happens at
            # startup, so a malformed rendezvous config must die HERE
            host, sep, port = self.mesh_coordinator.rpartition(":")
            if not (sep and host and port.isdigit() and 0 < int(port) < 65536):
                raise ValueError(
                    "mesh_coordinator must be host:port when "
                    f"mesh_process_count > 1 (got {self.mesh_coordinator!r})"
                )
            if not 0 <= self.mesh_process_id < self.mesh_process_count:
                raise ValueError(
                    f"mesh_process_id {self.mesh_process_id} out of range "
                    f"for mesh_process_count {self.mesh_process_count}"
                )
        elif self.mesh_process_id != 0:
            raise ValueError(
                "mesh_process_id must be 0 when mesh_process_count is 1"
            )
        if self.qos_interactive_queue < 1 or self.qos_bulk_queue < 1:
            raise ValueError("qos tier queue budgets must be >= 1")
        if (
            self.qos_interactive_deadline_ms < 0
            or self.qos_bulk_deadline_ms < 0
        ):
            raise ValueError("qos deadlines must be >= 0 (0 disables)")
        if self.qos_trip_after < 1:
            raise ValueError("qos_trip_after must be >= 1")
        if self.qos_recover_seconds <= 0:
            raise ValueError("qos_recover_seconds must be > 0")
        if self.stall_min_rate_kbps < 1:
            raise ValueError("stall_min_rate_kbps must be >= 1")
        if self.tier_interval_seconds < 0:
            raise ValueError("tier_interval_seconds must be >= 0")
        if self.tier_host_cache_mb < 0:
            raise ValueError("tier_host_cache_mb must be >= 0")
        if self.tier_half_life_seconds <= 0:
            raise ValueError("tier_half_life_seconds must be > 0")
        if self.tier_promote_ratio < 1.0:
            raise ValueError(
                "tier_promote_ratio must be >= 1 (hysteresis margin)"
            )
        if self.tier_min_residency_seconds < 0:
            raise ValueError("tier_min_residency_seconds must be >= 0")
        if not 0.0 <= self.tier_bulk_weight <= 1.0:
            raise ValueError("tier_bulk_weight must be in [0, 1]")
        return self
