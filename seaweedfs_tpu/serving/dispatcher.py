"""Pipelined continuous-batching dispatcher for EC needle reads.

Sits between the volume server's EC read handler (server/volume.py
h_read) and the device-resident reconstruct path (storage/ec/volume.py
read_needles_batch -> ops/rs_resident.py).  Three rules:

  1. ROUTE: reads of a volume with enough resident shards to reconstruct
     on-device ride the batching queue; everything else (no cache, pin
     thread still running, dispatcher disabled) takes the native per-read
     path immediately — a cold volume's concurrent disk reads must not
     serialize behind a batch queue.
  2. COALESCE + PIPELINE: queued reads pack into wide
     `read_needles_batch` calls (Coalescer); up to `max_inflight` batches
     run concurrently, so batch N+1's device dispatch and H2D overlap
     batch N's D2H and response fan-out instead of idling the device
     through every tunnel round-trip (the round-5 13%-of-ceiling gap).
     A hot drain loop holds a µs-scale admission window open so bursts
     fill batches instead of fragmenting.
  3. SHED: past `max_queue` queued requests the dispatcher stops
     admitting and serves the overflow on the native path (counted in
     the fallback series) — saturation degrades to round-5 behavior, it
     never grows an unbounded queue.

Each in-flight lane's device call is itself staged pack -> H2D ->
execute -> D2H through the cache's two-slot DevicePipeline
(ops/rs_resident.py, configured from ServingConfig.overlap): a lane
packs batch N+1's host vectors outside the slot while another lane's
batch N executes, so lanes overlap at the stage level rather than just
racing whole calls — the overlap-fraction gauge and the batch_pack /
h2d_copy / d2h_copy trace stages make the overlap visible per batch.

Every decision is visible on /metrics: batch-width histogram, per-request
queue wait, in-flight batch occupancy, fallback and native-route
counters (stats/metrics.py).
"""
from __future__ import annotations

import asyncio
import logging
import time

from .. import obs, stats
from ..obs import devledger
from ..obs import incident as obs_incident
from ..utils import faultpolicy
from ..utils.tasks import spawn_logged
from .coalescer import Coalescer, ReadRequest
from .config import ServingConfig
from .qos import QosController, normalize_tier

log = logging.getLogger("serving")


class EcReadDispatcher:
    """Continuous-batching front of Store.read_ec_needles_batch.

    `store` needs `read_ec_needles_batch`, `read_ec_needle`, and
    `ec_volume_is_resident`; `remote_reader_factory(vid)` supplies the
    peer-shard hook both paths thread through (server/volume.py's
    VolumeEcShardRead client)."""

    def __init__(
        self,
        store,
        remote_reader_factory,
        config: ServingConfig | None = None,
    ):
        self.store = store
        self._remote_reader = remote_reader_factory
        self.cfg = (config or ServingConfig()).validated()
        self.coalescer = Coalescer(self.cfg.max_batch, self.cfg.max_queue)
        self.qos = QosController.from_config(self.cfg)
        self._inflight = 0
        # heat-tiered residency (serving/tiering.py): when a controller
        # is attached, every EC read's (vid, tier) feeds its decayed
        # popularity counters BEFORE routing — the ladder's heat signal
        # is the same per-volume accounting the read_route series sees
        self.tiering = None
        # strong refs to the live drain-lane tasks (the event loop only
        # holds weak ones) + an exception-logging done-callback: a lane
        # dying outside _serve_batch's own catch must be attributable,
        # not a silent narrowing of the pipeline (GL111)
        self._lanes: set = set()

    # ----------------------------------------------------------- telemetry

    @property
    def queue_depth(self) -> int:
        """Reads waiting in the coalescer right now."""
        return len(self.coalescer)

    @property
    def inflight(self) -> int:
        """Batches currently in flight on the device (occupancy)."""
        return self._inflight

    def shutdown(self) -> None:
        """Clean-shutdown zeroing of the occupancy/queue gauges: the
        registry is process-global (co-hosted roles, in-process restarts
        share it), so a dispatcher that dies mid-batch would otherwise
        leave its last occupancy standing until the replacement's first
        batch overwrites it — a restarted server must report idle."""
        stats.VOLUME_SERVER_EC_BATCH_INFLIGHT.set(0)
        stats.VOLUME_SERVER_EC_QUEUE_DEPTH.set(0)
        # the per-device residency series (r19 mesh layout) follows the
        # same contract: a restarted server's devices report empty
        # until its pin threads repopulate them
        cache = getattr(self.store, "ec_device_cache", None)
        if cache is not None:
            for d in range(cache.n_devices):
                stats.VOLUME_SERVER_EC_DEVICE_CACHE_BYTES.labels(
                    device=str(d)
                ).set(0)
        self.qos.shutdown()

    # ------------------------------------------------------------- admission

    def _route(self, route: str, origin: str) -> None:
        """Count the admitting route; S3-originated reads (the gateway's
        direct volume path) are attributed IN ADDITION under s3_<route>
        so a dashboard can see S3 GETs riding the resident dispatcher."""
        stats.VOLUME_SERVER_EC_READ_ROUTE.labels(route=route).inc()
        if origin == "s3":
            stats.VOLUME_SERVER_EC_READ_ROUTE.labels(
                route=f"s3_{route}"
            ).inc()

    async def read(
        self,
        vid: int,
        nid: int,
        cookie: int | None,
        tier: str = "interactive",
        origin: str = "",
    ):
        """Serve one EC needle read; returns a Needle or raises the
        per-needle error (NeedleNotFound / CookieMismatch / ...).
        `tier` is the QoS tier (serving/qos.py; unknown values map to
        interactive); `origin` attributes the read's source in the
        read_route series ("s3" = the gateway's direct volume path)."""
        cfg = self.cfg
        tier = normalize_tier(tier)
        # refuse doomed work early: a spent deadline budget raises here
        # (504 at the front door) instead of burning a queue slot and a
        # device dispatch on a client that already gave up — the
        # admission end of the one continuous budget (faultpolicy)
        remaining_s = faultpolicy.check_remaining("ec read admission")
        if self.tiering is not None:
            self.tiering.note_read(vid, tier)
        if not cfg.enabled:
            # dispatcher disabled = the pre-batching per-read behavior,
            # device reconstruct included: an idle device on a resident
            # volume should still serve width-1 reads
            self._route("native", origin)
            return await self._read_native(vid, nid, cookie, use_device=True)
        if not self.store.ec_volume_is_resident(vid):
            self._route("native", origin)
            return await self._read_native(vid, nid, cookie)
        if cfg.qos and self.qos.admit(
            tier, len(self.coalescer), cfg.max_inflight,
            remaining_s=remaining_s,
        ) is not None:
            # QoS shed (tier budget / deadline / breaker): serve on the
            # host path NOW rather than joining a queue this request
            # would time out inside — reasons are counted per tier in
            # the qos_shed series by admit() itself
            self._route("native", origin)
            return await self._read_native(vid, nid, cookie)
        loop = asyncio.get_running_loop()
        req = ReadRequest(
            vid, nid, cookie, loop.create_future(), loop.time(),
            obs_ctx=obs.current(), tier=tier,
        )
        if not self.coalescer.offer(req):
            # saturated: shed to the native path rather than queue without
            # bound — the fallback count is the dashboard's overload signal,
            # and QoS must see it as overload too (breaker + shed series),
            # not as the success admit() pre-approved
            stats.VOLUME_SERVER_EC_BATCH_FALLBACK.inc()
            # flight recorder: the raw saturation decision (also visible
            # when -ec.qos.disable leaves no QoS layer to record it)
            obs_incident.record(
                "dispatch_saturated", vid=vid, tier=tier,
                queue_depth=len(self.coalescer),
            )
            if cfg.qos:
                self.qos.saturated(tier)
            self._route("native", origin)
            return await self._read_native(vid, nid, cookie)
        if cfg.qos:
            # commit the admission (admitted counter, breaker success,
            # tier queue gauge).  Guarded so -ec.qos.disable really
            # leaves every qos series flat — req.tier is cleared too so
            # the drain loop's dequeue credit stays symmetric even if
            # the flag is toggled while requests are queued.
            self.qos.enqueued(tier)
        else:
            req.tier = ""
        self._route("batched", origin)
        stats.VOLUME_SERVER_EC_QUEUE_DEPTH.set(len(self.coalescer))
        self._maybe_spawn()
        t_resident = time.perf_counter()
        try:
            return await req.future
        finally:
            # the request's WHOLE dispatcher residency, enqueue ->
            # waiter resume, as a low-priority queue_wait span
            # (observe=False: the admission-window histogram sample is
            # the drain loop's).  The batch stage spans outrank it in
            # critical-path attribution, so all it claims is the slice
            # nothing else covers — chiefly the future-resume gap where
            # the batch is done but the event loop hasn't scheduled
            # this coroutine yet, which under load is milliseconds a
            # tail forensics answer must not call untraced.
            obs.record_span(
                req.obs_ctx, "queue_wait", t_resident,
                time.perf_counter() - t_resident, observe=False,
            )

    async def _read_native(
        self, vid: int, nid: int, cookie: int | None, use_device: bool = False
    ):
        # use_device defaults False: the shed route must be the HOST
        # reconstruct (under saturation the device is the bottleneck —
        # width-1 device dispatches racing the batched lanes would make
        # overload worse), and for unpinned volumes the device path is a
        # guaranteed CacheMiss anyway.  Only the disabled-dispatcher
        # route allows the device per-read.
        return await asyncio.to_thread(
            self.store.read_ec_needle,
            vid,
            nid,
            cookie,
            self._remote_reader(vid),
            use_device,
            self.cfg.zero_copy,
        )

    # ------------------------------------------------------------ dispatch

    def _maybe_spawn(self) -> None:
        if len(self.coalescer) and self._inflight < self.cfg.max_inflight:
            self._inflight += 1
            stats.VOLUME_SERVER_EC_BATCH_INFLIGHT.set(self._inflight)
            # detached: the new task copies this context, and a drain
            # lane spawned from a traced request would otherwise append
            # every LATER request's batch spans to the spawner's
            # (finished) trace — member traces ride ReadRequest.obs_ctx
            # instead.  The DEADLINE detaches for the same reason: a
            # lane outliving its spawner's budget must not doom every
            # later batch it serves (faultpolicy.detached).
            with obs.detached(), faultpolicy.detached():
                spawn_logged(
                    self._drain(), log, "ec-read drain lane",
                    registry=self._lanes,
                )

    async def _drain(self) -> None:
        """One pipeline lane: serve batches until the queue empties.

        A lane's first batch on an IDLE dispatcher (no other lane in
        flight) dispatches immediately, so a lone request keeps its idle
        latency.  In every other state — a hot lane looping, or a fresh
        lane spawning while sibling lanes have the device busy — a
        partial queue gets the admission window to fill before the take:
        waiting is free while the device is occupied, and it is exactly
        how a response-triggered re-issue burst (closed-loop clients)
        packs into wide batches instead of fragmenting.  With several
        lanes live this is continuous batching: each lane's blocking
        device call runs in its own thread while the event loop keeps
        admitting and the other lanes keep the device fed."""
        cfg = self.cfg
        first = self._inflight == 1  # idle spawn: skip the first window
        try:
            while len(self.coalescer):
                if (
                    not first
                    and cfg.max_wait_us > 0
                    and len(self.coalescer) < cfg.max_batch
                ):
                    await asyncio.sleep(cfg.max_wait_s)
                first = False
                now = asyncio.get_running_loop().time()
                now_pc = time.perf_counter()
                taken = self.coalescer.take()
                stats.VOLUME_SERVER_EC_QUEUE_DEPTH.set(len(self.coalescer))
                for vid, items in taken.items():
                    stats.VOLUME_SERVER_EC_BATCH_SIZE.observe(len(items))
                    for r in items:
                        if r.tier:  # "" = enqueued with qos off
                            self.qos.dequeued(r.tier)
                        wait = now - r.enqueued
                        stats.VOLUME_SERVER_EC_BATCH_QUEUE_WAIT.observe(wait)
                        # the trace's view of the same wait: admission ->
                        # batch take, per request
                        obs.record_span(
                            r.obs_ctx, "queue_wait", now_pc - wait, wait
                        )
                    await self._serve_batch(vid, items)
        finally:
            self._inflight -= 1
            stats.VOLUME_SERVER_EC_BATCH_INFLIGHT.set(self._inflight)
            self._maybe_spawn()  # raced with an offer after the loop check

    async def _serve_batch(self, vid: int, items: list[ReadRequest]) -> None:
        # one batch serves many traces: the worker's stage spans
        # (device_execute / host_reconstruct / shard_read) land in a
        # sink and are replayed onto every member trace afterwards —
        # observe=False so the stage histograms count each stage once
        t0 = time.perf_counter()
        # device-ledger class for the batch: a batch is bulk-tier only
        # when every member is (mixed batches serve an interactive
        # reader, so they attribute interactive); "" = qos off =
        # interactive.  asyncio.to_thread copies the context, so the
        # tag reaches the device section in ops/rs_resident.
        wl = (
            "serving_bulk"
            if items and all(r.tier == "bulk" for r in items)
            else "serving_interactive"
        )
        with obs.stage_sink() as sink:
            try:
                with devledger.workload(wl), obs.span(
                    "batch_dispatch", needles=len(items), vid=vid
                ):
                    results = await asyncio.to_thread(
                        self.store.read_ec_needles_batch,
                        vid,
                        [(r.nid, r.cookie) for r in items],
                        self._remote_reader(vid),
                        self.cfg.zero_copy,
                    )
            except Exception as e:  # noqa: BLE001 — volume-level failure
                results = [e] * len(items)
        # feed the deadline estimator: per-needle service time of THIS
        # batch (wall across the store call / width)
        self.qos.observe_service(
            (time.perf_counter() - t0) / max(1, len(items))
        )
        for r in items:
            if r.obs_ctx is None:
                continue
            for stage, (dur, calls, ann) in sink.items():
                obs.record_span(
                    r.obs_ctx, stage, t0, dur, observe=False,
                    annotations={"calls": calls, **ann},
                )
        for r, res in zip(items, results):
            if r.future.done():  # client went away mid-batch
                continue
            if isinstance(res, Exception):
                r.future.set_exception(res)
            else:
                r.future.set_result(res)
