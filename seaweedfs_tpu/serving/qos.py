"""QoS admission control for the EC serving dispatcher.

The r13 load harness (seaweedfs_tpu/loadgen) showed what a single shared
queue does under a thousands-of-connections front door: bulk traffic
fills the coalescer, interactive p99 rides the full queue depth, and by
the time the hard `max_queue` backstop sheds, every queued request has
already blown its deadline.  This module puts three policies in front of
the queue, all exported as `SeaweedFS_volumeServer_ec_qos_*` series:

  1. TIER BUDGETS — requests carry a tier ("interactive" front-door
     reads vs "bulk" background/batch traffic, from the X-Seaweed-QoS
     header); each tier owns a slice of the queue (-ec.qos.*Queue), so
     bulk saturation sheds bulk, never interactive.
  2. DEADLINE-AWARE SHED — admission estimates the queue wait from an
     EWMA of recent per-needle service time; a request whose estimated
     wait already exceeds its tier deadline is served on the host path
     NOW instead of joining a queue it will time out inside.  Shedding
     early keeps the queue short enough that admitted requests meet
     their deadlines — degradation instead of collapse.
  3. BREAKER — sustained shedding trips a per-tier breaker that
     fast-fails (host path) without re-evaluating the queue for a
     cooldown, then half-opens for a probe.  The same `Breaker` class
     backs the S3 gateway's circuit breaker (s3api/circuit_breaker.py),
     so S3 overload behavior and volume-server QoS share one
     trip/recover policy.

Reference: weed/s3api/s3api_circuit_breaker.go motivates the fast-fail
shape; the tiering follows the load harness's findings, not the
reference (which has no QoS on the volume server).
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Callable

from .. import stats
from ..obs import incident as obs_incident

if TYPE_CHECKING:
    from .config import ServingConfig

INTERACTIVE = "interactive"
BULK = "bulk"
TIERS = (INTERACTIVE, BULK)

# admit() verdicts (shed reasons; None = admitted)
SHED_QUEUE_BUDGET = "queue_budget"
SHED_DEADLINE = "deadline"
SHED_BREAKER_OPEN = "breaker_open"


def normalize_tier(raw: str | None) -> str:
    """Map a client-supplied tier string onto a known tier (unknown or
    absent -> interactive: the front door must not be deniable into the
    bulk budget by a typo)."""
    return raw if raw in TIERS else INTERACTIVE


class Breaker:
    """Consecutive-rejection circuit breaker with half-open recovery.

    closed -> (trip_after consecutive rejections) -> open for
    `cooldown_s` -> half-open (allow() passes probes) -> one success
    closes, one rejection re-opens.  Open-state fast-fails do NOT extend
    the trip (the cooldown clock runs from the trip), so a storm of
    arrivals can't hold the breaker open forever.

    `clock` is injectable for tests (defaults to time.monotonic).
    """

    CLOSED, HALF_OPEN, OPEN = 0, 1, 2

    def __init__(
        self,
        trip_after: int = 64,
        cooldown_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.trip_after = max(1, int(trip_after))
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._consecutive = 0
        self._opened_at: float | None = None

    @property
    def state(self) -> int:
        if self._opened_at is None:
            return self.CLOSED
        if self._clock() - self._opened_at >= self.cooldown_s:
            return self.HALF_OPEN
        return self.OPEN

    def allow(self) -> bool:
        """True when a request may be evaluated (closed or half-open)."""
        return self.state != self.OPEN

    def record_rejection(self) -> None:
        st = self.state
        if st == self.HALF_OPEN:
            # failed probe: re-open for a fresh cooldown
            self._opened_at = self._clock()
            return
        if st == self.OPEN:
            return  # fast-fails don't extend the trip
        self._consecutive += 1
        if self._consecutive >= self.trip_after:
            self._opened_at = self._clock()

    def record_success(self) -> None:
        self._consecutive = 0
        self._opened_at = None


@dataclass
class TierPolicy:
    name: str
    queue_budget: int  # max requests of this tier queued at once
    deadline_s: float  # 0 = no deadline shedding for this tier


class QosController:
    """Per-tier admission bookkeeping for EcReadDispatcher.

    The dispatcher calls `admit()` before offering to the coalescer,
    `enqueued()/dequeued()` around the queue hop, and
    `observe_service()` after each batch so the deadline estimate tracks
    the device's actual service rate.  All state is event-loop-
    confined (no locks): every caller runs on the dispatcher's loop.
    """

    # EWMA weight for new service-time observations; ~last 10 batches
    _ALPHA = 0.2

    def __init__(
        self,
        policies: dict[str, TierPolicy],
        trip_after: int = 64,
        cooldown_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.policies = policies
        self._queued: dict[str, int] = {t: 0 for t in policies}
        self._breakers: dict[str, Breaker] = {
            t: Breaker(trip_after, cooldown_s, clock) for t in policies
        }
        # last gauge-published breaker state per tier: the gauge is only
        # touched on transitions, not on every hot-path admission
        self._published_state: dict[str, int] = {t: -1 for t in policies}
        # per-needle service seconds EWMA; None until the first batch
        self._service_s: float | None = None

    @classmethod
    def from_config(cls, cfg: ServingConfig) -> "QosController":
        """Build from a ServingConfig (the -ec.qos.* flags)."""
        return cls(
            {
                INTERACTIVE: TierPolicy(
                    INTERACTIVE,
                    cfg.qos_interactive_queue,
                    cfg.qos_interactive_deadline_ms / 1e3,
                ),
                BULK: TierPolicy(
                    BULK, cfg.qos_bulk_queue, cfg.qos_bulk_deadline_ms / 1e3
                ),
            },
            trip_after=cfg.qos_trip_after,
            cooldown_s=cfg.qos_recover_seconds,
        )

    # ------------------------------------------------------------ admission

    def breaker_state(self, tier: str) -> int:
        return self._breakers[tier].state

    def estimated_wait_s(self, queue_depth: int, max_inflight: int) -> float:
        """Expected queue wait for a request admitted behind
        `queue_depth` others: depth x the EWMA per-needle service time,
        divided by the pipeline width actually draining the queue."""
        if self._service_s is None or queue_depth <= 0:
            return 0.0
        return queue_depth * self._service_s / max(1, max_inflight)

    def admit(
        self,
        tier: str,
        queue_depth: int,
        max_inflight: int,
        remaining_s: float | None = None,
    ) -> str | None:
        """None = may proceed to the coalescer; else the shed reason.
        Counts sheds; the SUCCESS side (admitted counter, breaker
        success, queue accounting) is committed by `enqueued()` only
        once the coalescer actually accepted the request — the global
        max_queue backstop can still reject between the two, and that
        rejection must read as overload (`saturated()`), not success.

        `remaining_s` is the request's propagated deadline budget
        (utils/faultpolicy.py): when present, the deadline shed judges
        the estimated queue wait against min(tier deadline, remaining
        budget) — the admission end of ONE continuous budget stamped at
        the front door, instead of a local per-tier guess."""
        pol = self.policies[tier]
        br = self._breakers[tier]
        if br.state != self._published_state[tier]:
            # breaker TRANSITION: the gauge flip doubles as the flight
            # recorder's moment — "when exactly did the front door trip"
            # is the first question an incident bundle answers
            names = ("closed", "half_open", "open")
            prev = self._published_state[tier]
            obs_incident.record(
                "qos_breaker", tier=tier, state=names[br.state],
                prev=names[prev] if 0 <= prev < len(names) else "unset",
            )
            self._published_state[tier] = br.state
            stats.VOLUME_SERVER_EC_QOS_BREAKER_STATE.labels(tier=tier).set(
                br.state
            )
        if not br.allow():
            stats.VOLUME_SERVER_EC_QOS_SHED.labels(
                tier=tier, reason=SHED_BREAKER_OPEN
            ).inc()
            obs_incident.record(
                "qos_shed", tier=tier, reason=SHED_BREAKER_OPEN
            )
            return SHED_BREAKER_OPEN
        # the effective deadline: the tier policy's, tightened by the
        # request's own remaining budget when one was propagated
        deadline_s = pol.deadline_s
        if remaining_s is not None:
            deadline_s = (
                min(deadline_s, remaining_s) if deadline_s > 0
                else remaining_s
            )
        reason = None
        if self._queued[tier] >= pol.queue_budget:
            reason = SHED_QUEUE_BUDGET
        elif (
            deadline_s > 0
            and self.estimated_wait_s(queue_depth, max_inflight)
            > deadline_s
        ):
            reason = SHED_DEADLINE
        if reason is not None:
            br.record_rejection()
            stats.VOLUME_SERVER_EC_QOS_SHED.labels(
                tier=tier, reason=reason
            ).inc()
            obs_incident.record(
                "qos_shed", tier=tier, reason=reason,
                queue_depth=queue_depth,
            )
            return reason
        return None

    def saturated(self, tier: str) -> None:
        """The global max_queue backstop rejected a request admit()
        passed: count it as a queue_budget shed and feed the breaker —
        sustained coalescer saturation must be able to trip into
        fast-fail exactly like a tier-budget overload."""
        self._breakers[tier].record_rejection()
        stats.VOLUME_SERVER_EC_QOS_SHED.labels(
            tier=tier, reason=SHED_QUEUE_BUDGET
        ).inc()
        obs_incident.record(
            "qos_shed", tier=tier, reason=SHED_QUEUE_BUDGET,
            saturated=True,
        )

    # ----------------------------------------------------------- accounting

    def enqueued(self, tier: str) -> None:
        """Commit a successful admission (the coalescer accepted)."""
        self._breakers[tier].record_success()
        stats.VOLUME_SERVER_EC_QOS_ADMITTED.labels(tier=tier).inc()
        self._queued[tier] += 1
        stats.VOLUME_SERVER_EC_QOS_QUEUE_DEPTH.labels(tier=tier).set(
            self._queued[tier]
        )

    def dequeued(self, tier: str) -> None:
        self._queued[tier] = max(0, self._queued[tier] - 1)
        stats.VOLUME_SERVER_EC_QOS_QUEUE_DEPTH.labels(tier=tier).set(
            self._queued[tier]
        )

    def observe_service(self, per_needle_s: float) -> None:
        """Feed one batch's per-needle service time into the EWMA the
        deadline estimate rides on."""
        if per_needle_s <= 0:
            return
        if self._service_s is None:
            self._service_s = per_needle_s
        else:
            self._service_s += self._ALPHA * (per_needle_s - self._service_s)

    def shutdown(self) -> None:
        """Zero the per-tier gauges on clean dispatcher shutdown (the
        registry is process-global; see EcReadDispatcher.shutdown)."""
        for tier in self.policies:
            stats.VOLUME_SERVER_EC_QOS_QUEUE_DEPTH.labels(tier=tier).set(0)
            stats.VOLUME_SERVER_EC_QOS_BREAKER_STATE.labels(tier=tier).set(0)
            self._published_state[tier] = 0
