"""Heat-tiered residency: HBM -> host RAM -> disk, driven by observed
popularity.

Every serving win so far assumed the working set fits in HBM and that
residency is a manual pin/unpin decision; at production scale the
working set never fits.  This module closes the loop the way SeaweedFS's
own hot/warm storage backends tier `.dat` files (SURVEY §1,
weed/storage tiers), but for the DEVICE shard cache:

  * `HeatTracker` — decayed per-volume read counters, fed from the
    serving dispatcher's admission path (`EcReadDispatcher.read` calls
    `note_read` for every EC read it routes, so the heat signal is the
    same per-volume accounting the read_route/QoS series ride on).
    Interactive-tier reads weigh 1.0, bulk reads `-ec.tier.bulkWeight`:
    a bulk scan must not evict the front door's hot set (the QoS-aware
    half of demotion).
  * `HostShardCache` — the warm tier: shard bytes pinned in host RAM
    (numpy arrays staged once from the shard files), served through the
    EXISTING host reconstruct fallback via zero-copy memoryview slices —
    a warm read touches no disk.  Prepared parity systems are process-
    cached already (`rs_tpu._prepared_*` / `rs.RSCodec`), so staging the
    bytes is all the warm tier needs.
  * `TieringController` — the ladder: hot volumes promote into HBM
    (with the r11 AOT pre-warm from the observed-shapes persistence, so
    a promotion never puts a cold device shape on the live path), warm
    volumes demote into the host cache, cold volumes fall back to
    disk/S3.  Demotion under HBM pressure is heat-chosen (coldest
    victim) instead of the blind LRU budget eviction, and hysteresis —
    a promotion/demotion threshold separated by `-ec.tier.promoteRatio`
    plus a `-ec.tier.minResidencySeconds` floor — keeps a flash crowd
    from thrashing the ladder.

All ladder moves go through the store/cache release paths the r14
viewguard sanitizer wraps: a demotion racing outstanding zero-copy
exports is byte-exact or a clean CacheMiss, never stale bytes
(tests/test_viewguard_stress.py pins the race).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import TYPE_CHECKING, Callable

import numpy as np

from ..obs import incident as obs_incident
from ..stats import metrics as stats_metrics
from .qos import BULK

if TYPE_CHECKING:
    from .config import ServingConfig

log = logging.getLogger("serving.tiering")

TIER_HBM = "hbm"
TIER_HOST = "host"
TIER_DISK = "disk"
TIERS = (TIER_HBM, TIER_HOST, TIER_DISK)

# ladder moves per rebalance cycle: bounds promotion/demotion churn (and
# the pin/stage IO it costs) no matter how violently the heat ranking
# reshuffles between cycles
MAX_MOVES_PER_CYCLE = 2
# a volume whose promotion pin FAILED is not retried for this long: the
# failure already cost (at worst) one healthy demotion, and retrying
# every cycle would turn one unreadable shard file into a permanent
# demote-thrash loop
PROMOTE_FAILURE_BACKOFF_S = 60.0
# most residents one swap may demote to fit a single big candidate:
# bounds the per-cycle pin/stage IO a giant volume can trigger (a
# candidate needing more victims than this is skipped, not served)
MAX_SWAP_VICTIMS = 4


class HeatTracker:
    """Exponentially-decayed per-volume read counters.

    `note(vid, tier)` adds one (QoS-weighted) observation; `value(vid)`
    reads the decayed count.  Decay uses a half-life rather than a
    fixed window so a volume's heat is continuous — no cliff at a
    window edge — and idle volumes converge to zero, which is what lets
    the controller treat "heat 0" as never-promote."""

    def __init__(
        self,
        half_life_s: float = 60.0,
        bulk_weight: float = 0.25,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.half_life_s = max(half_life_s, 1e-3)
        self.bulk_weight = bulk_weight
        self._clock = clock
        self._lock = threading.Lock()
        self._heat: dict[int, float] = {}
        self._stamp: dict[int, float] = {}

    def _decayed(self, vid: int, now: float) -> float:
        h = self._heat.get(vid, 0.0)
        if h <= 0.0:
            return 0.0
        dt = now - self._stamp.get(vid, now)
        if dt <= 0.0:
            return h
        return h * 0.5 ** (dt / self.half_life_s)

    # entries whose decayed heat fell below this are dropped at prune
    # time — after ~10 half-lives a single read's trace is gone
    PRUNE_FLOOR = 1e-3
    # tracked-vid cap: note() prunes past this so a client probing
    # random fids (the dispatcher feeds every requested vid, existent
    # or not) cannot grow the dicts without bound
    MAX_TRACKED = 8192

    def note(self, vid: int, tier: str = "", n: int = 1) -> None:
        """Record `n` reads of `vid`; bulk-tier reads are down-weighted
        (-ec.tier.bulkWeight) so background scans cannot out-heat the
        interactive front door."""
        w = (self.bulk_weight if tier == BULK else 1.0) * n
        now = self._clock()
        with self._lock:
            self._heat[vid] = self._decayed(vid, now) + w
            self._stamp[vid] = now
            if len(self._heat) > self.MAX_TRACKED:
                self._prune_locked(now)

    def _prune_locked(self, now: float) -> None:
        """Drop cooled-off entries; if probing traffic keeps more than
        MAX_TRACKED vids warm, keep the hottest half (caller holds the
        lock)."""
        for vid in [
            v
            for v in self._heat
            if self._decayed(v, now) < self.PRUNE_FLOOR
        ]:
            del self._heat[vid]
            del self._stamp[vid]
        if len(self._heat) > self.MAX_TRACKED:
            keep = sorted(
                self._heat, key=lambda v: -self._decayed(v, now)
            )[: self.MAX_TRACKED // 2]
            keep_set = set(keep)
            for vid in list(self._heat):
                if vid not in keep_set:
                    del self._heat[vid]
                    del self._stamp[vid]

    def prune(self, now: float | None = None) -> None:
        """Periodic cleanup hook (the controller calls it per
        rebalance): keeps the tracked-vid set bounded even when note()
        never crosses the cap."""
        now = self._clock() if now is None else now
        with self._lock:
            self._prune_locked(now)

    def value(self, vid: int, now: float | None = None) -> float:
        now = self._clock() if now is None else now
        with self._lock:
            return self._decayed(vid, now)

    def snapshot(self, now: float | None = None) -> dict[int, float]:
        now = self._clock() if now is None else now
        with self._lock:
            return {vid: self._decayed(vid, now) for vid in self._heat}

    def forget(self, vid: int) -> None:
        with self._lock:
            self._heat.pop(vid, None)
            self._stamp.pop(vid, None)


class HostShardCache:
    """Warm tier: EC shard bytes pinned in host RAM, whole volumes at a
    time (partial shard sets cannot reconstruct, so per-shard residency
    would only fake coverage).  Reads hand out zero-copy memoryview
    slices of the staged arrays — the arrays are never mutated in place
    (eviction just drops the reference; an outstanding view keeps its
    buffer alive via the ordinary refcount), which is what keeps the
    viewguard contract trivially true for this tier."""

    def __init__(self, budget_bytes: int) -> None:
        self.budget = budget_bytes
        self._lock = threading.Lock()
        self._shards: dict[int, dict[int, np.ndarray]] = {}
        self.bytes_used = 0
        # cumulative stage/evict counters for telemetry
        self.stages = 0
        self.evictions = 0

    def put_volume(self, vid: int, shards: dict[int, np.ndarray]) -> bool:
        """Stage a whole volume's shard bytes; all-or-nothing against
        the budget (False = did not fit — the CONTROLLER picks victims
        by heat; this cache never blindly evicts)."""
        size = sum(int(a.nbytes) for a in shards.values())
        if not shards:
            return False
        with self._lock:
            old = self._shards.get(vid)
            old_size = (
                sum(int(a.nbytes) for a in old.values()) if old else 0
            )
            if self.bytes_used - old_size + size > self.budget:
                return False
            if old is not None:
                self.bytes_used -= old_size
            self._shards[vid] = dict(shards)
            self.bytes_used += size
            self.stages += 1
            stats_metrics.VOLUME_SERVER_EC_TIER_HOST_BYTES.set(
                self.bytes_used
            )
        return True

    def shard_array(self, vid: int, shard_id: int) -> np.ndarray | None:
        with self._lock:
            vol = self._shards.get(vid)
            return None if vol is None else vol.get(shard_id)

    def read(self, vid: int, shard_id: int, off: int, size: int):
        """-> zero-copy memoryview of the staged bytes, or None when the
        shard is not host-resident.  Short slices at the shard tail
        mirror a disk pread's short read (callers already handle it);
        only FULL serves count in the host-reads series — a short slice
        the caller throws away and re-reads from disk must not read as
        'the warm tier served it'."""
        arr = self.shard_array(vid, shard_id)
        if arr is None:
            return None
        view = memoryview(arr.data)[off : off + size]
        if len(view) == size:
            stats_metrics.VOLUME_SERVER_EC_TIER_HOST_READS.inc()
        return view

    def resident_count(self, vid: int) -> int:
        with self._lock:
            vol = self._shards.get(vid)
            return 0 if vol is None else len(vol)

    def volume_bytes(self, vid: int) -> int:
        with self._lock:
            vol = self._shards.get(vid)
            if vol is None:
                return 0
            return sum(int(a.nbytes) for a in vol.values())

    def vids(self) -> list[int]:
        with self._lock:
            return sorted(self._shards)

    def evict(self, vid: int) -> int:
        """Drop a volume's staged bytes; returns bytes freed.  Any
        outstanding memoryview keeps its own array alive — eviction
        only ends the cache's claim on the budget."""
        with self._lock:
            vol = self._shards.pop(vid, None)
            if vol is None:
                return 0
            freed = sum(int(a.nbytes) for a in vol.values())
            self.bytes_used -= freed
            self.evictions += 1
            stats_metrics.VOLUME_SERVER_EC_TIER_HOST_BYTES.set(
                self.bytes_used
            )
        return freed


class TieringController:
    """The residency ladder over one Store's EC volumes.

    `rebalance()` is the single decision point, run by the volume
    server's tier loop (-ec.tier.intervalSeconds) or driven manually by
    tests/bench.  Each cycle:

      1. PRESSURE — while the HBM cache is over budget, demote the
         coldest resident volume (heat-chosen, not LRU) to the host
         tier (or disk when no host budget); over-budget demotion
         ignores the min-residency floor — staying over budget would
         re-trigger the BLIND per-shard LRU eviction this controller
         replaces.
      2. PROMOTE — hottest non-resident volumes move into free HBM
         budget; when the budget is full, a candidate must out-heat the
         coldest eligible resident by `promote_ratio` AND the victim
         must be past `min_residency_s` (hysteresis: the demotion
         threshold sits promote_ratio below the promotion threshold, so
         a flash crowd flapping between two volumes cannot thrash).
         While any QoS breaker is open (overload), swaps are frozen —
         promotion churn must not add pin traffic to a device already
         shedding — but free-budget promotions still run.
      3. HOST FILL — the hottest non-HBM volumes fill the host-RAM
         budget in heat order; host entries that fell cold (or got
         promoted) are dropped.

    Promotion pins shards (host-cache bytes first, disk otherwise) and
    immediately re-arms the r11 AOT warm plan from the observed-shape
    ranking (`rs_resident.warm(..., wait=False)`), so a promoted
    volume's first reads either hit already-compiled shapes or shed
    cleanly to host — never an inline compile stall on the live path."""

    def __init__(
        self,
        store,
        cfg: "ServingConfig",
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.store = store
        self.cfg = cfg
        self._clock = clock
        self.heat = HeatTracker(
            cfg.tier_half_life_seconds, cfg.tier_bulk_weight, clock
        )
        self.host_cache: HostShardCache | None = None
        if cfg.tier_host_cache_mb > 0:
            self.host_cache = HostShardCache(cfg.tier_host_cache_mb << 20)
        # attach the host tier to every mounted (and future) EC volume
        # so its reads serve from RAM without the controller on the path
        store.set_ec_host_cache(self.host_cache)
        self.qos = None  # serving/qos.QosController | None
        self._lock = threading.Lock()  # rebalance is single-flight
        self._resident_since: dict[int, float] = {}
        # vid -> monotonic time of the last FAILED promotion pin
        # (unreadable shard file, claim lost): backed off so one broken
        # hot volume cannot demote a healthy resident every cycle
        self._promote_failed_at: dict[int, float] = {}
        # cumulative ladder counters (heartbeat telemetry + /metrics)
        self.promotions = {TIER_HBM: 0, TIER_HOST: 0}
        self.demotions = {TIER_HBM: 0, TIER_HOST: 0}
        # last rebalance's tier census (telemetry reads this instead of
        # re-scanning the store per heartbeat pulse)
        self.last_sizes = {TIER_HBM: 0, TIER_HOST: 0, TIER_DISK: 0}

    # ------------------------------------------------------------- signals

    def note_read(self, vid: int, tier: str = "") -> None:
        """The dispatcher's per-read heat feed (every EC read, batched
        or native, before routing)."""
        self.heat.note(vid, tier)

    def attach_qos(self, qos) -> None:
        """Wire the QoS controller so overload (any open breaker)
        freezes ladder swaps for the cycle."""
        self.qos = qos

    def _qos_storm(self) -> bool:
        q = self.qos
        if q is None:
            return False
        try:
            return any(
                q.breaker_state(t) != 0 for t in q.policies
            )
        except Exception:  # noqa: BLE001 — a QoS probe failure must
            # never stall the ladder; treat as calm
            return False

    # ------------------------------------------------------------ census

    def _volumes(self) -> tuple[dict[int, object], dict[int, tuple[int, int]]]:
        """(vid -> EcVolume, vid -> (local shard count, shard size)) for
        every locally mounted EC volume (first location wins, matching
        Store.find_ec_volume's resolution).  BOTH snapshots are taken
        under the store lock: mount/unmount RPCs mutate the ec_volumes
        dicts AND each volume's shards dict under it, so the sizing
        arithmetic below must never iterate them live from the tier
        thread (the same convention Store.set_ec_host_cache follows)."""
        out: dict[int, object] = {}
        meta: dict[int, tuple[int, int]] = {}
        with self.store._lock:
            for loc in self.store.locations:
                for vid, ev in loc.ec_volumes.items():
                    if vid in out:
                        continue
                    out[vid] = ev
                    shards = list(ev.shards.values())
                    meta[vid] = (
                        len(shards),
                        shards[0].size if shards else 0,
                    )
        return out, meta

    @staticmethod
    def _pin_need(cache, vid: int, meta: tuple[int, int]) -> dict[int, int]:
        """device -> padded bytes a full pin of `vid` would add,
        previewing the cache's placement rule (mesh-sharded volumes
        split evenly, small ones land whole on the least-loaded device
        — unless `vid` still holds a placement claim, which the pin
        will follow): the budget-fit arithmetic promotions and
        pressure demotions share.  Empty dict = nothing to pin
        (unknown sizing)."""
        n, shard_size = meta
        if not n or not shard_size:
            return {}
        return cache.plan_pin(n, shard_size, vid=vid)

    @staticmethod
    def _fits(cache, need: dict[int, int], freed: dict[int, int]) -> bool:
        """Would `need` fit every device it lands on, after `freed`
        bytes per device are released?  Judged against the PER-DEVICE
        budget (r19): an aggregate-fits answer would still overflow the
        one chip a whole-pin lands on and hand eviction back to the
        blind per-shard LRU."""
        if not need:
            return False
        budget = cache.device_budget
        stats = cache.device_stats()
        return all(
            stats[d]["used_bytes"] - freed.get(d, 0) + add <= budget
            for d, add in need.items()
        )

    def tier_of(self, vid: int) -> str:
        """Delegates to Store.ec_volume_tier — ONE home for the
        hbm/host/disk classification (the controller's host cache IS
        store.ec_host_cache, attached in __init__), so the read-routing
        view and the ladder's view can never drift."""
        return self.store.ec_volume_tier(vid)

    # ------------------------------------------------------------- moves

    def _promote_hbm(self, ev, now: float) -> bool:
        """Pin `ev` into the device cache (host-cache bytes first, disk
        otherwise) and re-arm its AOT warm plan from the observed-shape
        ranking — stall-free promotion is the contract the bench's
        `promotion_stall_free` verdict checks."""
        cache = self.store.ec_device_cache
        try:
            n = ev.load_shards_to_device(cache)
        except Exception:  # noqa: BLE001 — an unreadable shard file
            # must not kill the tier loop; the volume stays where it was
            log.exception("tier promotion failed for volume %d", ev.id)
            cache.release_pin_source(ev.id, ev.dir)
            self._promote_failed_at[ev.id] = now
            return False
        if not n and not cache.resident_count(ev.id):
            self._promote_failed_at[ev.id] = now
            return False
        self._promote_failed_at.pop(ev.id, None)
        from ..ops import rs_resident

        # r11 AOT pre-warm, observed-buckets-first (the persisted
        # observed_shapes.json ranking): queued on the background
        # executor so the tier loop never blocks on a 20-40s compile,
        # while the armed shed keeps any still-cold shape off the live
        # path (host reconstruct) until its executable lands
        rs_resident.warm(
            cache, ev.id,
            sizes=cache.warm_sizes, counts=cache.warm_counts,
            aot=cache.shed_cold, wait=False,
        )
        self._resident_since[ev.id] = now
        self.promotions[TIER_HBM] += 1
        stats_metrics.VOLUME_SERVER_EC_TIER_PROMOTIONS.labels(
            tier=TIER_HBM
        ).inc()
        obs_incident.record(
            "tier_promote", vid=ev.id, tier=TIER_HBM, shards=n
        )
        return True

    def _demote_hbm(self, ev, stage: bool = True) -> None:
        """Release a volume's device residency through the claim/evict
        release path (the one the viewguard eviction races pin down:
        in-flight zero-copy reads stay byte-exact or fail a clean
        CacheMiss).  Shard bytes are staged host-side FIRST so a warm
        demotion never opens a window where the volume serves from
        neither RAM tier; `stage=False` skips that for heat-0 victims —
        a cold demotion must not pay a whole-volume disk read for bytes
        the same cycle's host fill would immediately evict."""
        cache = self.store.ec_device_cache
        if stage and self.host_cache is not None:
            self._stage_host(ev)
        cache.evict(ev.id)
        self._resident_since.pop(ev.id, None)
        self.demotions[TIER_HBM] += 1
        stats_metrics.VOLUME_SERVER_EC_TIER_DEMOTIONS.labels(
            tier=TIER_HBM
        ).inc()
        obs_incident.record(
            "tier_demote", vid=ev.id, tier=TIER_HBM, staged_host=stage
        )

    def _stage_host(self, ev) -> bool:
        hc = self.host_cache
        if hc is None:
            return False
        from ..storage.ec.layout import DATA_SHARDS

        if hc.resident_count(ev.id) >= DATA_SHARDS:
            return True  # already staged
        # budget pre-check BEFORE the whole-volume disk read: a full
        # host tier (the steady state) must not cost a multi-MB/GB
        # stage that put_volume then rejects and throws away
        snap = list(ev.shards.values())
        est = len(snap) * (snap[0].size if snap else 0)
        if not est or (
            hc.bytes_used - hc.volume_bytes(ev.id) + est > hc.budget
        ):
            return False
        try:
            shards = ev.stage_host_shards()
        except OSError:
            log.exception("host-tier staging failed for volume %d", ev.id)
            return False
        if len(shards) < DATA_SHARDS:
            return False
        if hc.put_volume(ev.id, shards):
            self.promotions[TIER_HOST] += 1
            stats_metrics.VOLUME_SERVER_EC_TIER_PROMOTIONS.labels(
                tier=TIER_HOST
            ).inc()
            obs_incident.record(
                "tier_promote", vid=ev.id, tier=TIER_HOST
            )
            return True
        return False

    def _evict_host(self, vid: int) -> None:
        if self.host_cache is not None and self.host_cache.evict(vid):
            self.demotions[TIER_HOST] += 1
            stats_metrics.VOLUME_SERVER_EC_TIER_DEMOTIONS.labels(
                tier=TIER_HOST
            ).inc()
            obs_incident.record("tier_demote", vid=vid, tier=TIER_HOST)

    # ---------------------------------------------------------- rebalance

    def rebalance(self, now: float | None = None) -> list[tuple[str, int]]:
        """One ladder cycle; returns the moves made as (kind, vid)
        tuples — kinds: promote_hbm, demote_hbm, stage_host,
        evict_host."""
        cache = self.store.ec_device_cache
        if cache is None or not self.cfg.tier:
            return []
        self.heat.prune(now)  # bound the tracked-vid set (probe traffic)
        with self._lock:
            return self._rebalance_locked(
                cache, self._clock() if now is None else now
            )

    def _rebalance_locked(self, cache, now: float) -> list[tuple[str, int]]:
        from ..storage.ec.layout import DATA_SHARDS

        cfg = self.cfg
        vols, meta = self._volumes()
        heat = self.heat.snapshot(now)
        moves: list[tuple[str, int]] = []

        def resident(vid: int) -> bool:
            return cache.resident_count(vid) >= DATA_SHARDS

        # volumes resident before this controller existed (mount-time
        # pin threads) enter the hysteresis clock on first sight
        for vid in vols:
            if resident(vid):
                self._resident_since.setdefault(vid, now)
            else:
                self._resident_since.pop(vid, None)

        def age_ok(vid: int) -> bool:
            return (
                now - self._resident_since.get(vid, now)
                >= cfg.tier_min_residency_seconds
            )

        # r20 host-aware ladder: on a multi-process mesh, mesh-sharded
        # volumes are SPMD-coupled — every pod member holds one lane of
        # the same global array, so a heat-driven LOCAL demotion (heat
        # is per-host read traffic, which differs across members) would
        # strand the other hosts' lanes and deadlock the next
        # collective.  Those vids demote only through the deterministic
        # put-order eviction partition inside DeviceShardCache; the
        # ladder keeps full authority over whole-device pins and every
        # volume in single-process mode.
        multiproc = bool(getattr(cache, "multiprocess", False))

        def demotable(vid: int) -> bool:
            return not (multiproc and cache.vid_sharded(vid))

        # 1. PRESSURE: any device over ITS budget -> demote coldest
        # residents actually HOLDING bytes on the fullest over-budget
        # device (r19 per-device accounting: demoting a volume parked
        # on an idle chip frees nothing where the pressure is).
        # Ignores the min-residency floor: staying over budget would
        # hand control back to the blind per-shard LRU eviction in
        # DeviceShardCache.put.
        def hbm_residents() -> list[int]:
            return [vid for vid in vols if resident(vid)]

        while True:
            pressure = cache.pressure_devices()
            if not pressure:
                break
            dev = pressure[0]  # fullest first
            # one locked footprint snapshot per demotion round (a
            # per-volume vid_device_bytes probe would rescan the whole
            # map under the serving-path lock once per resident)
            foot = cache.device_bytes_by_vid()

            def on_dev(v: int) -> bool:
                return bool(foot.get(v, {}).get(dev))

            pool = [
                v for v in hbm_residents() if on_dev(v) and demotable(v)
            ]
            if not pool:
                # partial shard sets (mount pins racing the LRU, or a
                # budget shrink mid-pin) hold device bytes without ever
                # serving a reconstruct: under pressure they are pure
                # waste — evict them too, or the orphaned bytes block
                # every future promotion forever
                pool = [
                    v
                    for v in vols
                    if cache.resident_count(v) > 0
                    and on_dev(v)
                    and demotable(v)
                ]
            if not pool:
                break
            vid = min(pool, key=lambda v: (heat.get(v, 0.0), v))
            # heat-0 victims skip host staging: nobody reads them, and
            # the stage would be a wasted whole-volume disk read this
            # same cycle's host fill evicts again
            self._demote_hbm(vols[vid], stage=heat.get(vid, 0.0) > 0.0)
            moves.append(("demote_hbm", vid))
            if len(moves) >= 2 * MAX_MOVES_PER_CYCLE:
                break  # pathological budget shrink: finish next cycle

        # 2. PROMOTE hottest non-resident volumes
        storm = self._qos_storm()
        candidates = sorted(
            (vid for vid in vols if not resident(vid)),
            key=lambda v: (-heat.get(v, 0.0), v),
        )
        for vid in candidates:
            if len(moves) >= MAX_MOVES_PER_CYCLE:
                break
            h = heat.get(vid, 0.0)
            if h <= 0.0:
                break  # never promote a volume nobody reads
            if (
                now - self._promote_failed_at.get(vid, float("-inf"))
                < PROMOTE_FAILURE_BACKOFF_S
            ):
                continue  # recent pin failure: don't burn a victim on it
            need = self._pin_need(cache, vid, meta[vid])
            if not need:
                continue
            if self._fits(cache, need, {}):
                if self._promote_hbm(vols[vid], now):
                    moves.append(("promote_hbm", vid))
                continue
            if storm:
                # overload: no swap churn while breakers are open — but
                # a COLDER candidate that fits the free budget may
                # still promote, so keep scanning instead of breaking
                continue
            # collect enough eligible victims (coldest first, each one
            # beaten by promote_ratio — hysteresis: the demotion
            # threshold sits promote_ratio below the promotion
            # threshold, so equally hot volumes never flap) to actually
            # FIT the candidate before demoting anything: a one-victim
            # swap that still overflowed would hand eviction back to
            # the blind per-shard LRU in DeviceShardCache.put.  Only
            # volumes holding bytes on a device the candidate still
            # lacks headroom on count (r19): demoting a resident parked
            # on an idle chip frees nothing where the pin lands, loses
            # its HBM residency for nothing, and can exhaust the victim
            # cap before a useful victim is ever reached.
            budget = cache.device_budget

            def still_tight(freed: dict[int, int]) -> set[int]:
                stats = cache.device_stats()
                return {
                    d
                    for d, add in need.items()
                    if stats[d]["used_bytes"] - freed.get(d, 0) + add
                    > budget
                }

            victims: list[int] = []
            freed: dict[int, int] = {}
            # one locked footprint snapshot for the whole victim scan
            foot = cache.device_bytes_by_vid()
            for v in sorted(
                (v for v in hbm_residents() if age_ok(v) and demotable(v)),
                key=lambda v: (heat.get(v, 0.0), v),
            ):
                if h < cfg.tier_promote_ratio * max(
                    heat.get(v, 0.0), 1e-9
                ) or len(victims) >= MAX_SWAP_VICTIMS:
                    break  # remaining victims are hotter still / capped
                # freed = bytes the victim ACTUALLY holds per device —
                # a partially resident victim frees less than a full
                # pin's estimate, and bytes freed on an idle chip do
                # not make room where the candidate lands
                held = foot.get(v, {})
                tight = still_tight(freed)
                if not tight & held.keys():
                    continue  # holds nothing where room is still needed
                victims.append(v)
                for d, b in held.items():
                    freed[d] = freed.get(d, 0) + b
                if self._fits(cache, need, freed):
                    break
            if not victims or not self._fits(cache, need, freed):
                # cannot fit THIS candidate without demoting something
                # too hot — but a colder, smaller candidate further down
                # may still fit the free budget, so keep scanning (the
                # same reasoning as the storm branch above)
                continue
            for v in victims:
                self._demote_hbm(vols[v], stage=heat.get(v, 0.0) > 0.0)
                moves.append(("demote_hbm", v))
            if self._promote_hbm(vols[vid], now):
                moves.append(("promote_hbm", vid))

        # 3. HOST FILL: warmest non-HBM volumes hold the host budget.
        # Still-warm HBM volumes KEEP their host copy (a later pressure
        # demotion then costs no re-stage), accounted against the
        # budget first; everything else not in the desired warm set is
        # evicted so cold entries never squat on the RAM a warmer
        # volume needs.
        hc = self.host_cache
        if hc is not None:
            keep: set[int] = {
                vid
                for vid in hc.vids()
                if vid in vols
                and resident(vid)
                and heat.get(vid, 0.0) > 0.0
            }
            acc = sum(hc.volume_bytes(vid) for vid in keep)
            desired: set[int] = set()
            for vid in sorted(
                (v for v in vols if not resident(v)),
                key=lambda v: (-heat.get(v, 0.0), v),
            ):
                if heat.get(vid, 0.0) <= 0.0:
                    break
                n_shards, shard_size = meta[vid]
                size = hc.volume_bytes(vid) or n_shards * shard_size
                if not size or acc + size > hc.budget:
                    continue
                desired.add(vid)
                acc += size
            for vid in hc.vids():
                if vid not in desired and vid not in keep:
                    self._evict_host(vid)
                    moves.append(("evict_host", vid))
            for vid in desired:
                if hc.resident_count(vid) < DATA_SHARDS:
                    if self._stage_host(vols[vid]):
                        moves.append(("stage_host", vid))

        # census for telemetry + gauges (cheap: reuses this cycle's scan)
        sizes = {TIER_HBM: 0, TIER_HOST: 0, TIER_DISK: 0}
        for vid in vols:
            sizes[self.tier_of(vid)] += 1
        self.last_sizes = sizes
        for tier in TIERS:
            stats_metrics.VOLUME_SERVER_EC_TIER_VOLUMES.labels(
                tier=tier
            ).set(sizes[tier])
        return moves

    # ------------------------------------------------------------- status

    def status(self) -> dict:
        """The volume.tier.status / telemetry view: per-tier census,
        cumulative ladder counters, host-tier occupancy, and the decayed
        heat ranking."""
        hc = self.host_cache
        return {
            "tiers": dict(self.last_sizes),
            "promotions": dict(self.promotions),
            "demotions": dict(self.demotions),
            "host_bytes": hc.bytes_used if hc is not None else 0,
            "host_budget_bytes": hc.budget if hc is not None else 0,
            "heat": {
                vid: round(h, 3)
                for vid, h in sorted(
                    self.heat.snapshot().items(), key=lambda kv: -kv[1]
                )
            },
        }
