"""Admin shell (reference: weed/shell/, 12.5k LoC).

`weed shell` REPL equivalent: commands registered in commands.COMMANDS,
executed against a CommandEnv holding master stubs + the exclusive admin
lock.  Usable programmatically (the tests and the CLI both call
run_command) or interactively via repl().
"""
from .command_env import CommandEnv, TopoNode
from .commands import COMMANDS, run_command

__all__ = ["CommandEnv", "TopoNode", "COMMANDS", "run_command", "repl"]


async def repl(masters: list[str]) -> None:
    """Interactive loop (shell_liner.go:28)."""
    import asyncio
    import sys

    env = CommandEnv(masters)
    env.write("seaweedfs-tpu shell; 'help' lists commands, Ctrl-D exits")
    while True:
        sys.stdout.write("> ")
        sys.stdout.flush()
        line = await asyncio.to_thread(sys.stdin.readline)
        if not line:
            break
        try:
            await run_command(env, line)
        except Exception as e:  # noqa: BLE001 — REPL survives command errors
            env.write(f"error: {e}")
    await env.release_lock()
