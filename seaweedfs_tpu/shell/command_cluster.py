"""cluster.* commands (reference: weed/shell/command_cluster_ps.go etc.)."""
import grpc

from ..pb import master_pb2
from .commands import command, parse_flags


@command("cluster.ps")
async def cmd_cluster_ps(env, args):
    """list masters, filers/clients, and volume servers
    (command_cluster_ps.go)"""
    nodes, limit_mb = await env.collect_topology()
    env.write(f"masters: {', '.join(env.masters)}")
    try:
        resp = await env.master_stub.ListClusterNodes(
            master_pb2.ListClusterNodesRequest()
        )
        by_type: dict[str, list[str]] = {}
        for cn in resp.cluster_nodes:
            by_type.setdefault(cn.client_type, []).append(cn.address)
        for ctype in sorted(by_type):
            env.write(f"{ctype}s: {', '.join(sorted(by_type[ctype]))}")
    except grpc.RpcError as e:
        # older masters lack the RPC; anything else is worth surfacing
        if e.code() != grpc.StatusCode.UNIMPLEMENTED:
            env.write(f"cluster node listing failed: {e.code()}")
    env.write(f"volume size limit: {limit_mb} MB")
    for n in nodes:
        env.write(
            f"  {n.data_center}/{n.rack}/{n.url}"
            f"  volumes={len(n.volumes)} ec_vols={len(n.ec_shards)}"
            f" free_slots={n.free_slots()}"
        )


@command("cluster.raft.ps")
async def cmd_cluster_raft_ps(env, args):
    """list raft cluster servers (command_cluster_raft_ps.go)"""
    resp = await env.master_stub.RaftListClusterServers(
        master_pb2.RaftListClusterServersRequest()
    )
    env.write(f"term: {resp.term}")
    for s in resp.cluster_servers:
        env.write(f"  {s.id}{'  leader' if s.is_leader else ''}")


@command("cluster.raft.add")
async def cmd_cluster_raft_add(env, args):
    """-id <raft grpc addr> : add a master to the raft cluster
    (command_cluster_raft_add.go).  Start the new master with -peers
    including the existing members, then add it here."""
    env.confirm_is_locked()
    flags = parse_flags(args)
    await env.master_stub.RaftAddServer(
        master_pb2.RaftAddServerRequest(id=flags["id"])
    )
    env.write(f"added raft server {flags['id']}")


@command("cluster.raft.remove")
async def cmd_cluster_raft_remove(env, args):
    """-id <raft grpc addr> : remove a master from the raft cluster
    (command_cluster_raft_remove.go)"""
    env.confirm_is_locked()
    flags = parse_flags(args)
    await env.master_stub.RaftRemoveServer(
        master_pb2.RaftRemoveServerRequest(id=flags["id"])
    )
    env.write(f"removed raft server {flags['id']}")


@command("cluster.check")
async def cmd_cluster_check(env, args):
    """sanity-check cluster connectivity (master + every volume server)"""
    from ..pb import volume_server_pb2

    nodes, _ = await env.collect_topology()
    ok = 0
    for n in nodes:
        try:
            await env.volume_stub(n.grpc_address).VolumeServerStatus(
                volume_server_pb2.VolumeServerStatusRequest()
            )
            ok += 1
        except Exception as e:  # noqa: BLE001
            env.write(f"  {n.url}: UNREACHABLE ({e})")
    env.write(f"{ok}/{len(nodes)} volume servers reachable")
