"""cluster.* commands (reference: weed/shell/command_cluster_ps.go etc.)."""
import json

import grpc

from ..pb import master_pb2, server_address
from .commands import command, parse_flags


async def fetch_cluster_health(env) -> dict:
    """GET /cluster/health.json from the master's HTTP port (shared by
    cluster.health and volume.device.status)."""
    import aiohttp

    url = f"http://{server_address.http_address(env.masters[0])}/cluster/health.json"
    async with aiohttp.ClientSession() as sess:
        async with sess.get(url, allow_redirects=True) as r:
            if r.status != 200:
                raise ValueError(f"{url} returned HTTP {r.status}")
            return await r.json()


def fmt_bytes(n: int) -> str:
    # one shell-wide byte formatter (fs.ls uses the same one)
    from .command_fs import _fmt_size

    return _fmt_size(n)


@command("cluster.ps")
async def cmd_cluster_ps(env, args):
    """list masters, filers/clients, and volume servers
    (command_cluster_ps.go)"""
    nodes, limit_mb = await env.collect_topology()
    env.write(f"masters: {', '.join(env.masters)}")
    try:
        resp = await env.master_stub.ListClusterNodes(
            master_pb2.ListClusterNodesRequest()
        )
        by_type: dict[str, list[str]] = {}
        for cn in resp.cluster_nodes:
            by_type.setdefault(cn.client_type, []).append(cn.address)
        for ctype in sorted(by_type):
            env.write(f"{ctype}s: {', '.join(sorted(by_type[ctype]))}")
    except grpc.RpcError as e:
        # older masters lack the RPC; anything else is worth surfacing
        if e.code() != grpc.StatusCode.UNIMPLEMENTED:
            env.write(f"cluster node listing failed: {e.code()}")
    env.write(f"volume size limit: {limit_mb} MB")
    for n in nodes:
        env.write(
            f"  {n.data_center}/{n.rack}/{n.url}"
            f"  volumes={len(n.volumes)} ec_vols={len(n.ec_shards)}"
            f" free_slots={n.free_slots()}"
        )


@command("cluster.raft.ps")
async def cmd_cluster_raft_ps(env, args):
    """list raft cluster servers (command_cluster_raft_ps.go)"""
    resp = await env.master_stub.RaftListClusterServers(
        master_pb2.RaftListClusterServersRequest()
    )
    env.write(f"term: {resp.term}")
    for s in resp.cluster_servers:
        env.write(f"  {s.id}{'  leader' if s.is_leader else ''}")


@command("cluster.raft.add")
async def cmd_cluster_raft_add(env, args):
    """-id <raft grpc addr> : add a master to the raft cluster
    (command_cluster_raft_add.go).  Start the new master with -peers
    including the existing members, then add it here."""
    env.confirm_is_locked()
    flags = parse_flags(args)
    await env.master_stub.RaftAddServer(
        master_pb2.RaftAddServerRequest(id=flags["id"])
    )
    env.write(f"added raft server {flags['id']}")


@command("cluster.raft.remove")
async def cmd_cluster_raft_remove(env, args):
    """-id <raft grpc addr> : remove a master from the raft cluster
    (command_cluster_raft_remove.go)"""
    env.confirm_is_locked()
    flags = parse_flags(args)
    await env.master_stub.RaftRemoveServer(
        master_pb2.RaftRemoveServerRequest(id=flags["id"])
    )
    env.write(f"removed raft server {flags['id']}")


@command("cluster.health")
async def cmd_cluster_health(env, args):
    """[-json] : aggregated cluster health from heartbeat telemetry —
    per-node freshness (stale after 2 missed pulses), device HBM
    used/budget/headroom, dispatcher queue/occupancy/shed, EC residency
    map, and merged per-stage p50/p99 latency estimates"""
    flags = parse_flags(args)
    health = await fetch_cluster_health(env)
    if "json" in flags:
        env.write(json.dumps(health, indent=2, sort_keys=True))
        return
    cluster = health["cluster"]
    env.write(
        f"nodes: {cluster['nodes_total']} "
        f"({cluster['nodes_stale']} stale; stale after "
        f"{health['stale_after_seconds']:.1f}s without a heartbeat)"
    )
    env.write(
        "  {:<22} {:>7} {:>6} {:>20} {:>6} {:>9} {:>7} {:>8}".format(
            "node", "age_s", "stale", "hbm used/budget", "queue",
            "inflight", "shed", "overlap"
        )
    )
    for url, n in health["nodes"].items():
        dev = n.get("device", {})
        disp = n.get("dispatcher", {})
        hbm = (
            f"{fmt_bytes(dev['used_bytes'])}/{fmt_bytes(dev['budget_bytes'])}"
            if dev else "-"
        )
        ov = disp.get("overlap_fraction")
        env.write(
            "  {:<22} {:>7.1f} {:>6} {:>20} {:>6} {:>9} {:>7} {:>8}".format(
                url, n["age_seconds"], "YES" if n["stale"] else "no",
                hbm, disp.get("queue_depth", "-"),
                disp.get("inflight", "-"), disp.get("shed_total", "-"),
                # >1 means the double-buffer's staging slots overlapped
                f"{ov:.2f}" if isinstance(ov, (int, float)) else "-",
            )
        )
    residency = cluster.get("ec_volume_residency", {})
    if residency:
        env.write("ec residency (vid: node=shards):")
        for vid, by_node in residency.items():
            env.write(
                f"  {vid}: "
                + " ".join(f"{u}={c}" for u, c in by_node.items())
            )
    stages = cluster.get("stages", {})
    if stages:
        env.write("stage latency estimates (merged digests):")

        def us(v):  # the schema allows null quantiles (empty buckets)
            return "-" if v is None else f"{v * 1e6:.1f}us"

        for stage, s in stages.items():
            env.write(
                f"  {stage:<18} n={s['count']:<8} "
                f"p50={us(s['p50_seconds'])} p99={us(s['p99_seconds'])}"
                + (f" (+{s['overflow']} overflow)" if s["overflow"] else "")
            )


@command("cluster.check")
async def cmd_cluster_check(env, args):
    """sanity-check cluster connectivity (master + every volume server)"""
    from ..pb import volume_server_pb2

    nodes, _ = await env.collect_topology()
    ok = 0
    for n in nodes:
        try:
            await env.volume_stub(n.grpc_address).VolumeServerStatus(
                volume_server_pb2.VolumeServerStatusRequest()
            )
            ok += 1
        except Exception as e:  # noqa: BLE001
            env.write(f"  {n.url}: UNREACHABLE ({e})")
    env.write(f"{ok}/{len(nodes)} volume servers reachable")
