"""cluster.* commands (reference: weed/shell/command_cluster_ps.go etc.)."""
import json

import grpc

from ..pb import master_pb2, server_address
from .commands import command, parse_flags


async def fetch_cluster_health(env) -> dict:
    """GET /cluster/health.json from the master's HTTP port (shared by
    cluster.health and volume.device.status)."""
    import aiohttp

    url = f"http://{server_address.http_address(env.masters[0])}/cluster/health.json"
    async with aiohttp.ClientSession() as sess:
        async with sess.get(url, allow_redirects=True) as r:
            if r.status != 200:
                raise ValueError(f"{url} returned HTTP {r.status}")
            return await r.json()


def fmt_bytes(n: int) -> str:
    # one shell-wide byte formatter (fs.ls uses the same one)
    from .command_fs import _fmt_size

    return _fmt_size(n)


@command("cluster.ps")
async def cmd_cluster_ps(env, args):
    """list masters, filers/clients, and volume servers
    (command_cluster_ps.go)"""
    nodes, limit_mb = await env.collect_topology()
    env.write(f"masters: {', '.join(env.masters)}")
    try:
        resp = await env.master_stub.ListClusterNodes(
            master_pb2.ListClusterNodesRequest()
        )
        by_type: dict[str, list[str]] = {}
        for cn in resp.cluster_nodes:
            by_type.setdefault(cn.client_type, []).append(cn.address)
        for ctype in sorted(by_type):
            env.write(f"{ctype}s: {', '.join(sorted(by_type[ctype]))}")
    except grpc.RpcError as e:
        # older masters lack the RPC; anything else is worth surfacing
        if e.code() != grpc.StatusCode.UNIMPLEMENTED:
            env.write(f"cluster node listing failed: {e.code()}")
    env.write(f"volume size limit: {limit_mb} MB")
    for n in nodes:
        env.write(
            f"  {n.data_center}/{n.rack}/{n.url}"
            f"  volumes={len(n.volumes)} ec_vols={len(n.ec_shards)}"
            f" free_slots={n.free_slots()}"
        )


@command("cluster.raft.ps")
async def cmd_cluster_raft_ps(env, args):
    """list raft cluster servers (command_cluster_raft_ps.go)"""
    resp = await env.master_stub.RaftListClusterServers(
        master_pb2.RaftListClusterServersRequest()
    )
    env.write(f"term: {resp.term}")
    for s in resp.cluster_servers:
        env.write(f"  {s.id}{'  leader' if s.is_leader else ''}")


@command("cluster.raft.add")
async def cmd_cluster_raft_add(env, args):
    """-id <raft grpc addr> : add a master to the raft cluster
    (command_cluster_raft_add.go).  Start the new master with -peers
    including the existing members, then add it here."""
    env.confirm_is_locked()
    flags = parse_flags(args)
    await env.master_stub.RaftAddServer(
        master_pb2.RaftAddServerRequest(id=flags["id"])
    )
    env.write(f"added raft server {flags['id']}")


@command("cluster.raft.remove")
async def cmd_cluster_raft_remove(env, args):
    """-id <raft grpc addr> : remove a master from the raft cluster
    (command_cluster_raft_remove.go)"""
    env.confirm_is_locked()
    flags = parse_flags(args)
    await env.master_stub.RaftRemoveServer(
        master_pb2.RaftRemoveServerRequest(id=flags["id"])
    )
    env.write(f"removed raft server {flags['id']}")


@command("cluster.health")
async def cmd_cluster_health(env, args):
    """[-json] : aggregated cluster health from heartbeat telemetry —
    per-node freshness (stale after 2 missed pulses), device HBM
    used/budget/headroom, dispatcher queue/occupancy/shed, EC residency
    map, and merged per-stage p50/p99 latency estimates"""
    flags = parse_flags(args)
    health = await fetch_cluster_health(env)
    if "json" in flags:
        env.write(json.dumps(health, indent=2, sort_keys=True))
        return
    cluster = health["cluster"]
    env.write(
        f"nodes: {cluster['nodes_total']} "
        f"({cluster['nodes_stale']} stale; stale after "
        f"{health['stale_after_seconds']:.1f}s without a heartbeat)"
    )
    env.write(
        "  {:<22} {:>7} {:>6} {:>20} {:>6} {:>9} {:>7} {:>8}".format(
            "node", "age_s", "stale", "hbm used/budget", "queue",
            "inflight", "shed", "overlap"
        )
    )
    for url, n in health["nodes"].items():
        dev = n.get("device", {})
        disp = n.get("dispatcher", {})
        hbm = (
            f"{fmt_bytes(dev['used_bytes'])}/{fmt_bytes(dev['budget_bytes'])}"
            if dev else "-"
        )
        ov = disp.get("overlap_fraction")
        env.write(
            "  {:<22} {:>7.1f} {:>6} {:>20} {:>6} {:>9} {:>7} {:>8}".format(
                url, n["age_seconds"], "YES" if n["stale"] else "no",
                hbm, disp.get("queue_depth", "-"),
                disp.get("inflight", "-"), disp.get("shed_total", "-"),
                # >1 means the double-buffer's staging slots overlapped
                f"{ov:.2f}" if isinstance(ov, (int, float)) else "-",
            )
        )
    residency = cluster.get("ec_volume_residency", {})
    if residency:
        env.write("ec residency (vid: node=shards):")
        for vid, by_node in residency.items():
            env.write(
                f"  {vid}: "
                + " ".join(f"{u}={c}" for u, c in by_node.items())
            )
    stages = cluster.get("stages", {})
    if stages:
        env.write("stage latency estimates (merged digests):")

        def us(v):  # the schema allows null quantiles (empty buckets)
            return "-" if v is None else f"{v * 1e6:.1f}us"

        for stage, s in stages.items():
            env.write(
                f"  {stage:<18} n={s['count']:<8} "
                f"p50={us(s['p50_seconds'])} p99={us(s['p99_seconds'])}"
                + (f" (+{s['overflow']} overflow)" if s["overflow"] else "")
            )


@command("cluster.slo")
async def cmd_cluster_slo(env, args):
    """[-json] : declared SLOs and their live burn state from the
    master's SLO engine — per-objective fast/slow burn rates, budget
    remaining, violation counts, and the latency objective's windowed
    p99 estimate (obs/slo.py)"""
    flags = parse_flags(args)
    health = await fetch_cluster_health(env)
    slo = health.get("slo") or {}
    if "json" in flags:
        env.write(json.dumps(slo, indent=2, sort_keys=True))
        return
    objectives = slo.get("objectives") or {}
    if not slo.get("enabled", False) or not objectives:
        env.write(
            "no SLOs declared (set -obs.slo.readP99Ms / "
            "-obs.slo.errorRatePct / -obs.slo.timeToHealthySeconds / "
            "-obs.slo.breakerOpenPct on the master)"
        )
        return
    env.write(
        f"windows: fast={slo['fast_window_seconds']:.0f}s "
        f"slow={slo['slow_window_seconds']:.0f}s "
        f"threshold={slo['burn_threshold']}"
    )
    env.write(
        "  {:<16} {:>10} {:>10} {:>10} {:>8} {:>10} {:>6}".format(
            "slo", "target", "fast_burn", "slow_burn", "budget",
            "violations", "state"
        )
    )
    for name, o in objectives.items():
        target = o["target"]
        target_s = (
            f"{target * 1e3:.1f}ms" if name == "read_p99"
            else f"{target:.0f}s" if name == "time_to_healthy"
            else f"{target * 100:.2f}%"
        )
        env.write(
            "  {:<16} {:>10} {:>10.2f} {:>10.2f} {:>7.0%} {:>10} {:>6}".format(
                name, target_s, o["fast_burn"], o["slow_burn"],
                o["budget_remaining"], o["violations_total"],
                "BURN" if o["violating"] else "ok",
            )
        )
        if name == "read_p99" and o.get("window_p99_seconds") is not None:
            overflow = o.get("window_p99_overflow", 0)
            env.write(
                f"    window p99 ~{o['window_p99_seconds'] * 1e3:.2f}ms "
                f"(stage {o['stage']}"
                + (f"; +{overflow} overflow — estimate is a floor"
                   if overflow else "")
                + ")"
            )


@command("cluster.timeline")
async def cmd_cluster_timeline(env, args):
    """[-window <seconds>] [-json] : the cluster flight timeline —
    clock-aligned ~1s samples shipped in heartbeats from every node
    (per-workload device busy/dispatch deltas, QoS depth/shed/breaker,
    ingest pressure, resident bytes, slowest-trace exemplars)"""
    import aiohttp

    flags = parse_flags(args)
    url = (
        f"http://{server_address.http_address(env.masters[0])}"
        "/debug/timeline"
    )
    params = {}
    if flags.get("window"):
        params["window"] = flags["window"]
    async with aiohttp.ClientSession() as sess:
        async with sess.get(url, params=params, allow_redirects=True) as r:
            if r.status != 200:
                raise ValueError(f"{url} returned HTTP {r.status}")
            doc = await r.json()
    if "json" in flags:
        env.write(json.dumps(doc, indent=2, sort_keys=True))
        return
    samples = doc.get("samples", [])
    env.write(
        f"nodes: {', '.join(doc.get('nodes', [])) or '-'}  "
        f"samples: {len(samples)}"
        + (f"  window: {doc['window_seconds']:.0f}s"
           if doc.get("window_seconds") else "")
    )
    if not samples:
        env.write(
            "no samples yet (nodes ship one per heartbeat; check "
            "-obs.timeline.disable)"
        )
        return
    for row in samples:
        for node, s in sorted(row.get("nodes", {}).items()):
            busy = " ".join(
                f"{wl}={ms:.0f}ms"
                for wl, ms in sorted(s.get("busy_ms", {}).items())
            )
            qos = s.get("qos", {})
            shed = sum(qos.get("shed", {}).values())
            ingest = s.get("ingest", {})
            line = (
                f"  t={row['t']} {node}: "
                + (busy or "idle")
                + (f" qshed={shed}" if shed else "")
                + (f" ingest={fmt_bytes(ingest['bytes'])}"
                   if ingest.get("bytes") else "")
                + (f" backpressure={ingest['backpressure']}"
                   if ingest.get("backpressure") else "")
            )
            ex = s.get("exemplar")
            if ex:
                line += (
                    f"  [slowest {ex['name']} {ex['ms']:.1f}ms "
                    f"trace={ex['trace_id']} span={ex['span']}]"
                )
            env.write(line)


@command("cluster.tail")
async def cmd_cluster_tail(env, args):
    """[-limit N] [-json] : the cluster tail-forensics view — every
    node's /debug/tail (per-route latency stats + critical-path
    composition + pinned slow/incident traces) merged into one route
    table and a worst-offenders list; feed a pin's trace id to
    volume.trace.why for the assembled critical path"""
    import aiohttp

    flags = parse_flags(args)
    limit = int(flags.get("limit", 10))
    master = server_address.http_address(env.masters[0])
    async with aiohttp.ClientSession() as sess:
        async with sess.get(
            f"http://{master}/cluster/health.json", allow_redirects=True
        ) as r:
            if r.status != 200:
                raise ValueError(
                    f"{master}/cluster/health.json returned HTTP {r.status}"
                )
            health = await r.json()
        targets = [master] + sorted(health.get("nodes", {}))

        async def one(u):
            try:
                async with sess.get(
                    f"http://{u}/debug/tail",
                    timeout=aiohttp.ClientTimeout(total=2.5),
                ) as rr:
                    if rr.status != 200:
                        return u, None
                    return u, await rr.json()
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                return u, None

        docs = dict(await asyncio.gather(*(one(u) for u in targets)))
    if "json" in flags:
        env.write(json.dumps(docs, indent=2, sort_keys=True))
        return
    routes: dict = {}
    pins = []
    reached = 0
    for u, doc in sorted(docs.items()):
        if doc is None:
            continue
        reached += 1
        for route, st in doc.get("routes", {}).items():
            agg = routes.setdefault(
                route,
                {"count": 0, "total_s": 0.0, "pinned": 0, "seg_s": {}},
            )
            agg["count"] += st.get("count", 0)
            agg["total_s"] += st.get("total_s", 0.0)
            agg["pinned"] += st.get("pinned", 0)
            for seg, s in st.get("segments_s", {}).items():
                agg["seg_s"][seg] = agg["seg_s"].get(seg, 0.0) + s
        for p in doc.get("pinned", []):
            pins.append({**p, "node": u})
    env.write(f"tail view from {reached}/{len(targets)} nodes")
    for route, agg in sorted(
        routes.items(), key=lambda kv: -kv[1]["total_s"]
    ):
        total = agg["total_s"]
        comp = " ".join(
            f"{seg}={s * 100.0 / total:.0f}%"
            for seg, s in sorted(
                agg["seg_s"].items(), key=lambda kv: -kv[1]
            )
            if total > 0 and s > 0
        )
        env.write(
            f"  {route:<24} n={agg['count']:<6} {total:8.3f}s "
            f"pinned={agg['pinned']:<4} {comp}"
        )
    pins.sort(key=lambda p: -p.get("total_ms", 0.0))
    for p in pins[:limit]:
        env.write(
            f"  pin {p['trace_id']} {p.get('name', '?')} "
            f"{p.get('total_ms', 0):.1f}ms [{p.get('reason', '?')}] "
            f"@{p['node']}"
        )
    if not pins:
        env.write(
            "  no pinned traces yet (nothing beat its route's p99 "
            "estimate; check -obs.tail.disable / -obs.tail.floorMs)"
        )


@command("cluster.incident.dump")
async def cmd_cluster_incident_dump(env, args):
    """[-window <seconds>] [-json] : snapshot the cluster's flight
    recorders + trace rings into one incident bundle on the master
    (same fan-out an SLO violation triggers; needs -obs.incident.dir)"""
    import aiohttp

    flags = parse_flags(args)
    url = (
        f"http://{server_address.http_address(env.masters[0])}"
        "/cluster/incident/dump"
    )
    params = {}
    if flags.get("window"):
        params["window"] = flags["window"]
    async with aiohttp.ClientSession() as sess:
        async with sess.post(
            url, params=params, allow_redirects=True
        ) as r:
            payload = await r.json()
            if r.status != 200:
                raise ValueError(
                    payload.get("error", f"{url} returned HTTP {r.status}")
                )
    if "json" in flags:
        env.write(json.dumps(payload, indent=2, sort_keys=True))
        return
    corr = payload.get("correlation", {})
    env.write(f"incident bundle written: {payload['path']}")
    env.write(
        f"  nodes snapshotted: {len(payload.get('nodes', []))} "
        f"({corr.get('nodes_with_data', 0)} with data)"
    )
    multi = corr.get("trace_ids_multi_node", [])
    env.write(
        f"  trace ids seen on 2+ nodes: {len(multi)}"
        + (f" (e.g. {multi[0]})" if multi else "")
    )
    prof = payload.get("profile")
    if prof:
        env.write(
            f"  device profile: "
            + (prof.get("trace_dir") or f"failed ({prof.get('error')})")
            + f" on {prof.get('node')}"
        )


@command("cluster.check")
async def cmd_cluster_check(env, args):
    """sanity-check cluster connectivity (master + every volume server)"""
    from ..pb import volume_server_pb2

    nodes, _ = await env.collect_topology()
    ok = 0
    for n in nodes:
        try:
            await env.volume_stub(n.grpc_address).VolumeServerStatus(
                volume_server_pb2.VolumeServerStatusRequest()
            )
            ok += 1
        except Exception as e:  # noqa: BLE001
            env.write(f"  {n.url}: UNREACHABLE ({e})")
    env.write(f"{ok}/{len(nodes)} volume servers reachable")
