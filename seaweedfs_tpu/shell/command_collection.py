"""collection.* commands (reference: weed/shell/command_collection_*.go)."""
from ..pb import master_pb2
from .commands import command, parse_flags


@command("collection.list")
async def cmd_collection_list(env, args):
    """list collections"""
    resp = await env.master_stub.CollectionList(
        master_pb2.CollectionListRequest(
            include_normal_volumes=True, include_ec_volumes=True
        )
    )
    for c in resp.collections:
        env.write(f"  {c.name}")
    env.write(f"{len(resp.collections)} collections")


@command("collection.delete")
async def cmd_collection_delete(env, args):
    """-collection <name> : delete all volumes of a collection"""
    env.confirm_is_locked()
    flags = parse_flags(args)
    name = flags.get("collection", flags.get(""))
    if not name:
        raise ValueError("usage: collection.delete -collection <name>")
    await env.master_stub.CollectionDelete(
        master_pb2.CollectionDeleteRequest(name=name)
    )
    env.write(f"deleted collection {name}")
