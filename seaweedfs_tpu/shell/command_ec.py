"""ec.* commands: encode / rebuild / balance / decode orchestration.

Reference: weed/shell/command_ec_encode.go:57-269 (mark readonly →
generate → spread with balancedEcDistribution → delete original),
command_ec_rebuild.go:99-176, command_ec_balance.go, command_ec_decode.go,
command_ec_common.go:19-58 (moveMountedShardToEcNode).

The GF(256) math itself runs wherever VolumeEcShardsGenerate lands — on
the volume server's configured backend (TPU MXU kernels by default).
"""
from __future__ import annotations

from ..pb import master_pb2, volume_server_pb2
from ..storage.ec import TOTAL_SHARDS
from .command_env import CommandEnv, TopoNode
from .commands import command, parse_flags


def ec_nodes_by_freeness(nodes: list[TopoNode]) -> list[TopoNode]:
    return sorted(nodes, key=lambda n: n.free_slots(), reverse=True)


def node_shards(node: TopoNode, vid: int) -> list[int]:
    for s in node.ec_shards:
        if s["id"] == vid:
            return [i for i in range(TOTAL_SHARDS) if s["ec_index_bits"] >> i & 1]
    return []


def balanced_ec_distribution(nodes: list[TopoNode], n_shards: int = TOTAL_SHARDS):
    """Round-robin shards over nodes sorted by free slots
    (balancedEcDistribution command_ec_encode.go:253-269).  Returns
    [(node, [shard ids])]."""
    ranked = ec_nodes_by_freeness(nodes)
    if not ranked:
        return []
    alloc = {n.url: [] for n in ranked}
    free = {n.url: max(0, n.free_slots() * TOTAL_SHARDS) for n in ranked}
    i = 0
    for sid in range(n_shards):
        for _ in range(len(ranked)):
            n = ranked[i % len(ranked)]
            i += 1
            if free[n.url] > 0 or all(f <= 0 for f in free.values()):
                alloc[n.url].append(sid)
                free[n.url] -= 1
                break
    return [(n, alloc[n.url]) for n in ranked if alloc[n.url]]


async def spread_ec_shards(
    env: CommandEnv,
    vid: int,
    collection: str,
    source: TopoNode,
    targets: list[tuple[TopoNode, list[int]]],
) -> None:
    """Copy+mount each target's shard set from source, then unmount the
    moved shards at the source (parallelCopyEcShardsFromSource →
    unmountEcShards, command_ec_encode.go:145-188)."""
    first = True
    for node, shard_ids in targets:
        if node.url == source.url:
            first = False
            continue
        stub = env.volume_stub(node.grpc_address)
        await stub.VolumeEcShardsCopy(
            volume_server_pb2.VolumeEcShardsCopyRequest(
                volume_id=vid,
                collection=collection,
                shard_ids=shard_ids,
                copy_ecx_file=True,
                copy_ecj_file=True,
                copy_vif_file=first,
                source_data_node=source.grpc_address,
            )
        )
        first = False
        await stub.VolumeEcShardsMount(
            volume_server_pb2.VolumeEcShardsMountRequest(
                volume_id=vid, collection=collection, shard_ids=shard_ids
            )
        )
        src_stub = env.volume_stub(source.grpc_address)
        await src_stub.VolumeEcShardsUnmount(
            volume_server_pb2.VolumeEcShardsUnmountRequest(
                volume_id=vid, shard_ids=shard_ids
            )
        )
        await src_stub.VolumeEcShardsDelete(
            volume_server_pb2.VolumeEcShardsDeleteRequest(
                volume_id=vid, collection=collection, shard_ids=shard_ids
            )
        )


@command("ec.encode")
async def cmd_ec_encode(env, args):
    """-volumeId N [-collection c] : erasure-code a volume (RS 10+4 on TPU)
    and spread the shards across the cluster"""
    env.confirm_is_locked()
    flags = parse_flags(args)
    collection = flags.get("collection", "")
    vids: list[int] = []
    if "volumeId" in flags:
        vids = [int(flags["volumeId"])]
    nodes, _ = await env.collect_topology()
    if not vids and collection:
        vids = sorted(
            {
                v["id"]
                for n in nodes
                for v in n.volumes
                if v["collection"] == collection
            }
        )
    if not vids:
        raise ValueError("usage: ec.encode -volumeId N | -collection c")
    for vid in vids:
        await _encode_one(env, nodes, vid, collection)
        env.write(f"ec encoded volume {vid}")


async def _encode_one(env, nodes: list[TopoNode], vid: int, collection: str):
    holders = [n for n in nodes if any(v["id"] == vid for v in n.volumes)]
    if not holders:
        raise ValueError(f"volume {vid} not found")
    # 1. freeze all replicas (markVolumeReplicasWritable false)
    for n in holders:
        await env.volume_stub(n.grpc_address).VolumeMarkReadonly(
            volume_server_pb2.VolumeMarkReadonlyRequest(volume_id=vid)
        )
    source = holders[0]
    src_stub = env.volume_stub(source.grpc_address)
    collection = next(
        (v["collection"] for v in source.volumes if v["id"] == vid), collection
    )
    # 2. generate shards on the source (TPU kernels server-side)
    await src_stub.VolumeEcShardsGenerate(
        volume_server_pb2.VolumeEcShardsGenerateRequest(
            volume_id=vid, collection=collection
        )
    )
    await src_stub.VolumeEcShardsMount(
        volume_server_pb2.VolumeEcShardsMountRequest(
            volume_id=vid, collection=collection,
            shard_ids=list(range(TOTAL_SHARDS)),
        )
    )
    # 3. spread with balanced distribution
    targets = balanced_ec_distribution(nodes)
    await spread_ec_shards(env, vid, collection, source, targets)
    # 4. drop the original volume from every replica
    for n in holders:
        await env.volume_stub(n.grpc_address).VolumeDelete(
            volume_server_pb2.VolumeDeleteRequest(volume_id=vid)
        )


async def collect_ec_volume_shards(env) -> dict[int, dict[int, TopoNode]]:
    """vid -> shard_id -> node holding it, from the topology snapshot."""
    nodes, _ = await env.collect_topology()
    out: dict[int, dict[int, TopoNode]] = {}
    for n in nodes:
        for s in n.ec_shards:
            for sid in range(TOTAL_SHARDS):
                if s["ec_index_bits"] >> sid & 1:
                    out.setdefault(s["id"], {})[sid] = n
    return out


@command("ec.rebuild")
async def cmd_ec_rebuild(env, args):
    """[-force] : rebuild missing EC shards onto a rebuilder node
    (command_ec_rebuild.go:99-176)"""
    env.confirm_is_locked()
    flags = parse_flags(args)
    apply = "force" in flags
    shard_map = await collect_ec_volume_shards(env)
    nodes, _ = await env.collect_topology()
    for vid, shards in sorted(shard_map.items()):
        missing = [sid for sid in range(TOTAL_SHARDS) if sid not in shards]
        if not missing:
            continue
        if len(shards) < 10:
            env.write(f"ec volume {vid}: only {len(shards)} shards left, unrecoverable")
            continue
        env.write(f"ec volume {vid}: rebuilding shards {missing}")
        if not apply:
            continue
        rebuilder = ec_nodes_by_freeness(nodes)[0]
        collection = next(
            (
                s["collection"]
                for n in nodes
                for s in n.ec_shards
                if s["id"] == vid
            ),
            "",
        )
        stub = env.volume_stub(rebuilder.grpc_address)
        # gather every available shard onto the rebuilder (prepareToRecoverMissingEcShard)
        local = set(node_shards(rebuilder, vid))
        to_copy: dict[str, list[int]] = {}
        for sid, holder in shards.items():
            if sid not in local and holder.url != rebuilder.url:
                to_copy.setdefault(holder.grpc_address, []).append(sid)
        for src_addr, sids in to_copy.items():
            await stub.VolumeEcShardsCopy(
                volume_server_pb2.VolumeEcShardsCopyRequest(
                    volume_id=vid,
                    collection=collection,
                    shard_ids=sids,
                    copy_ecx_file=True,
                    copy_ecj_file=True,
                    copy_vif_file=True,
                    source_data_node=src_addr,
                )
            )
        resp = await stub.VolumeEcShardsRebuild(
            volume_server_pb2.VolumeEcShardsRebuildRequest(
                volume_id=vid, collection=collection
            )
        )
        await stub.VolumeEcShardsMount(
            volume_server_pb2.VolumeEcShardsMountRequest(
                volume_id=vid, collection=collection,
                shard_ids=list(resp.rebuilt_shard_ids),
            )
        )
        # drop the borrowed shards it only needed as rebuild input
        borrowed = [sid for sids in to_copy.values() for sid in sids]
        if borrowed:
            await stub.VolumeEcShardsUnmount(
                volume_server_pb2.VolumeEcShardsUnmountRequest(
                    volume_id=vid, shard_ids=borrowed
                )
            )
            await stub.VolumeEcShardsDelete(
                volume_server_pb2.VolumeEcShardsDeleteRequest(
                    volume_id=vid, collection=collection, shard_ids=borrowed
                )
            )
        env.write(f"ec volume {vid}: rebuilt {list(resp.rebuilt_shard_ids)}")


@command("ec.balance")
async def cmd_ec_balance(env, args):
    """[-force] : even EC shard counts across nodes (command_ec_balance.go)"""
    env.confirm_is_locked()
    flags = parse_flags(args)
    apply = "force" in flags
    nodes, _ = await env.collect_topology()
    counts = {
        n.url: sum(bin(s["ec_index_bits"]).count("1") for s in n.ec_shards)
        for n in nodes
    }
    by_url = {n.url: n for n in nodes}
    moves = []
    while True:
        hi = max(counts, key=counts.get)
        lo = min(counts, key=counts.get)
        if counts[hi] - counts[lo] <= 1:
            break
        src = by_url[hi]
        moved = False
        for s in src.ec_shards:
            sids = [i for i in range(TOTAL_SHARDS) if s["ec_index_bits"] >> i & 1]
            dst_held = node_shards(by_url[lo], s["id"])
            movable = [sid for sid in sids if sid not in dst_held]
            if movable:
                moves.append((s["id"], s["collection"], movable[0], src, by_url[lo]))
                s["ec_index_bits"] &= ~(1 << movable[0])
                counts[hi] -= 1
                counts[lo] += 1
                moved = True
                break
        if not moved:
            break
    for vid, collection, sid, src, dst in moves:
        env.write(f"move ec shard {vid}.{sid}: {src.url} -> {dst.url}")
        if apply:
            await move_ec_shard(env, vid, collection, sid, src, dst)
    env.write(f"{len(moves)} shard moves{' applied' if apply else ' planned (use -force)'}")


async def move_ec_shard(env, vid, collection, sid, src, dst):
    """copy → mount → unmount+delete at source (moveMountedShardToEcNode
    command_ec_common.go:19-58)."""
    stub = env.volume_stub(dst.grpc_address)
    await stub.VolumeEcShardsCopy(
        volume_server_pb2.VolumeEcShardsCopyRequest(
            volume_id=vid, collection=collection, shard_ids=[sid],
            copy_ecx_file=True, copy_ecj_file=True, copy_vif_file=True,
            source_data_node=src.grpc_address,
        )
    )
    await stub.VolumeEcShardsMount(
        volume_server_pb2.VolumeEcShardsMountRequest(
            volume_id=vid, collection=collection, shard_ids=[sid]
        )
    )
    src_stub = env.volume_stub(src.grpc_address)
    await src_stub.VolumeEcShardsUnmount(
        volume_server_pb2.VolumeEcShardsUnmountRequest(volume_id=vid, shard_ids=[sid])
    )
    await src_stub.VolumeEcShardsDelete(
        volume_server_pb2.VolumeEcShardsDeleteRequest(
            volume_id=vid, collection=collection, shard_ids=[sid]
        )
    )


@command("ec.decode")
async def cmd_ec_decode(env, args):
    """-volumeId N : convert an EC volume back to a normal volume
    (command_ec_decode.go)"""
    env.confirm_is_locked()
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    shard_map = await collect_ec_volume_shards(env)
    shards = shard_map.get(vid)
    if not shards:
        raise ValueError(f"ec volume {vid} not found")
    # choose the node already holding the most shards as the decoder
    holders: dict[str, list[int]] = {}
    for sid, n in shards.items():
        holders.setdefault(n.url, []).append(sid)
    nodes, _ = await env.collect_topology()
    by_url = {n.url: n for n in nodes}
    decoder = by_url[max(holders, key=lambda u: len(holders[u]))]
    collection = next(
        (s["collection"] for n in nodes for s in n.ec_shards if s["id"] == vid), ""
    )
    stub = env.volume_stub(decoder.grpc_address)
    local = set(holders.get(decoder.url, []))
    to_copy: dict[str, list[int]] = {}
    for sid, holder in shards.items():
        if sid not in local and holder.url != decoder.url:
            to_copy.setdefault(holder.grpc_address, []).append(sid)
    for src_addr, sids in to_copy.items():
        await stub.VolumeEcShardsCopy(
            volume_server_pb2.VolumeEcShardsCopyRequest(
                volume_id=vid, collection=collection, shard_ids=sids,
                copy_ecx_file=True, copy_ecj_file=True, copy_vif_file=True,
                source_data_node=src_addr,
            )
        )
    await stub.VolumeEcShardsToVolume(
        volume_server_pb2.VolumeEcShardsToVolumeRequest(
            volume_id=vid, collection=collection
        )
    )
    # remove EC shards everywhere
    for n in {n.url: n for n in shards.values()}.values():
        sids = node_shards(n, vid)
        if sids:
            s_stub = env.volume_stub(n.grpc_address)
            await s_stub.VolumeEcShardsUnmount(
                volume_server_pb2.VolumeEcShardsUnmountRequest(
                    volume_id=vid, shard_ids=sids
                )
            )
            await s_stub.VolumeEcShardsDelete(
                volume_server_pb2.VolumeEcShardsDeleteRequest(
                    volume_id=vid, collection=collection, shard_ids=sids
                )
            )
    await env.volume_stub(decoder.grpc_address).VolumeEcShardsDelete(
        volume_server_pb2.VolumeEcShardsDeleteRequest(
            volume_id=vid, collection=collection,
            shard_ids=list(range(TOTAL_SHARDS)),
        )
    )
    env.write(f"decoded ec volume {vid} back to a normal volume on {decoder.url}")
