"""ec.* commands: encode / rebuild / balance / decode orchestration.

Reference: weed/shell/command_ec_encode.go:57-269 (mark readonly →
generate → spread with balancedEcDistribution → delete original),
command_ec_rebuild.go:99-176, command_ec_balance.go, command_ec_decode.go,
command_ec_common.go:19-58 (moveMountedShardToEcNode).

The GF(256) math itself runs wherever VolumeEcShardsGenerate lands — on
the volume server's configured backend (TPU MXU kernels by default).
"""
from __future__ import annotations

import asyncio
import math

from ..pb import master_pb2, volume_server_pb2
from ..storage.ec import DATA_SHARDS, TOTAL_SHARDS
from ..utils.faultpolicy import retry_rpc
from .command_env import CommandEnv, TopoNode
from .commands import command, parse_flags


def ec_nodes_by_freeness(nodes: list[TopoNode]) -> list[TopoNode]:
    return sorted(nodes, key=lambda n: n.free_slots(), reverse=True)


def node_shards(node: TopoNode, vid: int) -> list[int]:
    for s in node.ec_shards:
        if s["id"] == vid:
            return [i for i in range(TOTAL_SHARDS) if s["ec_index_bits"] >> i & 1]
    return []


def rack_of(node: TopoNode) -> tuple[str, str]:
    return (node.data_center, node.rack)


def held_shard_count(n: TopoNode) -> int:
    """Total EC shards a node holds across all volumes."""
    return sum(bin(s["ec_index_bits"]).count("1") for s in n.ec_shards)


def rack_cap(n_shards: int, racks) -> int:
    """Per-rack shard ceiling: ceil(n_shards / n_racks)."""
    return math.ceil(n_shards / len(racks)) if racks else n_shards


def free_shard_slots(n: TopoNode) -> int:
    """Receive capacity in SHARD units: volume slots not taken by regular
    volumes, times 14, minus EC shards already held.  (free_slots() is in
    volume-slot units and counts one held shard as a whole slot — using it
    directly would declare a receiver full after one shard.)"""
    return (
        sum(n.max_volume_counts.values()) - len(n.volumes)
    ) * TOTAL_SHARDS - held_shard_count(n)


def group_by_rack(nodes: list[TopoNode]) -> dict[tuple[str, str], list[TopoNode]]:
    racks: dict[tuple[str, str], list[TopoNode]] = {}
    for n in nodes:
        racks.setdefault(rack_of(n), []).append(n)
    return racks


def balanced_ec_distribution(nodes: list[TopoNode], n_shards: int = TOTAL_SHARDS):
    """Spread shards rack-aware: each (dc, rack) holds at most
    ceil(n_shards / n_racks) shards, minimising how many shards one rack
    failure takes out (with >=4 racks and free capacity that stays within
    the 4-shard RS tolerance; fewer racks or a full cluster can exceed it
    — the capacity fallbacks below prefer placing somewhere over failing);
    within a rack,
    shards round-robin over nodes by free slots (the reference balances
    across racks in command_ec_common.go pickRackToBalanceShardsInto and
    within them via balancedEcDistribution, command_ec_encode.go:253-269).
    Returns [(node, [shard ids])]."""
    ranked = ec_nodes_by_freeness(nodes)
    if not ranked:
        return []
    racks = group_by_rack(ranked)
    rack_limit = rack_cap(n_shards, racks)
    rack_count = {r: 0 for r in racks}
    rack_rr = {r: 0 for r in racks}  # round-robin cursor within the rack
    alloc = {n.url: [] for n in ranked}
    free = {n.url: max(0, free_shard_slots(n)) for n in ranked}

    def rack_free(r):
        return sum(free[n.url] for n in racks[r])

    for sid in range(n_shards):
        # least-loaded rack under the cap with free space; fall back to
        # ignoring the cap, then to ignoring free space, so every shard
        # lands somewhere even on tiny clusters
        candidates = [
            r for r in racks if rack_count[r] < rack_limit and rack_free(r) > 0
        ] or [r for r in racks if rack_free(r) > 0] or list(racks)
        r = min(candidates, key=lambda r: (rack_count[r], -rack_free(r)))
        members = racks[r]
        for _ in range(len(members)):
            n = members[rack_rr[r] % len(members)]
            rack_rr[r] += 1
            if free[n.url] > 0 or all(free[m.url] <= 0 for m in members):
                alloc[n.url].append(sid)
                free[n.url] -= 1
                rack_count[r] += 1
                break
    return [(n, alloc[n.url]) for n in ranked if alloc[n.url]]


# shell fan-out knobs: shard-set copies ship tens of MB each, so the
# concurrency bound keeps a wide cluster from saturating the source's
# uplink, and the per-RPC timeout/retry keeps one wedged peer from
# hanging the whole verb (the reference's parallelCopyEcShardsFromSource
# runs one goroutine per target with an ErrorWaitGroup).  The retry
# policy itself — backoff, jitter, per-peer retry budget — is the ONE
# shared implementation in utils/faultpolicy.py (the repair executor
# rides the same one); `retry_rpc`'s defaults match the knobs here.
FANOUT_CONCURRENCY = 4
RPC_ATTEMPTS = 3
RPC_TIMEOUT_S = 300.0
# generate/rebuild/decode re-stripe whole volumes: heavy but FINITE
RPC_HEAVY_TIMEOUT_S = 600.0


async def spread_ec_shards(
    env: CommandEnv,
    vid: int,
    collection: str,
    source: TopoNode,
    targets: list[tuple[TopoNode, list[int]]],
    concurrency: int = FANOUT_CONCURRENCY,
) -> None:
    """Copy+mount each target's shard set from source CONCURRENTLY
    (bounded), then unmount the moved shards at the source
    (parallelCopyEcShardsFromSource → unmountEcShards,
    command_ec_encode.go:145-188).  The `.vif` sidecar ships with exactly
    ONE copy target — decided before the fan-out starts, so concurrent
    copies can't race it — and each target's copy→mount→source-unmount→
    source-delete sequence stays ordered within its own task."""
    real = [
        (node, shard_ids)
        for node, shard_ids in targets
        if node.url != source.url and shard_ids
    ]
    vif_url = real[0][0].url if real else None
    sem = asyncio.Semaphore(max(1, concurrency))

    async def ship(node: TopoNode, shard_ids: list[int]) -> None:
        async with sem:
            stub = env.volume_stub(node.grpc_address)
            await retry_rpc(
                lambda: stub.VolumeEcShardsCopy(
                    volume_server_pb2.VolumeEcShardsCopyRequest(
                        volume_id=vid,
                        collection=collection,
                        shard_ids=shard_ids,
                        copy_ecx_file=True,
                        copy_ecj_file=True,
                        copy_vif_file=node.url == vif_url,
                        source_data_node=source.grpc_address,
                    )
                ),
                f"copy shards {shard_ids} of {vid} to {node.url}",
                peer=node.grpc_address,
            )
            await retry_rpc(
                lambda: stub.VolumeEcShardsMount(
                    volume_server_pb2.VolumeEcShardsMountRequest(
                        volume_id=vid, collection=collection,
                        shard_ids=shard_ids,
                    )
                ),
                f"mount shards {shard_ids} of {vid} on {node.url}",
                peer=node.grpc_address,
            )
            src_stub = env.volume_stub(source.grpc_address)
            await retry_rpc(
                lambda: src_stub.VolumeEcShardsUnmount(
                    volume_server_pb2.VolumeEcShardsUnmountRequest(
                        volume_id=vid, shard_ids=shard_ids
                    )
                ),
                f"unmount shards {shard_ids} of {vid} at source",
                peer=source.grpc_address,
            )
            await retry_rpc(
                lambda: src_stub.VolumeEcShardsDelete(
                    volume_server_pb2.VolumeEcShardsDeleteRequest(
                        volume_id=vid, collection=collection,
                        shard_ids=shard_ids,
                    )
                ),
                f"delete shards {shard_ids} of {vid} at source",
                peer=source.grpc_address,
            )

    await _gather_strict(ship(node, sids) for node, sids in real)


async def _gather_strict(coros) -> None:
    """gather that lets every sibling RUN TO COMPLETION, then raises the
    first failure.  Plain gather() re-raises early while the surviving
    tasks keep mutating cluster state (unmounting/deleting source shards)
    after the verb has already 'failed' — and their own exceptions die as
    never-retrieved warnings.  Cancelling siblings instead would strand a
    peer mid copy→mount→unmount move, which is worse than finishing it."""
    results = await asyncio.gather(*coros, return_exceptions=True)
    for r in results:
        if isinstance(r, BaseException):
            raise r


@command("ec.encode")
async def cmd_ec_encode(env, args):
    """-volumeId N [-collection c] : erasure-code a volume (RS 10+4 on TPU)
    and spread the shards across the cluster"""
    env.confirm_is_locked()
    flags = parse_flags(args)
    collection = flags.get("collection", "")
    vids: list[int] = []
    if "volumeId" in flags:
        vids = [int(flags["volumeId"])]
    nodes, _ = await env.collect_topology()
    if not vids and collection:
        vids = sorted(
            {
                v["id"]
                for n in nodes
                for v in n.volumes
                if v["collection"] == collection
            }
        )
    if not vids:
        raise ValueError("usage: ec.encode -volumeId N | -collection c")
    for vid in vids:
        await _encode_one(env, nodes, vid, collection)
        env.write(f"ec encoded volume {vid}")


async def _encode_one(env, nodes: list[TopoNode], vid: int, collection: str):
    holders = [n for n in nodes if any(v["id"] == vid for v in n.volumes)]
    if not holders:
        raise ValueError(f"volume {vid} not found")
    # 1. freeze all replicas (markVolumeReplicasWritable false)
    for n in holders:
        await env.volume_stub(n.grpc_address).VolumeMarkReadonly(
            volume_server_pb2.VolumeMarkReadonlyRequest(volume_id=vid),
            timeout=RPC_TIMEOUT_S,
        )
    source = holders[0]
    src_stub = env.volume_stub(source.grpc_address)
    collection = next(
        (v["collection"] for v in source.volumes if v["id"] == vid), collection
    )
    # 2. generate shards on the source (TPU kernels server-side)
    await src_stub.VolumeEcShardsGenerate(
        volume_server_pb2.VolumeEcShardsGenerateRequest(
            volume_id=vid, collection=collection
        ),
        timeout=RPC_HEAVY_TIMEOUT_S,
    )
    await src_stub.VolumeEcShardsMount(
        volume_server_pb2.VolumeEcShardsMountRequest(
            volume_id=vid, collection=collection,
            shard_ids=list(range(TOTAL_SHARDS)),
        ),
        timeout=RPC_TIMEOUT_S,
    )
    # 3. spread with balanced distribution
    targets = balanced_ec_distribution(nodes)
    await spread_ec_shards(env, vid, collection, source, targets)
    # 4. drop the original volume from every replica
    for n in holders:
        await env.volume_stub(n.grpc_address).VolumeDelete(
            volume_server_pb2.VolumeDeleteRequest(volume_id=vid),
            timeout=RPC_TIMEOUT_S,
        )


async def collect_ec_volume_shards(env) -> dict[int, dict[int, TopoNode]]:
    """vid -> shard_id -> node holding it, from the topology snapshot."""
    nodes, _ = await env.collect_topology()
    out: dict[int, dict[int, TopoNode]] = {}
    for n in nodes:
        for s in n.ec_shards:
            for sid in range(TOTAL_SHARDS):
                if s["ec_index_bits"] >> sid & 1:
                    out.setdefault(s["id"], {})[sid] = n
    return out


def _fmt_scrub_row(env, vid, mism, backend, bytes_verified, seconds):
    bad = sum(mism)
    # ONE byte basis for both figures: data bytes covered (shard span
    # x DATA_SHARDS, the same basis bench.py's scrub GB/s uses), so
    # the printed rate actually equals size/seconds
    data_bytes = bytes_verified * DATA_SHARDS
    mb = data_bytes / 1e6
    rate = data_bytes / seconds / 1e9 if seconds else 0.0
    status = (
        "OK" if bad == 0
        else f"CORRUPT: {list(mism)} mismatch bytes"
    )
    env.write(
        f"ec volume {vid}: {status} backend={backend} "
        f"{mb:.0f}MB data in {seconds:.2f}s ({rate:.2f} GB/s)"
    )


@command("ec.scrub")
async def cmd_ec_scrub(env, args):
    """[-volumeId <id>] : verify parity consistency of mounted EC volumes
    (VolumeEcShardsVerify).  Runs on nodes holding all 14 shards of a
    volume — device-resident volumes scrub first via ONE fused megakernel
    pass per node (all_resident: the whole HBM cache in a handful of
    device dispatches), the rest per volume through the CPU kernel over
    the shard files; spread volumes are reported skipped."""
    flags = parse_flags(args)
    target = int(flags.get("volumeId", 0) or 0)
    shard_map = await collect_ec_volume_shards(env)
    # pick each volume's scrub node up front so the megakernel pre-pass
    # knows which nodes are worth one all_resident RPC
    chosen: dict[int, str] = {}
    for vid, shards in sorted(shard_map.items()):
        if target and vid != target:
            continue
        holders: dict[str, set[int]] = {}
        for sid, node in shards.items():
            holders.setdefault(node.grpc_address, set()).add(sid)
        full = [a for a, sids in holders.items() if len(sids) == TOTAL_SHARDS]
        if not full:
            env.write(
                f"ec volume {vid}: shards spread over {len(holders)} "
                f"node(s), none holds all {TOTAL_SHARDS} — skipped"
            )
            continue
        chosen[vid] = full[0]
    # megakernel pre-pass (skipped for a targeted scrub — one volume
    # doesn't justify sweeping a node's whole cache): per-vid verdicts
    # land in `mega`, and anything it didn't cover (not fully resident)
    # falls through to the per-volume RPC below
    mega: dict[tuple[str, int], object] = {}
    if not target:
        for addr in sorted(set(chosen.values())):
            try:
                r = await env.volume_stub(addr).VolumeEcShardsVerify(
                    volume_server_pb2.VolumeEcShardsVerifyRequest(
                        all_resident=True
                    ),
                    timeout=RPC_HEAVY_TIMEOUT_S,
                )
            except Exception:  # noqa: BLE001 — pre-r11 server: the
                # per-volume path below still covers everything
                continue
            # getattr-guarded like the exception above: a pre-r11
            # response object has no `volumes` field at all
            for row in getattr(r, "volumes", ()):
                mega[(addr, row.volume_id)] = row
    for vid, addr in chosen.items():
        row = mega.get((addr, vid))
        if row is not None:
            _fmt_scrub_row(
                env, vid, row.parity_mismatch_bytes, row.backend,
                row.bytes_verified, row.seconds,
            )
            continue
        r = await env.volume_stub(addr).VolumeEcShardsVerify(
            volume_server_pb2.VolumeEcShardsVerifyRequest(volume_id=vid),
            timeout=RPC_HEAVY_TIMEOUT_S,
        )
        _fmt_scrub_row(
            env, vid, r.parity_mismatch_bytes, r.backend,
            r.bytes_verified, r.seconds,
        )


async def gather_ec_shards(
    stub,
    vid: int,
    collection: str,
    to_copy: dict[str, list[int]],
    concurrency: int = FANOUT_CONCURRENCY,
) -> None:
    """Pull every borrowed shard set onto the rebuilder CONCURRENTLY
    (bounded, per-RPC retry/timeout).  All copies land on the SAME node,
    so the sidecars (.ecx/.ecj/.vif) ship with exactly one of them —
    concurrent pulls writing the same sidecar path would race."""
    sidecar_src = next(iter(to_copy), None)
    sem = asyncio.Semaphore(max(1, concurrency))

    async def pull(src_addr: str, sids: list[int]) -> None:
        async with sem:
            await retry_rpc(
                lambda: stub.VolumeEcShardsCopy(
                    volume_server_pb2.VolumeEcShardsCopyRequest(
                        volume_id=vid,
                        collection=collection,
                        shard_ids=sids,
                        copy_ecx_file=src_addr == sidecar_src,
                        copy_ecj_file=src_addr == sidecar_src,
                        copy_vif_file=src_addr == sidecar_src,
                        source_data_node=src_addr,
                    )
                ),
                f"gather shards {sids} of {vid} from {src_addr}",
                peer=src_addr,
            )

    await _gather_strict(pull(src, sids) for src, sids in to_copy.items())


@command("ec.rebuild")
async def cmd_ec_rebuild(env, args):
    """[-force] [-fsync] : rebuild missing EC shards onto a rebuilder node
    (command_ec_rebuild.go:99-176); -fsync makes the rebuilt shards
    durable before the RPC returns"""
    env.confirm_is_locked()
    flags = parse_flags(args)
    apply = "force" in flags
    fsync = "fsync" in flags
    shard_map = await collect_ec_volume_shards(env)
    nodes, _ = await env.collect_topology()
    for vid, shards in sorted(shard_map.items()):
        missing = [sid for sid in range(TOTAL_SHARDS) if sid not in shards]
        if not missing:
            continue
        if len(shards) < 10:
            env.write(f"ec volume {vid}: only {len(shards)} shards left, unrecoverable")
            continue
        env.write(f"ec volume {vid}: rebuilding shards {missing}")
        if not apply:
            continue
        rebuilder = ec_nodes_by_freeness(nodes)[0]
        collection = next(
            (
                s["collection"]
                for n in nodes
                for s in n.ec_shards
                if s["id"] == vid
            ),
            "",
        )
        stub = env.volume_stub(rebuilder.grpc_address)
        # gather every available shard onto the rebuilder (prepareToRecoverMissingEcShard)
        local = set(node_shards(rebuilder, vid))
        to_copy: dict[str, list[int]] = {}
        for sid, holder in shards.items():
            if sid not in local and holder.url != rebuilder.url:
                to_copy.setdefault(holder.grpc_address, []).append(sid)
        await gather_ec_shards(stub, vid, collection, to_copy)
        resp = await stub.VolumeEcShardsRebuild(
            volume_server_pb2.VolumeEcShardsRebuildRequest(
                volume_id=vid, collection=collection, fsync=fsync
            ),
            timeout=RPC_HEAVY_TIMEOUT_S,
        )
        await stub.VolumeEcShardsMount(
            volume_server_pb2.VolumeEcShardsMountRequest(
                volume_id=vid, collection=collection,
                shard_ids=list(resp.rebuilt_shard_ids),
            ),
            timeout=RPC_TIMEOUT_S,
        )
        # drop the borrowed shards it only needed as rebuild input
        borrowed = [sid for sids in to_copy.values() for sid in sids]
        if borrowed:
            await stub.VolumeEcShardsUnmount(
                volume_server_pb2.VolumeEcShardsUnmountRequest(
                    volume_id=vid, shard_ids=borrowed
                ),
                timeout=RPC_TIMEOUT_S,
            )
            await stub.VolumeEcShardsDelete(
                volume_server_pb2.VolumeEcShardsDeleteRequest(
                    volume_id=vid, collection=collection, shard_ids=borrowed
                ),
                timeout=RPC_TIMEOUT_S,
            )
        env.write(f"ec volume {vid}: rebuilt {list(resp.rebuilt_shard_ids)}")


def plan_rack_moves(nodes: list[TopoNode]) -> list[tuple[int, str, int, TopoNode, TopoNode]]:
    """Per EC volume: move shards out of racks holding more than
    ceil(14 / n_racks) of its shards, into the rack holding fewest
    (balanceEcShardsAcrossRacks, command_ec_common.go).  Mutates the
    nodes' ec_index_bits to reflect planned moves; returns
    [(vid, collection, shard_id, src_node, dst_node)]."""
    racks = group_by_rack(nodes)
    if len(racks) <= 1:
        return []
    rack_limit = rack_cap(TOTAL_SHARDS, racks)
    moves = []
    vids = sorted(
        {s["id"] for n in nodes for s in n.ec_shards}
    )
    for vid in vids:
        collection = next(
            (s["collection"] for n in nodes for s in n.ec_shards if s["id"] == vid),
            "",
        )
        # one scan per volume; maintained incrementally across its moves
        holders = {n.url: node_shards(n, vid) for n in nodes}
        loads = {
            r: sum(len(holders[n.url]) for n in racks[r]) for r in racks
        }
        while True:
            over = [r for r in racks if loads[r] > rack_limit]
            if not over:
                break
            src_rack = max(over, key=lambda r: loads[r])
            # only racks with free EC capacity can receive
            # (pickRackToBalanceShardsInto's freeEcSlot requirement)
            open_racks = [
                r
                for r in racks
                if r != src_rack
                and any(free_shard_slots(n) > 0 for n in racks[r])
            ]
            if not open_racks:
                break
            dst_rack = min(open_racks, key=lambda r: loads[r])
            if loads[dst_rack] >= rack_limit:
                break
            src_node = next(
                n for n in reversed(racks[src_rack]) if holders[n.url]
            )
            sid = holders[src_node.url][-1]
            # within the destination rack, the freest node without this
            # volume's shards
            dst_node = min(
                (n for n in racks[dst_rack] if free_shard_slots(n) > 0),
                key=lambda n: (len(holders[n.url]), -free_shard_slots(n)),
            )
            moves.append((vid, collection, sid, src_node, dst_node))
            _move_shard_bits(src_node, dst_node, vid, collection, sid)
            holders[src_node.url].remove(sid)
            holders[dst_node.url].append(sid)
            loads[src_rack] -= 1
            loads[dst_rack] += 1
    return moves


def _move_shard_bits(src: TopoNode, dst: TopoNode, vid, collection, sid) -> None:
    """Update the in-memory topology snapshot to reflect a planned move."""
    for s in src.ec_shards:
        if s["id"] == vid:
            s["ec_index_bits"] &= ~(1 << sid)
    for s in dst.ec_shards:
        if s["id"] == vid:
            s["ec_index_bits"] |= 1 << sid
            return
    dst.ec_shards.append(
        {"id": vid, "collection": collection, "ec_index_bits": 1 << sid}
    )


def plan_node_moves(nodes: list[TopoNode]) -> list[tuple[int, str, int, TopoNode, TopoNode]]:
    """Even aggregate shard counts across nodes (the reference's
    balanceEcShardsWithinRacks + balanceEcRacks rolled into one hi/lo
    loop) — a cross-rack move is only allowed while it keeps the
    destination rack under the per-volume cap plan_rack_moves enforces.
    Mutates the nodes' ec_index_bits; returns
    [(vid, collection, shard_id, src_node, dst_node)]."""
    racks = group_by_rack(nodes)
    rack_limit = rack_cap(TOTAL_SHARDS, racks)

    def vid_rack_load(rack: tuple[str, str], vid: int) -> int:
        return sum(len(node_shards(n, vid)) for n in racks[rack])

    counts = {
        n.url: held_shard_count(n) for n in nodes
    }
    by_url = {n.url: n for n in nodes}
    moves: list[tuple[int, str, int, TopoNode, TopoNode]] = []

    def try_move(hi: str, lo: str) -> bool:
        src, dst = by_url[hi], by_url[lo]
        if free_shard_slots(dst) <= 0:
            # receivers need free EC capacity (the reference's freeEcSlot
            # requirement, command_ec_common.go)
            return False
        for s in src.ec_shards:
            vid = s["id"]
            cross_rack = rack_of(src) != rack_of(dst)
            if cross_rack and vid_rack_load(rack_of(dst), vid) >= rack_limit:
                continue
            sids = [i for i in range(TOTAL_SHARDS) if s["ec_index_bits"] >> i & 1]
            dst_held = node_shards(dst, vid)
            movable = [sid for sid in sids if sid not in dst_held]
            if movable:
                moves.append((vid, s["collection"], movable[0], src, dst))
                _move_shard_bits(src, dst, vid, s["collection"], movable[0])
                counts[hi] -= 1
                counts[lo] += 1
                return True
        return False

    while counts:
        # try every donor (fullest first) against every recipient
        # (emptiest first): the top pair may be blocked by the rack cap
        # while e.g. a same-rack move still improves balance
        moved = False
        for hi in sorted(counts, key=counts.get, reverse=True):
            for lo in sorted(counts, key=counts.get):
                if counts[hi] - counts[lo] <= 1:
                    break  # later recipients are even fuller
                if try_move(hi, lo):
                    moved = True
                    break
            if moved:
                break
        if not moved:
            break
    return moves


@command("ec.balance")
async def cmd_ec_balance(env, args):
    """[-force] : even EC shards across racks, then across nodes
    (command_ec_balance.go, command_ec_common.go)"""
    env.confirm_is_locked()
    flags = parse_flags(args)
    apply = "force" in flags
    nodes, _ = await env.collect_topology()

    # pass 1: rack dimension — no rack holds more of a volume's shards
    # than ceil(14 / n_racks)
    rack_moves = plan_rack_moves(nodes)
    for vid, collection, sid, src, dst in rack_moves:
        env.write(
            f"move ec shard {vid}.{sid}: {src.url} -> {dst.url} (rack balance)"
        )
        if apply:
            await move_ec_shard(env, vid, collection, sid, src, dst)

    # pass 2: aggregate node counts across the cluster
    moves = plan_node_moves(nodes)
    for vid, collection, sid, src, dst in moves:
        env.write(f"move ec shard {vid}.{sid}: {src.url} -> {dst.url}")
        if apply:
            await move_ec_shard(env, vid, collection, sid, src, dst)
    total = len(rack_moves) + len(moves)
    env.write(
        f"{total} shard moves{' applied' if apply else ' planned (use -force)'}"
    )


async def move_ec_shard(env, vid, collection, sid, src, dst):
    """copy → mount → unmount+delete at source (moveMountedShardToEcNode
    command_ec_common.go:19-58)."""
    stub = env.volume_stub(dst.grpc_address)
    await stub.VolumeEcShardsCopy(
        volume_server_pb2.VolumeEcShardsCopyRequest(
            volume_id=vid, collection=collection, shard_ids=[sid],
            copy_ecx_file=True, copy_ecj_file=True, copy_vif_file=True,
            source_data_node=src.grpc_address,
        ),
        timeout=RPC_TIMEOUT_S,
    )
    await stub.VolumeEcShardsMount(
        volume_server_pb2.VolumeEcShardsMountRequest(
            volume_id=vid, collection=collection, shard_ids=[sid]
        ),
        timeout=RPC_TIMEOUT_S,
    )
    src_stub = env.volume_stub(src.grpc_address)
    await src_stub.VolumeEcShardsUnmount(
        volume_server_pb2.VolumeEcShardsUnmountRequest(volume_id=vid, shard_ids=[sid]),
        timeout=RPC_TIMEOUT_S,
    )
    await src_stub.VolumeEcShardsDelete(
        volume_server_pb2.VolumeEcShardsDeleteRequest(
            volume_id=vid, collection=collection, shard_ids=[sid]
        ),
        timeout=RPC_TIMEOUT_S,
    )


@command("ec.decode")
async def cmd_ec_decode(env, args):
    """-volumeId N : convert an EC volume back to a normal volume
    (command_ec_decode.go)"""
    env.confirm_is_locked()
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    shard_map = await collect_ec_volume_shards(env)
    shards = shard_map.get(vid)
    if not shards:
        raise ValueError(f"ec volume {vid} not found")
    # choose the node already holding the most shards as the decoder
    holders: dict[str, list[int]] = {}
    for sid, n in shards.items():
        holders.setdefault(n.url, []).append(sid)
    nodes, _ = await env.collect_topology()
    by_url = {n.url: n for n in nodes}
    decoder = by_url[max(holders, key=lambda u: len(holders[u]))]
    collection = next(
        (s["collection"] for n in nodes for s in n.ec_shards if s["id"] == vid), ""
    )
    stub = env.volume_stub(decoder.grpc_address)
    local = set(holders.get(decoder.url, []))
    to_copy: dict[str, list[int]] = {}
    for sid, holder in shards.items():
        if sid not in local and holder.url != decoder.url:
            to_copy.setdefault(holder.grpc_address, []).append(sid)
    for src_addr, sids in to_copy.items():
        await stub.VolumeEcShardsCopy(
            volume_server_pb2.VolumeEcShardsCopyRequest(
                volume_id=vid, collection=collection, shard_ids=sids,
                copy_ecx_file=True, copy_ecj_file=True, copy_vif_file=True,
                source_data_node=src_addr,
            ),
            timeout=RPC_TIMEOUT_S,
        )
    await stub.VolumeEcShardsToVolume(
        volume_server_pb2.VolumeEcShardsToVolumeRequest(
            volume_id=vid, collection=collection
        ),
        timeout=RPC_HEAVY_TIMEOUT_S,
    )
    # remove EC shards everywhere
    for n in {n.url: n for n in shards.values()}.values():
        sids = node_shards(n, vid)
        if sids:
            s_stub = env.volume_stub(n.grpc_address)
            await s_stub.VolumeEcShardsUnmount(
                volume_server_pb2.VolumeEcShardsUnmountRequest(
                    volume_id=vid, shard_ids=sids
                ),
                timeout=RPC_TIMEOUT_S,
            )
            await s_stub.VolumeEcShardsDelete(
                volume_server_pb2.VolumeEcShardsDeleteRequest(
                    volume_id=vid, collection=collection, shard_ids=sids
                ),
                timeout=RPC_TIMEOUT_S,
            )
    await env.volume_stub(decoder.grpc_address).VolumeEcShardsDelete(
        volume_server_pb2.VolumeEcShardsDeleteRequest(
            volume_id=vid, collection=collection,
            shard_ids=list(range(TOTAL_SHARDS)),
        ),
        timeout=RPC_TIMEOUT_S,
    )
    env.write(f"decoded ec volume {vid} back to a normal volume on {decoder.url}")
