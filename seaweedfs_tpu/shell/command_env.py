"""CommandEnv: the shell's handle on the cluster.

Reference: weed/shell/commands.go:51-89 — holds the MasterClient, the
exclusive admin lock lease, and option state shared by all commands.
"""
from __future__ import annotations

import io
import json
import time
from dataclasses import dataclass, field

from ..pb import Stub, channel, master_pb2, server_address, volume_server_pb2

LOCK_NAME = "admin"


@dataclass
class TopoNode:
    """Flattened view of one volume server from VolumeList's topology JSON."""

    url: str
    grpc_port: int
    data_center: str
    rack: str
    volumes: list[dict] = field(default_factory=list)
    ec_shards: list[dict] = field(default_factory=list)
    max_volume_counts: dict = field(default_factory=dict)
    # r20 host failure domain: the node's multi-controller pod id
    # ("" = not in a pod) — ec.balance/repair spread across pods
    mesh_pod: str = ""

    @property
    def grpc_address(self) -> str:
        host = self.url.rsplit(":", 1)[0]
        return f"{host}:{self.grpc_port}"

    def free_slots(self, disk_type: str = "") -> int:
        from ..storage.ec import TOTAL_SHARDS

        used = sum(
            1
            for v in self.volumes
            if not disk_type or v.get("disk_type", "hdd") == disk_type
        )
        used += (
            sum(
                bin(s["ec_index_bits"]).count("1")
                for s in self.ec_shards
                if not disk_type or s.get("disk_type", "hdd") == disk_type
            )
            + TOTAL_SHARDS - 1
        ) // TOTAL_SHARDS
        if disk_type:
            return self.max_volume_counts.get(disk_type, 0) - used
        return sum(self.max_volume_counts.values()) - used


def topo_nodes_from_info(info: dict) -> list[TopoNode]:
    """Flatten a Topology.to_info() snapshot into TopoNodes — shared by
    the shell's collect_topology (which gets the JSON over VolumeList)
    and the master's repair scheduler (which reads its own topology
    in-process), so the two views can never parse differently."""
    nodes = []
    for dc in info.get("data_centers", []):
        for rack in dc.get("racks", []):
            for n in rack.get("nodes", []):
                nodes.append(
                    TopoNode(
                        url=n["id"],
                        grpc_port=n.get("grpc_port", 0),
                        data_center=dc["id"],
                        rack=rack["id"],
                        volumes=n.get("volumes", []),
                        ec_shards=n.get("ec_shards", []),
                        max_volume_counts=n.get("max_volume_counts", {}),
                        mesh_pod=n.get("mesh_pod", ""),
                    )
                )
    return nodes


class CommandEnv:
    def __init__(self, masters: list[str], out: io.TextIOBase | None = None):
        self.masters = masters
        self.out = out
        self.lock_token = 0
        self.lock_ts = 0
        self.option: dict = {}

    def write(self, *args) -> None:
        text = " ".join(str(a) for a in args)
        if self.out is not None:
            self.out.write(text + "\n")
        else:
            print(text)

    # -- stubs ---------------------------------------------------------------

    @property
    def master_stub(self) -> Stub:
        return Stub(
            channel(server_address.grpc_address(self.masters[0])),
            master_pb2,
            "Seaweed",
        )

    def volume_stub(self, grpc_address: str) -> Stub:
        return Stub(channel(grpc_address), volume_server_pb2, "VolumeServer")

    async def find_filer(self) -> str:
        """One live filer's host:port from the master's cluster registry."""
        resp = await self.master_stub.ListClusterNodes(
            master_pb2.ListClusterNodesRequest(client_type="filer")
        )
        if not resp.cluster_nodes:
            raise RuntimeError("no filer registered with the master")
        return resp.cluster_nodes[0].address

    def filer_stub(self, filer_address: str) -> Stub:
        from ..pb import filer_pb2

        return Stub(
            channel(server_address.grpc_address(filer_address)),
            filer_pb2,
            "SeaweedFiler",
        )

    # -- admin lock (commands.go:78, confirmIsLocked) ------------------------

    async def acquire_lock(self, client_name: str = "shell", message: str = "") -> None:
        resp = await self.master_stub.LeaseAdminToken(
            master_pb2.LeaseAdminTokenRequest(
                previous_token=self.lock_token,
                previous_lock_time=self.lock_ts,
                lock_name=LOCK_NAME,
                client_name=client_name,
                message=message,
            )
        )
        self.lock_token, self.lock_ts = resp.token, resp.lock_ts_ns

    async def release_lock(self) -> None:
        if self.lock_token:
            await self.master_stub.ReleaseAdminToken(
                master_pb2.ReleaseAdminTokenRequest(
                    previous_token=self.lock_token,
                    previous_lock_time=self.lock_ts,
                    lock_name=LOCK_NAME,
                )
            )
            self.lock_token = self.lock_ts = 0

    def confirm_is_locked(self) -> None:
        if not self.lock_token:
            raise RuntimeError(
                "lock is lost, or this command needs to be executed inside `lock` ... `unlock`"
            )

    # -- topology snapshot ---------------------------------------------------

    async def collect_topology(self) -> tuple[list[TopoNode], int]:
        """-> (nodes, volume_size_limit_mb) from master VolumeList
        (collectTopologyInfo command_ec_common.go:208)."""
        resp = await self.master_stub.VolumeList(master_pb2.VolumeListRequest())
        info = json.loads(resp.topology_info_json)
        return topo_nodes_from_info(info), resp.volume_size_limit_mb
