"""fs.* commands: filer namespace operations from the admin shell.

Reference: weed/shell/command_fs_ls.go, _cat.go, _du.go, _rm.go,
_mkdir.go, _mv.go — the shell resolves a filer via the master's cluster
registry and drives its gRPC surface.
"""
from __future__ import annotations

import time

from ..filer.client import list_all_entries
from ..pb import filer_pb2
from .commands import command


def _split(path: str) -> tuple[str, str]:
    path = "/" + path.strip("/")
    d, _, name = path.rpartition("/")
    return d or "/", name


def _cwd(env) -> str:
    return env.option.get("fs_cwd", "/")


def _resolve(env, p: str | None) -> str:
    """Join a (possibly relative) shell path against fs.cd's cwd, with
    `.`/`..` normalization (the reference shell keeps the same state in
    commandEnv.option.Directory)."""
    if not p:
        return _cwd(env)
    base = "/" if p.startswith("/") else _cwd(env)
    out = [x for x in base.strip("/").split("/") if x]
    for x in p.split("/"):
        if not x or x == ".":
            continue
        if x == "..":
            if out:
                out.pop()
        else:
            out.append(x)
    return "/" + "/".join(out)


async def _stub(env):
    return env.filer_stub(await env.find_filer())


async def _lookup(stub, path: str):
    import grpc

    d, name = _split(path)
    try:
        resp = await stub.LookupDirectoryEntry(
            filer_pb2.LookupDirectoryEntryRequest(directory=d, name=name)
        )
    except grpc.aio.AioRpcError as e:
        if e.code() == grpc.StatusCode.NOT_FOUND:
            return None
        raise
    return resp.entry if resp.HasField("entry") else None


def _fmt_size(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if n < 1024 or unit == "TB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024


def _entry_size(e: filer_pb2.Entry) -> int:
    extent = max((c.offset + int(c.size) for c in e.chunks), default=0)
    return max(e.attributes.file_size, extent, len(e.content))


async def _walk_entries(stub, directory: str):
    """DFS over a filer subtree; yields (dir, entry) with parents before
    children (shared by fs.du and fs.meta.save)."""
    for e in await list_all_entries(stub, directory):
        yield directory, e
        if e.is_directory:
            async for pair in _walk_entries(
                stub, f"{directory.rstrip('/')}/{e.name}"
            ):
                yield pair


def _positional(args: list[str], value_flags: set[str] = frozenset()) -> list[str]:
    """Non-flag tokens; tokens consumed as a value flag's argument (e.g.
    `-o FILE`) are excluded."""
    out = []
    skip = False
    for i, a in enumerate(args):
        if skip:
            skip = False
            continue
        if a.startswith("-"):
            name = a.lstrip("-").partition("=")[0]
            if name in value_flags and "=" not in a and i + 1 < len(args):
                skip = True
            continue
        out.append(a)
    return out


@command("fs.ls")
async def cmd_fs_ls(env, args):
    """[-l] /dir : list a filer directory"""
    long_form = "-l" in args
    pos = _positional(args)
    path = _resolve(env, pos[0] if pos else None)
    stub = await _stub(env)
    for e in await list_all_entries(stub, path or "/"):
        if long_form:
            a = e.attributes
            kind = "d" if e.is_directory else "-"
            env.write(
                f"{kind}{a.file_mode & 0o777:03o} "
                f"{_fmt_size(_entry_size(e)):>10} "
                f"{time.strftime('%Y-%m-%d %H:%M', time.localtime(a.mtime or 0))} "
                f"{e.name}{'/' if e.is_directory else ''}"
            )
        else:
            env.write(e.name + ("/" if e.is_directory else ""))


@command("fs.cat")
async def cmd_fs_cat(env, args):
    """/path/to/file : print a filer file's contents"""
    pos = _positional(args)
    if not pos:
        env.write("usage: fs.cat /path")
        return
    path = _resolve(env, pos[0])
    import urllib.parse

    import aiohttp

    from ..pb import server_address

    filer = await env.find_filer()
    async with aiohttp.ClientSession() as s:
        async with s.get(
            f"http://{server_address.http_address(filer)}"
            f"{urllib.parse.quote(path)}"
        ) as r:
            if r.status >= 300:
                env.write(f"fs.cat {path}: HTTP {r.status}")
                return
            env.write((await r.read()).decode(errors="replace"))


@command("fs.du")
async def cmd_fs_du(env, args):
    """/dir : disk usage of a filer subtree"""
    pos = _positional(args)
    path = _resolve(env, pos[0] if pos else None)
    stub = await _stub(env)
    files = dirs = size = 0
    async for _, e in _walk_entries(stub, path or "/"):
        if e.is_directory:
            dirs += 1
        else:
            files += 1
            size += _entry_size(e)
    env.write(
        f"{path or '/'}: {_fmt_size(size)} in {files} files, {dirs} dirs"
    )


@command("fs.mkdir")
async def cmd_fs_mkdir(env, args):
    """/dir/path : create a filer directory (and parents)"""
    pos = _positional(args)
    if not pos:
        env.write("usage: fs.mkdir /dir")
        return
    path = _resolve(env, pos[0])
    stub = await _stub(env)
    existing = await _lookup(stub, path)
    if existing is not None:
        if existing.is_directory:
            env.write(f"{path} already exists")
        else:
            env.write(f"fs.mkdir {path}: a file is in the way")
        return
    # one leaf create: the filer auto-creates parents and refuses to
    # thread a directory through an existing file
    d, name = _split(path)
    resp = await stub.CreateEntry(
        filer_pb2.CreateEntryRequest(
            directory=d,
            entry=filer_pb2.Entry(
                name=name, is_directory=True,
                attributes=filer_pb2.FuseAttributes(
                    file_mode=0o770, mtime=int(time.time()),
                ),
            ),
        )
    )
    if resp.error:
        env.write(f"fs.mkdir {path}: {resp.error}")
    else:
        env.write(f"created {path}")


@command("fs.rm")
async def cmd_fs_rm(env, args):
    """[-r] /path : delete a filer file or (with -r) directory tree"""
    recursive = "-r" in args
    pos = _positional(args)
    if not pos:
        env.write("usage: fs.rm [-r] /path")
        return
    path = _resolve(env, pos[0])
    d, name = _split(path)
    stub = await _stub(env)
    if await _lookup(stub, path) is None:
        env.write(f"fs.rm {path}: no such file or directory")
        return
    resp = await stub.DeleteEntry(
        filer_pb2.DeleteEntryRequest(
            directory=d, name=name, is_delete_data=True,
            is_recursive=recursive, ignore_recursive_error=False,
        )
    )
    if resp.error:
        env.write(f"fs.rm {path}: {resp.error}")
    else:
        env.write(f"deleted {path}")


@command("fs.mv")
async def cmd_fs_mv(env, args):
    """/src /dst : move/rename within the filer"""
    parts = _positional(args)
    if len(parts) != 2:
        env.write("usage: fs.mv /src /dst")
        return
    src, dst = (_resolve(env, p) for p in parts)
    sd, sn = _split(src)
    dd, dn = _split(dst)
    stub = await _stub(env)
    await stub.AtomicRenameEntry(
        filer_pb2.AtomicRenameEntryRequest(
            old_directory=sd, old_name=sn,
            new_directory=dd, new_name=dn,
        )
    )
    env.write(f"moved {src} -> {dst}")


@command("fs.meta.save")
async def cmd_fs_meta_save(env, args):
    """[-o file] [/dir] : dump the filer metadata tree as length-prefixed
    FullEntry protos (command_fs_meta_save.go wire shape)"""
    import struct

    from .commands import parse_flags

    flags = parse_flags(args)
    pos = _positional(args, value_flags={"o"})
    root = _resolve(env, pos[0] if pos else None)
    out_path = flags.get("o", "filer-meta.bin")
    stub = await _stub(env)
    n = 0
    import asyncio

    from ..utils.aiofile import open_in_thread

    # file IO via to_thread: the shell shares its loop with the
    # in-flight ListEntries stream feeding _walk_entries.  Records are
    # buffered and flushed in ~1MB slabs — one executor hop per slab,
    # not two per entry
    buf = bytearray()
    async with open_in_thread(out_path, "wb") as f:
        async for d, e in _walk_entries(stub, root or "/"):
            fe = filer_pb2.FullEntry(dir=d, entry=e)
            blob = fe.SerializeToString()
            # big-endian length prefix: byte-compatible with the
            # reference's fs.meta.save files (util.Uint32toBytes)
            buf += struct.pack(">I", len(blob)) + blob
            n += 1
            if len(buf) >= 1 << 20:
                await asyncio.to_thread(f.write, bytes(buf))
                buf.clear()
        if buf:
            await asyncio.to_thread(f.write, bytes(buf))
    env.write(f"saved {n} entries from {root or '/'} to {out_path}")


@command("fs.meta.load")
async def cmd_fs_meta_load(env, args):
    """-i file : restore filer metadata saved by fs.meta.save (entries
    only — chunk data must still exist in the cluster)"""
    import struct

    from .commands import parse_flags

    flags = parse_flags(args)
    pos = _positional(args, value_flags={"i"})
    in_path = flags.get("i") or (pos[0] if pos else "")
    if not in_path:
        env.write("usage: fs.meta.load -i file")
        return
    stub = await _stub(env)
    n = 0
    import asyncio

    from ..utils.aiofile import open_in_thread

    # stream in ~1MB slabs through to_thread and parse records from the
    # rolling buffer: one executor hop per slab (not two per entry) and
    # constant memory even for multi-GB backups
    async with open_in_thread(in_path, "rb") as f:
        buf = b""
        eof = False
        while True:
            while not eof and (
                len(buf) < 4 or len(buf) < 4 + struct.unpack(
                    ">I", buf[:4]
                )[0]
            ):
                chunk = await asyncio.to_thread(f.read, 1 << 20)
                if not chunk:
                    eof = True
                    break
                buf += chunk
            if len(buf) < 4:
                break
            (size,) = struct.unpack(">I", buf[:4])
            blob, buf = buf[4 : 4 + size], buf[4 + size :]
            if len(blob) < size:
                env.write(
                    f"warning: truncated backup — last record dropped"
                )
                break
            fe = filer_pb2.FullEntry.FromString(blob)
            resp = await stub.CreateEntry(
                filer_pb2.CreateEntryRequest(directory=fe.dir, entry=fe.entry)
            )
            if resp.error:
                env.write(f"{fe.dir}/{fe.entry.name}: {resp.error}")
                continue
            n += 1
    env.write(f"restored {n} entries from {in_path}")


@command("fs.pwd")
async def cmd_fs_pwd(env, args):
    """print the shell's current filer directory (command_fs_pwd.go)"""
    env.write(_cwd(env))


@command("fs.cd")
async def cmd_fs_cd(env, args):
    """/dir | relative/dir | .. : change the shell's current filer
    directory (command_fs_cd.go)"""
    pos = _positional(args)
    path = _resolve(env, pos[0] if pos else "/")
    if path != "/":
        stub = await _stub(env)
        e = await _lookup(stub, path)
        if e is None or not e.is_directory:
            env.write(f"fs.cd {path}: no such directory")
            return
    env.option["fs_cwd"] = path


@command("fs.tree")
async def cmd_fs_tree(env, args):
    """[/dir] : recursively print the filer subtree (command_fs_tree.go)"""
    pos = _positional(args)
    root = _resolve(env, pos[0] if pos else None)
    stub = await _stub(env)
    files = dirs = 0

    async def walk(directory: str, depth: int):
        nonlocal files, dirs
        for e in await list_all_entries(stub, directory):
            env.write("  " * depth + e.name + ("/" if e.is_directory else ""))
            if e.is_directory:
                dirs += 1
                await walk(f"{directory.rstrip('/')}/{e.name}", depth + 1)
            else:
                files += 1

    env.write(root)
    await walk(root, 1)
    env.write(f"{dirs} directories, {files} files")


@command("fs.meta.cat")
async def cmd_fs_meta_cat(env, args):
    """/path : print one entry's metadata as the raw pb text
    (command_fs_meta_cat.go)"""
    pos = _positional(args)
    if not pos:
        env.write("usage: fs.meta.cat /path")
        return
    path = _resolve(env, pos[0])
    stub = await _stub(env)
    e = await _lookup(stub, path)
    if e is None:
        env.write(f"fs.meta.cat {path}: not found")
        return
    env.write(str(e))


@command("fs.verify")
async def cmd_fs_verify(env, args):
    """[-v] [/dir] : check that every file chunk under the subtree is
    readable from some volume server (command_fs_verify.go)"""
    import aiohttp

    from ..operation.lookup import lookup_file_id

    verbose = "-v" in args
    pos = _positional(args)
    root = _resolve(env, pos[0] if pos else None)
    stub = await _stub(env)
    master = env.masters[0]
    ok = broken = 0
    vol_locations: dict[str, list[str]] = {}  # vid -> server urls (cached)
    async with aiohttp.ClientSession() as http:
        async for d, e in _walk_entries(stub, root):
            if e.is_directory:
                continue
            for c in e.chunks:
                fid = c.file_id
                vid = fid.partition(",")[0]
                try:
                    if vid not in vol_locations:
                        urls = await lookup_file_id(master, fid)
                        vol_locations[vid] = [
                            u.rsplit("/", 1)[0] for u in urls
                        ]
                    servers = vol_locations[vid]
                    if not servers:
                        raise RuntimeError("no locations")
                    async with http.head(f"{servers[0]}/{fid}") as r:
                        if r.status >= 300:
                            raise RuntimeError(f"HTTP {r.status}")
                    ok += 1
                    if verbose:
                        env.write(f"  ok {d}/{e.name} chunk {fid}")
                except Exception as err:  # noqa: BLE001
                    broken += 1
                    env.write(
                        f"  BROKEN {d}/{e.name} chunk {fid}: {err}"
                    )
    env.write(f"verified {ok} chunks, {broken} broken")


@command("fs.configure")
async def cmd_fs_configure(env, args):
    """[-locationPrefix /p/ -collection c -replication XYZ -ttl 1h
    -disk ssd -readOnly] [-delete] [-apply] : view or edit per-path
    storage rules in /etc/seaweedfs/filer.conf (command_fs_configure.go).
    Without -apply the resulting conf is printed but not saved."""
    from .commands import parse_flags
    from ..filer.path_conf import CONF_DIR, CONF_NAME, CONF_PATH, FilerConf, PathConf

    flags = parse_flags(args)
    stub = await _stub(env)
    existing = await _lookup(stub, CONF_PATH)
    conf = FilerConf.from_bytes(
        bytes(existing.content) if existing is not None else b""
    )
    prefix = flags.get("locationPrefix", "")
    if prefix:
        if "delete" in flags:
            if not conf.delete(prefix):
                env.write(f"no rule for {prefix}")
        else:
            # merge into any existing rule: fields not passed on THIS
            # invocation survive (so editing the ttl can't silently clear
            # a quota lock's read_only flag, and vice versa)
            rule = next(
                (
                    l
                    for l in conf.locations
                    if l.location_prefix == prefix
                ),
                PathConf(location_prefix=prefix),
            )
            if "collection" in flags:
                rule.collection = flags["collection"]
            if "replication" in flags:
                rule.replication = flags["replication"]
            if "ttl" in flags:
                rule.ttl = flags["ttl"]
            if "disk" in flags:
                rule.disk_type = flags["disk"]
            if "readOnly" in flags:
                rule.read_only = flags["readOnly"] != "false"
            conf.upsert(rule)
    env.write(conf.to_bytes().decode())
    if "apply" not in flags:
        if prefix:
            env.write("(not saved — add -apply)")
        return
    from ..filer.path_conf import save_conf_entry

    await save_conf_entry(stub, CONF_DIR, CONF_NAME, conf.to_bytes())
    env.write(f"saved {CONF_PATH}")


@command("fs.meta.notify")
async def cmd_fs_meta_notify(env, args):
    """[-spool file] [/dir] : re-publish every entry under the subtree as
    a metadata-change notification (command_fs_meta_notify.go — seeds an
    external consumer that missed the live stream).  Events go to the
    spool-file queue backend (replication/notification.py), the stand-in
    for kafka/SQS in this environment."""
    from .commands import parse_flags
    from ..replication.notification import FileQueueNotifier, LogNotifier

    flags = parse_flags(args)
    pos = _positional(args, value_flags={"spool"})
    root = _resolve(env, pos[0] if pos else None)
    notifier = (
        FileQueueNotifier(flags["spool"]) if "spool" in flags else LogNotifier()
    )
    stub = await _stub(env)
    n = 0
    async for d, e in _walk_entries(stub, root):
        await notifier.publish(
            f"{d.rstrip('/')}/{e.name}",
            filer_pb2.EventNotification(new_entry=e),
        )
        n += 1
    close = getattr(notifier, "close", None)
    if close:
        close()
    env.write(f"notified {n} entries under {root}")


@command("fs.meta.change.volume.id")
async def cmd_fs_meta_change_volume_id(env, args):
    """-from N -to M [-force] [/dir] : rewrite chunk volume ids in filer
    metadata after a volume id migration (command_fs_meta_change_volume_id.go)"""
    from .commands import parse_flags

    env.confirm_is_locked()
    flags = parse_flags(args)
    vid_from = int(flags["from"])
    vid_to = int(flags["to"])
    apply = "force" in flags
    pos = _positional(args, value_flags={"from", "to"})
    root = _resolve(env, pos[0] if pos else None)
    stub = await _stub(env)
    changed = skipped = 0
    async for d, e in _walk_entries(stub, root):
        if e.is_directory:
            continue
        if any(c.is_chunk_manifest for c in e.chunks):
            # nested chunk ids live in a serialized manifest blob this
            # command can't rewrite — claiming success would leave reads
            # pointing at the old volume
            env.write(
                f"{d.rstrip('/')}/{e.name}: has manifest chunks — "
                f"skipped (re-write the file to re-home it)"
            )
            skipped += 1
            continue
        hit = False
        for c in e.chunks:
            vid_s, _, rest = c.file_id.partition(",")
            if vid_s and int(vid_s) == vid_from:
                hit = True
                if apply:
                    c.file_id = f"{vid_to},{rest}"
        if not hit:
            continue
        env.write(f"{d.rstrip('/')}/{e.name}: volume {vid_from} -> {vid_to}")
        if apply:
            await stub.UpdateEntry(
                filer_pb2.UpdateEntryRequest(directory=d, entry=e)
            )
        changed += 1
    env.write(
        f"{changed} entries{' rewritten' if apply else ' affected (use -force)'}"
        + (f", {skipped} skipped (manifest chunks)" if skipped else "")
    )
